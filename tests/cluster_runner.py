"""Cluster elastic-resume drill harness (ISSUE 13).

``member`` mode is one host of a cluster training run:

* joins the ClusterMaster (TCP), heartbeats on a lease;
* multi-member worlds init ``jax.distributed`` (gloo) and train a
  fixed-seed MLP on the GLOBAL ``(dp=1, fsdp=N*devs)`` mesh, feeding
  each host's slice of the same deterministic global batches;
* every dispatch goes through the master's **step barrier**
  (``enter_step``) — lockstep SPMD members never enter a collective
  with a dead peer: a death surfaces as a lease expiry and the barrier
  answers ``reshape`` instead of hanging an all-reduce;
* checkpoints are per-host SHARDED TrainState artifacts (sync saves;
  the manifest committer is master-elected via ``request_save``);
* on ``reshape`` with itself as the only survivor, the member RE-EXECS
  into a single-host world: fresh jax runtime, the mesh rebuilt at the
  new (smaller) size, state restored from the last committed step
  through ``ParallelExecutor.state_shardings()`` — elastic resume with
  no operator action;
* a designated victim SIGKILLs itself at a step boundary (mid-run,
  between checkpoint commits).

``supervise`` mode (also importable: ``supervise()``) runs the whole
drill — reference solo run, 2-member world, kill, elastic resume — and
checks the acceptance criteria: every logged step loss within the
parity band of the uninterrupted smaller-mesh reference, and per-host
shard bytes ~1/N in the committed manifest.

Run:  python cluster_runner.py supervise <workdir>
      python cluster_runner.py member <id> <n> <master> <coordinator>
             <ckpt> <log> <total> <kill_step> <devs_per_host>
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOTAL_STEPS = 12
KILL_STEP = 8
SAVE_INTERVAL = 3
# generous vs the ~1.3s heartbeat cadence: a member's heartbeat thread
# can starve for a beat behind a cold XLA compile on a loaded box, and
# a spurious mid-compile expiry turns the drill into a reshape storm
LEASE_SECONDS = 4.0
BATCH = 16
# mesh-size-change parity band: fsdp reduce order differs between mesh
# sizes, so losses match to float noise, not bitwise, and Adam
# compounds the noise step over step (PR 5 measured ~1e-6 over 3 Adam
# steps; measured here ~2e-5 over 12 steps at lr 2e-3 — an aggressive
# lr amplifies reduce-order noise chaotically, x30/step at lr 1e-2)
PARITY_RTOL = 1e-3


def _global_batch(step):
    import numpy as np

    rng = np.random.RandomState(1000 + step)
    x = rng.rand(BATCH, 64).astype("float32")
    y = x[:, :4].argmax(1).astype("int64").reshape(-1, 1)
    return x, y


def member_main(argv):
    (member_id, nmembers, master_addr, coordinator, ckpt_dir, log_path,
     total, kill_step, devs) = (int(argv[0]), int(argv[1]), argv[2],
                                argv[3], argv[4], argv[5], int(argv[6]),
                                int(argv[7]), int(argv[8]))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % devs)
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, REPO)
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.cluster import ClusterMember
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.checkpoint import TrainStateCheckpointManager

    if nmembers > 1:
        # init_distributed (not raw jax.distributed.initialize): it
        # re-scopes the persistent XLA cache per world shape, so the
        # elastic-resume survivor never deserializes this 2-process
        # world's executables into its solo world
        from paddle_tpu.parallel import distributed

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        distributed.init_distributed(coordinator_address=coordinator,
                                     num_processes=nmembers,
                                     process_id=member_id)

    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[64])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=256, act="relu")
    pred = fluid.layers.fc(h, size=4, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    lr = fluid.layers.exponential_decay(2e-3, decay_steps=4,
                                        decay_rate=0.8)
    fluid.optimizer.Adam(learning_rate=lr).minimize(loss)

    member = ClusterMember(master_addr, "host%d" % member_id,
                           meta={"devices": devs})
    mesh = make_mesh((1, len(jax.devices())), ("dp", "fsdp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        mgr = TrainStateCheckpointManager(
            ckpt_dir, sharded=True, async_save=False,
            save_interval_steps=SAVE_INTERVAL,
            saver_elect=member.request_save, commit_timeout=60.0)
        step = mgr.restore(scope=scope,
                           program=fluid.default_main_program(),
                           executors={"train": pe},
                           shardings=pe.state_shardings())
        if step is None:
            step = 0
        else:
            print("RESUMED", step, "mesh", len(jax.devices()),
                  flush=True)
        log = open(log_path, "a") if member_id == 0 else None

        # wait for the full world to form before the first barrier, so
        # the join-order epoch bumps are absorbed up front
        deadline = time.monotonic() + 60.0
        while nmembers > 1 and len(member.members) < nmembers:
            if time.monotonic() > deadline:
                raise RuntimeError("world never formed: %s"
                                   % member.members)
            member.heartbeat()
            time.sleep(0.05)

        while step < total:
            step += 1
            while True:
                res = member.enter_step(step, timeout=90.0)
                if res["action"] != "reshape":
                    break
                survivors = member.members
                if len(survivors) >= nmembers:
                    # benign epoch move (a join at world formation):
                    # same world size, nothing to rebuild — accept THE
                    # VIEW WE SAW (not the latest observed epoch, which
                    # the heartbeat thread may advance concurrently)
                    member.accept_world(res["epoch"])
                    continue
                print("RESHAPE epoch", member.epoch, "members",
                      survivors, flush=True)
                if survivors != ["host%d" % member_id]:
                    # a multi-survivor reshape needs a fresh gloo world
                    # — out of this drill's scope
                    print("RESHAPE_UNSUPPORTED", survivors, flush=True)
                    sys.exit(3)
                if log is not None:
                    log.close()
                member.close()
                # elastic resume: re-exec into a single-host world — a
                # fresh jax runtime over this host's local devices; the
                # restore above rebuilds state on the smaller mesh
                os.execv(sys.executable, [
                    sys.executable, os.path.abspath(__file__), "member",
                    str(member_id), "1", master_addr, "-", ckpt_dir,
                    log_path, str(total), "0", str(devs)])
            assert res["action"] == "go", res

            xg, yg = _global_batch(step)
            lo = member_id * (BATCH // nmembers)
            hi = lo + BATCH // nmembers
            (lv,) = pe.run(feed={"x": xg[lo:hi], "label": yg[lo:hi]},
                           fetch_list=[loss])
            lv = np.asarray(lv, "float32")
            if log is not None:
                log.write(json.dumps(
                    {"step": step, "loss_hex": lv.tobytes().hex(),
                     "loss": float(lv.ravel()[0]),
                     "mesh": len(jax.devices())}) + "\n")
                log.flush()
                os.fsync(log.fileno())
            mgr.save(step, scope=scope,
                     program=fluid.default_main_program(),
                     executors={"train": pe})
            if kill_step and step == kill_step \
                    and member_id == nmembers - 1:
                print("KILLING_SELF", step, flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
        mgr.wait_until_finished()
        print("DONE", step, flush=True)
        member.leave()


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _member_cmd(member_id, nmembers, master, coordinator, ckpt, log,
                total, kill_step, devs):
    return [sys.executable, os.path.abspath(__file__), "member",
            str(member_id), str(nmembers), master, coordinator,
            str(ckpt), str(log), str(total), str(kill_step), str(devs)]


def _member_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)      # member mode sets its own count
    # NO persistent compile cache: deserialized MULTI-DEVICE CPU
    # executables are numerically NONDETERMINISTIC (measured here:
    # warm replays of one artifact drifted 1e-3..1e-1 run to run,
    # fresh compiles are bit-exact) — a parity drill cannot ride them.
    # Single-device warm restarts (test_elastic_drill) stay exact.
    env.pop("FLAGS_compile_cache_dir", None)
    return env


def _read_log(log_path):
    """step -> {loss_hex values seen} + step -> [float losses]."""
    hexes, losses = {}, {}
    with open(log_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            hexes.setdefault(rec["step"], set()).add(rec["loss_hex"])
            losses.setdefault(rec["step"], []).append(rec["loss"])
    return hexes, losses


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def supervise(workdir, total_steps=TOTAL_STEPS, kill_step=KILL_STEP,
              devs=4, timeout=420.0):
    """Run the full drill; returns the evidence dict (asserting the
    acceptance criteria along the way)."""
    sys.path.insert(0, REPO)
    import numpy as np
    from paddle_tpu.cluster import ClusterMaster
    from paddle_tpu.cloud import MasterServer

    workdir = os.path.abspath(str(workdir))
    os.makedirs(workdir, exist_ok=True)

    # reference: an UNINTERRUPTED solo run on the small mesh (its own
    # master so its membership never perturbs the drill's epochs)
    ref_srv = MasterServer(
        ClusterMaster(lease_timeout=LEASE_SECONDS)).start()
    ref_log = os.path.join(workdir, "ref.jsonl")
    p = subprocess.run(
        _member_cmd(0, 1, ref_srv.address, "-",
                    os.path.join(workdir, "ref_ckpt"), ref_log,
                    total_steps, 0, devs),
        env=_member_env(), capture_output=True, text=True,
        timeout=timeout)
    ref_srv.shutdown()
    assert p.returncode == 0, (p.returncode, p.stderr[-4000:])
    ref_hexes, ref_losses = _read_log(ref_log)
    assert sorted(ref_hexes) == list(range(1, total_steps + 1))

    # the drill world: 2 members, one global mesh, shared sharded ckpt
    master = ClusterMaster(lease_timeout=LEASE_SECONDS)
    srv = MasterServer(master).start()
    ckpt = os.path.join(workdir, "ckpt")
    log = os.path.join(workdir, "drill.jsonl")
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = [subprocess.Popen(
        _member_cmd(i, 2, srv.address, coordinator, ckpt, log,
                    total_steps, kill_step, devs),
        env=_member_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for i in range(2)]
    try:
        out1, err1 = procs[1].communicate(timeout=timeout)
        out0, err0 = procs[0].communicate(timeout=timeout)
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.kill()
    assert procs[1].returncode == -signal.SIGKILL, (
        procs[1].returncode, err1[-4000:])
    assert "KILLING_SELF %d" % kill_step in out1, out1[-2000:]
    assert procs[0].returncode == 0, (procs[0].returncode,
                                      err0[-4000:])
    # the survivor observed the lease expiry, reshaped, resumed solo
    assert "RESHAPE epoch" in out0, out0[-2000:]
    assert "RESUMED" in out0, out0[-2000:]
    resumed_from = int(out0.split("RESUMED")[1].split()[0])
    assert 0 < resumed_from <= kill_step, (resumed_from, out0[-2000:])
    assert "DONE %d" % total_steps in out0, out0[-2000:]
    srv.shutdown()

    # parity: every logged loss (2-member mesh, replayed, resumed) sits
    # in the float-noise band of the uninterrupted small-mesh run
    hexes, losses = _read_log(log)
    assert sorted(hexes) == list(range(1, total_steps + 1)), \
        sorted(hexes)
    max_rel = 0.0
    for step, vals in losses.items():
        ref = ref_losses[step][0]
        for v in vals:
            assert np.isfinite(v), (step, v)
            max_rel = max(max_rel, abs(v - ref) / max(abs(ref), 1e-9))
    assert max_rel <= PARITY_RTOL, (
        "loss trajectory out of the parity band: max rel dev %g"
        % max_rel)

    # manifest-verified 1/N per-host bytes: a world-A artifact
    # (writers=2) must exist with both hosts contributing ~half
    two_writer = None
    for d in sorted(os.listdir(ckpt)):
        mf = os.path.join(ckpt, d, "MANIFEST.json")
        if d.startswith("step_") and os.path.exists(mf):
            man = json.load(open(mf))
            if man.get("writers") == 2:
                two_writer = (d, man)
    assert two_writer is not None, os.listdir(ckpt)
    pw = two_writer[1]["per_writer_bytes"]
    total_bytes = sum(pw.values())
    max_frac = max(pw.values()) / total_bytes
    assert len(pw) == 2 and max_frac < 0.7, (pw, max_frac)

    return {"resumed_from": resumed_from,
            "max_rel_loss_dev": max_rel,
            "parity_rtol": PARITY_RTOL,
            "sharded_artifact": two_writer[0],
            "per_writer_bytes": pw,
            "max_writer_fraction": max_frac,
            "steps": total_steps, "kill_step": kill_step}


def main():
    mode = sys.argv[1]
    if mode == "member":
        member_main(sys.argv[2:])
    elif mode == "supervise":
        evidence = supervise(sys.argv[2],
                             *[int(a) for a in sys.argv[3:]])
        print("CLUSTER_DRILL", json.dumps(evidence))
        print("CLUSTER_DRILL OK: survivor resumed from step %d on the "
              "smaller mesh; max loss deviation %.2e (band %.0e); "
              "per-host shard bytes %s (max fraction %.3f)"
              % (evidence["resumed_from"], evidence["max_rel_loss_dev"],
                 evidence["parity_rtol"],
                 evidence["per_writer_bytes"],
                 evidence["max_writer_fraction"]))
    else:
        raise SystemExit("unknown mode %r" % mode)


if __name__ == "__main__":
    main()
