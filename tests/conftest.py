"""Test configuration: force the CPU backend with 8 virtual devices so
multi-chip sharding tests run without TPU hardware (SURVEY.md §4 TPU
translation of the reference's multi-device test strategy)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin (if present) overrides JAX_PLATFORMS at import time;
# the config update below wins over it, keeping tests on the virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    """Isolate each test: fresh default programs, scope, and name counter
    (the reference achieves this with new Program() per test; we reset the
    singletons)."""
    import paddle_tpu as fluid
    from paddle_tpu import framework, unique_name
    from paddle_tpu.scope import Scope

    old_main = framework.switch_main_program(fluid.Program())
    old_startup = framework.switch_startup_program(fluid.Program())
    old_gen = unique_name.switch()
    scope = Scope()
    with fluid.scope_guard(scope):
        yield
    framework.switch_main_program(old_main)
    framework.switch_startup_program(old_startup)
    unique_name.switch(old_gen)
    # the per-test unique_name reset makes structurally identical
    # programs from DIFFERENT tests fingerprint-collide in the
    # process-global trace cache; drop it so a monkeypatched op in one
    # test can never serve a stale trace to the next
    from paddle_tpu import compile_cache

    compile_cache.clear()
