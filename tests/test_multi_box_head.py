"""multi_box_head — the SSD prediction head (reference
detection.py:1015): prior boxes + loc/conf convolutions across feature
maps, concatenated."""

import numpy as np
import pytest

import paddle_tpu as fluid

L = fluid.layers


def _build(num_classes=5):
    img = L.data("img", shape=[3, 64, 64])
    c1 = L.conv2d(img, 8, 3, stride=8, padding=1)    # [N, 8, 8, 8]
    c2 = L.conv2d(img, 8, 3, stride=16, padding=1)   # [N, 8, 4, 4]
    return img, L.multi_box_head(
        inputs=[c1, c2], image=img, num_classes=num_classes,
        min_sizes=[10.0, 20.0], max_sizes=[20.0, 40.0],
        aspect_ratios=[[2.0], [2.0, 3.0]], base_size=64)


def test_multi_box_head_shapes_align():
    img, (locs, confs, box, var) = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    lv, cv, bv, vv = [np.asarray(v) for v in exe.run(
        feed={"img": np.random.rand(2, 3, 64, 64).astype("float32")},
        fetch_list=[locs, confs, box, var])]
    # priors: 8x8 map with (1 min + 1 max + 2 flipped ARs) = 4 boxes,
    # 4x4 map with (1 + 1 + 4) = 6 boxes -> 8*8*4 + 4*4*6 = 352
    assert lv.shape == (2, 352, 4)
    assert cv.shape == (2, 352, 5)
    assert bv.shape == (352, 4) and vv.shape == (352, 4)
    # prior boxes and conv predictions must agree on P
    assert lv.shape[1] == bv.shape[0]


def test_multi_box_head_ratio_schedule_and_training():
    """min_ratio/max_ratio schedule path (>=3 maps) + ssd_loss-style
    training step keeps gradients finite."""
    img = L.data("img", shape=[3, 64, 64])
    feats = [L.conv2d(img, 4, 3, stride=s, padding=1)
             for s in (8, 16, 32)]
    locs, confs, box, var = L.multi_box_head(
        inputs=feats, image=img, num_classes=3,
        min_ratio=20, max_ratio=90,
        aspect_ratios=[[2.0], [2.0], [2.0]], base_size=64)
    loss = L.mean(L.elementwise_mul(locs, locs)) \
        + L.mean(L.elementwise_mul(confs, confs))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"img": np.random.rand(2, 3, 64, 64).astype("float32")}
    l0 = float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])[0])
    for _ in range(5):
        lv, = exe.run(feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()
    assert float(np.asarray(lv)[0]) < l0   # shrinking the L2 objective


def test_multi_box_head_validation():
    img = L.data("img", shape=[3, 64, 64])
    c1 = L.conv2d(img, 4, 3, stride=8)
    with pytest.raises(AssertionError):
        # <=2 maps without explicit min/max sizes
        L.multi_box_head(inputs=[c1], image=img, num_classes=3,
                         aspect_ratios=[[2.0]], base_size=64)
    with pytest.raises(ValueError):
        L.multi_box_head(inputs=[c1], image=img, num_classes=3,
                         min_sizes=[10.0], max_sizes=[20.0],
                         aspect_ratios=[[2.0], [3.0]], base_size=64)
