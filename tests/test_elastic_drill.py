"""Elastic end-to-end drill (VERDICT r3 #10): cloud master + a REAL
training loop + sharded checkpoints in one multi-process run.

A trainer process leases chunk-tasks from the master, reads each task's
recordio chunk range, trains a linear model through the Executor, and
checkpoints (params via ShardedCheckpointManager + a sample ledger) at
task boundaries.  The drill SIGKILLs the first trainer mid-task; a
replacement trainer resumes from the checkpoint, the master re-leases
the orphaned task after its lease times out, and the pass completes with
every sample accounted for EXACTLY once (partial work from the killed
task is discarded with its un-checkpointed state).

Extends tests/test_cloud_master.py's toy kill-mid-task test to a real
training loop; reference capability: go/master/service.go task leases +
doc/v2/design/cluster_train/checkpointing.md.
"""

import json
import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.cloud import InMemStore, MasterServer
from paddle_tpu.cloud.master import MasterService
from paddle_tpu import recordio as rio

TRAINER_SRC = '''
import json, os, pickle, sys, time
import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, sys.argv[5])
import paddle_tpu as fluid
from paddle_tpu.cloud import MasterClient
from paddle_tpu.cloud.master import (NoMoreAvailable, PassBefore,
                                     AllTasksFailed)
from paddle_tpu import recordio as rio
from paddle_tpu.parallel.checkpoint import ShardedCheckpointManager

addr, rio_path, ckpt_dir, kill_after = (sys.argv[1], sys.argv[2],
                                        sys.argv[3], int(sys.argv[4]))

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 3
with fluid.program_guard(main, startup):
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.data("y", shape=[1])
    pred = fluid.layers.fc(x, size=1, act=None,
                           param_attr=fluid.ParamAttr(name="w"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

scope = fluid.Scope()
ledger_path = os.path.join(ckpt_dir, "ledger.json")
with fluid.scope_guard(scope):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mgr = ShardedCheckpointManager(os.path.join(ckpt_dir, "params"),
                                   async_save=False)
    processed = []
    step = mgr.restore(scope=scope, program=main)
    if step is not None and os.path.exists(ledger_path):
        processed = json.load(open(ledger_path))
        print("RESUMED", step, len(processed), flush=True)

    c = MasterClient(addr)
    tasks_done = 0
    while True:
        try:
            t = c.get_task(0)
        except (PassBefore, AllTasksFailed):
            break
        except NoMoreAvailable:
            time.sleep(0.05)
            continue
        print("TASK_STARTED", t.task_id, flush=True)
        ids = []
        for path, start, cnt in t.chunks:
            with rio.Scanner(path, skip_chunks=start, max_chunks=cnt) as s:
                for rec in s:
                    sid, xv, yv = pickle.loads(rec)
                    (lv,) = exe.run(main,
                                    feed={"x": xv[None], "y": yv[None]},
                                    fetch_list=[loss])
                    assert np.isfinite(lv).all()
                    ids.append(sid)
                    if kill_after and len(processed) + len(ids) \\
                            >= kill_after:
                        print("KILL_POINT", flush=True)
                        time.sleep(600)   # parent SIGKILLs here
        # task boundary: commit samples + params atomically-enough
        processed.extend(ids)
        json.dump(processed, open(ledger_path + ".tmp", "w"))
        os.replace(ledger_path + ".tmp", ledger_path)
        mgr.save_now(len(processed), scope=scope, program=main)
        c.task_finished(t.task_id)
        tasks_done += 1
        print("TASK_DONE", t.task_id, flush=True)
        if c.stats()["cur_pass"] >= 1:
            break
print("FINISHED", json.dumps(sorted(processed)), flush=True)
'''


def test_elastic_kill_and_resume_full_training_pass(tmp_path):
    n_samples = 12
    rng = np.random.RandomState(0)
    w_true = rng.rand(4, 1).astype("float32")
    rio_path = str(tmp_path / "data.rio")
    with rio.Writer(rio_path, max_chunk_bytes=1) as w:  # 1 sample/chunk
        for i in range(n_samples):
            xv = rng.rand(4).astype("float32")
            yv = (xv @ w_true).astype("float32")
            w.write(pickle.dumps((i, xv, yv)))
    n_chunks = rio.num_chunks(rio_path)
    assert n_chunks == n_samples

    # 3 samples per task -> 4 tasks
    chunk_list = [(rio_path, start, 3) for start in range(0, n_chunks, 3)]
    svc = MasterService(store=InMemStore(), chunks_per_task=1, timeout=2.0)
    svc.set_dataset(chunk_list)
    server = MasterServer(svc).start()

    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    trainer = tmp_path / "trainer.py"
    trainer.write_text(TRAINER_SRC)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")

    try:
        # trainer A: killed mid-second-task (after 4 samples: task 0
        # committed, task 1 in flight)
        a = subprocess.Popen(
            [sys.executable, str(trainer), server.address, rio_path,
             ckpt, "4", repo],
            stdout=subprocess.PIPE, text=True, env=env)
        killed_task = None
        # watchdog: a silently-hung trainer must fail the test at the
        # bound, not block the blocking stdout read forever
        watchdog = __import__("threading").Timer(120, a.kill)
        watchdog.start()
        try:
            for line in a.stdout:
                if line.startswith("TASK_STARTED"):
                    killed_task = int(line.split()[1])
                if line.startswith("KILL_POINT"):
                    break
        finally:
            watchdog.cancel()
        assert killed_task is not None, "trainer A hung before KILL_POINT"
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=30)
        assert killed_task is not None

        # ledger holds ONLY committed (task-boundary) samples
        committed = json.load(open(os.path.join(ckpt, "ledger.json")))
        assert len(committed) == 3

        # trainer B resumes and drains the pass (master re-leases the
        # orphaned task after its 2s lease expires)
        b = subprocess.run(
            [sys.executable, str(trainer), server.address, rio_path,
             ckpt, "0", repo],
            stdout=subprocess.PIPE, text=True, env=env, timeout=180)
        assert b.returncode == 0, b.stdout[-2000:]
        assert "RESUMED" in b.stdout
        final = None
        for line in b.stdout.splitlines():
            if line.startswith("FINISHED"):
                final = json.loads(line[len("FINISHED"):])
        # sample accounting: every sample exactly once — the killed
        # task's partial work died with the un-checkpointed state
        assert final == list(range(n_samples)), final
        assert svc.stats()["cur_pass"] == 1
    finally:
        server.shutdown()
