"""Deterministic kill-and-resume drill (ISSUE 6): subprocess fault
injection against the TrainState checkpoint subsystem.

A trainer subprocess runs a fixed-seed MLP (dropout + LR decay + Adam,
deterministic reader) for N steps with async TrainState checkpoints
every K steps, logging every step's loss bit-pattern.  The drill:

* ``kill_mode=step``: the trainer SIGKILLs ITSELF at a step-indexed
  point (no load-based timing — this replaces the flaky lease-timeout
  drill) — death mid-run, between checkpoint boundaries;
* ``kill_mode=save``: a ``fault.kill_mid_save`` drill (the public
  ``paddle_tpu.fault`` registry, scheduled at the checkpoint's
  ``before_commit`` point) SIGKILLs during the background write —
  death mid-save, leaving a torn .tmp artifact the restore must
  ignore;
* deliberate corruption: the latest committed artifact is garbled on
  disk; restore must fall back to the previous step, not crash.

Headline assertion: every step's loss, across the killed run and the
resumed run, is BIT-identical to the uninterrupted reference run —
params, optimizer slots, LR counter, PRNG counter, and reader position
all resumed exactly.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAINER_SRC = '''
import json, os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, sys.argv[6])
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import fault
from paddle_tpu.parallel import checkpoint as ck
from paddle_tpu.reader import checkpointable

ckpt_dir, log_path, total, kill_step, kill_mode = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 7
with fluid.program_guard(main, startup):
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    h = fluid.layers.dropout(h, dropout_prob=0.3)
    pred = fluid.layers.fc(h, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    lr = fluid.layers.exponential_decay(1e-2, decay_steps=4,
                                        decay_rate=0.8)
    fluid.optimizer.Adam(learning_rate=lr).minimize(loss)

def data_reader():
    rng = np.random.RandomState(0)
    for _ in range(1000):
        yield {"x": rng.rand(4, 8).astype("float32"),
               "label": rng.randint(0, 4, (4, 1)).astype("int64")}

reader = checkpointable(data_reader)

if kill_mode == "save" and kill_step:
    # mid-save preemption through the public fault registry: SIGKILL at
    # the write protocol's before_commit point, step-indexed
    fault.kill_mid_save(fault.FaultSchedule(steps=[kill_step]))

scope = fluid.Scope()
with fluid.scope_guard(scope):
    fluid.Executor(fluid.CPUPlace()).run(startup)
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = ck.TrainStateCheckpointManager(ckpt_dir, max_to_keep=3,
                                         save_interval_steps=5,
                                         async_save=True)
    step = mgr.restore(scope=scope, program=main,
                       executors={"train": exe},
                       readers={"train": reader})
    if step is None:
        step = 0
    else:
        print("RESUMED", step, flush=True)
    log = open(log_path, "a")
    it = iter(reader())
    while step < total:
        try:
            data = next(it)
        except StopIteration:
            it = iter(reader())
            data = next(it)
        (lv,) = exe.run(main, feed=data, fetch_list=[loss])
        step += 1
        log.write(json.dumps(
            {"step": step,
             "loss_hex": np.asarray(lv, "float32").tobytes().hex()}) + chr(10))
        log.flush()
        os.fsync(log.fileno())
        if kill_mode == "step" and step == kill_step:
            print("KILLING_SELF", step, flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        mgr.save(step, scope=scope, program=main,
                 executors={"train": exe}, readers={"train": reader})
    mgr.wait_until_finished()
    print("DONE", step, flush=True)
'''

TOTAL_STEPS = 18


def _run_trainer(tmp_path, name, ckpt_dir, log_path, kill_step=0,
                 kill_mode="step", expect_sigkill=False, cache_dir=None):
    trainer = tmp_path / "trainer.py"
    if not trainer.exists():
        trainer.write_text(TRAINER_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if cache_dir:
        # warm restart rides the persistent XLA compile cache: the
        # resumed process deserializes the reference run's executables
        env["FLAGS_compile_cache_dir"] = cache_dir
    p = subprocess.run(
        [sys.executable, str(trainer), str(ckpt_dir), str(log_path),
         str(TOTAL_STEPS), str(kill_step), kill_mode, REPO],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, timeout=600)
    if expect_sigkill:
        assert p.returncode == -signal.SIGKILL, (
            name, p.returncode, p.stderr[-3000:])
    else:
        assert p.returncode == 0, (name, p.returncode, p.stderr[-3000:])
    return p


def _losses(log_path):
    """step -> set of logged loss bit patterns (re-executed steps may be
    logged by both the killed and the resumed run; a torn final line
    from a SIGKILL mid-write is ignored)."""
    out = {}
    if not os.path.exists(log_path):
        return out
    with open(log_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            out.setdefault(rec["step"], set()).add(rec["loss_hex"])
    return out


@pytest.fixture(scope="module")
def xla_cache(tmp_path_factory):
    """Shared persistent XLA compile cache: the reference run warms it,
    killed/resumed runs restart warm (the r6 -32% wall-clock path)."""
    return str(tmp_path_factory.mktemp("xla_cache"))


@pytest.fixture(scope="module")
def ref(tmp_path_factory, xla_cache):
    """The uninterrupted reference trajectory, run once per module (the
    drills compare their logs against it step-by-step)."""
    tmp = tmp_path_factory.mktemp("ref")
    ref_log = tmp / "ref.jsonl"
    _run_trainer(tmp, "reference", tmp / "ref_ckpt", ref_log,
                 cache_dir=xla_cache)
    ref = _losses(ref_log)
    assert sorted(ref) == list(range(1, TOTAL_STEPS + 1))
    assert all(len(v) == 1 for v in ref.values())
    return {s: v.pop() for s, v in ref.items()}


@pytest.mark.parametrize("kill_step,kill_mode", [
    (8, "step"),    # SIGKILL mid-run: 2 un-checkpointed steps replay
    (11, "save"),   # SIGKILL mid-save (a save step): torn .tmp +
                    # fallback to the previous committed artifact
])
def test_kill9_resume_loss_trajectory_bit_identical(
        tmp_path, ref, xla_cache, kill_step, kill_mode):

    ckpt = tmp_path / "ckpt"
    log = tmp_path / "drill.jsonl"
    _run_trainer(tmp_path, "killed", ckpt, log, kill_step=kill_step,
                 kill_mode=kill_mode, expect_sigkill=True,
                 cache_dir=xla_cache)
    killed = _losses(log)
    assert killed, "killed run logged no steps"
    assert max(killed) >= min(kill_step, TOTAL_STEPS) - 1

    # resume: must restore from the newest INTACT checkpoint and run to
    # completion (the mid-save kill leaves only older artifacts);
    # restarts warm off the persistent compile cache
    p = _run_trainer(tmp_path, "resumed", ckpt, log, cache_dir=xla_cache)
    assert "RESUMED" in p.stdout, p.stdout
    resumed_from = int(p.stdout.split("RESUMED")[1].split()[0])
    assert 0 < resumed_from <= kill_step
    assert "DONE %d" % TOTAL_STEPS in p.stdout

    # the headline guarantee: EVERY logged loss (killed run, replayed
    # steps, resumed run) is bit-identical to the uninterrupted run
    combined = _losses(log)
    assert sorted(combined) == list(range(1, TOTAL_STEPS + 1))
    for step, hexes in combined.items():
        assert hexes == {ref[step]}, (
            "step %d diverged: %s vs reference %s"
            % (step, sorted(hexes), ref[step]))


@pytest.mark.slow   # three PE compiles (~25s); the sharded-IO units in
                    # test_cluster.py keep the invariants tier-1
def test_mesh_size_change_resume_sharded_artifact(tmp_path):
    """Elastic resume across mesh sizes through the SHARDED artifact
    path (ISSUE 13): train on a dp x fsdp = 4 virtual mesh, save a
    per-host sharded TrainState, restore onto fsdp=2 AND fsdp=8 meshes
    via ``ParallelExecutor.state_shardings()`` — restored values are
    BIT-identical, and the continued loss trajectory stays in the
    float-noise parity band of the uninterrupted fsdp=4 run."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.checkpoint import (
        TrainStateCheckpointManager)

    def build():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        x = fluid.layers.data("x", shape=[16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        pred = fluid.layers.fc(h, size=4, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        return loss

    def batch(step):
        x = np.random.RandomState(100 + step).rand(8, 16).astype(
            "float32")
        y = x[:, :4].argmax(1).astype("int64").reshape(-1, 1)
        return {"x": x, "label": y}

    def run_world(fsdp, ckpt, restore, steps):
        """Build a (1, fsdp) mesh world; restore (optionally), run
        ``steps``, sharded-save at the last one.  Returns losses and
        the restored values."""
        from paddle_tpu import unique_name

        with unique_name.guard(), \
                fluid.program_guard(fluid.Program(), fluid.Program()):
            return _run_world_body(fsdp, ckpt, restore, steps)

    def _run_world_body(fsdp, ckpt, restore, steps):
        loss = build()
        mesh = make_mesh((1, fsdp), ("dp", "fsdp"),
                         devices=jax.devices()[:fsdp])
        bs = fluid.BuildStrategy()
        bs.sharding_rules = True
        scope = fluid.Scope()
        out, values = [], {}
        with fluid.scope_guard(scope):
            fluid.Executor(fluid.CPUPlace()).run(
                fluid.default_startup_program())
            pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                        build_strategy=bs)
            mgr = TrainStateCheckpointManager(
                ckpt, sharded=True, async_save=False,
                save_interval_steps=1000)
            start = 0
            if restore:
                start = mgr.restore(
                    scope=scope, program=fluid.default_main_program(),
                    executors={"train": pe},
                    shardings=pe.state_shardings())
                assert start is not None
                for n, v in (mgr.last_restored.arrays or {}).items():
                    # copy=True: np.asarray of a CPU jax.Array is a
                    # zero-copy view the next step's donation reuses
                    values[n] = np.array(scope.var(n), copy=True)
            for s in range(start + 1, start + 1 + steps):
                (lv,) = pe.run(feed=batch(s), fetch_list=[loss])
                out.append(float(np.asarray(lv).ravel()[0]))
            if not restore:
                mgr.save_now(start + steps, scope=scope,
                             program=fluid.default_main_program(),
                             executors={"train": pe})
        return out, values

    ckpt = str(tmp_path / "ck")
    first = run_world(4, ckpt, restore=False, steps=4)[0]
    ref = run_world(4, str(tmp_path / "ref_unused"), restore=False,
                    steps=4)[0]
    assert first == ref           # determinism sanity of the harness

    # the uninterrupted fsdp=4 continuation is the parity reference
    cont4, vals4 = run_world(4, ckpt, restore=True, steps=4)
    for fsdp in (2, 8):
        cont, vals = run_world(fsdp, ckpt, restore=True, steps=4)
        # restored state lands BIT-identical regardless of mesh size
        for n, v in vals4.items():
            np.testing.assert_array_equal(vals[n], v, err_msg=n)
        # the continued trajectory stays in the float-noise band
        np.testing.assert_allclose(cont, cont4, rtol=1e-4, atol=1e-6,
                                   err_msg="fsdp=%d" % fsdp)


def test_corrupt_latest_checkpoint_falls_back_on_resume(tmp_path, ref,
                                                        xla_cache):
    """Corrupt the latest committed artifact after a kill: the resume
    must fall back to the previous checkpoint and still reproduce the
    reference trajectory exactly."""
    ckpt = tmp_path / "ckpt"
    log = tmp_path / "drill.jsonl"
    _run_trainer(tmp_path, "killed", ckpt, log, kill_step=12,
                 kill_mode="step", expect_sigkill=True,
                 cache_dir=xla_cache)

    steps = sorted(int(d.split("_")[1]) for d in os.listdir(ckpt)
                   if d.startswith("step_"))
    assert len(steps) >= 2, steps
    latest = os.path.join(ckpt, "step_%010d" % steps[-1], "arrays.npz")
    with open(latest, "r+b") as f:
        f.seek(16)
        f.write(b"\xff" * 64)

    p = _run_trainer(tmp_path, "resumed", ckpt, log, cache_dir=xla_cache)
    resumed_from = int(p.stdout.split("RESUMED")[1].split()[0])
    assert resumed_from == steps[-2], (resumed_from, steps, p.stdout)

    combined = _losses(log)
    assert sorted(combined) == list(range(1, TOTAL_STEPS + 1))
    for step, hexes in combined.items():
        assert hexes == {ref[step]}, "step %d diverged" % step
