"""Deterministic kill-and-resume drill (ISSUE 6): subprocess fault
injection against the TrainState checkpoint subsystem.

A trainer subprocess runs a fixed-seed MLP (dropout + LR decay + Adam,
deterministic reader) for N steps with async TrainState checkpoints
every K steps, logging every step's loss bit-pattern.  The drill:

* ``kill_mode=step``: the trainer SIGKILLs ITSELF at a step-indexed
  point (no load-based timing — this replaces the flaky lease-timeout
  drill) — death mid-run, between checkpoint boundaries;
* ``kill_mode=save``: a ``fault.kill_mid_save`` drill (the public
  ``paddle_tpu.fault`` registry, scheduled at the checkpoint's
  ``before_commit`` point) SIGKILLs during the background write —
  death mid-save, leaving a torn .tmp artifact the restore must
  ignore;
* deliberate corruption: the latest committed artifact is garbled on
  disk; restore must fall back to the previous step, not crash.

Headline assertion: every step's loss, across the killed run and the
resumed run, is BIT-identical to the uninterrupted reference run —
params, optimizer slots, LR counter, PRNG counter, and reader position
all resumed exactly.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAINER_SRC = '''
import json, os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, sys.argv[6])
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import fault
from paddle_tpu.parallel import checkpoint as ck
from paddle_tpu.reader import checkpointable

ckpt_dir, log_path, total, kill_step, kill_mode = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 7
with fluid.program_guard(main, startup):
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    h = fluid.layers.dropout(h, dropout_prob=0.3)
    pred = fluid.layers.fc(h, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    lr = fluid.layers.exponential_decay(1e-2, decay_steps=4,
                                        decay_rate=0.8)
    fluid.optimizer.Adam(learning_rate=lr).minimize(loss)

def data_reader():
    rng = np.random.RandomState(0)
    for _ in range(1000):
        yield {"x": rng.rand(4, 8).astype("float32"),
               "label": rng.randint(0, 4, (4, 1)).astype("int64")}

reader = checkpointable(data_reader)

if kill_mode == "save" and kill_step:
    # mid-save preemption through the public fault registry: SIGKILL at
    # the write protocol's before_commit point, step-indexed
    fault.kill_mid_save(fault.FaultSchedule(steps=[kill_step]))

scope = fluid.Scope()
with fluid.scope_guard(scope):
    fluid.Executor(fluid.CPUPlace()).run(startup)
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = ck.TrainStateCheckpointManager(ckpt_dir, max_to_keep=3,
                                         save_interval_steps=5,
                                         async_save=True)
    step = mgr.restore(scope=scope, program=main,
                       executors={"train": exe},
                       readers={"train": reader})
    if step is None:
        step = 0
    else:
        print("RESUMED", step, flush=True)
    log = open(log_path, "a")
    it = iter(reader())
    while step < total:
        try:
            data = next(it)
        except StopIteration:
            it = iter(reader())
            data = next(it)
        (lv,) = exe.run(main, feed=data, fetch_list=[loss])
        step += 1
        log.write(json.dumps(
            {"step": step,
             "loss_hex": np.asarray(lv, "float32").tobytes().hex()}) + chr(10))
        log.flush()
        os.fsync(log.fileno())
        if kill_mode == "step" and step == kill_step:
            print("KILLING_SELF", step, flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        mgr.save(step, scope=scope, program=main,
                 executors={"train": exe}, readers={"train": reader})
    mgr.wait_until_finished()
    print("DONE", step, flush=True)
'''

TOTAL_STEPS = 18


def _run_trainer(tmp_path, name, ckpt_dir, log_path, kill_step=0,
                 kill_mode="step", expect_sigkill=False, cache_dir=None):
    trainer = tmp_path / "trainer.py"
    if not trainer.exists():
        trainer.write_text(TRAINER_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if cache_dir:
        # warm restart rides the persistent XLA compile cache: the
        # resumed process deserializes the reference run's executables
        env["FLAGS_compile_cache_dir"] = cache_dir
    p = subprocess.run(
        [sys.executable, str(trainer), str(ckpt_dir), str(log_path),
         str(TOTAL_STEPS), str(kill_step), kill_mode, REPO],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, timeout=600)
    if expect_sigkill:
        assert p.returncode == -signal.SIGKILL, (
            name, p.returncode, p.stderr[-3000:])
    else:
        assert p.returncode == 0, (name, p.returncode, p.stderr[-3000:])
    return p


def _losses(log_path):
    """step -> set of logged loss bit patterns (re-executed steps may be
    logged by both the killed and the resumed run; a torn final line
    from a SIGKILL mid-write is ignored)."""
    out = {}
    if not os.path.exists(log_path):
        return out
    with open(log_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            out.setdefault(rec["step"], set()).add(rec["loss_hex"])
    return out


@pytest.fixture(scope="module")
def xla_cache(tmp_path_factory):
    """Shared persistent XLA compile cache: the reference run warms it,
    killed/resumed runs restart warm (the r6 -32% wall-clock path)."""
    return str(tmp_path_factory.mktemp("xla_cache"))


@pytest.fixture(scope="module")
def ref(tmp_path_factory, xla_cache):
    """The uninterrupted reference trajectory, run once per module (the
    drills compare their logs against it step-by-step)."""
    tmp = tmp_path_factory.mktemp("ref")
    ref_log = tmp / "ref.jsonl"
    _run_trainer(tmp, "reference", tmp / "ref_ckpt", ref_log,
                 cache_dir=xla_cache)
    ref = _losses(ref_log)
    assert sorted(ref) == list(range(1, TOTAL_STEPS + 1))
    assert all(len(v) == 1 for v in ref.values())
    return {s: v.pop() for s, v in ref.items()}


@pytest.mark.parametrize("kill_step,kill_mode", [
    (8, "step"),    # SIGKILL mid-run: 2 un-checkpointed steps replay
    (11, "save"),   # SIGKILL mid-save (a save step): torn .tmp +
                    # fallback to the previous committed artifact
])
def test_kill9_resume_loss_trajectory_bit_identical(
        tmp_path, ref, xla_cache, kill_step, kill_mode):

    ckpt = tmp_path / "ckpt"
    log = tmp_path / "drill.jsonl"
    _run_trainer(tmp_path, "killed", ckpt, log, kill_step=kill_step,
                 kill_mode=kill_mode, expect_sigkill=True,
                 cache_dir=xla_cache)
    killed = _losses(log)
    assert killed, "killed run logged no steps"
    assert max(killed) >= min(kill_step, TOTAL_STEPS) - 1

    # resume: must restore from the newest INTACT checkpoint and run to
    # completion (the mid-save kill leaves only older artifacts);
    # restarts warm off the persistent compile cache
    p = _run_trainer(tmp_path, "resumed", ckpt, log, cache_dir=xla_cache)
    assert "RESUMED" in p.stdout, p.stdout
    resumed_from = int(p.stdout.split("RESUMED")[1].split()[0])
    assert 0 < resumed_from <= kill_step
    assert "DONE %d" % TOTAL_STEPS in p.stdout

    # the headline guarantee: EVERY logged loss (killed run, replayed
    # steps, resumed run) is bit-identical to the uninterrupted run
    combined = _losses(log)
    assert sorted(combined) == list(range(1, TOTAL_STEPS + 1))
    for step, hexes in combined.items():
        assert hexes == {ref[step]}, (
            "step %d diverged: %s vs reference %s"
            % (step, sorted(hexes), ref[step]))


def test_corrupt_latest_checkpoint_falls_back_on_resume(tmp_path, ref,
                                                        xla_cache):
    """Corrupt the latest committed artifact after a kill: the resume
    must fall back to the previous checkpoint and still reproduce the
    reference trajectory exactly."""
    ckpt = tmp_path / "ckpt"
    log = tmp_path / "drill.jsonl"
    _run_trainer(tmp_path, "killed", ckpt, log, kill_step=12,
                 kill_mode="step", expect_sigkill=True,
                 cache_dir=xla_cache)

    steps = sorted(int(d.split("_")[1]) for d in os.listdir(ckpt)
                   if d.startswith("step_"))
    assert len(steps) >= 2, steps
    latest = os.path.join(ckpt, "step_%010d" % steps[-1], "arrays.npz")
    with open(latest, "r+b") as f:
        f.seek(16)
        f.write(b"\xff" * 64)

    p = _run_trainer(tmp_path, "resumed", ckpt, log, cache_dir=xla_cache)
    resumed_from = int(p.stdout.split("RESUMED")[1].split()[0])
    assert resumed_from == steps[-2], (resumed_from, steps, p.stdout)

    combined = _losses(log)
    assert sorted(combined) == list(range(1, TOTAL_STEPS + 1))
    for step, hexes in combined.items():
        assert hexes == {ref[step]}, "step %d diverged" % step
