"""Training-run guardian (ISSUE 8): in-graph skip recovery is
bit-deterministic across sync/async dispatch, the rollback drill
restores a clean TrainState and reproduces the clean run's final loss,
the rollback budget raises a typed error instead of looping, and the
disabled guardian costs nothing observable."""

import glob
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import fault, guardian, monitor


@pytest.fixture(autouse=True)
def _clean():
    yield
    fault.clear()
    fault.clear_injections()
    guardian.uninstall()
    fluid.set_flags({
        "FLAGS_guardian": False,
        "FLAGS_guardian_policy": "skip,rollback,abort",
    })
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()


def _build_mlp(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(bs, 8).astype("float32"),
             "label": rng.randint(0, 4, (bs, 1)).astype("int64")}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# disabled-is-free (acceptance gate, like monitor's)
# ---------------------------------------------------------------------------

def test_disabled_guardian_records_nothing_and_adds_no_fetch():
    assert guardian.active() is None
    assert not guardian.skip_guard_enabled()
    monitor.enable()
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        outs = exe.run(main, feed=_batches(1)[0], fetch_list=[loss])
    assert len(outs) == 1                 # no trailing ok fetch
    reg = monitor.registry()
    assert all(not m.startswith("guardian/")
               for m in reg.expose_text().splitlines())


# ---------------------------------------------------------------------------
# in-graph skip: deterministic across sync/async dispatch (satellite)
# ---------------------------------------------------------------------------

def _skip_run(tmp_path, return_numpy, steps=12, poison_step=5):
    fault.clear()
    fault.clear_injections()
    fluid.set_flags({"FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        g = guardian.install(guardian.Guardian(
            quarantine_dir=str(tmp_path / ("q_%s" % return_numpy))))
        fault.poison_batch("x", fault.FaultSchedule(steps=[poison_step]))
        exe = fluid.Executor(fluid.CPUPlace())
        outs = []
        for feed in _batches(steps):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss],
                            return_numpy=return_numpy)
            outs.append(lv)
        exe.sync()
        g.flush()
        stats = g.stats()
        guardian.uninstall()
        return [np.asarray(o, "float32").tobytes() for o in outs], stats


def test_skip_recovery_bit_identical_sync_vs_async(tmp_path):
    """The satellite determinism gate: a NaN-injected run with
    skip-step recovery produces a bit-identical post-recovery loss
    trajectory whether return_numpy is True or False — the skip happens
    in-graph, so host observation timing cannot alter the state
    evolution."""
    sync_losses, sync_stats = _skip_run(tmp_path, True)
    async_losses, async_stats = _skip_run(tmp_path, False)
    assert sync_losses == async_losses
    # the poisoned step's loss is non-finite in both; later steps
    # (post-recovery) are finite in both
    assert not np.isfinite(np.frombuffer(sync_losses[5], "float32")).all()
    for later in sync_losses[6:]:
        assert np.isfinite(np.frombuffer(later, "float32")).all()
    assert sync_stats["skipped_steps"] == 1
    assert async_stats["skipped_steps"] == 1


def test_skip_suppresses_update_and_quarantines(tmp_path):
    fluid.set_flags({"FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    qdir = str(tmp_path / "quarantine")
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        g = guardian.install(guardian.Guardian(quarantine_dir=qdir))
        fault.poison_batch("x", fault.FaultSchedule(steps=[1]))
        exe = fluid.Executor(fluid.CPUPlace())
        feeds = _batches(3)
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        w_before = np.array(scope.var("fc_0.w_0"), copy=True)
        lr_before = np.array(scope.var("@LR_DECAY_COUNTER@"), copy=True) \
            if scope.has_var("@LR_DECAY_COUNTER@") else None
        (lv,) = exe.run(main, feed=feeds[1], fetch_list=[loss])
        assert not np.isfinite(np.asarray(lv)).all()
        # the poisoned step's whole update was dropped in-graph: params
        # unchanged, finite
        w_after = np.asarray(scope.var("fc_0.w_0"))
        assert np.array_equal(w_before, w_after)
        if lr_before is not None:
            assert np.array_equal(
                lr_before, np.asarray(scope.var("@LR_DECAY_COUNTER@")))
        # ...and training continues
        (lv2,) = exe.run(main, feed=feeds[2], fetch_list=[loss])
        assert np.isfinite(np.asarray(lv2)).all()
    # quarantine artifact: npz + sidecar with run_id/step/signature
    sidecars = glob.glob(os.path.join(qdir, "*.json"))
    assert len(sidecars) == 1
    rec = json.load(open(sidecars[0]))
    assert rec["run_id"] == monitor.run_id()
    assert rec["step"] == 1
    assert rec["reason"] == "nonfinite_in_graph"
    sig = {n: (tuple(s), d) for n, s, d in rec["feed_signature"]}
    assert sig["x"] == ((4, 8), "float32")
    with np.load(rec["path"]) as z:
        arrs = {n: z["arr_%d" % i]
                for i, n in enumerate(rec["feed_names"])}
    assert not np.isfinite(arrs["x"]).any()       # the poisoned batch


def test_parallel_quarantine_records_prepad_batch(tmp_path):
    """With pad_uneven_batches on, the ParallelExecutor quarantines the
    batch AS FED (pre-pad): the artifact's feed signature and arrays
    must match what the reader yielded — the repro contract — not the
    mesh-padded copy."""
    fluid.set_flags({"FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    qdir = str(tmp_path / "quarantine")
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        guardian.install(guardian.Guardian(quarantine_dir=qdir))
        fault.poison_batch("x", fault.FaultSchedule(steps=[1]))
        pe = fluid.ParallelExecutor(loss_name=loss.name,
                                    main_program=main)
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.rand(9, 8).astype("float32"),
                  "label": rng.randint(0, 4, (9, 1)).astype("int64")}
                 for _ in range(2)]                 # 9 % 8 devices != 0
        pe.run(feed=feeds[0], fetch_list=[loss])
        (lv,) = pe.run(feed=feeds[1], fetch_list=[loss])
        assert not np.isfinite(np.asarray(lv)).all()
    sidecars = glob.glob(os.path.join(qdir, "*.json"))
    assert len(sidecars) == 1
    rec = json.load(open(sidecars[0]))
    sig = {n: (tuple(s), d) for n, s, d in rec["feed_signature"]}
    assert sig["x"] == ((9, 8), "float32")     # true batch, not padded
    with np.load(rec["path"]) as z:
        arrs = {n: z["arr_%d" % i]
                for i, n in enumerate(rec["feed_names"])}
    assert arrs["x"].shape == (9, 8)
    assert not np.isfinite(arrs["x"]).any()


def test_skip_budget_exhaustion_aborts_typed_without_rollback_rung():
    fluid.set_flags({"FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        guardian.install(guardian.Guardian(policy="skip,abort",
                                           max_skips=2))
        fault.poison_batch("x", fault.FaultSchedule(every=1, start=1))
        exe = fluid.Executor(fluid.CPUPlace())
        with pytest.raises(guardian.GuardianAbortError,
                           match="skip budget"):
            for feed in _batches(8):
                exe.run(main, feed=feed, fetch_list=[loss])


# ---------------------------------------------------------------------------
# rollback drill through the Trainer (acceptance)
# ---------------------------------------------------------------------------

def _trainer_run(ckpt_dir, inject_step=None, persist=False,
                 max_rollbacks=2, log_dir=None, n_samples=64):
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    from paddle_tpu.reader import checkpointable

    fault.clear()
    fault.clear_injections()
    if log_dir:
        monitor.enable(log_dir=log_dir)
    if inject_step is not None:
        fault.inject_nan("fc_0.w_0",
                         fault.FaultSchedule(steps=[inject_step]),
                         once=not persist)

    def train_func():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(n_samples):
            x = rng.rand(8).astype("float32")
            yield x, np.array([int(np.argmax(x[:4]))], "int64")

    losses = []

    def handler(ev):
        if hasattr(ev, "metrics"):
            losses.append(float(np.ravel(ev.metrics[0])[0]))

    trainer = Trainer(
        train_func=train_func, place=fluid.CPUPlace(),
        optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
        checkpoint_config=CheckpointConfig(
            checkpoint_dir=str(ckpt_dir), step_interval=3,
            async_save=False),
        guardian_config={"policy": "rollback,abort",
                         "max_rollbacks": max_rollbacks})
    try:
        trainer.train(num_epochs=1, event_handler=handler,
                      reader=checkpointable(
                          fluid.batch(samples, batch_size=4)),
                      feed_order=["x", "label"])
    finally:
        if log_dir:
            monitor.disable()
    return losses


def test_rollback_drill_recovers_to_clean_final_loss(tmp_path):
    """Acceptance: NaN injected at a fixed step -> the guardian rolls
    back to the last clean checkpoint, the exact-resume machinery
    replays, and the completed run's final loss matches the clean
    uninterrupted run within rtol 1e-4 (here: the replay is exact, so
    it matches bitwise).  The decision trail lands in the JSONL with
    run_id correlation."""
    ref = _trainer_run(tmp_path / "ref_ckpt")
    log_dir = str(tmp_path / "monitor")
    drilled = _trainer_run(tmp_path / "ckpt", inject_step=6,
                           log_dir=log_dir)
    assert np.isfinite(drilled[-1])
    np.testing.assert_allclose(drilled[-1], ref[-1], rtol=1e-4)
    # the drilled run replayed the rolled-back window: more observed
    # steps than the reference, same trajectory tail
    assert len(drilled) > len(ref)
    assert drilled[-3:] == ref[-3:]

    events = []
    for path in glob.glob(os.path.join(log_dir, "*.jsonl")):
        with open(path) as f:
            events += [json.loads(l) for l in f if l.strip()]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("event"), []).append(e)
    assert "fault_injected" in by_kind
    assert "guardian_nonfinite" in by_kind
    rollbacks = by_kind["guardian_rollback"]
    assert len(rollbacks) == 1
    # saves land at global steps 1, 4, 7, ... (interval 3); the step-7
    # artifact was taken after the poison landed and is skipped as
    # unclean, so the newest CLEAN checkpoint is step 4
    assert rollbacks[0]["restored_step"] == 4
    assert rollbacks[0]["step"] == 7              # detected next step
    assert rollbacks[0]["run_id"] == monitor.run_id()
    # checkpoints taken after the poison landed were skipped as unclean
    assert any(e["reason"] == "nonfinite_state"
               for e in by_kind.get("guardian_checkpoint_skipped", []))
    # ...and the decisions counted into the metrics registry
    assert monitor.registry().get("guardian/rollbacks").value == 1
    assert monitor.registry().get("fault/injections").value >= 1


def test_rollback_budget_exhausted_raises_typed_error(tmp_path):
    """Acceptance: a PERSISTENT fault (re-injected on every replay of
    its step) exhausts the rollback budget and raises
    GuardianAbortError instead of looping."""
    with pytest.raises(guardian.GuardianAbortError,
                       match="rollback budget"):
        _trainer_run(tmp_path / "ckpt", inject_step=6, persist=True,
                     max_rollbacks=1)


def test_rollback_without_checkpoint_config_aborts(tmp_path):
    from paddle_tpu.contrib import Trainer
    from paddle_tpu.reader import checkpointable

    fault.clear()
    fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[2]))

    def train_func():
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(32):
            x = rng.rand(8).astype("float32")
            yield x, np.array([0], "int64")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                      guardian_config={"policy": "rollback,abort"})
    with pytest.raises(guardian.GuardianAbortError,
                       match="no CheckpointConfig"):
        trainer.train(num_epochs=1, event_handler=lambda ev: None,
                      reader=checkpointable(
                          fluid.batch(samples, batch_size=4)),
                      feed_order=["x", "label"])


def test_trainer_quarantine_default_applies_to_guardian_instance(tmp_path):
    """A Guardian INSTANCE passed as guardian_config gets the same
    <checkpoint_dir>/quarantine default as the kwargs-dict path — the
    repro artifact the skip path exists to produce must not be silently
    lost just because the caller built the Guardian themselves."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        return fluid.layers.mean(fluid.layers.fc(x, size=1))

    g = guardian.Guardian(policy="rollback,abort")
    assert not g.quarantine_dir
    g._rollbacks = 5                 # stale budget from a previous run
    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                      checkpoint_config=CheckpointConfig(
                          checkpoint_dir=str(tmp_path / "ckpt")),
                      guardian_config=g)
    assert trainer._make_guardian() is g
    assert g.quarantine_dir == os.path.join(str(tmp_path), "ckpt",
                                            "quarantine")
    assert g._rollbacks == 0         # per-run state reset on reuse


# ---------------------------------------------------------------------------
# detectors (unit level)
# ---------------------------------------------------------------------------

def _feed_losses(g, values, start=0):
    for i, v in enumerate(values):
        g.note_step("test", start + i, fetch_names=("loss",),
                    fetches=(np.float32(v),), sync=True)


def test_spike_detector_median_mad():
    monitor.enable()
    g = guardian.Guardian(policy="skip", window=16, zmax=6.0,
                          spike_action="warn")
    rng = np.random.RandomState(3)
    _feed_losses(g, 1.0 + 0.01 * rng.randn(12))
    assert monitor.registry().get("guardian/loss_spikes") is None
    _feed_losses(g, [9.0], start=12)          # far outside 6 MADs
    assert monitor.registry().get("guardian/loss_spikes").value == 1
    # the outlier stayed out of the baseline window
    assert max(g.stats()["window"]) < 2.0
    # spike_action=rollback escalates instead
    g2 = guardian.Guardian(policy="skip,rollback", window=16, zmax=6.0,
                           spike_action="rollback")
    _feed_losses(g2, 1.0 + 0.01 * rng.randn(12))
    with pytest.raises(guardian.GuardianRollback, match="spike"):
        _feed_losses(g2, [9.0], start=12)


def test_spike_detector_one_sided_and_bounded():
    """A sharp IMPROVEMENT is healthy (one-sided detector: only upward
    moves are anomalies), and a genuine upward level shift stops being
    flagged once it persists for half a window — the baseline resets to
    the new regime instead of wedging on the pre-shift median forever
    (which would spam a spike event on every remaining step)."""
    monitor.enable()
    g = guardian.Guardian(policy="skip", window=16, zmax=6.0,
                          spike_action="warn")
    rng = np.random.RandomState(5)
    _feed_losses(g, 2.0 + 0.01 * rng.randn(16))
    # LR-decay-style drop: no spike, enters the baseline
    _feed_losses(g, 1.4 + 0.01 * rng.randn(4), start=16)
    assert monitor.registry().get("guardian/loss_spikes") is None
    assert min(g.stats()["window"]) < 1.5
    # upward level shift: flagged at most window//2 + 1 times, then the
    # baseline adopts the new level and goes quiet
    g2 = guardian.Guardian(policy="skip", window=16, zmax=6.0,
                           spike_action="warn")
    _feed_losses(g2, 1.0 + 0.01 * rng.randn(16))
    _feed_losses(g2, 3.0 + 0.01 * rng.randn(40), start=16)
    flagged = monitor.registry().get("guardian/loss_spikes").value
    assert 0 < flagged <= 16 // 2 + 1
    assert float(np.median(g2.stats()["window"])) > 2.5


def test_plateau_detector_fires_once():
    monitor.enable()
    g = guardian.Guardian(policy="skip", plateau_steps=8, zmax=0)
    _feed_losses(g, [1.0] * 20)
    c = monitor.registry().get("guardian/plateaus")
    assert c is not None and c.value == 1     # armed once per plateau


def test_plateau_window_longer_than_spike_window_fires():
    """plateau_steps > window used to leave the loss history deque too
    small for the plateau check to ever run (silently dead detector);
    the spike baseline must still be the last `window` losses."""
    monitor.enable()
    g = guardian.Guardian(policy="skip", window=8, plateau_steps=24,
                          zmax=0)
    _feed_losses(g, [1.0] * 30)
    c = monitor.registry().get("guardian/plateaus")
    assert c is not None and c.value == 1


def test_stall_escalation_arms_typed_abort():
    g = guardian.Guardian(policy="skip", stall_escalations=2)
    guardian.install(g)
    diag = {"stalled_for_s": 120.0, "stall_seconds": 120.0}
    g._on_stall(diag)
    g._on_stall(diag)
    with pytest.raises(guardian.GuardianAbortError, match="wedged"):
        g.note_step("test", 0, fetch_names=(), fetches=(), sync=True)
    # a completed step in between re-arms instead
    g2 = guardian.Guardian(policy="skip", stall_escalations=2)
    g2._on_stall(diag)
    g2.note_step("test", 0, fetch_names=(), fetches=(), sync=True)
    g2._on_stall(diag)
    g2.note_step("test", 1, fetch_names=(), fetches=(), sync=True)


def test_rollback_restore_skips_poisoned_and_corrupt_artifacts(tmp_path):
    """The guardian's restore scan: newest-first, skipping artifacts
    that are corrupt on disk or contain non-finite state (a checkpoint
    taken after the corruption landed)."""
    from paddle_tpu.parallel import checkpoint as ck

    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        mgr = ck.TrainStateCheckpointManager(str(tmp_path),
                                             async_save=False)
        feeds = _batches(3)
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        mgr.save(1, scope=scope, program=main,
                 executors={"train": exe})
        exe.run(main, feed=feeds[1], fetch_list=[loss])
        mgr.save(2, scope=scope, program=main,
                 executors={"train": exe})
        # poison the live state, then checkpoint it (step 3 = unclean)
        scope.set_var("fc_0.w_0", np.full_like(
            np.asarray(scope.var("fc_0.w_0")), np.nan))
        mgr.save(3, scope=scope, program=main,
                 executors={"train": exe})
        # corrupt step 2's artifact on disk
        with open(os.path.join(str(tmp_path), "step_%010d" % 2,
                               "arrays.npz"), "r+b") as f:
            f.seek(16)
            f.write(b"\xff" * 32)

        g = guardian.Guardian(policy="rollback,abort")
        rb = guardian.GuardianRollback(9, "drill", quarantined=False)
        restored = g.rollback_restore(
            mgr, rb, scope=scope, program=main,
            executors={"train": exe})
        assert restored == 1
        assert np.isfinite(np.asarray(scope.var("fc_0.w_0"))).all()
        assert g.post_restore(rb, restored) == 0      # transient: replay
        rb_q = guardian.GuardianRollback(9, "drill", quarantined=True)
        assert g.post_restore(rb_q, restored) == 9    # skip poisoned win


def test_rollback_abort_leaves_live_state_untouched(tmp_path):
    """Rejected artifacts are validated WITHOUT being applied: when
    every candidate is poisoned, the abort leaves the pre-rollback
    state in place instead of the last rejected checkpoint's NaNs, and
    the save cadence is not reseeded by checkpoints the guardian
    rejected."""
    from paddle_tpu.parallel import checkpoint as ck

    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        mgr = ck.TrainStateCheckpointManager(str(tmp_path),
                                             async_save=False)
        exe.run(main, feed=_batches(1)[0], fetch_list=[loss])
        scope.set_var("fc_0.w_0", np.full_like(
            np.asarray(scope.var("fc_0.w_0")), np.nan))
        mgr.save(1, scope=scope, program=main, executors={"train": exe})
        # the live state heals after the poisoned save landed on disk
        w_live = np.zeros_like(np.asarray(scope.var("fc_0.w_0")))
        scope.set_var("fc_0.w_0", np.array(w_live, copy=True))
        mgr._last_saved = None
        g = guardian.Guardian(policy="rollback,abort")
        rb = guardian.GuardianRollback(5, "drill", quarantined=False)
        with pytest.raises(guardian.GuardianAbortError, match="no clean"):
            g.rollback_restore(mgr, rb, scope=scope, program=main,
                               executors={"train": exe})
        assert np.array_equal(np.asarray(scope.var("fc_0.w_0")), w_live)
        assert mgr._last_saved is None      # rejected != restored


def test_unobserved_skip_guard_warns_once(tmp_path):
    """FLAGS_guardian set (or leaked from a Trainer) without an
    installed guardian: the lowered skip guard drops poisoned updates
    with no decision trail — the executor says so once instead of
    staying silent forever."""
    import warnings as _w

    fluid.set_flags({"FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        feeds = _batches(2)
        with pytest.warns(UserWarning, match="no guardian is installed"):
            exe.run(main, feed=feeds[0], fetch_list=[loss])
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            exe.run(main, feed=feeds[1], fetch_list=[loss])
        assert not [w for w in rec
                    if "no guardian" in str(w.message)]   # once only


def test_rollback_with_unrewindable_reader_warns(tmp_path):
    """A plain reader (no state_dict) cannot be rewound on rollback:
    recovery proceeds, but the Trainer warns that the replay will not
    exactly reproduce the clean trajectory."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[4]))

    def train_func():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(32):
            x = rng.rand(8).astype("float32")
            yield x, np.array([0], "int64")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                      checkpoint_config=CheckpointConfig(
                          checkpoint_dir=str(tmp_path / "ckpt"),
                          step_interval=2, async_save=False),
                      guardian_config={"policy": "rollback,abort"})
    with pytest.warns(UserWarning, match="cannot rewind"):
        trainer.train(num_epochs=1, event_handler=lambda ev: None,
                      reader=fluid.batch(samples, batch_size=4),
                      feed_order=["x", "label"])
    # the flag train() set is restored: nothing later in the process
    # runs guarded with nobody deciding
    assert not fluid.get_flags("FLAGS_guardian")["FLAGS_guardian"]


def test_trainer_construction_does_not_warn_unobserved_guard():
    """guardian_config enables FLAGS_guardian at train() time, not in
    __init__: the startup program must not be lowered guarded (and
    warned about as 'no guardian installed') before the guardian
    exists."""
    import warnings as _w
    from paddle_tpu.contrib import Trainer

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        return fluid.layers.mean(fluid.layers.fc(x, size=1))

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        Trainer(train_func=train_func, place=fluid.CPUPlace(),
                optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                guardian_config={"policy": "rollback,abort"})
    assert not [w for w in rec if "no guardian" in str(w.message)]
    assert not fluid.get_flags("FLAGS_guardian")["FLAGS_guardian"]


def test_restore_clears_pending_fast_forward_debt():
    """A restore supersedes pending fast-forward debt: the rollback
    protocol re-applies its own fast_forward AFTER the restore, so
    stale debt would silently skip healthy batches at the restored
    position."""
    from paddle_tpu.reader import checkpointable

    r = checkpointable(lambda: iter(range(10)))
    r.fast_forward(4)
    r.load_state_dict({"epoch": 0, "offset": 2})
    assert list(r()) == list(range(2, 10))    # no stale skip


def test_fast_forward_carries_across_epoch_boundary():
    """A rollback fast-forward that overshoots the epoch must still
    skip the poisoned batch at the START of the next epoch, not replay
    it: the overshoot remainder carries (only a SHRUNK source's saved
    offset resets at the boundary)."""
    from paddle_tpu.reader import checkpointable

    r = checkpointable(lambda: iter(range(10)))
    r.load_state_dict({"epoch": 0, "offset": 8})
    r.fast_forward(3)                 # items 8, 9, then next epoch's 0
    assert list(r()) == []            # epoch 0 exhausted mid-skip
    assert list(r()) == list(range(1, 10))    # batch 0 skipped
    assert r.state_dict() == {"epoch": 2, "offset": 0}


def test_saturated_window_float_noise_not_a_spike():
    """MAD = 0 (saturated/clamped loss) must not turn float noise into
    z ~ 1e4 spikes and burn the rollback budget: the dispersion floor
    is relative to the loss level."""
    monitor.enable()
    g = guardian.Guardian(policy="skip", window=16, zmax=8.0,
                          spike_action="warn")
    _feed_losses(g, [2.0] * 16)
    _feed_losses(g, [2.00001], start=16)      # ~5e-6 relative: noise
    assert monitor.registry().get("guardian/loss_spikes") is None
    _feed_losses(g, [2.1], start=17)          # 5% jump: a real spike
    assert monitor.registry().get("guardian/loss_spikes").value == 1


def test_invalid_guardian_config_does_not_leak_flag():
    """A raising Guardian construction (typo'd policy) must restore
    the FLAGS_guardian that train() set — otherwise every later
    executor in the process silently lowers the skip guard."""
    from paddle_tpu.contrib import Trainer
    from paddle_tpu.reader import checkpointable

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        return fluid.layers.mean(fluid.layers.fc(x, size=1))

    def samples():
        yield np.zeros(4, "float32")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                      guardian_config={"policy": "rollbak,abort"})
    with pytest.raises(ValueError, match="policy"):
        trainer.train(num_epochs=1, event_handler=lambda ev: None,
                      reader=checkpointable(
                          fluid.batch(samples, batch_size=1)),
                      feed_order=["x"])
    assert not fluid.get_flags("FLAGS_guardian")["FLAGS_guardian"]


def test_guardian_instance_reset_between_runs():
    """A Guardian reused across train() calls gets a fresh per-run
    budget (the Trainer resets caller-provided instances)."""
    g = guardian.Guardian(policy="rollback,abort", max_rollbacks=1)
    rb = guardian.GuardianRollback(3, "drill")
    g.begin_rollback(rb)
    with pytest.raises(guardian.GuardianAbortError, match="budget"):
        g.begin_rollback(rb)
    g.reset_run_state()
    g.begin_rollback(rb)                    # fresh budget, no raise


def test_finite_scan_covers_ml_dtypes():
    """The poisoned-checkpoint scan must see NaNs in ml_dtypes state
    (bf16, float8) that np.issubdtype misses — same hole fault._nan_like
    closes on the injection side."""
    import ml_dtypes
    assert guardian._finite(np.array([1, 2], np.int32))
    nan32 = np.array([1.0, np.nan], np.float32)
    assert not guardian._finite(nan32)
    assert not guardian._finite(nan32.astype(ml_dtypes.bfloat16))
    assert not guardian._finite(nan32.astype(ml_dtypes.float8_e4m3fn))
    assert guardian._finite(
        np.array([1.0, 2.0], np.float32).astype(ml_dtypes.bfloat16))
