"""ps_dispatcher (RoundRobin/HashName) + DistributeTranspiler placement
map + the host-side type shims exported for reference-API parity
(Tensor / LoDTensor / LoDTensorArray / CUDAPinnedPlace / _switch_scope)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import program_guard
from paddle_tpu.transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig, HashName,
    PSDispatcher, RoundRobin)


def test_round_robin_cycles_and_resets():
    d = RoundRobin(["a", "b", "c"])
    assert d.dispatch(["v1", "v2", "v3", "v4"]) == ["a", "b", "c", "a"]
    # the counter persists across dispatch calls (the reference cycles
    # globally so consecutive param groups keep balancing)
    assert d.dispatch(["v5"]) == ["b"]
    d.reset()
    assert d.dispatch(["v6"]) == ["a"]


def test_hash_name_is_stable_and_name_keyed():
    d1 = HashName(["a", "b", "c"])
    d2 = HashName(["a", "b", "c"])
    names = ["w_%d" % i for i in range(20)]
    # deterministic across dispatcher instances (and processes: crc32,
    # not the salted builtin hash)
    assert d1.dispatch(names) == d2.dispatch(names)
    # same name -> same endpoint regardless of position
    assert d1.dispatch(["w_3"]) == d2.dispatch(["w_3"])
    # accepts objects with .name like the reference's var lists
    class V:
        name = "w_3"
    assert d1.dispatch([V()]) == d1.dispatch(["w_3"])


def test_base_dispatcher_is_abstract():
    with pytest.raises(NotImplementedError):
        PSDispatcher(["a"]).dispatch(["x"])


def _small_program():
    prog, start = fluid.Program(), fluid.Program()
    with program_guard(prog, start):
        x = fluid.layers.data("x", shape=[64], dtype="float32")
        h = fluid.layers.fc(x, size=512)      # 64*512 >= min_block_size
        y = fluid.layers.fc(h, size=4)        # small: stays whole
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog


def test_transpiler_placement_round_robin_vs_hash():
    prog = _small_program()
    t = DistributeTranspiler()
    t.transpile(0, program=prog, pservers="h1:6174,h2:6174", trainers=2)
    pl = t.placement()
    assert pl, "placement map should not be empty"
    assert set(pl.values()) <= {"h1:6174", "h2:6174"}
    # round robin balances block counts within 1
    counts = [list(pl.values()).count(e) for e in ("h1:6174", "h2:6174")]
    assert abs(counts[0] - counts[1]) <= 1, pl

    cfg = DistributeTranspilerConfig()
    cfg.split_method = HashName
    t2 = DistributeTranspiler(cfg)
    t2.transpile(0, program=prog, pservers="h1:6174,h2:6174", trainers=2)
    t3 = DistributeTranspiler(cfg)
    t3.transpile(0, program=prog, pservers="h1:6174,h2:6174", trainers=2)
    assert t2.placement() == t3.placement()   # stable

    bad = DistributeTranspilerConfig()
    bad.split_method = "NotADispatcher"
    bt = DistributeTranspiler(bad)
    with pytest.raises(ValueError):
        bt.transpile(0, program=prog, trainers=2)
    # a failed transpile leaves the object cleanly un-transpiled
    with pytest.raises(RuntimeError):
        bt.placement()
    with pytest.raises(RuntimeError):
        bt.sharding_plan()


def test_transpiler_placement_defaults_to_dp_ranks():
    prog = _small_program()
    t = DistributeTranspiler()
    t.transpile(0, program=prog, trainers=4)
    assert set(t.placement().values()) <= {"dp:%d" % r for r in range(4)}


def test_host_tensor_shims():
    t = fluid.Tensor()
    t.set(np.arange(6).reshape(2, 3))
    assert t.shape() == (2, 3)
    assert np.asarray(t).sum() == 15

    lt = fluid.LoDTensor()
    lt.set(np.zeros((2, 3, 1), "int64"))
    lt.set_recursive_sequence_lengths([[2, 3]])
    assert lt.lod() == [[0, 2, 5]]
    lt.set_lod([[0, 1, 4]])
    assert lt.recursive_sequence_lengths() == [[1, 3]]
    with pytest.raises(ValueError):
        lt.set_lod([[2, 5, 7]])      # offsets must start at 0
    with pytest.raises(ValueError):
        lt.set_lod([[0, 5, 3]])      # and be non-decreasing

    arr = fluid.LoDTensorArray()
    arr.append(lt)
    assert len(arr) == 1

    assert fluid.CUDAPinnedPlace() == fluid.CUDAPinnedPlace()
    assert fluid.CUDAPinnedPlace() != fluid.CPUPlace()

    s = fluid.Scope()
    prev = fluid._switch_scope(s)
    assert fluid.global_scope() is s
    fluid._switch_scope(prev)
    assert fluid.global_scope() is prev

    # learning_rate_decay module alias exposes the in-graph decays
    assert hasattr(fluid.learning_rate_decay, "noam_decay")
