"""io.py save/load round-trips: vars, params, persistables, inference
model (incl. pruning), trainer checkpoint serials + resume (VERDICT weak
item 5: these subsystems had zero tests)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _build(scope_seed=11):
    fluid.default_startup_program().random_seed = scope_seed
    x = fluid.layers.data("x", shape=[4])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=8, act="relu")
    pred = fluid.layers.fc(h, size=3, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return x, pred, loss


def _params_snapshot(scope, program):
    return {p.name: np.asarray(scope.var(p.name))
            for p in program.global_block().all_parameters()}


def test_save_load_params_roundtrip(tmp_path, fresh_programs):
    _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        before = _params_snapshot(scope, fluid.default_main_program())
        fluid.io.save_params(exe, str(tmp_path / "p"))
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(fluid.default_startup_program())   # different init values
        fluid.io.load_params(exe, str(tmp_path / "p"))
        after = _params_snapshot(scope2, fluid.default_main_program())
    assert before.keys() == after.keys() and before
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])


def test_save_persistables_includes_optimizer_state(tmp_path,
                                                    fresh_programs):
    _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype("float32"),
            "label": rng.randint(0, 3, (8, 1)).astype("int64")}
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        for _ in range(3):   # builds Adam moments
            exe.run(feed=feed, fetch_list=[])
        fluid.io.save_persistables(exe, str(tmp_path / "ck"))
        persist = {v.name for v in
                   fluid.default_main_program().global_block()
                   .vars.values() if v.persistable}
        moments = [n for n in persist if "moment" in n.lower() or
                   "beta" in n.lower()]
        assert moments, persist  # Adam state must be persistable
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(fluid.default_startup_program())
        fluid.io.load_persistables(exe, str(tmp_path / "ck"))
        for n in moments:
            np.testing.assert_array_equal(
                np.asarray(scope2.var(n)), np.asarray(scope.var(n)))


def test_save_load_inference_model_prunes_and_predicts(tmp_path,
                                                       fresh_programs):
    x, pred, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    xv = rng.rand(5, 4).astype("float32")
    test_prog = fluid.default_main_program().clone(for_test=True)
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        # evaluate through the test clone: the train program's fetch
        # would also run the Adam update and change the params
        (want,) = exe.run(
            test_prog.prune_feed_fetch(["x"], [pred.name]),
            feed={"x": xv}, fetch_list=[pred])
        fluid.io.save_inference_model(str(tmp_path / "m"), ["x"], [pred],
                                      exe)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog, feed_names, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path / "m"), exe)
        assert feed_names == ["x"]
        # pruned: no optimizer/backward ops in the inference program
        optypes = {op.type for op in prog.global_block().ops}
        assert "adam" not in optypes
        assert not any(t.endswith("_grad") for t in optypes)
        (got,) = exe.run(prog, feed={"x": xv}, fetch_list=fetch_vars)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_checkpoint_serials_and_resume(tmp_path, fresh_programs):
    _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    ckdir = str(tmp_path / "ckpt")
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_checkpoint(exe, ckdir)
        fluid.io.save_checkpoint(exe, ckdir)
        serial = fluid.io.get_latest_checkpoint_serial(ckdir)
        assert serial == 1
        before = _params_snapshot(scope, fluid.default_main_program())
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(fluid.default_startup_program())
        fluid.io.load_checkpoint(exe, ckdir)
        after = _params_snapshot(scope2, fluid.default_main_program())
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])
    fluid.io.clean_checkpoint(ckdir, delete_dir=True)
    assert not os.path.exists(ckdir)


def test_trainer_checkpoint_resume_mid_training(tmp_path, fresh_programs):
    """Kill training after epoch 0; a new Trainer over the same
    checkpoint dir resumes instead of restarting (CheckpointConfig
    parity, contrib/trainer.py:100,580)."""
    from paddle_tpu.contrib import Trainer, CheckpointConfig

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=3, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.1)

    rng = np.random.RandomState(2)

    def reader():
        for _ in range(6):
            yield rng.rand(4).astype("float32"), np.array([1], "int64")

    ck = CheckpointConfig(checkpoint_dir=str(tmp_path / "tck"),
                          epoch_interval=1, step_interval=2)
    t1 = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                 optimizer_func=optimizer_func, checkpoint_config=ck)
    seen = []
    t1.train(num_epochs=1, event_handler=lambda e: seen.append(e),
             reader=fluid.batch(reader, batch_size=2),
             feed_order=["x", "label"])
    w1 = {p.name: np.asarray(t1.scope.var(p.name)) for p in
          t1.train_program.global_block().all_parameters()}

    # second trainer: auto-loads the checkpoint on construction
    t2 = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                 optimizer_func=optimizer_func, checkpoint_config=ck)
    w2 = {p.name: np.asarray(t2.scope.var(p.name)) for p in
          t2.train_program.global_block().all_parameters()}
    assert w1.keys() == w2.keys() and w1
    for k in w1:
        np.testing.assert_array_equal(w1[k], w2[k])


def test_train_from_saved_program_cli(tmp_path):
    """Train-without-python-build: save the FULL train program (fwd +
    bwd + optimizer), then run steps through the CLI with no model code
    (reference train/demo/demo_trainer.cc capability)."""
    import subprocess
    import sys

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(x, size=1, act=None)
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        fluid.io.save_train_program(str(tmp_path), main, startup,
                                    loss_name=loss.name,
                                    feed_names=["x", "y"])

    # real data via npz: y = x @ w_true (learnable -> loss must drop)
    rng = np.random.RandomState(0)
    xv = rng.rand(64, 8).astype("float32")
    w_true = rng.rand(8, 1).astype("float32")
    np.savez(str(tmp_path / "data.npz"), x=xv, y=xv @ w_true)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "train_from_program.py"),
         "--model_dir", str(tmp_path), "--steps", "30",
         "--batch_size", "64", "--feed", str(tmp_path / "data.npz"),
         "--save_params_dir", str(tmp_path / "params")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    losses = [float(line.split("loss:")[1])
              for line in out.stdout.splitlines() if "loss:" in line]
    assert len(losses) == 30
    assert losses[-1] < losses[0] * 0.1, losses
    assert os.path.exists(str(tmp_path / "params"))

    # synthetic-feed path: runs and stays finite
    out2 = subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "train_from_program.py"),
         "--model_dir", str(tmp_path), "--steps", "3",
         "--params_dir", str(tmp_path / "params")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo)
    assert out2.returncode == 0, out2.stderr[-2000:]
    assert out2.stdout.count("loss:") == 3
