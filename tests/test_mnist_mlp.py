"""End-to-end model test: MNIST-style MLP trains and converges
(reference tests/book/test_recognize_digits.py pattern — the BASELINE
config-1 minimum slice)."""

import numpy as np

import paddle_tpu as fluid


def _synthetic_mnist(rng, n):
    x = rng.rand(n, 784).astype("float32")
    # learnable synthetic rule: class = argmax of 10 fixed projections
    proj = np.linspace(-1, 1, 7840).reshape(784, 10).astype("float32")
    y = (x @ proj).argmax(axis=1).astype("int64").reshape(-1, 1)
    return x, y


def test_mnist_mlp_trains():
    img = fluid.layers.data("img", shape=[784])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h1 = fluid.layers.fc(img, size=64, act="relu")
    h2 = fluid.layers.fc(h1, size=64, act="relu")
    pred = fluid.layers.fc(h2, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    acc = fluid.layers.accuracy(pred, label)
    opt = fluid.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(42)
    first_loss = last_loss = None
    accs = []
    for step in range(60):
        x, y = _synthetic_mnist(rng, 64)
        lv, av = exe.run(feed={"img": x, "label": y},
                         fetch_list=[loss, acc])
        if step == 0:
            first_loss = float(lv[0])
        last_loss = float(lv[0])
        accs.append(float(av[0]))
    assert last_loss < first_loss * 0.8, (first_loss, last_loss)
    # >= : with a lucky init the model can saturate accuracy 1.0 inside
    # the first 10 steps, making strict > flaky
    assert np.mean(accs[-10:]) >= np.mean(accs[:10])


def test_mnist_mlp_save_load_inference(tmp_path):
    img = fluid.layers.data("img", shape=[16])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = fluid.layers.fc(img, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(7)
    x = rng.rand(8, 16).astype("float32")
    y = rng.randint(0, 4, (8, 1)).astype("int64")
    exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
    (ref_pred,) = exe.run(
        fluid.default_main_program().prune_feed_fetch(["img"], [pred.name]),
        feed={"img": x}, fetch_list=[pred.name])

    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["img"], [pred], exe)

    # fresh scope: load and compare
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            model_dir, exe2)
        (loaded_pred,) = exe2.run(
            program, feed={feed_names[0]: x},
            fetch_list=[v.name for v in fetch_vars])
    np.testing.assert_allclose(ref_pred, loaded_pred, rtol=1e-5)


def test_checkpoint_save_load(tmp_path):
    img = fluid.layers.data("img", shape=[8])
    pred = fluid.layers.fc(img, size=2)
    loss = fluid.layers.mean(pred)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.RandomState(3).rand(4, 8).astype("float32")
    for _ in range(3):
        exe.run(feed={"img": x}, fetch_list=[loss])
    ckpt = str(tmp_path / "ckpt")
    fluid.io.save_checkpoint(exe, ckpt, serial=5)
    names = [
        v.name for v in fluid.default_main_program().list_vars()
        if v.persistable
    ]
    snapshot = {n: np.asarray(fluid.global_scope().var(n)) for n in names}

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        ok = fluid.io.load_checkpoint(exe2, ckpt)
        assert ok
        for n, want in snapshot.items():
            got = np.asarray(fluid.global_scope().var(n))
            np.testing.assert_allclose(got, want)
