"""Fleet telemetry plane (ISSUE 19): digest building/commit, exact
merged percentiles, fake-clock aggregator semantics, straggler
detection, alert lifecycle, routing deprioritization, and the
disabled-path zero-cost A/B.

Tier-1 coverage is all fake-clock/direct-service; the multi-process
``delay_dispatch`` straggler drill (``fleet_telemetry_runner``) is
slow-marked and also driven by ``tools/run_ci.sh`` step 19."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu import monitor                              # noqa: E402
from paddle_tpu.cluster.membership import ClusterMaster     # noqa: E402
from paddle_tpu.cluster.runtime import ClusterMember        # noqa: E402
from paddle_tpu.monitor import aggregate, alerts            # noqa: E402
from paddle_tpu.monitor.registry import (DEFAULT_BUCKETS,   # noqa: E402
                                         MetricsRegistry)
from paddle_tpu.serving.fleet import FleetMaster            # noqa: E402


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    yield
    aggregate.disable()
    monitor.disable()
    monitor.registry().reset()


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _digest(host, seq, ts, counters=None, gauges=None, hists=None,
            steps=None, goodput=None, run="run-1"):
    d = {"v": 1, "seq": seq, "host": host, "ts": ts, "run": run,
         "counters": counters or {}, "gauges": gauges or {},
         "hists": hists or {}, "steps": steps or []}
    if goodput is not None:
        d["goodput"] = goodput
    return d


def _hist_payload(reg_hist):
    s = reg_hist.snapshot()
    return {"b": s["buckets"], "c": s["counts"], "sum": s["sum"],
            "n": s["count"]}


# ---------------------------------------------------------------------------
# exact percentiles: merged == pooled, bit-equal
# ---------------------------------------------------------------------------

def test_merged_percentiles_bit_equal_to_pooled():
    import random

    rng = random.Random(7)
    per_host = {"h%d" % i: [rng.uniform(0.0001, 12.0)
                            for _ in range(200 + 50 * i)]
                for i in range(4)}
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock)
    pooled = MetricsRegistry().histogram("lat")
    for seq, (host, vals) in enumerate(sorted(per_host.items()), 1):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for v in vals:
            h.observe(v)
            pooled.observe(v)
        agg.ingest(host, _digest(host, 1, clock.t,
                                 hists={"lat": _hist_payload(h)}))
    snap = pooled.snapshot()
    for q in (0.5, 0.9, 0.99, 0.999):
        want = aggregate.hist_percentile(snap["buckets"], snap["counts"],
                                         q)
        assert agg.percentile("lat", q) == want
    view = agg.fleet_view()
    assert view["percentiles"]["lat"]["count"] == \
        sum(len(v) for v in per_host.values())
    assert view["percentiles"]["lat"]["p50"] == aggregate.hist_percentile(
        snap["buckets"], snap["counts"], 0.5)


def test_hist_percentile_edges():
    bounds = list(DEFAULT_BUCKETS)
    counts = [0] * (len(bounds) + 1)
    assert aggregate.hist_percentile(bounds, counts, 0.5) is None
    counts[-1] = 3      # everything in the +Inf overflow slot
    assert aggregate.hist_percentile(bounds, counts, 0.99) == \
        float("inf")
    counts = [1] + [0] * len(bounds)
    assert aggregate.hist_percentile(bounds, counts, 0.5) == bounds[0]


# ---------------------------------------------------------------------------
# DigestBuilder: delta snapshots, commit-on-delivery, size guard
# ---------------------------------------------------------------------------

def test_digest_builder_changed_only_and_commit():
    reg = MetricsRegistry()
    clock = _Clock()
    b = aggregate.DigestBuilder("h0", registry=reg, clock=clock)
    reg.counter("steps").inc(3)
    reg.gauge("depth").set(2.0)
    reg.histogram("lat").observe(0.2)
    d1 = b.build()
    assert d1["seq"] == 1 and d1["host"] == "h0"
    assert d1["counters"] == {"steps": 3.0}
    assert d1["gauges"] == {"depth": 2.0}
    assert set(d1["hists"]) == {"lat"}
    # NOT committed: the next build re-ships the same still-undelivered
    # values (a lost heartbeat loses nothing)
    d2 = b.build()
    assert d2["seq"] == 2 and d2["counters"] == {"steps": 3.0}
    assert set(d2["hists"]) == {"lat"}
    # commit seq 2 (subsumes 1); nothing changed -> empty delta
    assert b.committed(2)
    d3 = b.build()
    assert d3["counters"] == {} and d3["gauges"] == {} \
        and d3["hists"] == {}
    # only the moved metric ships after the baseline
    reg.counter("steps").inc()
    b.committed(3)
    d4 = b.build()
    assert d4["counters"] == {"steps": 4.0} and d4["hists"] == {}


def test_digest_builder_rebaselines_on_registry_reset():
    reg = MetricsRegistry()
    b = aggregate.DigestBuilder("h0", registry=reg)
    reg.counter("steps").inc(5)
    b.committed(b.build()["seq"])
    assert b.build()["counters"] == {}
    reg.reset()
    reg.counter("steps").inc(2)
    # generation moved: committed views drop, everything re-ships
    assert b.build()["counters"] == {"steps": 2.0}


def test_digest_size_guard_decimates_and_counts():
    reg = MetricsRegistry()
    clock = _Clock()
    for i in range(40):
        h = reg.histogram("hist/%02d" % i)
        for _ in range(i + 1):
            h.observe(0.01)
    b = aggregate.DigestBuilder("h0", registry=reg, max_bytes=2048,
                                clock=clock)
    for i in range(64):
        aggregate.note_step_time(0.01, now=clock.t + i)
    d = b.build()
    assert d.get("trunc") is True
    assert b.truncations == 1
    assert len(json.dumps(d, separators=(",", ":"))) <= 2048
    # newest step samples survive the decimation, lowest-n histograms
    # dropped first (the survivors are the highest-traffic ones)
    if d["steps"]:
        assert d["steps"][-1][0] == clock.t + 63
    if d["hists"]:
        kept_n = min(h["n"] for h in d["hists"].values())
        assert kept_n > 1
    # the enabled-gated counter lands when the master monitors
    monitor.enable()
    b2 = aggregate.DigestBuilder("h1", registry=reg, max_bytes=2048,
                                 clock=clock)
    b2.build()
    assert monitor.registry().get("fleet/digest_truncated").value >= 1
    aggregate._STEP_RING.clear()


# ---------------------------------------------------------------------------
# FleetAggregator: ordering, death, restart, goodput (fake clock)
# ---------------------------------------------------------------------------

def test_late_and_duplicate_digests_dropped():
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock)
    assert agg.ingest("a", _digest("a", 2, clock.t,
                                   counters={"steps": 5.0}))
    # duplicate and out-of-order deliveries fold nothing twice
    assert not agg.ingest("a", _digest("a", 2, clock.t,
                                       counters={"steps": 5.0}))
    assert not agg.ingest("a", _digest("a", 1, clock.t,
                                       counters={"steps": 3.0}))
    assert agg.fleet_view()["counters"]["steps"] == 5.0
    # the next new seq folds only the cumulative difference
    assert agg.ingest("a", _digest("a", 3, clock.t,
                                   counters={"steps": 7.0}))
    assert agg.fleet_view()["counters"]["steps"] == 7.0


def test_member_death_drops_gauges_keeps_counters():
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock)
    agg.ingest("a", _digest("a", 1, clock.t, counters={"steps": 10.0},
                            gauges={"depth": 3.0}))
    agg.ingest("b", _digest("b", 1, clock.t, counters={"steps": 4.0},
                            gauges={"depth": 1.0}))
    agg.note_expired(["a"])
    view = agg.fleet_view()
    assert sorted(view["hosts"]) == ["b"]          # gauges/state dropped
    assert view["counters"]["steps"] == 14.0       # contributions stay
    assert "a" in view["expired"]
    # the lease-expiry alert fired for the dead member...
    assert any(a["rule"] == "lease_expired" and a["member_id"] == "a"
               for a in view["alerts"])
    # ...and resolves when the host rejoins (fresh digest clears the
    # tombstone)
    agg.ingest("a", _digest("a", 2, clock.t, counters={"steps": 11.0}))
    view = agg.fleet_view()
    assert not any(a["rule"] == "lease_expired" for a in view["alerts"])
    assert view["counters"]["steps"] == 15.0


def test_member_restart_rebaselines_without_double_count():
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock)
    agg.ingest("a", _digest("a", 5, clock.t, counters={"steps": 100.0},
                            run="run-1"))
    # restarted process: new run token, seq resets, counters restart —
    # the fresh cumulative value folds as NEW contribution, the old
    # run's contribution stays (it happened)
    agg.ingest("a", _digest("a", 1, clock.t, counters={"steps": 3.0},
                            run="run-2"))
    assert agg.fleet_view()["counters"]["steps"] == 103.0


def test_fleet_goodput_ratio_merges_across_hosts():
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock)
    agg.ingest("a", _digest("a", 1, clock.t, goodput={
        "compute": 8.0, "wall": 10.0, "ratio": 0.8, "steps": 10}))
    agg.ingest("b", _digest("b", 1, clock.t, goodput={
        "compute": 2.0, "wall": 10.0, "ratio": 0.2, "steps": 10}))
    view = agg.fleet_view()
    assert view["goodput_ratio"] == pytest.approx(0.5)
    assert view["hosts"]["a"]["goodput_ratio"] == 0.8
    # cumulative growth folds only the delta
    agg.ingest("a", _digest("a", 2, clock.t, goodput={
        "compute": 9.0, "wall": 11.0, "ratio": 9.0 / 11.0,
        "steps": 11}))
    assert agg.fleet_view()["goodput_ratio"] == \
        pytest.approx(11.0 / 21.0, abs=1e-4)    # view rounds to 4 places


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------

def test_straggler_detector_flags_and_clears():
    det = aggregate.StragglerDetector(zmax=8.0, persist=2, min_hosts=3)
    slow = {"a": 0.1, "b": 0.1, "c": 0.1, "d": 2.0}
    fast = {"a": 0.1, "b": 0.1, "c": 0.1, "d": 0.1}
    assert det.update({"step_time": slow}, 1.0) == set()   # persist=2
    assert det.update({"step_time": slow}, 2.0) == {"d"}
    assert det.verdicts()["d"]["series"] == "step_time"
    assert det.verdicts()["d"]["z"] > 8.0
    # first in-band window clears the flag
    assert det.update({"step_time": fast}, 3.0) == set()
    # below min_hosts: no verdicts even for a wild outlier
    assert det.update({"step_time": {"a": 0.1, "d": 50.0}}, 4.0) == set()


def test_straggler_detector_saturated_window_no_false_positive():
    det = aggregate.StragglerDetector(persist=1)
    # every host bit-identical: MAD == 0, the relative floor keeps z
    # finite and in-band (the guardian's saturated-window lesson)
    same = {"a": 0.5, "b": 0.5, "c": 0.5, "d": 0.5}
    assert det.update({"step_time": same}, 1.0) == set()


def test_aggregator_detects_straggler_from_digests_and_alerts():
    clock = _Clock()
    rules = alerts.default_rules(straggler_for_s=0.0)
    agg = aggregate.FleetAggregator(clock=clock, rules=rules,
                                    stale_after=60.0)
    hosts = {"a": 0.01, "b": 0.01, "c": 0.5}
    for rnd in range(1, 3):
        for h, sec in sorted(hosts.items()):
            agg.ingest(h, _digest(h, rnd, clock.t,
                                  steps=[[clock.t, sec]]))
        clock.t += 5.0
    assert agg.straggler_hosts() == frozenset({"c"})
    view = agg.fleet_view()
    assert view["hosts"]["c"]["straggler"] and view["hosts"]["c"]["z"]
    firing = [a for a in view["alerts"] if a["rule"] == "straggler"]
    assert firing and firing[0]["member_id"] == "c"
    # recovery: in-band windows clear the verdict and resolve the alert
    for rnd in range(3, 5):
        for h in sorted(hosts):
            agg.ingest(h, _digest(h, rnd, clock.t,
                                  steps=[[clock.t, 0.01]]))
        clock.t += 5.0
    assert agg.straggler_hosts() == frozenset()
    assert not [a for a in agg.fleet_view()["alerts"]
                if a["rule"] == "straggler"]


# ---------------------------------------------------------------------------
# alert engine lifecycle
# ---------------------------------------------------------------------------

def _view_with_goodput(ratio):
    return {"hosts": {}, "goodput_ratio": ratio, "counters": {},
            "percentiles": {}, "stragglers": {}, "expired": {},
            "quarantined": {}}


def test_alert_fires_once_with_hysteresis_and_rearms():
    clock = _Clock()
    rule = alerts.AlertRule("goodput_collapse", "goodput_ratio", 0.5,
                            op="<", for_seconds=10.0,
                            severity="critical")
    eng = alerts.AlertEngine([rule], clock=clock)
    # breach starts the pending window; no fire before for_seconds
    assert eng.evaluate(_view_with_goodput(0.2), clock.t) == []
    clock.t += 5.0
    assert eng.evaluate(_view_with_goodput(0.2), clock.t) == []
    # a recovery inside the window re-arms the hysteresis entirely
    clock.t += 1.0
    assert eng.evaluate(_view_with_goodput(0.9), clock.t) == []
    clock.t += 1.0
    assert eng.evaluate(_view_with_goodput(0.2), clock.t) == []
    # held for the full window: exactly ONE firing event, deduped after
    clock.t += 10.0
    evs = eng.evaluate(_view_with_goodput(0.2), clock.t)
    assert [e["state"] for e in evs] == ["firing"]
    assert evs[0]["rule"] == "goodput_collapse"
    assert evs[0]["severity"] == "critical"
    assert evs[0]["member_id"] is None
    assert eng.evaluate(_view_with_goodput(0.2), clock.t + 1.0) == []
    assert len(eng.active()) == 1
    # resolve emits once and re-arms: a fresh breach needs a fresh
    # for_seconds window before firing again
    clock.t += 5.0
    evs = eng.evaluate(_view_with_goodput(0.9), clock.t)
    assert [e["state"] for e in evs] == ["resolved"]
    assert eng.active() == []
    evs = eng.evaluate(_view_with_goodput(0.2), clock.t)
    assert evs == []
    clock.t += 10.0
    evs = eng.evaluate(_view_with_goodput(0.2), clock.t)
    assert [e["state"] for e in evs] == ["firing"]


def test_per_host_alerts_and_key_vanish_resolution():
    clock = _Clock()
    rule = alerts.AlertRule("q", "host:queue_depth", 10.0,
                            for_seconds=0.0)
    eng = alerts.AlertEngine([rule], clock=clock)
    view = {"hosts": {"a": {"queue_depth": 20}, "b": {"queue_depth": 1}}}
    evs = eng.evaluate(view, clock.t)
    assert [(e["state"], e["member_id"]) for e in evs] == \
        [("firing", "a")]
    # the host leaving the view resolves its alert
    evs = eng.evaluate({"hosts": {"b": {"queue_depth": 1}}},
                       clock.t + 1)
    assert [(e["state"], e["member_id"]) for e in evs] == \
        [("resolved", "a")]


def test_alert_counter_family():
    monitor.enable()
    clock = _Clock()
    eng = alerts.AlertEngine(
        [alerts.AlertRule("gp", "goodput_ratio", 0.5, op="<")],
        clock=clock)
    eng.evaluate(_view_with_goodput(0.1), clock.t)
    eng.evaluate(_view_with_goodput(0.9), clock.t + 1)
    reg = monitor.registry()
    assert reg.get("alerts/fired").value == 1
    assert reg.get("alerts/resolved").value == 1
    assert reg.get("alerts/severity/warning").value == 1
    assert reg.get("alerts/active").value == 0.0


def test_checkpoint_staleness_and_digest_age_alerts():
    clock = _Clock()
    rules = alerts.default_rules(ckpt_max_age_s=100.0,
                                 digest_stale_s=30.0)
    agg = aggregate.FleetAggregator(clock=clock, rules=rules,
                                    stale_after=1e9)
    agg.ingest("a", _digest("a", 1, clock.t,
                            counters={"checkpoint/snapshot": 1.0}))
    agg.ingest("b", _digest("b", 1, clock.t))
    assert agg.fleet_view()["hosts"]["a"]["checkpoint_age_s"] == 0.0
    # host b never checkpointed: the staleness rule has nothing to
    # measure there (no false positive)
    assert agg.fleet_view()["hosts"]["b"]["checkpoint_age_s"] is None
    # 200s later host a still digests (no ckpt movement) -> stale fires;
    # host b went dark -> digest_stale names it
    clock.t += 200.0
    agg.ingest("a", _digest("a", 2, clock.t))
    view = agg.fleet_view()
    rules_firing = {(a["rule"], a["member_id"]) for a in view["alerts"]}
    assert ("checkpoint_stale", "a") in rules_firing
    assert ("digest_stale", "b") in rules_firing
    # checkpoint movement (histogram count advancing also counts) and a
    # fresh digest from b resolve both
    agg.ingest("a", _digest("a", 3, clock.t,
                            counters={"checkpoint/snapshot": 2.0}))
    agg.ingest("b", _digest("b", 2, clock.t))
    rules_firing = {a["rule"] for a in agg.fleet_view()["alerts"]}
    assert "checkpoint_stale" not in rules_firing
    assert "digest_stale" not in rules_firing


# ---------------------------------------------------------------------------
# routing deprioritization (fake-clock FleetMaster)
# ---------------------------------------------------------------------------

def _fleet_master(n, clock):
    m = FleetMaster(lease_timeout=10.0, clock=clock)
    for i in range(n):
        m.join("rep-%d" % i, {"address": "127.0.0.1:%d" % (9000 + i),
                              "kind": "generate"})
    return m


def test_straggler_loses_routing_ties_but_still_serves():
    clock = _Clock()
    master = _fleet_master(3, clock)
    agg = aggregate.FleetAggregator(master=master, stale_after=1e9)

    def route_loop(n=9):
        got = []
        for _ in range(n):
            a = master.route(None, "generate", 8)
            got.append(a["replica"])
            master.complete(a["ticket"], a["attempt"])
        return got

    # baseline: all scores equal -> the deterministic tie-winner
    # (sorted first) takes EVERY request
    assert route_loop() == ["rep-0"] * 9
    # flag rep-0 a straggler via digests (rep-0 slow step windows)
    for rnd in range(1, 3):
        for h, sec in (("rep-0", 0.9), ("rep-1", 0.01), ("rep-2", 0.01)):
            agg.ingest(h, _digest(h, rnd, clock.t,
                                  steps=[[clock.t, sec]]))
        clock.t += 1.0
    assert agg.straggler_hosts() == frozenset({"rep-0"})
    # the soft deprioritization: rep-0 loses every tie now — load
    # measurably shifts off the straggler (to rep-1, the deterministic
    # tie-winner among the non-flagged replicas)
    shifted = route_loop()
    assert "rep-0" not in shifted
    assert shifted == ["rep-1"] * 9
    # but a straggler is NOT quarantine: when it is genuinely least
    # loaded it still serves
    a = master.route(None, "generate", 8)     # rep-1 busy (in-flight)
    b = master.route(None, "generate", 8)     # rep-2 busy
    assert {a["replica"], b["replica"]} == {"rep-1", "rep-2"}
    c = master.route(None, "generate", 8)
    assert c["replica"] == "rep-0"


def test_quarantine_feeds_alert_rule():
    clock = _Clock()
    master = _fleet_master(2, clock)
    agg = aggregate.FleetAggregator(master=master, stale_after=1e9)
    agg.ingest("rep-0", _digest("rep-0", 1, clock.t))
    agg.ingest("rep-1", _digest("rep-1", 1, clock.t))
    clock.t += 11.0
    # rep-1's heartbeat only: rep-0's lease expires at the sweep
    master.heartbeat("rep-1")
    view = agg.fleet_view()
    firing = {(a["rule"], a["member_id"]) for a in view["alerts"]}
    assert ("replica_quarantined", "rep-0") in firing
    assert ("lease_expired", "rep-0") in firing
    assert "rep-0" in view["quarantined"]


# ---------------------------------------------------------------------------
# transport integration: digest rides the heartbeat, /metrics, watchdog
# ---------------------------------------------------------------------------

def test_digest_rides_heartbeat_and_commits_on_delivery():
    monitor.enable()
    aggregate.enable()
    master = ClusterMaster(lease_timeout=5.0)
    agg = aggregate.FleetAggregator(master=master)
    mem = ClusterMember(master, "hostA", auto_heartbeat=False,
                        register_local=False)
    monitor.count("train/steps", 3)
    mem.heartbeat(step=1)
    view = master.fleet_view()
    assert view["hosts"]["hostA"]["seq"] == 1
    assert view["counters"]["train/steps"] == 3.0
    # delivery committed: an unchanged registry ships an empty delta
    d = mem._digest.build()
    assert d["counters"] == {}
    mem.close()


def test_fleet_series_published_to_master_metrics():
    monitor.enable()
    clock = _Clock()
    agg = aggregate.FleetAggregator(clock=clock, stale_after=1e9)
    reg_a = MetricsRegistry()
    h = reg_a.histogram("serving/request_latency_seconds")
    for v in (0.01, 0.02, 0.3):
        h.observe(v)
    agg.ingest("a", _digest("a", 1, clock.t,
                            counters={"steps": 5.0},
                            gauges={"depth": 2.0},
                            hists={"serving/request_latency_seconds":
                                   _hist_payload(h)}))
    agg.ingest("b", _digest("b", 1, clock.t, counters={"steps": 7.0},
                            gauges={"depth": 4.0}))
    reg = monitor.registry()
    assert reg.get("fleet/steps").value == 12.0
    assert reg.get("fleet/depth/min").value == 2.0
    assert reg.get("fleet/depth/med").value == 3.0
    assert reg.get("fleet/depth/max").value == 4.0
    assert reg.get("fleet/hosts").value == 2.0
    p99 = reg.get("fleet/serving/request_latency_seconds/p99")
    assert p99 is not None and p99.value > 0
    text = monitor.expose_text()
    assert "fleet_steps 12" in text
    assert "fleet_hosts 2" in text


def test_watchdog_stall_dump_includes_fleet_view():
    monitor.enable()
    aggregate.enable()
    clock = _Clock()
    master = ClusterMaster(lease_timeout=5.0)
    agg = aggregate.FleetAggregator(master=master)
    agg.ingest("peer", _digest("peer", 1, clock.t))
    mem = ClusterMember(master, "hostA", auto_heartbeat=False)
    mem.heartbeat()      # push hostA's own digest into the aggregator
    try:
        assert mem is __import__(
            "paddle_tpu.cluster.runtime",
            fromlist=["local_member"]).local_member()
        diag = monitor._stall_probe()
        fleet = diag["fleet"]
        assert fleet is not None
        assert set(fleet["digest_age_s"]) >= {"peer", "hostA"}
        rendered = monitor._format_diag(dict(diag, stalled_for_s=1.0))
        assert "fleet digest" in rendered
    finally:
        mem.close()


def test_stall_probe_fleet_absent_when_disabled_or_no_member():
    monitor.enable()
    assert monitor._stall_probe()["fleet"] is None
    aggregate.enable()
    assert monitor._stall_probe()["fleet"] is None   # no local member


# ---------------------------------------------------------------------------
# the disabled path makes ZERO aggregation calls (raising monkeypatch)
# ---------------------------------------------------------------------------

def test_disabled_path_zero_aggregation_calls(monkeypatch):
    def boom(*a, **k):
        raise AssertionError("aggregation touched on the disabled path")

    monkeypatch.setattr(aggregate.DigestBuilder, "build", boom)
    monkeypatch.setattr(aggregate.FleetAggregator, "ingest", boom)
    monkeypatch.setattr(aggregate, "note_step_time", boom)
    monitor.enable()
    assert not aggregate.enabled()
    # instrumented step path: record_step must not touch aggregation
    monitor.record_step("executor", 0.01, 4, 0)
    # heartbeat path: no digest built, none ingested
    master = ClusterMaster(lease_timeout=5.0)
    aggregate.FleetAggregator(master=master)
    mem = ClusterMember(master, "hostA", auto_heartbeat=False,
                        register_local=False)
    mem.heartbeat(step=1)
    mem.close()
    # control: with the flag ON the same calls DO hit the patched
    # functions — proving the A/B measured the real sites
    aggregate.enable()
    with pytest.raises(AssertionError, match="disabled path"):
        monitor.record_step("executor", 0.01, 4, 0)
    mem2 = ClusterMember(master, "hostB", auto_heartbeat=False,
                         register_local=False)
    with pytest.raises(AssertionError, match="disabled path"):
        mem2.heartbeat(step=1)
    mem2.close()


# ---------------------------------------------------------------------------
# fleet_report: JSONL replay + render
# ---------------------------------------------------------------------------

def test_fleet_report_replay_and_json(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import fleet_report

    clock = _Clock()
    monitor.enable(log_dir=str(tmp_path))
    agg = aggregate.FleetAggregator(clock=clock, emit_every=1,
                                    stale_after=1e9)
    agg.ingest("a", _digest("a", 1, clock.t, counters={"steps": 3.0},
                            steps=[[clock.t, 0.02]]))
    agg.ingest("b", _digest("b", 1, clock.t, counters={"steps": 4.0}))
    monitor.disable()        # flush/close the JSONL writer
    records = fleet_report.load_records(str(tmp_path))
    view, history = fleet_report.view_from_records(records)
    assert view is not None and sorted(view["hosts"]) == ["a", "b"]
    assert view["counters"]["steps"] == 7.0
    lines = "\n".join(fleet_report.render_table(view, history))
    assert "a" in lines and "fleet goodput ratio" in lines
    assert fleet_report.main([str(tmp_path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert sorted(out["view"]["hosts"]) == ["a", "b"]


# ---------------------------------------------------------------------------
# multi-process drill (slow; run_ci.sh step 19 drives the same runner)
# ---------------------------------------------------------------------------

@pytest.mark.slow   # 3 trainer subprocesses + fault window, ~60s
def test_delay_dispatch_straggler_drill(tmp_path):
    from fleet_telemetry_runner import supervise

    evidence = supervise(str(tmp_path), members=3)
    assert evidence["straggler_member"] == "m-0"
    assert evidence["alert_jsonl"]["firing"] >= 1
    assert evidence["alert_jsonl"]["resolved"] >= 1
    assert evidence["hosts_reporting"] == 3
    assert evidence["fleet_view_records"] >= 1
    assert all(rc == 0 for rc in evidence["member_rcs"])
