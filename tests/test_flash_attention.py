"""Flash-attention kernel + fused_attention op tests.

Parity oracle: a plain materialized softmax-attention (the reference's
``nets.scaled_dot_product_attention`` math, ``nets.py:323``) — the Pallas
kernel (interpret mode on CPU) and the XLA fallback must both match it
forward and backward, under padding masks, causal masks, and dropout
(the dropout mask is a shared counter hash, so the two paths agree
exactly)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.ops.pallas import flash_attention as fa


def _oracle(q, k, v, k_len=None, causal=False, scale=None):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
    mask = jnp.ones((b, 1, tq, tk), bool)
    if k_len is not None:
        mask = jnp.arange(tk)[None, None, None, :] < k_len.reshape(b, 1, 1, 1)
    if causal:
        mask = mask & (jnp.arange(tq)[:, None] >=
                       jnp.arange(tk)[None, :])[None, None]
    s = jnp.where(mask, s, -1e30)
    y = jax.nn.softmax(s, axis=-1)
    y = jnp.where(mask, y, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", y, v)


def _rand(shape, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


@pytest.mark.parametrize("tq,tk,causal", [
    (16, 16, False), (16, 16, True),
    (24, 40, False),          # non-multiple-of-block lengths, cross shape
    (64, 64, True),
])
def test_fwd_parity(tq, tk, causal):
    if causal and tq != tk:
        pytest.skip("causal needs tq == tk")
    q = _rand((2, 3, tq, 8), 0)
    k = _rand((2, 3, tk, 8), 1)
    v = _rand((2, 3, tk, 8), 2)
    out = fa.flash_attention(q, k, v, None, None, causal, 0.0, None, True)
    ref = _oracle(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # XLA fallback agrees too
    fb = fa.reference_attention(q, k, v, None, None, causal, 0.0, None)
    np.testing.assert_allclose(fb, ref, rtol=2e-5, atol=2e-5)


def test_fwd_klen_padding():
    q, k, v = _rand((3, 2, 16, 8), 0), _rand((3, 2, 16, 8), 1), \
        _rand((3, 2, 16, 8), 2)
    k_len = jnp.asarray([16, 7, 1], jnp.int32)
    out = fa.flash_attention(q, k, v, k_len, None, False, 0.0, None, True)
    ref = _oracle(q, k, v, k_len=k_len)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_zero_and_grad_safe():
    # causal + k_len=0 would be degenerate; here: k_len smaller than some
    # query positions under causal gives rows with zero valid keys only if
    # k_len == 0 — use k_len 0 on one batch element
    q, k, v = _rand((2, 1, 8, 4), 0), _rand((2, 1, 8, 4), 1), \
        _rand((2, 1, 8, 4), 2)
    k_len = jnp.asarray([8, 0], jnp.int32)

    def f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, k_len, None, False, 0.0,
                                          None, True) ** 2)

    out = fa.flash_attention(q, k, v, k_len, None, False, 0.0, None, True)
    assert np.all(np.asarray(out[1]) == 0.0)
    grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(causal):
    q, k, v = _rand((2, 2, 16, 8), 0), _rand((2, 2, 16, 8), 1), \
        _rand((2, 2, 16, 8), 2)
    k_len = jnp.asarray([16, 11], jnp.int32)
    w = _rand((2, 2, 16, 8), 3)   # nonuniform cotangent

    def f_flash(q, k, v):
        return jnp.sum(w * fa.flash_attention(q, k, v, k_len, None, causal,
                                              0.0, None, True))

    def f_ref(q, k, v):
        return jnp.sum(w * _oracle(q, k, v, k_len=k_len, causal=causal))

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


def test_dropout_fwd_and_grad_match_fallback():
    """Pallas path and XLA fallback share the counter-hash dropout mask:
    outputs and gradients agree exactly (same math, different schedule)."""
    q, k, v = _rand((2, 2, 16, 8), 0), _rand((2, 2, 16, 8), 1), \
        _rand((2, 2, 16, 8), 2)
    seed = jnp.asarray(1234, jnp.uint32)
    rate = 0.4

    def f_pl(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, None, seed, False, rate,
                                          None, True) ** 2)

    def f_fb(q, k, v):
        return jnp.sum(fa.reference_attention(q, k, v, None, seed, False,
                                              rate) ** 2)

    out_pl = fa.flash_attention(q, k, v, None, seed, False, rate, None, True)
    out_fb = fa.reference_attention(q, k, v, None, seed, False, rate)
    np.testing.assert_allclose(out_pl, out_fb, rtol=1e-5, atol=1e-5)
    g_pl = jax.grad(f_pl, argnums=(0, 1, 2))(q, k, v)
    g_fb = jax.grad(f_fb, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pl, g_fb):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
    # different seeds give different masks
    out2 = fa.flash_attention(q, k, v, None, seed + 1, False, rate, None,
                              True)
    assert not np.allclose(out_pl, out2)


def test_dropout_expectation_matches_infer_scale():
    """downgrade_in_infer: E[train dropout(y)] = (1-p)*y, which is exactly
    the (1-p) scale the op applies at eval — train/eval consistent."""
    q, k, v = _rand((1, 1, 32, 8), 0), _rand((1, 1, 32, 8), 1), \
        _rand((1, 1, 32, 8), 2)
    rate = 0.3
    outs = [fa.reference_attention(q, k, v, None,
                                   jnp.asarray(s, jnp.uint32), False, rate)
            for s in range(40)]
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    base = (1.0 - rate) * np.asarray(_oracle(q, k, v))
    np.testing.assert_allclose(mean, base, rtol=0.3, atol=0.12)


def _attention_program(use_fused, dropout_rate=0.0):
    """fused_attention op vs the manual matmul+softmax composition."""
    b, h, t, d = 2, 2, 8, 4
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        q = fluid.layers.data("q", shape=[h, t, d])
        k = fluid.layers.data("k", shape=[h, t, d])
        v = fluid.layers.data("vv", shape=[h, t, d])
        klen = fluid.layers.data("klen", shape=[], dtype="int32")
        if use_fused:
            out = fluid.layers.fused_attention(
                q, k, v, k_len=klen, causal=True,
                dropout_rate=dropout_rate)
        else:
            s = fluid.layers.matmul(q, k, transpose_y=True)
            s = fluid.layers.scale(s, scale=d ** -0.5)
            # padding_attn_bias/causal_mask take T from ref dim 1
            ref = fluid.layers.transpose(q, perm=[0, 2, 1, 3])  # [B,T,H,D]
            bias = fluid.layers.padding_attn_bias(klen, ref)
            s = fluid.layers.elementwise_add(s, bias)
            causal = fluid.layers.causal_mask(ref=ref)
            s = fluid.layers.elementwise_add(s, causal)
            w = fluid.layers.softmax(s)
            out = fluid.layers.matmul(w, v)
        rng = np.random.RandomState(7)
        feed = {"q": rng.randn(b, h, t, d).astype("float32"),
                "k": rng.randn(b, h, t, d).astype("float32"),
                "vv": rng.randn(b, h, t, d).astype("float32"),
                "klen": np.asarray([t, t - 3], "int32")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            return exe.run(feed=feed, fetch_list=[out])[0]


def test_fused_attention_op_matches_composition():
    fused = _attention_program(True)
    manual = _attention_program(False)
    np.testing.assert_allclose(fused, manual, rtol=1e-4, atol=1e-4)


def test_fused_attention_op_pallas_flag():
    base = _attention_program(True)
    fluid.set_flags({"FLAGS_pallas_kernels": True})
    try:
        pallas = _attention_program(True)
    finally:
        fluid.set_flags({"FLAGS_pallas_kernels": False})
    np.testing.assert_allclose(base, pallas, rtol=1e-4, atol=1e-4)


def test_label_smooth_fused_matches_composition():
    n, c, eps = 6, 11, 0.1
    rng = np.random.RandomState(0)
    logits_np = rng.randn(n, c).astype("float32")
    label_np = rng.randint(0, c, (n, 1)).astype("int64")

    def run(fused):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            logits = fluid.layers.data("logits", shape=[c])
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            if fused:
                loss = fluid.layers.softmax_with_cross_entropy(
                    logits, label, label_smooth_eps=eps)
            else:
                oh = fluid.layers.one_hot(label, depth=c)
                soft = fluid.layers.label_smooth(oh, epsilon=eps)
                loss = fluid.layers.softmax_with_cross_entropy(
                    logits, soft, soft_label=True)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(fluid.default_startup_program())
                return exe.run(feed={"logits": logits_np, "label": label_np},
                               fetch_list=[loss])[0]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_transformer_emits_fused_attention():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        from paddle_tpu.models import transformer as tfm
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                lod_level=1)
        cost, _ = tfm.transformer(src, tgt, lbl, 16, 16, 64, 64, n_layer=2,
                                  n_head=2, d_model=16, d_inner=32,
                                  dropout_rate=0.1)
        ops = [op.type for op in
               fluid.default_main_program().global_block().ops]
        # 2 enc self + 2 dec self + 2 cross = 6 fused attentions
        assert ops.count("fused_attention") == 6
        # the fused label-smoothing path: no [B, T, V] one_hot materialized
        assert "one_hot" not in ops


def test_label_smooth_pallas_kernel_matches_xla():
    """The hand-tiled softmax_xent kernel with fused label smoothing must
    match the XLA fused path forward and backward."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import softmax_xent as px

    n, c, eps = 12, 17, 0.1
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(n, c).astype("float32"))
    label = jnp.asarray(rng.randint(0, c, (n,)))

    def xla(lg):
        lse = jax.scipy.special.logsumexp(lg, axis=-1, keepdims=True)
        picked = jnp.take_along_axis(lg - lse, label[:, None], axis=-1)
        uni = lse - jnp.mean(lg, axis=-1, keepdims=True)
        return jnp.sum(((1 - eps) * -picked + eps * uni) ** 2)

    def pallas(lg):
        loss, _ = px.softmax_xent(lg, label, True, eps)
        return jnp.sum(loss ** 2)

    np.testing.assert_allclose(xla(logits), pallas(logits), rtol=1e-5)
    np.testing.assert_allclose(jax.grad(xla)(logits),
                               jax.grad(pallas)(logits),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# suffix-query (bottom-aligned) causal masks: the KV-cache decode shape
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tq,klen", [(1, 16), (4, 9), (8, 16)])
def test_suffix_causal_decode_parity(tq, klen):
    """causal with Tq < Tk: queries are the LAST tq of the klen valid
    keys — parity against the sliced rows of a full-length causal call
    (the workaround this mask retires)."""
    tk, b, h, d = 16, 2, 3, 8
    q_full = _rand((b, h, tk, d), 0)
    k = _rand((b, h, tk, d), 1)
    v = _rand((b, h, tk, d), 2)
    k_len = jnp.asarray([klen] * b, jnp.int32)
    full = _oracle(q_full, k, v, k_len=k_len, causal=True)
    lo = klen - tq
    q_suf = q_full[:, :, lo:klen, :]
    want = full[:, :, lo:klen, :]
    got_fb = fa.reference_attention(q_suf, k, v, k_len, None, True, 0.0,
                                    None)
    np.testing.assert_allclose(got_fb, want, rtol=2e-5, atol=2e-5)
    got_pl = fa.flash_attention(q_suf, k, v, k_len, None, True, 0.0, None,
                                True)
    np.testing.assert_allclose(got_pl, want, rtol=2e-5, atol=2e-5)


def test_suffix_causal_per_batch_lengths():
    """Single-token decode (Tq=1) with DIFFERENT valid lengths per batch
    row: each query sits at its own batch's position klen-1."""
    tk, b, h, d = 16, 3, 2, 8
    q_full = _rand((b, h, tk, d), 0)
    k = _rand((b, h, tk, d), 1)
    v = _rand((b, h, tk, d), 2)
    k_len = jnp.asarray([16, 9, 1], jnp.int32)
    full = np.asarray(_oracle(q_full, k, v, k_len=k_len, causal=True))
    q_suf = jnp.stack([q_full[i, :, int(k_len[i]) - 1: int(k_len[i]), :]
                       for i in range(b)])
    want = np.stack([full[i, :, int(k_len[i]) - 1: int(k_len[i]), :]
                     for i in range(b)])
    for fn in (fa.reference_attention,
               lambda *a: fa.flash_attention(*a, True)):
        got = fn(q_suf, k, v, k_len, None, True, 0.0, None)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_suffix_causal_grad_parity():
    """Backward parity for the chunked-decode shape: grads of the suffix
    call equal the corresponding grads of the sliced full-length
    objective (rows outside the suffix contribute nothing).  Slow: two
    interpret-mode backward kernel compiles; the fwd parity set above
    stays tier-1."""
    tk, tq, klen, b, h, d = 16, 4, 11, 2, 2, 8
    q_full = _rand((b, h, tk, d), 0)
    k = _rand((b, h, tk, d), 1)
    v = _rand((b, h, tk, d), 2)
    k_len = jnp.asarray([klen] * b, jnp.int32)
    w = _rand((b, h, tq, d), 3)
    lo = klen - tq

    def f_full(qf, k, v):
        out = _oracle(qf, k, v, k_len=k_len, causal=True)
        return jnp.sum(w * out[:, :, lo:klen, :])

    gq_full, gk_full, gv_full = jax.grad(f_full, (0, 1, 2))(q_full, k, v)
    q_suf = q_full[:, :, lo:klen, :]
    for fn in (lambda q, k, v: fa.flash_attention(q, k, v, k_len, None,
                                                  True, 0.0, None, True),
               lambda q, k, v: fa.reference_attention(q, k, v, k_len,
                                                      None, True, 0.0)):
        f = lambda q, k, v: jnp.sum(w * fn(q, k, v))  # noqa: E731
        gq, gk, gv = jax.grad(f, (0, 1, 2))(q_suf, k, v)
        np.testing.assert_allclose(gq, gq_full[:, :, lo:klen, :],
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(gk, gk_full, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(gv, gv_full, rtol=2e-4, atol=2e-4)


def test_fused_attention_op_rejects_query_longer_than_keys():
    """Tq > Tk under causal stays a build-time error (a suffix cannot be
    longer than the sequence it suffixes); Tq < Tk now builds."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        q = fluid.layers.data("q", shape=[2, 8, 4])
        k = fluid.layers.data("k", shape=[2, 4, 4])
        v = fluid.layers.data("vv", shape=[2, 4, 4])
        with pytest.raises(ValueError, match="Tq <= Tk"):
            fluid.layers.fused_attention(q, k, v, causal=True)
        # the decode shape builds: Tq=4 suffix against Tk=8 keys
        q2 = fluid.layers.data("q2", shape=[2, 4, 4])
        k2 = fluid.layers.data("k2", shape=[2, 8, 4])
        v2 = fluid.layers.data("v2", shape=[2, 8, 4])
        out = fluid.layers.fused_attention(q2, k2, v2, causal=True)
        assert tuple(out.shape) == (-1, 2, 4, 4)
