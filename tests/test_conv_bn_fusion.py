"""conv+BN fusion pass: structural rewrite + numerical parity.

The fused program (transpiler.fuse_conv_bn + bn_act_conv2d Pallas
kernels, interpret-mode on CPU) must match the unfused program's loss,
gradients (via updated params), and running statistics over several
training steps of a bottleneck-style CNN.
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _build(fuse, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[8, 6, 6])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        # bottleneck-ish: 1x1 -> bn+relu -> 1x1 -> bn+relu -> 3x3 -> bn
        # with a residual add, so the pass sees absorbed convs, a
        # stats-producing conv, an un-absorbed (3x3) consumer, and a
        # multi-consumer bn output
        c1 = fluid.layers.conv2d(img, num_filters=16, filter_size=1,
                                 bias_attr=False)
        b1 = fluid.layers.batch_norm(c1, act="relu")
        c2 = fluid.layers.conv2d(b1, num_filters=8, filter_size=1,
                                 bias_attr=False)
        b2 = fluid.layers.batch_norm(c2, act="relu")
        c3 = fluid.layers.conv2d(b2, num_filters=8, filter_size=3,
                                 padding=1, bias_attr=False)
        b3 = fluid.layers.batch_norm(c3, act=None)
        res = fluid.layers.elementwise_add(x=b3, y=img, act="relu")
        pool = fluid.layers.pool2d(res, pool_size=6, pool_type="avg",
                                   global_pooling=True)
        pred = fluid.layers.fc(pool, size=5, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        if fuse:
            n = fluid.transpiler.fuse_conv_bn(main)
            assert n == 3, "expected all three BNs decomposed, got %d" % n
            types = [op.type for op in main.global_block().ops]
            assert "batch_norm" not in types
            assert types.count("bn_act_conv2d") == 2   # c1 + c2(absorbed)
            assert "stats_finalize" in types           # c2's stats ride c2
            assert "batch_stats" in types              # c3 (3x3) needs one
            assert types.count("bn_update_stats") == 3
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def _run(fuse, steps=4):
    main, startup, loss = _build(fuse)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feeds = [{"img": rng.rand(4, 8, 6, 6).astype("float32"),
              "label": rng.randint(0, 5, (4, 1)).astype("int64")}
             for _ in range(steps)]
    stat_names = []
    for op in main.global_block().ops:
        if op.type in ("batch_norm", "bn_update_stats"):
            stat_names += op.inputs["Mean"] + op.inputs["Variance"]
    assert stat_names
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for f in feeds:
            l, = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(l[0]))
        # positional list: the unique-name counter differs between the
        # two program builds, but op order (and thus stat order) matches
        stats = [np.array(scope.var(n)) for n in stat_names]
    return losses, stats


def test_fused_matches_unfused_training():
    base_losses, base_stats = _run(fuse=False)
    fused_losses, fused_stats = _run(fuse=True)
    # same seeds, same data: losses must track through several updates
    # (gradients therefore match through the fused backward)
    np.testing.assert_allclose(fused_losses, base_losses, rtol=2e-3,
                               atol=2e-4)
    # running statistics track: step 1 is bit-near-exact (measured
    # 2e-7); over several updates tiny fp reduction-order differences
    # compound through the weights, so the multi-step bound is looser
    assert len(fused_stats) == len(base_stats) and base_stats
    for a, b in zip(fused_stats, base_stats):
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=2e-3)


def test_fusion_pass_respects_two_pass_flag():
    fluid.set_flags({"FLAGS_bn_two_pass": True})
    try:
        main, _, _ = _build(fuse=False)
        with fluid.program_guard(main, fluid.Program()):
            assert fluid.transpiler.fuse_conv_bn(main) == 0
    finally:
        fluid.set_flags({"FLAGS_bn_two_pass": False})


def test_fused_infer_mode_untouched():
    """is_test BNs must not be decomposed (inference uses global stats)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[4, 5, 5])
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=1,
                                bias_attr=False)
        b = fluid.layers.batch_norm(c, act="relu", is_test=True)
        fluid.layers.mean(b)
        assert fluid.transpiler.fuse_conv_bn(main) == 0
        assert any(op.type == "batch_norm"
                   for op in main.global_block().ops)


@pytest.mark.parametrize("hw", [512, 9000])
def test_bn_act_matmul_kernel_parity_interpret(hw):
    """Pallas kernel (interpret mode) vs composed math: forward z/sum/
    sumsq and every vjp cotangent.  hw=9000 exceeds the 8192 HW-block
    cap, so the partial-last-block masking paths are exercised."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import conv_bn

    b, c, o = 2, 64, 64
    assert conv_bn.supported(b, c, o, hw, jnp.float32)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, c, hw).astype("float32"))
    w = jnp.asarray((rng.randn(o, c) * 0.1).astype("float32"))
    mean = jnp.asarray(rng.randn(c).astype("float32") * 0.1)
    var = jnp.asarray((rng.rand(c) + 0.5).astype("float32"))
    gamma = jnp.asarray((rng.rand(c) + 0.5).astype("float32"))
    beta = jnp.asarray(rng.randn(c).astype("float32") * 0.1)
    eps = 1e-5

    shift = jnp.asarray(rng.randn(o).astype("float32"))

    def ref(x, w, mean, var, gamma, beta):
        rstd = jax.lax.rsqrt(var + eps)
        xn = jnp.maximum(
            (x - mean[:, None]) * rstd[:, None] * gamma[:, None]
            + beta[:, None], 0.0)
        z = jnp.einsum("oc,bcx->box", w, xn)
        zc = z - shift[:, None]
        return z, jnp.sum(zc, (0, 2)), jnp.sum(zc * zc, (0, 2))

    def ker(x, w, mean, var, gamma, beta):
        return conv_bn.bn_act_matmul(x, w, mean, var, gamma, beta, shift,
                                     eps, "relu", True, True, True)

    zr, vjp_r = jax.vjp(ref, x, w, mean, var, gamma, beta)
    zk, vjp_k = jax.vjp(ker, x, w, mean, var, gamma, beta)
    for a, bb in zip(zk, zr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-3)
    cts = (jnp.asarray(rng.randn(b, o, hw).astype("float32")),
           jnp.asarray(rng.randn(o).astype("float32")),
           jnp.asarray(rng.randn(o).astype("float32")))
    gr = vjp_r(cts)
    gk = vjp_k(cts)
    names = ["dx", "dw", "dmean", "dvar", "dgamma", "dbeta"]
    for nm, a, bb in zip(names, gk, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-3, atol=5e-2,
            err_msg="cotangent %s mismatch" % nm)
    assert all(np.isfinite(np.asarray(g)).all() for g in gk)


def test_fused_program_keeps_relu_output_fetchable():
    """Regression: the absorbed relu's output var (what layers.batch_norm
    returns to the user) must survive the rewrite for fetching."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[8, 4, 4])
        c1 = fluid.layers.conv2d(img, 8, 1, bias_attr=False)
        b = fluid.layers.batch_norm(c1, act="relu")
        fluid.layers.conv2d(b, 8, 1, bias_attr=False)
        assert fluid.transpiler.fuse_conv_bn(main) == 1
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bv, = exe.run(main,
                      feed={"img": np.random.rand(2, 8, 4, 4
                                                  ).astype("float32")},
                      fetch_list=[b.name])
        assert np.isfinite(bv).all() and (bv >= 0).all()
