"""Serving subsystem tests (ISSUE 11).

Three layers, matching the subsystem's own split:

* the continuous-batching scheduler as a PURE unit — deterministic
  fake-clock admission tests (bucket selection, FIFO head priority,
  slot recycling, timeout expiry, quarantine record format) that run
  without any compiled program;
* the one-shot :class:`InferenceEngine` end to end over a toy MLP
  (tier-1): concurrent submits, output parity with direct execution,
  poison-request quarantine that does NOT kill the engine, SLO metric
  presence;
* the KV-cache decode loop (slow-marked): greedy generation through the
  :class:`GenerationEngine` reproduces the score program's full-forward
  logits bit-nearly at every decoded position, and in-flight slot
  recycling completes more requests than there are slots.
"""

import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import (BatchPlan, ContinuousBatchingScheduler,
                                GenerationEngine, InferenceEngine,
                                PoisonedRequestError, RequestTimeoutError,
                                ServingMetrics, build_decoder_lm)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# scheduler: pure control logic under a fake clock
# ---------------------------------------------------------------------------

def test_bucket_selection_smallest_cover():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(4, [8, 16, 32], clock=clk)
    assert s.bucket_for(1) == 8
    assert s.bucket_for(8) == 8
    assert s.bucket_for(9) == 16
    assert s.bucket_for(32) == 32
    with pytest.raises(ValueError, match="exceeds the top bucket"):
        s.submit({}, length=33)


def test_admission_head_picks_bucket_and_fills_fifo():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(4, [8, 16, 32], clock=clk)
    a = s.submit("a", length=12)      # head: bucket 16
    b = s.submit("b", length=20)      # too long for 16 — must wait
    c = s.submit("c", length=3)       # fits 16 — joins a's batch
    plan, expired = s.admit()
    assert not expired
    assert isinstance(plan, BatchPlan) and plan.bucket == 16
    assert plan.requests == [a, c]
    assert a.status == b.status != c.status or True  # a,c running; b queued
    assert a.status == "running" and c.status == "running"
    assert b.status == "queued" and s.queue_depth() == 1
    # the waiting longer request is next in line once slots free
    s.complete(a, None)
    s.complete(c, None)
    plan2, _ = s.admit()
    assert plan2.bucket == 32 and plan2.requests == [b]


def test_slot_recycling_refills_without_drain():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(2, clock=clk)
    r1, r2, r3 = (s.submit(i) for i in range(3))
    plan, _ = s.admit()
    assert plan.requests == [r1, r2] and set(plan.slots) == {0, 1}
    # r2 finishes while r1 keeps running: its slot refills immediately
    s.complete(r2, "done")
    plan2, _ = s.admit()
    assert plan2.requests == [r3]
    assert plan2.slots == [r2.slot]          # the recycled slot
    assert r1.status == "running"            # never drained
    assert s.occupancy() == 1.0


def test_timeout_expiry_queued_and_running():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(1, clock=clk, default_timeout_s=5.0)
    r1 = s.submit("a")
    plan, _ = s.admit()
    assert plan.requests == [r1]
    r2 = s.submit("b")                       # queued behind the one slot
    clk.tick(6.0)
    # queued request expires on the next admission decision
    plan2, expired = s.admit()
    assert plan2 is None and expired == [r2]
    assert r2.status == "expired"
    with pytest.raises(RequestTimeoutError):
        r2.result(0)
    # the running request is reported for eviction, not silently dropped
    assert s.expired_running() == [r1]
    s.fail(r1, RequestTimeoutError("evicted"), status="expired")
    assert s.busy_slots() == 0


def test_fixed_slot_cap_and_max_batch():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(3, clock=clk)
    reqs = [s.submit(i) for i in range(5)]
    plan, _ = s.admit(max_batch=2)
    assert plan.requests == reqs[:2]
    plan2, _ = s.admit()
    assert plan2.requests == [reqs[2]]       # only one slot left
    assert s.queue_depth() == 2


def test_close_fails_pending():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(1, clock=clk)
    r1 = s.submit("a")
    s.admit()
    r2 = s.submit("b")
    s.close()
    for r in (r1, r2):
        with pytest.raises(Exception, match="closed"):
            r.result(0)
    with pytest.raises(Exception, match="closed"):
        s.submit("c")


def test_quarantine_record_format(tmp_path):
    """Guardian-style npz + json sidecar, feed signature included."""
    clk = FakeClock()
    s = ContinuousBatchingScheduler(1, clock=clk)
    req = s.submit({"x": np.zeros((4,), "float32")}, length=0)
    m = ServingMetrics(quarantine_dir=str(tmp_path))
    rec = m.quarantine(req, feed=req.payload, reason="test poison")
    assert rec["path"] and rec["path"].endswith(".npz")
    data = np.load(rec["path"])
    assert data["arr_0"].shape == (4,)
    assert rec["feed_names"] == ["x"]
    assert rec["feed_signature"] == [("x", [4], "float32")]
    import json, os

    side = json.load(open(rec["path"].replace(".npz", ".json")))
    assert side["reason"] == "test poison"
    assert os.path.exists(rec["path"])
    assert m.summary()["counts"]["quarantined"] == 1


# ---------------------------------------------------------------------------
# one-shot InferenceEngine end to end (tier-1)
# ---------------------------------------------------------------------------

@pytest.fixture
def saved_mlp(tmp_path):
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[6])
    h = fluid.layers.fc(x, size=8, act="relu")
    pred = fluid.layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(str(tmp_path / "m"), ["x"],
                                      [pred], exe)
    return str(tmp_path / "m")


def test_engine_serves_toy_mlp_concurrently(saved_mlp):
    eng = InferenceEngine(model_dir=saved_mlp, slots=4, timeout_s=60.0)
    try:
        rng = np.random.RandomState(0)
        xs = [rng.rand(6).astype("float32") for _ in range(10)]
        results = {}

        def client(i):
            results[i] = eng.run({"x": xs[i]}, timeout=120)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(xs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # parity: the engine's batched answers == direct execution
        direct = fluid.Executor(fluid.CPUPlace())
        (want,) = direct.run(eng._program,
                             feed={"x": np.stack(xs)},
                             fetch_list=eng._fetch_vars,
                             scope=eng._scope)
        for i in range(len(xs)):
            np.testing.assert_allclose(results[i][0], want[i],
                                       rtol=1e-6, atol=1e-6)
        summ = eng.metrics.summary()
        assert summ["counts"]["completed"] == len(xs)
        assert summ["counts"]["batches"] >= 1
        assert summ["p50_ms"] is not None and summ["p99_ms"] is not None
    finally:
        eng.close()


@pytest.mark.slow
def test_engine_quarantines_poison_requests_and_survives(saved_mlp,
                                                         tmp_path):
    """A NaN-producing request is rejected + quarantined like a poisoned
    batch; the engine keeps serving (guardian-style request health)."""
    # sqrt of a negative input poisons exactly the rows that feed it
    fluid.default_startup_program().random_seed = 3
    x = fluid.layers.data("x", shape=[4])
    out = fluid.layers.sqrt(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(str(tmp_path / "p"), ["x"],
                                      [out], exe)
    qdir = tmp_path / "quarantine"
    eng = InferenceEngine(model_dir=str(tmp_path / "p"), slots=4,
                          timeout_s=60.0, quarantine_dir=str(qdir))
    try:
        good = eng.submit({"x": np.ones(4, "float32")})
        bad = eng.submit({"x": -np.ones(4, "float32")})
        np.testing.assert_allclose(good.result(120)[0], np.ones(4),
                                   rtol=1e-6)
        with pytest.raises(PoisonedRequestError):
            bad.result(120)
        assert bad.status == "quarantined"
        assert list(qdir.glob("request_*.npz"))
        # the engine is still alive and serving
        again = eng.run({"x": 4.0 * np.ones(4, "float32")}, timeout=120)
        np.testing.assert_allclose(again[0], 2.0 * np.ones(4), rtol=1e-6)
        assert eng.metrics.summary()["counts"]["quarantined"] == 1
    finally:
        eng.close()


def test_engine_times_out_stale_queued_requests(saved_mlp):
    """A request submitted before the loop starts and already past its
    budget expires instead of running."""
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0,
                          start=False)
    req = eng.submit({"x": np.zeros(6, "float32")}, timeout_s=0.001)
    import time

    time.sleep(0.05)
    eng.start()
    with pytest.raises(RequestTimeoutError):
        req.result(30)
    assert req.status == "expired"
    eng.close()


@pytest.mark.slow
def test_engine_bucketed_sequence_padding(tmp_path):
    """Variable-length sequence requests co-batch at bucket bounds; the
    @LEN companion carries each request's true length."""
    fluid.default_startup_program().random_seed = 5
    ids = fluid.layers.data("ids", shape=[1], dtype="int64", lod_level=1)
    emb = fluid.layers.embedding(ids, size=[20, 4])
    pooled = fluid.layers.sequence_pool(emb, "sum")
    out = fluid.layers.fc(pooled, size=2, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        # emb is a PER-TOKEN fetch: its padded time dim must come back
        # trimmed to each request's true length
        fluid.io.save_inference_model(str(tmp_path / "s"),
                                      ["ids", "ids@LEN"], [out, emb],
                                      exe)
    eng = InferenceEngine(model_dir=str(tmp_path / "s"), slots=4,
                          bucket_bounds=[4, 8], timeout_s=60.0)
    try:
        rng = np.random.RandomState(1)
        lens = [2, 4, 3, 7]
        reqs = [eng.submit(
            {"ids": rng.randint(0, 20, (n, 1)).astype("int64")})
            for n in lens]
        rows = [r.result(120) for r in reqs]
        # parity against direct padded execution, one request at a time
        direct = fluid.Executor(fluid.CPUPlace())
        for req, row, n in zip(reqs, rows, lens):
            padded = np.zeros((1, 8, 1), "int64")
            padded[0, :n] = req.payload["ids"]
            want = direct.run(
                eng._program,
                feed={"ids": padded,
                      "ids@LEN": np.asarray([n], "int32")},
                fetch_list=eng._fetch_vars, scope=eng._scope)
            np.testing.assert_allclose(row[0], want[0][0], rtol=1e-5,
                                       atol=1e-6)
            # the per-token fetch comes back TRIMMED to the request's
            # true length, not bucket-padded
            assert row[1].shape == (n, 4), row[1].shape
            np.testing.assert_allclose(row[1], want[1][0][:n],
                                       rtol=1e-5, atol=1e-6)
        # @LEN-companion models reject multi-row requests
        with pytest.raises(ValueError, match="fixed-shape only"):
            eng.submit({"ids": np.zeros((2, 3, 1), "int64")}, rows=2)
    finally:
        eng.close()


@pytest.mark.slow
def test_engine_micro_batch_requests_co_batch(saved_mlp):
    """rows>1 requests (the predictor's Run unit) co-batch with single
    examples; outputs keep each request's own shape."""
    eng = InferenceEngine(model_dir=saved_mlp, slots=8, timeout_s=60.0)
    try:
        rng = np.random.RandomState(3)
        xb = rng.rand(4, 6).astype("float32")
        x1 = rng.rand(6).astype("float32")
        rb = eng.submit({"x": xb}, rows=4)
        r1 = eng.submit({"x": x1})
        outb, out1 = rb.result(120), r1.result(120)
        assert outb[0].shape == (4, 3) and out1[0].shape == (3,)
        direct = fluid.Executor(fluid.CPUPlace())
        (want,) = direct.run(eng._program,
                             feed={"x": np.concatenate([xb, x1[None]])},
                             fetch_list=eng._fetch_vars,
                             scope=eng._scope)
        np.testing.assert_allclose(outb[0], want[:4], rtol=1e-6,
                                   atol=1e-6)
        np.testing.assert_allclose(out1[0], want[4], rtol=1e-6,
                                   atol=1e-6)
        with pytest.raises(ValueError, match="exceed the 8-slot"):
            eng.submit({"x": rng.rand(9, 6).astype("float32")}, rows=9)
    finally:
        eng.close()


def test_scheduler_multi_row_admission():
    clk = FakeClock()
    s = ContinuousBatchingScheduler(8, clock=clk)
    a = s.submit("a", rows=5)
    b = s.submit("b", rows=4)        # 5+4 > 8: waits
    c = s.submit("c", rows=3)        # fills around b
    plan, _ = s.admit()
    assert plan.requests == [a, c] and len(plan.slots) == 8
    assert s.occupancy() == 1.0
    s.complete(a, None)
    plan2, _ = s.admit()
    assert plan2.requests == [b]
    assert set(plan2.slots) <= set(range(8))


# ---------------------------------------------------------------------------
# decoder programs + KV-cache decode (slow: compiles three programs)
# ---------------------------------------------------------------------------

def test_decoder_programs_share_parameter_names():
    """Prefill and decode read the SAME weights the score program
    initializes — cross-program weight sharing is by explicit name."""
    spec = build_decoder_lm(vocab_size=11, max_len=16, slots=2,
                            n_layer=1, n_head=2, d_model=8, d_inner=16)

    def params(prog):
        from paddle_tpu.framework import Parameter

        return {v.name for v in prog.list_vars()
                if isinstance(v, Parameter)}

    score, prefill, decode = (params(spec.score_program),
                              params(spec.prefill_program),
                              params(spec.decode_program))
    assert score == prefill == decode
    assert "declm_tok_emb" in score
    # cache vars are persistable NON-parameters of prefill/decode only
    cache_names = set(spec.cache.names())
    pf_vars = {v.name for v in spec.prefill_program.list_vars()}
    dc_vars = {v.name for v in spec.decode_program.list_vars()}
    assert cache_names <= pf_vars and cache_names <= dc_vars


@pytest.mark.slow
def test_kv_cache_decode_matches_full_forward_recompute():
    """The acceptance contract: greedy decode through the donated
    KV-cache loop reproduces the score program's logits at every
    generated position (same weights, full-forward recompute)."""
    spec = build_decoder_lm(vocab_size=23, max_len=32, slots=4,
                            n_layer=2, n_head=2, d_model=16, d_inner=32)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=5, record_logits=True,
                           timeout_s=300.0)
    try:
        prompts = [[3, 5, 7], [2, 9, 4, 6, 8], [1, 2],
                   [11, 12, 13, 14]]
        results = [eng.submit(p).result(600) for p in prompts]
        exe = fluid.Executor(fluid.CPUPlace())
        for p, res in zip(prompts, results):
            assert len(res["tokens"]) == 5
            seq = p + res["tokens"]
            t = len(seq)
            (full,) = exe.run(
                spec.score_program,
                feed={"tok": np.asarray(seq, "int64").reshape(1, t, 1),
                      "tok@LEN": np.asarray([t], "int32"),
                      "pos": np.arange(t, dtype="int64").reshape(1, t, 1)},
                fetch_list=[spec.score_logits], scope=eng._scope)
            full = np.asarray(full)[0]
            for k, step_logits in enumerate(res["logits"]):
                np.testing.assert_allclose(
                    step_logits, full[len(p) - 1 + k], rtol=2e-4,
                    atol=2e-4)
    finally:
        eng.close()


@pytest.mark.slow
def test_generation_engine_recycles_slots_in_flight():
    """More requests than slots all complete — freed slots refill
    between decode steps without draining the batch — and the decode
    loop compiles ONCE (one signature) regardless of traffic."""
    spec = build_decoder_lm(vocab_size=13, max_len=16, slots=2,
                            n_layer=1, n_head=2, d_model=8, d_inner=16,
                            prefix="declm2")
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=3, timeout_s=300.0,
                           bucket_bounds=[4])
    try:
        reqs = [eng.submit([1 + i, 2 + i]) for i in range(5)]
        outs = [r.result(600) for r in reqs]
        assert all(len(o["tokens"]) == 3 for o in outs)
        counts = eng.metrics.summary()["counts"]
        assert counts["completed"] == 5
        assert counts["decode_steps"] >= 2
        # one compiled decode signature total: the decode executor saw
        # exactly one (program, feed-signature) pair
        sigs = {k[3] for k in eng._exe_decode._cache}
        assert len(eng._exe_decode._cache) == 1, sigs
    finally:
        eng.close()
