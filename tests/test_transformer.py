"""Transformer model tests (reference dist_transformer.py /
machine_translation.py capability): tiny config trains end-to-end on
padded sequences; masked loss ignores padding."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import transformer as tfm


def _tiny_cfg():
    return dict(n_layer=2, n_head=2, d_model=32, d_inner=64,
                dropout_rate=0.0)


def _build(src_vocab=20, tgt_vocab=20, max_len=8, smooth=0.1):
    src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                            lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                            lod_level=1)
    label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                              lod_level=1)
    cost, logits = tfm.transformer(
        src, tgt, label, max_len, max_len, src_vocab, tgt_vocab,
        label_smooth_eps=smooth, **_tiny_cfg())
    return src, tgt, label, cost, logits


def _copy_task_batch(rng, b, t_fixed, vocab):
    """Copy task: target = source; learnable quickly by a tiny model."""
    rows = []
    for _ in range(b):
        ln = rng.randint(2, t_fixed + 1)
        seq = rng.randint(2, vocab, (ln,)).astype("int64")
        # teacher forcing: tgt = <bos>=1 + seq[:-1], label = seq
        tgt = np.concatenate([[1], seq[:-1]]).astype("int64")
        rows.append((seq, tgt, seq))
    return rows


@pytest.mark.slow
def test_transformer_trains_on_copy_task():
    # slow: a ~21s convergence run (measured --durations, r11) — the
    # same class as the slow-marked cifar/book-model convergence runs
    # (tier-1 budget); the padding/masking/structure tests below keep
    # the transformer covered in tier-1
    fluid.default_main_program().random_seed = 5
    fluid.default_startup_program().random_seed = 5
    src, tgt, label, cost, _ = _build(smooth=0.0)
    opt = fluid.optimizer.Adam(learning_rate=3e-3)
    opt.minimize(cost)

    feeder = fluid.DataFeeder(feed_list=[src, tgt, label], pad_to=8)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(60):
        feed = feeder.feed(_copy_task_batch(rng, 8, 8, 20))
        (lv,) = exe.run(feed=feed, fetch_list=[cost])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert all(np.isfinite(losses)), losses
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.85, losses


def test_transformer_loss_ignores_padding():
    """Same data padded to different lengths must give the same loss."""
    fluid.default_main_program().random_seed = 3
    fluid.default_startup_program().random_seed = 3
    src, tgt, label, cost, _ = _build(max_len=12)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(1)
    rows = _copy_task_batch(rng, 4, 6, 20)

    feeder6 = fluid.DataFeeder(feed_list=[src, tgt, label], pad_to=6)
    feeder12 = fluid.DataFeeder(feed_list=[src, tgt, label], pad_to=12)
    (l6,) = exe.run(feed=feeder6.feed(rows), fetch_list=[cost])
    (l12,) = exe.run(feed=feeder12.feed(rows), fetch_list=[cost])
    np.testing.assert_allclose(np.asarray(l6).ravel(),
                               np.asarray(l12).ravel(), rtol=2e-4)
