"""The deployment/preprocessing utility suite (reference
python/paddle/utils/): image_util transforms, dataset creation,
config dumps, model merging, plotcurve, torch weight import, compat."""

import io
import json
import os

import numpy as np
import pytest
from PIL import Image

import paddle_tpu as fluid
from paddle_tpu import compat
from paddle_tpu.utils import (
    dump_config,
    dump_v2_config,
    image_util,
    make_model_diagram,
    merge_model,
    plotcurve,
    preprocess_img,
    preprocess_util,
    show_pb,
    torch2paddle,
)


# ---------------------------------------------------------------- image_util

def test_image_util_flip_and_crop():
    im = np.arange(3 * 8 * 10, dtype="float32").reshape(3, 8, 10)
    assert np.array_equal(image_util.flip(im), im[:, :, ::-1])
    gray = im[0]
    assert np.array_equal(image_util.flip(gray), gray[:, ::-1])

    # center crop of an even-sized image takes the middle window
    pic = image_util.crop_img(im, 4, color=True, test=True)
    assert pic.shape == (3, 4, 4)
    np.testing.assert_array_equal(pic, im[:, 2:6, 3:7])
    # images smaller than the crop get zero-padded, content centered
    small = np.ones((3, 2, 2), "float32")
    padded = image_util.crop_img(small, 4, test=True)
    assert padded.shape == (3, 4, 4)
    assert padded.sum() == small.sum()
    np.testing.assert_array_equal(padded[:, 1:3, 1:3], small)


def test_image_util_jpeg_preprocess_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 255, (12, 16, 3)).astype("uint8")
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "png")
    # decode_jpeg handles any PIL-decodable payload; returns CHW
    chw = image_util.decode_jpeg(buf.getvalue())
    assert chw.shape == (3, 12, 16)
    np.testing.assert_array_equal(chw, arr.transpose(2, 0, 1))

    mean = np.zeros((3, 8, 8), "float32")
    flat = image_util.preprocess_img(chw, mean, 8, is_train=False)
    assert flat.shape == (3 * 8 * 8,)
    np.testing.assert_array_equal(
        flat.reshape(3, 8, 8), chw[:, 2:10, 4:12].astype("float32"))


def test_image_util_oversample_and_transformer():
    img = np.random.RandomState(1).rand(8, 8, 3).astype("float32")
    crops = image_util.oversample([img], (4, 4))
    assert crops.shape == (10, 4, 4, 3)
    # second half is the mirrored first half
    np.testing.assert_array_equal(crops[5:], crops[:5][:, :, ::-1, :])
    # center crop is the middle window
    np.testing.assert_array_equal(crops[4], img[2:6, 2:6, :])

    t = image_util.ImageTransformer(transpose=(2, 0, 1),
                                    channel_swap=(2, 1, 0),
                                    mean=np.array([1.0, 2.0, 3.0]))
    out = t.transformer(img)
    ref = img.transpose(2, 0, 1)[(2, 1, 0), :, :] \
        - np.array([1.0, 2.0, 3.0])[:, None, None]
    np.testing.assert_allclose(out, ref, rtol=1e-6)


# ------------------------------------------------- preprocess_{util,img}

def _write_image_tree(root, n_per_label=3, size=10):
    rng = np.random.RandomState(7)
    for split in ("train", "test"):
        for label in ("cat", "dog"):
            d = os.path.join(root, split, label)
            os.makedirs(d)
            for i in range(n_per_label):
                arr = rng.randint(0, 255, (size + 2, size, 3)).astype("uint8")
                Image.fromarray(arr).save(os.path.join(d, "%d.png" % i))


def test_image_dataset_creation(tmp_path):
    _write_image_tree(str(tmp_path))
    creator = preprocess_img.ImageClassificationDatasetCreater(
        str(tmp_path), batch_size=4, processed_image_size=8)
    out = creator.create_dataset()
    assert set(out) == {"train", "test"}

    batch = preprocess_util.load_file(out["train"][0])
    assert batch["label_set"] == {"cat": 0, "dog": 1}
    assert len(batch["data"]) == len(batch["labels"]) == 4
    # stored records decode back to images
    arr = image_util.decode_jpeg(batch["data"][0])
    assert arr.shape[0] == 3 and min(arr.shape[1:]) == 8

    # meta round-trips through image_util.load_meta
    mean = image_util.load_meta(
        os.path.join(creator.output_path, creator.meta_filename),
        mean_img_size=8, crop_size=6)
    assert mean.shape == (3, 6, 6) and np.isfinite(mean).all()

    lists = open(os.path.join(creator.output_path, "train.list")).read()
    assert len(lists.splitlines()) == len(out["train"])


# ---------------------------------------------- config dumps + merge + show

def _v1_config():
    from paddle_tpu import trainer_config_helpers as tch

    tch.settings(batch_size=8, learning_rate=0.1)
    x = tch.data_layer(name="x", size=6)
    h = tch.fc_layer(input=x, size=4, act=tch.ReluActivation())
    tch.outputs(h)


def test_dump_config_and_diagram(tmp_path):
    out = io.StringIO()
    text = dump_config.dump_config(_v1_config, out=out)
    doc = json.loads(text)
    assert doc["opt_config"]["batch_size"] == 8
    assert any(op["type"] == "relu" or op["type"] == "mul"
               for b in doc["model_config"]["program"]["blocks"]
               for op in b["ops"])

    dot = str(tmp_path / "net.dot")
    make_model_diagram.make_diagram(_v1_config, dot)
    assert "digraph" in open(dot).read()


def test_dump_v2_merge_show(tmp_path):
    from paddle_tpu import v2 as paddle

    paddle.reset()
    try:
        x = paddle.layer.data(name="x",
                              type=paddle.data_type.dense_vector(6))
        pred = paddle.layer.fc(input=x, size=3,
                               act=paddle.activation.Softmax())
        params = paddle.parameters.create(pred)

        model_path = str(tmp_path / "model.json")
        doc = dump_v2_config.dump_v2_config(pred, model_path, binary=True)
        assert doc["fetch_names"] == [pred.name]

        tar_path = str(tmp_path / "params.tar")
        with open(tar_path, "wb") as f:
            params.to_tar(f)
        bundle = str(tmp_path / "bundle.tar")
        merge_model.merge_v2_model(pred, tar_path, bundle)

        doc2, weights = merge_model.load_merged_model(bundle)
        assert doc2["program"] == doc["program"]
        assert set(weights) == set(params.names())

        buf = io.StringIO()
        show_pb.show(bundle, out=buf)
        assert "feeds:" in buf.getvalue() and "mul" in buf.getvalue()
    finally:
        paddle.reset()


# ----------------------------------------------------------------- plotcurve

def test_plotcurve(tmp_path):
    log = io.StringIO(
        "Pass=0 Batch=0 AvgCost=2.5 Eval: err=0.9\n"
        "garbage line\n"
        "Pass=0 Batch=1 AvgCost=1.25 Eval: err=0.5\n")
    out = str(tmp_path / "curve.png")
    with open(out, "wb") as f:
        series = plotcurve.plot_paddle_curve(["AvgCost", "err"], log, f)
    assert series["AvgCost"] == [2.5, 1.25]
    assert series["err"] == [0.9, 0.5]
    assert open(out, "rb").read(4).startswith(b"\x89PNG")


# -------------------------------------------------------------- torch2paddle

def test_torch2paddle_fc_import():
    import torch

    lin = torch.nn.Linear(5, 3)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[5])
        y = fluid.layers.fc(x, size=3,
                            param_attr=fluid.ParamAttr(name="fc_w"),
                            bias_attr=fluid.ParamAttr(name="fc_b"))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            written = torch2paddle.torch2paddle(
                lin, scope=scope, program=fluid.default_main_program(),
                name_map={"weight": "fc_w", "bias": "fc_b"},
                transpose_fc=True)
            assert sorted(written) == ["fc_b", "fc_w"]
            xin = np.random.RandomState(3).rand(2, 5).astype("float32")
            (out,) = exe.run(feed={"x": xin}, fetch_list=[y])
    ref = lin(torch.tensor(xin)).detach().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)

    # module-aware transpose_fc=True must NOT touch non-Linear 2-D weights
    class EmbNet(__import__("torch").nn.Module):
        def __init__(self):
            import torch
            super().__init__()
            self.emb = torch.nn.Embedding(4, 4)
            self.lin = torch.nn.Linear(4, 4)

    net = EmbNet()
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        ids = fluid.layers.data("ids", shape=[1], dtype="int64")
        e = fluid.layers.embedding(ids, size=[4, 4],
                                   param_attr=fluid.ParamAttr(name="emb_w"))
        fluid.layers.fc(e, size=4, param_attr=fluid.ParamAttr(name="lin_w"))
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            fluid.Executor(fluid.CPUPlace()).run(
                fluid.default_startup_program())
            torch2paddle.torch2paddle(
                net, scope=scope2, program=fluid.default_main_program(),
                name_map={"emb.weight": "emb_w", "lin.weight": "lin_w"},
                transpose_fc=True)
            np.testing.assert_array_equal(
                np.asarray(scope2.find_var("emb_w")),
                net.emb.weight.detach().numpy())        # NOT transposed
            np.testing.assert_array_equal(
                np.asarray(scope2.find_var("lin_w")),
                net.lin.weight.detach().numpy().T)      # transposed

    with pytest.raises(ValueError, match="no torch tensors matched"):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            fluid.layers.fc(fluid.layers.data("x", shape=[5]), size=3)
            torch2paddle.torch2paddle(
                lin, scope=fluid.Scope(),
                program=fluid.default_main_program())


# -------------------------------------------------------------------- compat

def test_compat():
    assert compat.to_text(b"ab") == "ab"
    assert compat.to_bytes("ab") == b"ab"
    assert compat.to_text([b"a", {b"k": b"v"}]) == ["a", {"k": "v"}]
    l = [b"x"]
    assert compat.to_text(l, inplace=True) is l and l == ["x"]
    assert compat.round(2.5) == 3.0 and compat.round(-2.5) == -3.0
    assert compat.round(0.125, 2) == 0.13
    assert compat.floor_division(7, 2) == 3
    assert compat.get_exception_message(ValueError("boom")) == "boom"
    # unknown types pass through untouched (reference else-branch)
    t = (b"a", b"b")
    assert compat.to_text(t) is t
    assert compat.to_text(np.int64(3)) == np.int64(3)
