"""High-level-API book chapters (reference
tests/book/high-level-api/*): the same model flows driven end-to-end
through contrib.Trainer + Inferencer — fit_a_line (linear regression),
recognize_digits (conv net), word2vec (n-gram embedding) — on synthetic
data with real train/save/infer round-trips."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib import Inferencer, Trainer

L = fluid.layers


def _losses_collector(losses):
    def handler(event):
        if hasattr(event, "metrics"):
            losses.append(float(np.asarray(event.metrics[0]).reshape(())))
    return handler


def test_fit_a_line_highlevel(tmp_path):
    """Linear regression learns y = Xw + b (the fit_a_line chapter)."""
    W = np.array([[1.5], [-2.0], [0.5], [3.0]], "float32")

    def net():
        x = L.data("x", shape=[4])
        return L.fc(x, size=1, act=None)

    def train_func():
        y_pred = net()
        y = L.data("y", shape=[1])
        return L.mean(L.square_error_cost(y_pred, y))

    rng = np.random.RandomState(0)
    xs = rng.rand(128, 4).astype("float32")
    ys = xs @ W + 0.7
    data = list(zip(xs, ys))

    def reader():
        for i in range(0, len(data), 16):
            yield data[i:i + 16]

    losses = []
    trainer = Trainer(train_func=train_func,
                      optimizer_func=lambda: fluid.optimizer.SGD(0.3),
                      place=fluid.CPUPlace())
    trainer.train(num_epochs=30, event_handler=_losses_collector(losses),
                  reader=reader, feed_order=["x", "y"])
    assert losses[-1] < 0.01, losses[-1]

    param_dir = str(tmp_path / "fit_a_line")
    trainer.save_params(param_dir)
    inferencer = Inferencer(infer_func=net, param_path=param_dir,
                            place=fluid.CPUPlace())
    probe = rng.rand(8, 4).astype("float32")
    (pred,) = inferencer.infer({"x": probe})
    np.testing.assert_allclose(pred, probe @ W + 0.7, atol=0.25)


def test_recognize_digits_conv_highlevel(tmp_path):
    """simple_img_conv_pool stack from the recognize_digits chapter on a
    synthetic separable image task."""
    def net():
        img = L.data("img", shape=[1, 12, 12])
        conv_pool = fluid.nets.simple_img_conv_pool(
            input=img, filter_size=3, num_filters=4, pool_size=2,
            pool_stride=2, act="relu")
        return L.fc(conv_pool, size=3, act="softmax")

    def train_func():
        pred = net()
        label = L.data("label", shape=[1], dtype="int64")
        return L.mean(L.cross_entropy(pred, label))

    rng = np.random.RandomState(1)
    data = []
    for _ in range(96):
        cls = rng.randint(0, 3)
        img = rng.rand(1, 12, 12).astype("float32") * 0.1
        img[0, cls * 4:(cls + 1) * 4, :] += 1.0   # bright band per class
        data.append((img, cls))

    def reader():
        for i in range(0, len(data), 16):
            yield data[i:i + 16]

    losses = []
    trainer = Trainer(train_func=train_func,
                      optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                      place=fluid.CPUPlace())
    trainer.train(num_epochs=8, event_handler=_losses_collector(losses),
                  reader=reader, feed_order=["img", "label"])
    assert losses[-1] < 0.2, losses[-1]

    param_dir = str(tmp_path / "digits")
    trainer.save_params(param_dir)
    inferencer = Inferencer(infer_func=net, param_path=param_dir,
                            place=fluid.CPUPlace())
    imgs = np.stack([d[0] for d in data[:12]])
    (probs,) = inferencer.infer({"img": imgs})
    acc = (probs.argmax(1) == np.array([d[1] for d in data[:12]])).mean()
    assert acc > 0.8, acc


def test_word2vec_ngram_highlevel(tmp_path):
    """N-gram next-word model (word2vec chapter): four embedded context
    words -> softmax over the vocab; learns a deterministic sequence."""
    V, EMB, N = 12, 8, 4

    def net():
        words = [L.data("w%d" % i, shape=[1], dtype="int64")
                 for i in range(N)]
        embs = [L.embedding(w, size=[V, EMB],
                            param_attr=fluid.ParamAttr(name="emb"))
                for w in words]
        embs = [L.reshape(e, shape=[-1, EMB]) for e in embs]
        hidden = L.fc(L.concat(embs, axis=1), size=32, act="relu")
        return L.fc(hidden, size=V, act="softmax")

    def train_func():
        pred = net()
        nxt = L.data("next", shape=[1], dtype="int64")
        return L.mean(L.cross_entropy(pred, nxt))

    # deterministic cyclic sequence: next = (sum of context) % V
    rng = np.random.RandomState(2)
    data = []
    for _ in range(160):
        ctx = rng.randint(0, V, size=N)
        data.append(tuple(np.array([c], "int64") for c in ctx)
                    + (np.array([ctx.sum() % V], "int64"),))

    def reader():
        for i in range(0, len(data), 16):
            yield data[i:i + 16]

    losses = []
    trainer = Trainer(train_func=train_func,
                      optimizer_func=lambda: fluid.optimizer.Adam(5e-3),
                      place=fluid.CPUPlace())
    feed_order = ["w%d" % i for i in range(N)] + ["next"]
    trainer.train(num_epochs=30, event_handler=_losses_collector(losses),
                  reader=reader, feed_order=feed_order)
    assert losses[-1] < losses[0] * 0.7

    param_dir = str(tmp_path / "w2v")
    trainer.save_params(param_dir)
    inferencer = Inferencer(infer_func=net, param_path=param_dir,
                            place=fluid.CPUPlace())
    feed = {"w%d" % i: np.full((6, 1), i, "int64") for i in range(N)}
    (probs,) = inferencer.infer(feed)
    assert probs.shape == (6, V)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(6), atol=1e-5)
