"""The v1 trainer-config DSL dialect (reference
python/paddle/trainer_config_helpers/) re-hosted on the Program IR:
``*_layer`` calls, mixed_layer projections, layer math, settings(),
parse_network_config, and composition with the v2 trainer for
execution — three API dialects, one engine.
"""

import sys
import types

import numpy as np
import pytest

import paddle_tpu.trainer_config_helpers as tch
from paddle_tpu import v2 as paddle


@pytest.fixture(autouse=True)
def _fresh():
    tch.reset_parser()
    yield
    tch.reset_parser()


def test_parse_network_config_mnist_style():
    def net():
        img = tch.data_layer("img", size=784, height=28, width=28)
        conv = tch.simple_img_conv_pool(img, filter_size=5, num_filters=8,
                                        pool_size=2, pool_stride=2,
                                        act="relu")
        hidden = tch.fc_layer(conv, size=64, act=tch.ReluActivation())
        pred = tch.fc_layer(hidden, size=10, act=tch.SoftmaxActivation())
        lbl = tch.data_layer(
            "label", size=10,
            type=paddle.data_type.integer_value(10))
        cost = tch.classification_cost(input=pred, label=lbl)
        tch.outputs(cost)

    model = tch.parse_network_config(net)
    assert model.input_layer_names == ["img", "label"]
    assert len(model.output_layer_names) == 1
    d = model.to_dict()
    op_types = [op["type"] for b in d["program"]["blocks"]
                for op in b["ops"]]
    assert "conv2d" in op_types and "cross_entropy" in op_types


def test_mixed_layer_context_and_direct_forms():
    x = tch.data_layer("x", size=6)
    ids = tch.data_layer("ids", size=0,
                         type=paddle.data_type.integer_value(20))
    with tch.mixed_layer(size=4, bias_attr=True,
                         act=tch.ReluActivation()) as m:
        m += tch.full_matrix_projection(x)
        m += tch.table_projection(ids, size=4)
    direct = tch.mixed_layer(input=[tch.identity_projection(x, offset=2,
                                                            size=4)])
    assert m.var.shape[-1] == 4
    assert direct.var.shape[-1] == 4
    dm = tch.mixed_layer(input=tch.dotmul_projection(x))
    assert dm.var.shape[-1] == 6


def test_mixed_layer_rejects_bad_input():
    x = tch.data_layer("x", size=6)
    m = tch.mixed_layer(size=4)
    with pytest.raises(TypeError):
        m += x  # a Layer is not a projection
    with pytest.raises(ValueError):
        tch.mixed_layer(input=[])


def test_layer_math_numerics():
    """0.5 * x + 2 - x == 2 - 0.5 x, checked through infer."""
    x = tch.data_layer("x", size=3)
    y = 0.5 * x + 2 - x
    params = paddle.parameters.create(y)
    xs = np.arange(6, dtype="float32").reshape(2, 3)
    out = paddle.infer(output_layer=y, parameters=params,
                       input=[(row,) for row in xs])
    np.testing.assert_allclose(out, 2.0 - 0.5 * xs, rtol=1e-5)


def test_elementwise_and_seq_layers_shapes():
    a = tch.data_layer("a", size=5)
    b = tch.data_layer("b", size=5)
    prod = tch.dot_prod_layer(a, b)
    assert prod.var.shape[-1] == 1
    mul = a * b
    assert mul.var.shape[-1] == 5
    sc = tch.scaling_layer(a, prod)
    assert sc.var.shape[-1] == 5
    cost = tch.smooth_l1_cost(a, b)
    assert cost.var.shape in ((), (1,))


def test_settings_maps_to_v2_optimizer():
    st = tch.settings(
        batch_size=32, learning_rate=0.01,
        learning_method=tch.AdamOptimizer(beta1=0.8),
        regularization=tch.L2Regularization(1e-4),
        gradient_clipping_threshold=5.0,
        model_average=tch.ModelAverage(average_window=0.5))
    v2opt = st.to_v2()
    assert isinstance(v2opt, paddle.optimizer.Adam)
    assert v2opt.beta1 == 0.8
    assert v2opt.learning_rate == 0.01
    assert v2opt.gradient_clipping_threshold == 5.0
    fluid_opt = v2opt.to_optimizer()
    assert type(fluid_opt).__name__ == "AdamOptimizer"


def test_settings_async_refused():
    with pytest.raises(NotImplementedError):
        tch.settings(batch_size=8, is_async=True)


def test_settings_lr_decay_refused_not_silently_constant():
    st = tch.settings(batch_size=8, learning_rate=0.1,
                      learning_rate_decay_a=0.5,
                      learning_rate_decay_b=0.75,
                      learning_rate_schedule="discexp")
    with pytest.raises(NotImplementedError):
        st.to_v2()


def test_img_pool_geometry_kwargs_honored():
    img = tch.data_layer("im", size=1 * 7 * 7, height=7, width=7)
    ceil = tch.img_pool_layer(img, pool_size=2, stride=2, ceil_mode=True)
    floor = tch.img_pool_layer(img, pool_size=2, stride=2)
    assert ceil.var.shape[-2:] == (4, 4)
    assert floor.var.shape[-2:] == (3, 3)
    rect = tch.img_pool_layer(img, pool_size=3, pool_size_y=2,
                              stride=2, stride_y=1)
    assert rect.var.shape[-2:] == (6, 3)


def test_v1_config_trains_end_to_end():
    """A full v1-style config (settings + network + outputs) trains
    through the v2 trainer: the dialects share one graph + engine."""
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype("float32")
    xs = rng.randn(128, 4).astype("float32")
    ys = xs @ w + 0.01 * rng.randn(128, 1).astype("float32")

    tch.settings(batch_size=32, learning_rate=0.1,
                 learning_method=tch.MomentumOptimizer(momentum=0.9))
    x = tch.data_layer("x", size=4)
    pred = tch.fc_layer(x, size=1)
    lbl = tch.data_layer("y", size=1)
    cost = tch.square_error_cost(input=pred, label=lbl)
    tch.outputs(cost)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost, params,
                                 tch.current_settings().to_v2())

    def reader():
        for x_, y_ in zip(xs, ys):
            yield x_, y_

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(paddle.batch(reader, 32), num_passes=8,
                  event_handler=handler)
    assert costs[-1] < costs[0] * 0.2, (costs[0], costs[-1])


def test_data_sources_resolve():
    mod = types.ModuleType("_tch_provider_mod")

    def process(file_list, args):
        for i in range(3):
            yield [float(i)], [float(2 * i)]

    mod.process = process
    sys.modules["_tch_provider_mod"] = mod
    try:
        tch.define_py_data_sources2(
            train_list="train.list", test_list=None,
            module="_tch_provider_mod", obj="process")
        make = tch.resolve_provider("train")
        rows = list(make())
        assert len(rows) == 3 and rows[2] == ([2.0], [4.0])
        with pytest.raises(KeyError):
            tch.resolve_provider("test")
    finally:
        del sys.modules["_tch_provider_mod"]


def test_evaluators_register_on_graph():
    x = tch.data_layer("x", size=8)
    pred = tch.fc_layer(x, size=3, act=tch.SoftmaxActivation())
    lbl = tch.data_layer("l", size=0,
                         type=paddle.data_type.integer_value(3))
    tch.classification_error_evaluator(input=pred, label=lbl,
                                       name="err")
    tch.sum_evaluator(pred, name="s")
    from paddle_tpu.v2 import config as cfg
    names = [e[0] for e in cfg.graph().evaluators]
    assert "err" in names and "s" in names


def test_wrap_decorators():
    @tch.wrap_name_default("mylayer")
    @tch.wrap_act_default(act=tch.ReluActivation())
    def custom(input, name=None, act=None):
        return name, act

    name, act = custom("in")
    assert name.startswith("mylayer")
    assert isinstance(act, tch.ReluActivation)

    # positional None must be filled too, not produce a duplicate kwarg
    name2, act2 = custom("in", None, None)
    assert name2.startswith("mylayer")
    assert isinstance(act2, tch.ReluActivation)


def test_reset_parser_reparse_is_deterministic():
    def net():
        x = tch.data_layer("x", size=4)
        pred = tch.fc_layer(x, size=2)
        tch.outputs(pred)

    d1 = tch.parse_network_config(net).to_dict()
    d2 = tch.parse_network_config(net).to_dict()
    assert d1 == d2  # param names are save/load keys; no drifting suffix


def test_unnamed_evaluators_coexist():
    a = tch.data_layer("a", size=2)
    b = tch.data_layer("b", size=2)
    tch.sum_evaluator(a)
    tch.sum_evaluator(b)
    from paddle_tpu.v2 import config as cfg
    names = [e[0] for e in cfg.graph().evaluators]
    assert len(names) == 2 and len(set(names)) == 2


def test_mixed_layer_math_and_name():
    x = tch.data_layer("x", size=4)
    with tch.mixed_layer(size=4, name="score") as m:
        m += tch.full_matrix_projection(x)
    doubled = 2 * m  # layer math on a context-built mixed layer
    assert doubled.var.shape[-1] == 4
    assert "score" in m.name  # configured name reaches the program


def test_layer_attr_drop_rate_and_error_clip():
    from paddle_tpu.clip import ErrorClipByValue
    x = tch.data_layer("x", size=4)
    h = tch.fc_layer(x, size=8, act=tch.ReluActivation(),
                     layer_attr=tch.ExtraAttr(drop_rate=0.5,
                                              error_clipping_threshold=2.0))
    # drop_rate appended a dropout op on the fc output
    from paddle_tpu.v2 import config as cfg
    op_types = [op.type for op in cfg.graph().main.current_block().ops]
    assert "dropout" in op_types
    # error clip landed on the pre-dropout var
    clipped = h.parents[0]
    assert isinstance(clipped.var.error_clip, ErrorClipByValue)


def test_param_attr_gradient_clip_and_momentum():
    from paddle_tpu.clip import GradientClipByValue
    pa = tch.ParameterAttribute(gradient_clipping_threshold=3.0)
    assert isinstance(pa.gradient_clip, GradientClipByValue)
    assert pa.gradient_clip.max == 3.0 and pa.gradient_clip.min == -3.0
    with pytest.raises(NotImplementedError):
        tch.ParameterAttribute(momentum=0.5)


def test_data_sources_args_split():
    tch.define_py_data_sources2(
        train_list="t.list", test_list="e.list", module="m", obj="process",
        args={"train": {"f": 1}, "test": {"f": 2}})
    src = tch.current_data_sources()
    assert src["train"].args == {"f": 1}
    assert src["test"].args == {"f": 2}


def test_recurrent_group_is_design_boundary():
    with pytest.raises(NotImplementedError):
        tch.recurrent_group(step=None, input=[])
    with pytest.raises(NotImplementedError):
        tch.beam_search()


def test_trainer_config_parser_module():
    """paddle_tpu.trainer.config_parser.parse_config: the v1 entry point
    (reference python/paddle/trainer/config_parser.py)."""
    from paddle_tpu.trainer import config_parser

    def conf():
        tch.settings(batch_size=16, learning_rate=0.01,
                     learning_method=tch.MomentumOptimizer(momentum=0.9))
        x = tch.data_layer("x", size=8)
        y = tch.fc_layer(x, size=2, act=tch.SoftmaxActivation())
        tch.outputs(y)

    tc = config_parser.parse_config(conf)
    d = tc.to_dict()
    assert d["opt_config"]["batch_size"] == 16
    assert d["opt_config"]["learning_method"] == "MomentumOptimizer"
    assert d["model_config"]["input_layer_names"] == ["x"]
    assert any(op["type"] == "softmax"
               for b in d["model_config"]["program"]["blocks"]
               for op in b["ops"])


def test_extended_evaluators_register_and_run():
    """chunk/ctc-error/precision-recall evaluators build metric
    subgraphs on the v1 dialect (reference evaluators.py family)."""
    seq = tch.data_layer("tags", size=0,
                         type=paddle.data_type.integer_value_sequence(9))
    lbl = tch.data_layer("gold", size=0,
                         type=paddle.data_type.integer_value_sequence(9))
    tch.chunk_evaluator(seq, lbl, chunk_scheme="IOB", num_chunk_types=4,
                        name="chunks")
    tch.ctc_error_evaluator(seq, lbl, name="cer")
    x = tch.data_layer("x", size=6)
    pred = tch.fc_layer(x, size=3, act=tch.SoftmaxActivation())
    cls = tch.data_layer("cls", size=0,
                         type=paddle.data_type.integer_value(3))
    tch.precision_recall_evaluator(pred, cls, name="pr")
    from paddle_tpu.v2 import config as cfg
    names = {e[0] for e in cfg.graph().evaluators}
    assert {"chunks", "cer", "pr"} <= names
