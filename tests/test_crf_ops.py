"""CRF family tests: linear_chain_crf NLL vs brute-force enumeration,
gradient check, crf_decoding vs brute-force Viterbi, chunk_eval vs a
python chunk extractor (the OpTest numpy-oracle pattern, op_test.py:131)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid


def _brute_force_nll(emission, length, transition, label):
    """Enumerate all tag paths of one sequence."""
    d = emission.shape[1]
    start_w, end_w, trans = transition[0], transition[1], transition[2:]

    def score(path):
        s = start_w[path[0]] + emission[0, path[0]]
        for t in range(1, len(path)):
            s += trans[path[t - 1], path[t]] + emission[t, path[t]]
        s += end_w[path[-1]]
        return s

    paths = list(itertools.product(range(d), repeat=length))
    scores = np.array([score(p) for p in paths])
    m = scores.max()
    log_z = m + np.log(np.exp(scores - m).sum())
    gold = score(tuple(label[:length]))
    return log_z - gold, paths[int(np.argmax(scores))]


def _run_crf(emission, lengths, transition, label, fetch_decode=False):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        b, t, d = emission.shape
        em = fluid.layers.data("em", shape=[d], dtype="float32",
                               lod_level=1)
        lb = fluid.layers.data("lb", shape=[1], dtype="int64", lod_level=1)
        nll = fluid.layers.linear_chain_crf(
            em, lb, param_attr=fluid.ParamAttr(name="crfw"))
        fetches = [nll]
        if fetch_decode:
            fetches.append(fluid.layers.crf_decoding(
                em, param_attr=fluid.ParamAttr(name="crfw")))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            scope.set_var("crfw", transition)
            exe = fluid.Executor(fluid.CPUPlace())
            feed = {"em": emission, "em@LEN": lengths,
                    "lb": label[:, :, None], "lb@LEN": lengths}
            return exe.run(feed=feed, fetch_list=fetches)


def test_crf_nll_matches_brute_force():
    rng = np.random.RandomState(0)
    b, t, d = 3, 5, 4
    emission = rng.randn(b, t, d).astype("float32")
    transition = rng.randn(d + 2, d).astype("float32")
    lengths = np.array([5, 3, 1], "int32")
    label = rng.randint(0, d, (b, t)).astype("int64")
    (nll,) = _run_crf(emission, lengths, transition, label)
    for i in range(b):
        want, _ = _brute_force_nll(emission[i], int(lengths[i]),
                                   transition, label[i])
        assert nll[i, 0] == pytest.approx(want, rel=1e-4), i


def test_crf_decoding_matches_brute_force_viterbi():
    rng = np.random.RandomState(1)
    b, t, d = 4, 4, 3
    emission = rng.randn(b, t, d).astype("float32")
    transition = rng.randn(d + 2, d).astype("float32")
    lengths = np.array([4, 4, 2, 3], "int32")
    label = rng.randint(0, d, (b, t)).astype("int64")
    nll, path = _run_crf(emission, lengths, transition, label,
                         fetch_decode=True)
    path = path[:, :, 0]
    for i in range(b):
        _, best = _brute_force_nll(emission[i], int(lengths[i]),
                                   transition, label[i])
        np.testing.assert_array_equal(path[i, :lengths[i]],
                                      np.array(best), str(i))
        assert (path[i, lengths[i]:] == 0).all()


def test_crf_decoding_with_label_emits_correctness_mask():
    rng = np.random.RandomState(2)
    b, t, d = 2, 4, 3
    emission = rng.randn(b, t, d).astype("float32")
    transition = rng.randn(d + 2, d).astype("float32")
    lengths = np.array([4, 3], "int32")
    label = rng.randint(0, d, (b, t)).astype("int64")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        em = fluid.layers.data("em", shape=[d], dtype="float32", lod_level=1)
        lb = fluid.layers.data("lb", shape=[1], dtype="int64", lod_level=1)
        fluid.layers.linear_chain_crf(
            em, lb, param_attr=fluid.ParamAttr(name="crfw"))
        mask = fluid.layers.crf_decoding(
            em, param_attr=fluid.ParamAttr(name="crfw"), label=lb)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            scope.set_var("crfw", transition)
            exe = fluid.Executor(fluid.CPUPlace())
            (mv,) = exe.run(feed={"em": emission, "em@LEN": lengths,
                                  "lb": label[:, :, None],
                                  "lb@LEN": lengths},
                            fetch_list=[mask])
    assert set(np.unique(mv)) <= {0, 1}
    # mask is 1 exactly where viterbi == label (recompute path directly)
    for i in range(b):
        _, best = _brute_force_nll(emission[i], int(lengths[i]),
                                   fluid.Scope and transition, label[i])
        want = (np.array(best) == label[i, :lengths[i]]).astype("int64")
        np.testing.assert_array_equal(mv[i, :lengths[i], 0], want)


def test_crf_gradient_trains():
    """End-to-end: fc -> crf cost decreases under SGD (the
    label_semantic_roles pattern at miniature scale)."""
    rng = np.random.RandomState(3)
    b, t, d_in, d = 8, 6, 5, 4
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 5
        x = fluid.layers.data("x", shape=[d_in], dtype="float32",
                              lod_level=1)
        lb = fluid.layers.data("lb", shape=[1], dtype="int64", lod_level=1)
        em = fluid.layers.fc(x, size=d, num_flatten_dims=2, act=None)
        em._seq_len_name = x._seq_len_name
        cost = fluid.layers.linear_chain_crf(
            em, lb, param_attr=fluid.ParamAttr(name="crfw"))
        avg = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(avg)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            xs = rng.randn(b, t, d_in).astype("float32")
            lens = rng.randint(2, t + 1, (b,)).astype("int32")
            # learnable pattern: tag = argmax of first d features
            ys = xs[:, :, :d].argmax(-1).astype("int64")
            losses = []
            for _ in range(40):
                (lv,) = exe.run(feed={"x": xs, "x@LEN": lens,
                                      "lb": ys[:, :, None], "lb@LEN": lens},
                                fetch_list=[avg])
                losses.append(float(lv.ravel()[0]))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


_SCHEME_TAGS = {  # chunk_eval_op.h:118-141
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _py_chunks(labels, scheme, num_chunk_types):
    """Direct python port of the reference GetSegments state machine
    (chunk_eval_op.h:41-81) — the oracle the vectorized op must match."""
    n_tag, tb, ti, te, ts = _SCHEME_TAGS[scheme]
    other = num_chunk_types

    def chunk_end(pt, pty, t, ty):
        if pty == other:
            return False
        if ty == other or ty != pty:
            return True
        if pt == tb or pt == ti:
            return t == tb or t == ts
        return pt == te or pt == ts

    def chunk_begin(pt, pty, t, ty):
        if pty == other:
            return ty != other
        if ty == other:
            return False
        if ty != pty or t == tb or t == ts:
            return True
        if t == ti or t == te:
            return pt == te or pt == ts
        return False

    segs = []
    in_chunk = False
    start = 0
    tag, typ = -1, other
    for i, v in enumerate(labels):
        pt, pty = tag, typ
        tag, typ = int(v) % n_tag, int(v) // n_tag
        if in_chunk and chunk_end(pt, pty, tag, typ):
            segs.append((start, i - 1, pty))
            in_chunk = False
        if chunk_begin(pt, pty, tag, typ):
            start = i
            in_chunk = True
    if in_chunk:
        segs.append((start, len(labels) - 1, typ))
    return set(segs)


def test_chunk_eval_iob():
    # tags: B-typ = typ*2, I-typ = typ*2+1, O = num*2
    num_types = 2
    label = np.array([[0, 1, 4, 2, 3, 1]], "int64")   # B0 I0 O B1 I1 I0
    infer = np.array([[0, 1, 4, 2, 1, 1]], "int64")   # B0 I0 O B1 I0...
    lengths = np.array([6], "int32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        inf = fluid.layers.data("inf", shape=[1], dtype="int64", lod_level=1)
        lab = fluid.layers.data("lab", shape=[1], dtype="int64", lod_level=1)
        p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
            inf, lab, chunk_scheme="IOB", num_chunk_types=num_types)
        exe = fluid.Executor(fluid.CPUPlace())
        pv, rv, fv, niv, nlv, ncv = exe.run(
            feed={"inf": infer[:, :, None], "inf@LEN": lengths,
                  "lab": label[:, :, None], "lab@LEN": lengths},
            fetch_list=[p, r, f1, ni, nl, nc])
    want_inf = _py_chunks(infer[0], "IOB", num_types)
    want_lab = _py_chunks(label[0], "IOB", num_types)
    assert int(niv[0]) == len(want_inf)
    assert int(nlv[0]) == len(want_lab)
    assert int(ncv[0]) == len(want_inf & want_lab)
    assert pv[0] == pytest.approx(len(want_inf & want_lab) /
                                  max(len(want_inf), 1))
    assert rv[0] == pytest.approx(len(want_inf & want_lab) /
                                  max(len(want_lab), 1))


def test_chunk_eval_random_vs_python_oracle():
    rng = np.random.RandomState(7)
    num_types = 3
    for scheme, n_tag in (("IOB", 2), ("plain", 1), ("IOBES", 4)):
        b, t = 5, 12
        hi = n_tag * num_types + 1        # include the O tag
        label = rng.randint(0, hi, (b, t)).astype("int64")
        infer = rng.randint(0, hi, (b, t)).astype("int64")
        lengths = rng.randint(1, t + 1, (b,)).astype("int32")
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            inf = fluid.layers.data("inf", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data("lab", shape=[1], dtype="int64",
                                    lod_level=1)
            outs = fluid.layers.chunk_eval(
                inf, lab, chunk_scheme=scheme, num_chunk_types=num_types)
            exe = fluid.Executor(fluid.CPUPlace())
            res = exe.run(
                feed={"inf": infer[:, :, None], "inf@LEN": lengths,
                      "lab": label[:, :, None], "lab@LEN": lengths},
                fetch_list=list(outs))
        ni = nl = nc = 0
        for i in range(b):
            wi = _py_chunks(infer[i, :lengths[i]], scheme, num_types)
            wl = _py_chunks(label[i, :lengths[i]], scheme, num_types)
            ni += len(wi)
            nl += len(wl)
            nc += len(wi & wl)
        assert int(res[3][0]) == ni, scheme
        assert int(res[4][0]) == nl, scheme
        assert int(res[5][0]) == nc, scheme
