"""Pallas kernel parity tests: the hand-tiled softmax_with_cross_entropy
and layer_norm bodies (ops/pallas/) must match the pure-JAX registry
kernels bit-for-tolerance, forward and backward, on the CPU interpreter
(pallas interpret mode)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _train_step_losses(use_pallas, steps=5):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 3
        x = fluid.layers.data("x", shape=[32])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=64, act=None)
        h = fluid.layers.layer_norm(h)
        h = fluid.layers.relu(h)
        logits = fluid.layers.fc(h, size=10, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        rng = np.random.RandomState(0)
        xs = rng.rand(16, 32).astype("float32")
        ys = rng.randint(0, 10, (16, 1)).astype("int64")
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            fluid.set_flags({"FLAGS_pallas_kernels": use_pallas})
            try:
                losses = [float(exe.run(feed={"x": xs, "label": ys},
                                        fetch_list=[loss])[0].ravel()[0])
                          for _ in range(steps)]
            finally:
                fluid.set_flags({"FLAGS_pallas_kernels": False})
    return losses


def test_pallas_training_matches_xla_path():
    ref = _train_step_losses(False)
    pal = _train_step_losses(True)
    np.testing.assert_allclose(pal, ref, rtol=1e-4)


def test_pallas_softmax_xent_forward_backward_parity():
    from paddle_tpu.ops.pallas import softmax_xent as px
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    logits = rng.randn(24, 50).astype("float32") * 3
    label = rng.randint(0, 50, (24,))

    def pallas_loss(lg):
        loss, _ = px.softmax_xent(lg, jnp.asarray(label), True)
        return jnp.sum(loss)

    def ref_loss(lg):
        ls = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.sum(jnp.take_along_axis(ls, jnp.asarray(label)[:, None],
                                            axis=-1))

    lv_p, g_p = jax.value_and_grad(pallas_loss)(jnp.asarray(logits))
    lv_r, g_r = jax.value_and_grad(ref_loss)(jnp.asarray(logits))
    assert float(lv_p) == pytest.approx(float(lv_r), rel=1e-5)
    np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_r),
                               atol=1e-5)


def test_pallas_softmax_cotangent_through_softmax_output():
    """Gradient must be right when the SOFTMAX output (not just the
    loss) is consumed downstream — the Jacobian-vector-product path."""
    from paddle_tpu.ops.pallas import softmax_xent as px
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    logits = rng.randn(6, 9).astype("float32")
    label = jnp.asarray(rng.randint(0, 9, (6,)))

    def pallas_obj(lg):
        loss, sm = px.softmax_xent(lg, label, True)
        return jnp.sum(loss) + jnp.sum(sm ** 2)

    def ref_obj(lg):
        ls = jax.nn.log_softmax(lg, axis=-1)
        sm = jnp.exp(ls)
        loss = -jnp.take_along_axis(ls, label[:, None], axis=-1)
        return jnp.sum(loss) + jnp.sum(sm ** 2)

    g_p = jax.grad(pallas_obj)(jnp.asarray(logits))
    g_r = jax.grad(ref_obj)(jnp.asarray(logits))
    np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_r),
                               atol=1e-5)


def test_pallas_handles_odd_and_empty_row_counts():
    from paddle_tpu.ops.pallas import layer_norm as pln
    from paddle_tpu.ops.pallas import softmax_xent as px
    import jax.numpy as jnp

    # prime row count must not degenerate or crash (padding path)
    x = np.random.RandomState(4).randn(13, 20).astype("float32")
    g = np.ones(20, "float32")
    b = np.zeros(20, "float32")
    y = pln.layer_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
                       1e-5, True)
    mu = x.mean(-1, keepdims=True)
    want = (x - mu) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)
    # empty batch returns empty outputs, no ZeroDivisionError
    loss, sm = px.softmax_xent(jnp.zeros((0, 7)), jnp.zeros((0,),
                                                            jnp.int32),
                               True)
    assert loss.shape == (0, 1) and sm.shape == (0, 7)
    assert pln.layer_norm(jnp.zeros((0, 5)), jnp.ones(5), jnp.zeros(5),
                          1e-5, True).shape == (0, 5)


def test_flag_toggle_recompiles_cached_program():
    """Toggling FLAGS_pallas_kernels must not reuse the stale compiled
    function (the flag is part of the executor cache key)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.softmax(x)
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.random.rand(2, 4).astype("float32")
        exe.run(feed={"x": xv}, fetch_list=[out])
        n_before = len(exe._cache)
        fluid.set_flags({"FLAGS_pallas_kernels": True})
        try:
            exe.run(feed={"x": xv}, fetch_list=[out])
        finally:
            fluid.set_flags({"FLAGS_pallas_kernels": False})
        assert len(exe._cache) == n_before + 1


def test_pallas_layer_norm_forward_backward_parity():
    from paddle_tpu.ops.pallas import layer_norm as pln
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    x = rng.randn(16, 40).astype("float32")
    gamma = rng.rand(40).astype("float32") + 0.5
    beta = rng.randn(40).astype("float32")

    def pallas_fn(x_, g_, b_):
        return jnp.sum(pln.layer_norm(x_, g_, b_, 1e-5, True) ** 2)

    def ref_fn(x_, g_, b_):
        mu = jnp.mean(x_, -1, keepdims=True)
        var = jnp.mean((x_ - mu) ** 2, -1, keepdims=True)
        y = (x_ - mu) * jax.lax.rsqrt(var + 1e-5) * g_ + b_
        return jnp.sum(y ** 2)

    args = (jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta))
    v_p, g_p = jax.value_and_grad(pallas_fn, argnums=(0, 1, 2))(*args)
    v_r, g_r = jax.value_and_grad(ref_fn, argnums=(0, 1, 2))(*args)
    assert float(v_p) == pytest.approx(float(v_r), rel=1e-5)
    for a, b in zip(g_p, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4)
