"""Quantization-aware training via QuantizeTranspiler: rewrite before
backward, train (STE grads), freeze for inference, convert to int8."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.quantize import QuantizeTranspiler


def _build(qt=None):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[1, 8, 8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(img, 4, 3, padding=1, act="relu",
                                   bias_attr=False)
        pool = fluid.layers.pool2d(conv, 8, pool_type="avg",
                                   global_pooling=True)
        pred = fluid.layers.fc(pool, size=3, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        if qt is not None:
            n = qt.training_transpile(main, startup)
            assert n >= 4   # conv Input+Filter, fc mul X+Y
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss, pred


def test_qat_trains_and_freezes():
    qt = QuantizeTranspiler(activation_quantize_type="range_abs_max")
    main, startup, loss, pred = _build(qt)
    types = [op.type for op in main.global_block().ops]
    assert "fake_quantize_range_abs_max" in types
    assert "fake_quantize_abs_max" in types      # weights stay abs_max
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        first = None
        for i in range(25):
            x = rng.rand(8, 1, 8, 8).astype("float32")
            y = (x.mean(axis=(1, 2, 3)) > 0.5).astype("int64"
                                                      ).reshape(-1, 1)
            l, = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[loss])
            if first is None:
                first = float(l[0])
        assert np.isfinite(l).all()
        assert float(l[0]) < first   # STE grads flow; training moves

        # running activation scale was learned (nonzero persistable)
        scale_names = [op.inputs["InScale"][0]
                       for op in main.global_block().ops
                       if op.type == "fake_quantize_range_abs_max"]
        assert scale_names
        assert float(np.asarray(scope.var(scale_names[0]))[0]) > 0

        frozen = qt.freeze_program(main, fluid.CPUPlace(), scope=scope)
        (p,) = exe.run(frozen, feed={"img": x, "label": y},
                       fetch_list=[pred.name])
        assert np.isfinite(p).all()

        # int8 conversion stores int8 weights + scales in the scope
        converted = qt.convert_to_int8(main, scope=scope)
        assert converted
        for name, (iname, scale) in converted.items():
            q = np.asarray(scope.var(iname))
            assert q.dtype == np.int8 and scale > 0
            w = np.asarray(scope.var(name))
            np.testing.assert_allclose(
                q.astype(np.float32) * scale / 127.0, w, atol=scale / 100)


def test_transpile_after_backward_rejected():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(x, size=2)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
        with pytest.raises(ValueError, match="BEFORE append_backward"):
            QuantizeTranspiler().training_transpile(main, startup)


def test_frozen_program_scale_is_immutable():
    """Regression (review repro): the frozen program must CONSUME the
    trained running scale, never update it from serving data."""
    qt = QuantizeTranspiler(activation_quantize_type="range_abs_max")
    main, startup, loss, pred = _build(qt)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"img": rng.rand(4, 1, 8, 8).astype("float32"),
                "label": np.zeros((4, 1), "int64")}
        exe.run(main, feed=feed, fetch_list=[loss])
        names = [op.inputs["InScale"][0]
                 for op in main.global_block().ops
                 if op.type == "fake_quantize_range_abs_max"]
        trained = float(np.asarray(scope.var(names[0]))[0])
        assert trained > 0

        frozen = qt.freeze_program(main, fluid.CPUPlace(), scope=scope)
        big = {"img": 100.0 * rng.rand(4, 1, 8, 8).astype("float32"),
               "label": np.zeros((4, 1), "int64")}
        exe.run(frozen, feed=big, fetch_list=[pred.name])
        after = float(np.asarray(scope.var(names[0]))[0])
        assert after == trained, (trained, after)


def test_fake_quantize_ste_gradient():
    """QAT straight-through estimator: d(fake_quantize)/dX is the
    identity on the upstream cotangent — analytic, NOT numeric (the
    rounding's true derivative is zero a.e.; STE is the designed
    divergence, reference fake_quantize_op.cc grad)."""
    import numpy as np
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4])
        x.stop_gradient = False
        block = fluid.default_main_program().current_block()
        out = block.create_var(name="q", dtype="float32")
        scale = block.create_var(name="qs", dtype="float32")
        block.append_op(
            type="fake_quantize_abs_max", inputs={"X": [x]},
            outputs={"Out": [out], "OutScale": [scale]},
            attrs={"bit_length": 8})
        loss = fluid.layers.reduce_sum(
            fluid.layers.scale(out, scale=3.0))
        (gx,) = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            xv = np.random.RandomState(0).randn(2, 4).astype("float32")
            (gv,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    # STE: gradient passes 3.0 straight through the rounding
    np.testing.assert_allclose(gv, 3.0 * np.ones_like(xv), rtol=1e-6)
