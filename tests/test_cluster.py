"""Cluster runtime tests (ISSUE 13): ClusterMaster membership/epochs,
verdict arbitration, saver election, the step barrier, ClusterGuardian
bridging, member-context event stamping, and the per-host sharded
TrainState artifact IO (1/N bytes, bit-identical round trips,
corruption detection).  The multiprocess kill drill lives in
``test_cluster_drill.py`` (slow); this file is tier-1."""

import json
import os

import numpy as np
import pytest

import jax
import paddle_tpu as fluid
from paddle_tpu import guardian, monitor
from paddle_tpu.cloud import FileStore, InMemStore, MasterServer
from paddle_tpu.cluster import (ClusterGuardian, ClusterMaster,
                                ClusterMember, local_context,
                                local_member, set_local_member)
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.checkpoint import (
    CheckpointCorruptError, TrainStateCheckpointManager,
    capture_train_state, commit_sharded_train_state, load_train_state,
    partition_shards, save_train_state_sharded, write_train_state_shards)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# membership / epochs / leases
# ---------------------------------------------------------------------------

def test_join_heartbeat_expiry_bumps_epoch():
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    v0 = cm.join("a")
    assert v0["epoch"] == 1 and v0["members"] == ["a"]
    v1 = cm.join("b")
    assert v1["epoch"] == 2 and v1["members"] == ["a", "b"]
    # a re-join of a live member renews, does NOT bump
    assert cm.join("b")["epoch"] == 2
    # heartbeats keep the lease alive across the timeout
    clk.advance(8.0)
    cm.heartbeat("a")
    clk.advance(8.0)
    v = cm.heartbeat("a")     # b silent for 16s > 10s: expired
    assert v["members"] == ["a"] and v["epoch"] == 3
    # the expired member is told to rejoin
    assert cm.heartbeat("b").get("rejoin") is True
    assert cm.join("b")["epoch"] == 4


def test_leave_bumps_epoch_and_membership_view():
    cm = ClusterMaster(lease_timeout=10.0, clock=FakeClock())
    cm.join("a")
    cm.join("b")
    v = cm.leave("b")
    assert v["epoch"] == 3 and v["members"] == ["a"]
    m = cm.membership()
    assert sorted(m["members"]) == ["a"]


def test_store_recovery_preserves_membership_and_deadlines(tmp_path):
    clk = FakeClock()
    store = FileStore(tmp_path / "cluster.json")
    cm = ClusterMaster(store=store, lease_timeout=10.0, clock=clk)
    cm.join("a")
    cm.join("b")
    clk.advance(6.0)
    cm.heartbeat("a")          # a renewed at t+6; b's deadline is t+10

    # master dies; a new master over the same store resumes epochs AND
    # the live deadlines (the recovered master honors the dead one's
    # leases — it does NOT re-arm them to a fresh timeout)
    cm2 = ClusterMaster(store=store, lease_timeout=10.0, clock=clk)
    assert cm2.membership()["epoch"] == 2
    assert sorted(cm2.membership()["members"]) == ["a", "b"]
    clk.advance(5.0)           # t+11: past b's ORIGINAL deadline only
    v = cm2.heartbeat("a")
    assert v["members"] == ["a"] and v["epoch"] == 3


# ---------------------------------------------------------------------------
# verdict arbitration
# ---------------------------------------------------------------------------

def test_verdict_arbitration_first_wins_until_retired():
    cm = ClusterMaster(lease_timeout=10.0, clock=FakeClock())
    cm.join("a")
    cm.join("b")
    cmd = cm.propose_verdict("a", 7, "rollback", "nan")
    assert cmd["origin"] == "a" and cmd["step"] == 7 and cmd["seq"] == 1
    # a later (even conflicting) proposal returns THE active command
    cmd2 = cm.propose_verdict("b", 9, "abort", "stall")
    assert cmd2 == dict(cmd)
    # proposer and late proposer are auto-acked -> retired -> a new
    # incident arbitrates fresh
    assert cm.stats()["active_command"] is None
    cmd3 = cm.propose_verdict("b", 20, "abort", "stall")
    assert cmd3["seq"] == 2 and cmd3["origin"] == "b"


def test_poll_ack_delivery_and_retirement():
    cm = ClusterMaster(lease_timeout=10.0, clock=FakeClock())
    cm.join("a")
    cm.join("b")
    cmd = cm.propose_verdict("a", 3, "rollback", "spike")
    # b sees it exactly until it acks; a (auto-acked) does not
    assert cm.poll_command("a") is None
    got = cm.poll_command("b")
    assert got["seq"] == cmd["seq"]
    assert cm.ack_command("b", cmd["seq"]) is True
    assert cm.poll_command("b") is None
    assert cm.stats()["active_command"] is None


def test_dead_member_cannot_pin_a_command():
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    cm.join("a")
    cm.join("b")
    cm.propose_verdict("a", 3, "rollback", "spike")
    assert cm.stats()["active_command"] is not None   # b never acked
    clk.advance(11.0)          # b dies; the sweep retires the command
    cm.heartbeat("a")
    assert cm.stats()["active_command"] is None


def test_invalid_verdict_kind_rejected():
    cm = ClusterMaster(clock=FakeClock())
    cm.join("a")
    with pytest.raises(ValueError):
        cm.propose_verdict("a", 1, "skip", "nope")


# ---------------------------------------------------------------------------
# saver election + step barrier
# ---------------------------------------------------------------------------

def test_saver_election_one_committer_per_step():
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    cm.join("a")
    cm.join("b")
    assert cm.request_save("a", 5) is True
    assert cm.request_save("b", 5) is False
    assert cm.request_save("a", 5) is True    # idempotent for the winner
    # a NEW step elects fresh (possibly a different host)
    assert cm.request_save("b", 10) is True
    assert cm.request_save("a", 10) is False


def test_step_barrier_go_wait_reshape_and_command():
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    ea = cm.join("a")["epoch"]
    eb = cm.join("b")["epoch"]
    # a joined before b: its epoch is stale -> told to reshape (absorb)
    assert cm.enter_step("a", 1, ea)["action"] == "reshape"
    ea = eb
    assert cm.enter_step("a", 1, ea)["action"] == "wait"
    assert cm.enter_step("b", 1, eb)["action"] == "go"
    assert cm.enter_step("a", 1, ea)["action"] == "go"
    # an arbitration verdict is delivered at the barrier, once, until
    # acked
    cmd = cm.propose_verdict("b", 1, "rollback", "nan")
    res = cm.enter_step("a", 2, ea)
    assert res["action"] == "command" and res["command"]["seq"] == \
        cmd["seq"]
    cm.ack_command("a", cmd["seq"])
    assert cm.enter_step("a", 2, ea)["action"] == "wait"
    # a member death surfaces as reshape at the barrier, never a hang
    clk.advance(6.0)
    cm.heartbeat("a")          # a stays live; b goes silent
    clk.advance(6.0)           # b's lease (10s) lapses
    res = cm.enter_step("a", 3, ea)
    assert res["action"] == "reshape" and res["members"] == ["a"]


def test_cluster_member_session_over_tcp():
    srv = MasterServer(ClusterMaster(lease_timeout=5.0)).start()
    try:
        a = ClusterMember(srv.address, "a", auto_heartbeat=False,
                          register_local=False)
        b = ClusterMember(srv.address, "b", auto_heartbeat=False,
                          register_local=False)
        # a's world epoch predates b's join: the barrier says reshape
        # until a explicitly accepts the new view
        res = a.enter_step(1, timeout=5)
        if res["action"] == "reshape":
            a.accept_world(res["epoch"])
        r_b = b.enter_step(1, timeout=5)
        assert r_b["action"] == "go"
        assert a.enter_step(1, timeout=5)["action"] == "go"
        assert sorted(a.members) == ["a", "b"]
        assert b.request_save(1) in (True, False)
        b.leave()
        a.heartbeat()
        assert a.members == ["a"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# ClusterGuardian: verdicts win cluster-wide
# ---------------------------------------------------------------------------

def _member(cm, host):
    return ClusterMember(cm, host, auto_heartbeat=False,
                         register_local=False)


def test_cluster_guardian_local_escalation_becomes_cluster_command():
    cm = ClusterMaster(lease_timeout=30.0, clock=FakeClock())
    ma, mb = _member(cm, "a"), _member(cm, "b")
    ga = ClusterGuardian(ma, policy="rollback,abort")
    gb = ClusterGuardian(mb, policy="rollback,abort")
    # host a observes a non-finite loss -> proposes -> raises the
    # arbitrated command
    with pytest.raises(guardian.GuardianRollback) as ra:
        ga.note_step("exe", 7, ok=None, fetch_names=("loss",),
                     fetches=(np.float32("nan"),), sync=True)
    assert ra.value.step == 7 and "cluster[a]" in ra.value.reason
    # host b sees only CLEAN steps — the remote verdict still reaches
    # its ladder at the next step boundary, as the SAME rollback
    with pytest.raises(guardian.GuardianRollback) as rb:
        gb.note_step("exe", 8, ok=None, fetch_names=("loss",),
                     fetches=(np.float32(1.0),), sync=True)
    assert rb.value.step == 7 and "cluster[a]" in rb.value.reason
    # both applied -> the command retired
    assert cm.stats()["active_command"] is None


def test_cluster_guardian_abort_kind_propagates():
    cm = ClusterMaster(lease_timeout=30.0, clock=FakeClock())
    ma, mb = _member(cm, "a"), _member(cm, "b")
    # host a's ladder has NO rollback rung: it proposes an abort; b's
    # ladder has one, but the CLUSTER decision wins over local policy
    ga = ClusterGuardian(ma, policy="abort")
    gb = ClusterGuardian(mb, policy="rollback,abort")
    with pytest.raises(guardian.GuardianAbortError):
        ga.note_step("exe", 4, ok=None, fetch_names=("loss",),
                     fetches=(np.float32("inf"),), sync=True)
    with pytest.raises(guardian.GuardianAbortError):
        gb.note_step("exe", 5, ok=None, fetch_names=("loss",),
                     fetches=(np.float32(1.0),), sync=True)


def test_guardian_and_stall_events_carry_member_context(tmp_path):
    cm = ClusterMaster(lease_timeout=30.0, clock=FakeClock())
    m = ClusterMember(cm, "host7", auto_heartbeat=False)   # registers
    try:
        assert local_member() is m
        assert local_context() == {"member_id": "host7",
                                   "membership_epoch": m.epoch}
        monitor.enable(log_dir=str(tmp_path))
        guardian.Guardian._event({"event": "guardian_rollback",
                                  "step": 3})
        monitor._stall_sink({"event": "watchdog_stall", "ts": 0.0,
                             "stalled_for_s": 1.0})
        monitor.disable()
        recs = []
        for fn in os.listdir(tmp_path):
            with open(os.path.join(tmp_path, fn)) as f:
                recs += [json.loads(ln) for ln in f if ln.strip()]
        by_event = {r["event"]: r for r in recs}
        for ev in ("guardian_rollback", "watchdog_stall"):
            assert by_event[ev]["member_id"] == "host7", by_event[ev]
            assert by_event[ev]["membership_epoch"] == m.epoch
    finally:
        monitor.disable()
        m.close()
    assert local_member() is None     # close() deregisters


# ---------------------------------------------------------------------------
# per-host sharded TrainState artifacts
# ---------------------------------------------------------------------------

def _build_mlp(seed=7):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data("x", shape=[16])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=32, act="relu")
    pred = fluid.layers.fc(h, size=4, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return loss


def _train_steps(pe, loss, steps=2):
    for i in range(steps):
        x = np.random.RandomState(i).rand(8, 16).astype("float32")
        y = x[:, :4].argmax(1).astype("int64").reshape(-1, 1)
        pe.run(feed={"x": x, "label": y}, fetch_list=[loss])


# one cached (ts, full) capture for the pure-IO tests (round trips,
# partitioning, commit timeout): the fsdp PE build+train costs ~2.5s,
# and those tests only read the captured numpy data — tests that need
# a LIVE world (manager saves, corrupt fallback) build their own
_CAPTURE = []


def _cached_capture(tmp_path):
    if not _CAPTURE:
        _, _, ts, full, _ = _mesh_scope_state(tmp_path)
        _CAPTURE.append((ts, full))
    return _CAPTURE[0]


def _mesh_scope_state(tmp_path, writers=1):
    """Train 2 steps on a (1,4) fsdp mesh, capture sharded; returns
    (scope, pe, sharded ts, full reference arrays)."""
    from paddle_tpu.parallel.checkpoint import (_gather_host,
                                                _persistable_state)

    loss = _build_mlp()
    mesh = make_mesh((1, 4), ("dp", "fsdp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        _train_steps(pe, loss)
        ts = capture_train_state(2, scope=scope, executors=pe,
                                 sharded=True)
        full = {n: _gather_host(v) for n, v in _persistable_state(
            scope, fluid.default_main_program()).items()}
    return scope, pe, ts, full, loss


def test_sharded_capture_owns_disjoint_covering_shards(tmp_path):
    ts, full = _cached_capture(tmp_path)
    assert ts.arrays is None and ts.shards
    seen = {n: 0 for n in full}
    for e in ts.shards:
        seen[e["name"]] += e["data"].size
    for n, arr in full.items():
        assert seen[n] == arr.size, (n, seen[n], arr.size)


def test_sharded_single_host_roundtrip_bit_identical(tmp_path):
    """Acceptance: single-host restore of a sharded artifact
    round-trips bit-identical."""
    ts, full = _cached_capture(tmp_path)
    ck = str(tmp_path / "step_0000000002")
    save_train_state_sharded(ck, ts, writer_id=0, writers=1, saver=True)
    loaded = load_train_state(ck)
    assert sorted(loaded.arrays) == sorted(full)
    for n, v in full.items():
        np.testing.assert_array_equal(loaded.arrays[n], v, err_msg=n)
    assert loaded.host["executors"]["executor0"] == \
        ts.host["executors"]["executor0"]


def test_partition_shards_bytes_scale_inverse_n(tmp_path):
    """Acceptance: per-host bytes written scale as ~1/N (manifest-
    verified), and the N-writer artifact round-trips bit-identically."""
    ts, full = _cached_capture(tmp_path)
    ck = str(tmp_path / "v4" / "step_0000000002")
    os.makedirs(os.path.dirname(ck))
    parts = partition_shards(ts, 4)
    for w, entries in enumerate(parts):
        write_train_state_shards(ck, ts, w, entries=entries)
    commit_sharded_train_state(ck, ts, 4)
    man = json.load(open(os.path.join(ck, "MANIFEST.json")))
    per = man["per_writer_bytes"]
    total = sum(per.values())
    assert len(per) == 4
    assert max(per.values()) / total < 0.35, per     # ~0.25 each
    loaded = load_train_state(ck)
    for n, v in full.items():
        np.testing.assert_array_equal(loaded.arrays[n], v, err_msg=n)


def test_sharded_commit_times_out_on_missing_writer(tmp_path):
    ts, _ = _cached_capture(tmp_path)
    ck = str(tmp_path / "step_0000000002")
    write_train_state_shards(ck, ts, 0)
    with pytest.raises(CheckpointCorruptError, match="never delivered"):
        commit_sharded_train_state(ck, ts, 2, timeout=0.2)
    # nothing committed: the artifact is invisible to restores
    assert not os.path.exists(os.path.join(ck, "MANIFEST.json"))


def test_sharded_corrupt_shard_detected_and_fallback(tmp_path):
    """A garbled shard file fails its sha256; the manager falls back to
    the previous committed artifact (same contract as the full path)."""
    scope, pe, ts, full, loss = _mesh_scope_state(tmp_path)
    mgr = TrainStateCheckpointManager(str(tmp_path / "mgr"),
                                      sharded=True, async_save=False)
    with fluid.scope_guard(scope):
        mgr.save(2, scope=scope, program=fluid.default_main_program(),
                 executors={"train": pe})
        _train_steps(pe, loss)
        mgr._last_saved = None
        mgr.save(4, scope=scope, program=fluid.default_main_program(),
                 executors={"train": pe})
    assert mgr.all_steps() == [2, 4]
    shard = os.path.join(mgr._step_dir(4), "shard_00000.npz")
    with open(shard, "r+b") as f:
        f.seek(12)
        f.write(b"\xff" * 32)
    with pytest.raises(CheckpointCorruptError):
        mgr.load(4)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        with pytest.warns(UserWarning, match="corrupt"):
            step = mgr.restore(scope=scope2,
                               program=fluid.default_main_program())
    assert step == 2
    for n, v in full.items():
        np.testing.assert_array_equal(np.asarray(scope2.var(n)), v,
                                      err_msg=n)


def test_manager_saver_election_gates_commit(tmp_path):
    """A non-elected host writes its shards but never the manifest; the
    artifact becomes visible only when the elected saver commits."""
    scope, pe, ts, _, _ = _mesh_scope_state(tmp_path)
    mgr = TrainStateCheckpointManager(
        str(tmp_path / "mgr"), sharded=True, async_save=False,
        saver_elect=lambda step: False)
    with fluid.scope_guard(scope):
        mgr.save(2, scope=scope, program=fluid.default_main_program())
    assert mgr.all_steps() == []          # shards written, no commit
    mgr2 = TrainStateCheckpointManager(
        str(tmp_path / "mgr2"), sharded=True, async_save=False,
        saver_elect=lambda step: True)
    with fluid.scope_guard(scope):
        mgr2.save(2, scope=scope, program=fluid.default_main_program())
    assert mgr2.all_steps() == [2]


def test_full_capture_path_unchanged_single_host(tmp_path):
    """The single-host full-artifact path stays the default: capture
    without sharded gives full arrays and the classic layout."""
    loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        mgr = TrainStateCheckpointManager(str(tmp_path / "m"),
                                          async_save=False)
        assert mgr.sharded_mode() is False     # 1 process -> full
        mgr.save(1, scope=scope, program=fluid.default_main_program())
    ck = mgr._step_dir(1)
    assert os.path.exists(os.path.join(ck, "arrays.npz"))
    man = json.load(open(os.path.join(ck, "MANIFEST.json")))
    assert not man.get("sharded")


# ---------------------------------------------------------------------------
# FileStore durability satellite
# ---------------------------------------------------------------------------

def test_filestore_save_fsyncs_payload_and_directory(tmp_path,
                                                     monkeypatch):
    """The commit idiom: fsync the temp payload BEFORE os.replace and
    the directory entry AFTER — a power loss can no longer commit a
    torn master snapshot behind the atomic rename."""
    import paddle_tpu.cloud.store as store_mod

    fsyncs = []
    real_fsync = os.fsync
    monkeypatch.setattr(store_mod.os, "fsync",
                        lambda fd: (fsyncs.append(fd), real_fsync(fd)))
    dir_opens = []
    real_open = os.open

    def spy_open(path, flags, *a):
        fd = real_open(path, flags, *a)
        if os.path.isdir(path):
            dir_opens.append(path)
        return fd

    monkeypatch.setattr(store_mod.os, "open", spy_open)
    fs = FileStore(tmp_path / "snap.json")
    fs.save(b'{"state": 1}')
    assert fs.load() == b'{"state": 1}'
    assert len(fsyncs) >= 2, "payload AND directory must be fsynced"
    assert any(str(tmp_path) in d for d in dir_opens)


# ---------------------------------------------------------------------------
# review-pass regressions
# ---------------------------------------------------------------------------

def test_saver_election_released_when_elected_member_dies():
    """A dead member's saver election must not pin the step: survivors
    re-elect after the lease sweep, so the checkpoint still commits."""
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk,
                       save_block_secs=300.0)
    cm.join("a")
    cm.join("b")
    assert cm.request_save("a", 9) is True
    assert cm.request_save("b", 9) is False
    clk.advance(6.0)
    cm.heartbeat("b")
    clk.advance(6.0)               # a dies holding the election
    assert cm.request_save("b", 9) is True     # sweep released it


def test_expelled_member_latches_and_cannot_win_elections():
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    m = ClusterMember(cm, "a", auto_heartbeat=False,
                      register_local=False)
    assert m.expelled is False
    clk.advance(11.0)              # lease lapses silently
    m.heartbeat()
    assert m.expelled is True
    # a zombie must not win a commit election either
    assert cm.request_save("a", 5) is False
    # ...and its guardian exits typed instead of training on
    g = ClusterGuardian(m, policy="rollback,abort")
    with pytest.raises(guardian.GuardianAbortError, match="expelled"):
        g.note_step("exe", 6, ok=None, fetch_names=("loss",),
                    fetches=(np.float32(1.0),), sync=True)


def test_barrier_polls_do_not_snapshot_every_call():
    """Renewal-only calls (heartbeats, barrier 'wait' polls) persist at
    most once per lease_timeout/4; material changes always persist."""
    class CountingStore(InMemStore):
        saves = 0

        def save(self, data):
            type(self).saves += 1
            super().save(data)

    clk = FakeClock()
    cm = ClusterMaster(store=CountingStore(), lease_timeout=10.0,
                       clock=clk)
    ea = cm.join("a")["epoch"]
    cm.join("b")
    base = CountingStore.saves
    for _ in range(100):           # a 'wait' storm at one instant
        cm.enter_step("a", 1, cm.membership()["epoch"])
    assert CountingStore.saves - base <= 1
    before = CountingStore.saves
    cm.propose_verdict("a", 1, "rollback", "x")   # material: persists
    assert CountingStore.saves > before


def test_manager_init_spares_fresh_shared_tmp_reclaims_stale(tmp_path):
    """A rejoining host's manager init must not rmtree a live peer's
    in-flight shared sharded tmp; abandoned ones (older than the commit
    timeout) are still reclaimed."""
    import time as _time

    d = str(tmp_path / "mgr")
    os.makedirs(d)
    fresh = os.path.join(d, ".tmp.step_0000000009.shared")
    stale = os.path.join(d, ".tmp.step_0000000003.shared")
    plain = os.path.join(d, ".tmp.step_0000000004.123")
    for p in (fresh, stale, plain):
        os.makedirs(p)
        with open(os.path.join(p, "shard_00000.json"), "w") as f:
            f.write("{}")
    old = _time.time() - 999.0
    os.utime(stale, (old, old))
    TrainStateCheckpointManager(d, sharded=True, commit_timeout=120.0)
    assert os.path.isdir(fresh), "live peer's in-flight tmp deleted"
    assert not os.path.exists(stale)
    assert not os.path.exists(plain)   # pid-suffixed tmps stay garbage


def test_persistent_cache_world_scoped_at_enable_time(tmp_path,
                                                      monkeypatch):
    """Enabling the cache AFTER the world joined must land in the
    world_<N> subdir too (the enable-then-init order is covered by
    init_distributed's rescope hook)."""
    import jax

    from paddle_tpu import compile_cache
    from paddle_tpu.parallel import distributed

    base = str(tmp_path / "cache")
    prev_dir = compile_cache._persistent_dir[0]
    prev_base = compile_cache._persistent_base[0]
    try:
        monkeypatch.setattr(distributed, "is_initialized", lambda: True)
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        compile_cache.enable_persistent_cache(base)
        assert compile_cache.stats()["persistent_dir"] == \
            os.path.join(base, "world_4")
        # solo world: the base dir, unsuffixed
        monkeypatch.setattr(distributed, "is_initialized",
                            lambda: False)
        compile_cache.enable_persistent_cache(base)
        assert compile_cache.stats()["persistent_dir"] == base
    finally:
        compile_cache.enable_persistent_cache(prev_base or "")
        compile_cache._persistent_dir[0] = prev_dir
        compile_cache._persistent_base[0] = prev_base


def test_heartbeat_observed_death_still_surfaces_as_reshape():
    """The heartbeat thread may be the FIRST observer of a death (it
    absorbs the new epoch); the barrier must still answer reshape —
    the member presents the epoch of the world it BUILT, not the
    latest observed one, until accept_world()."""
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    a = ClusterMember(cm, "a", auto_heartbeat=False,
                      register_local=False)
    ClusterMember(cm, "b", auto_heartbeat=False, register_local=False)
    a.heartbeat()
    a.accept_world()                   # world formed: [a, b]
    # b enters the barrier first (raw service call), then a goes
    assert cm.enter_step("b", 1, a.world_epoch)["action"] == "wait"
    assert a.enter_step(1, timeout=1)["action"] == "go"
    clk.advance(6.0)
    a.heartbeat()                      # a renews; b goes silent
    clk.advance(6.0)
    # the HEARTBEAT observes b's death first and absorbs the epoch
    a.heartbeat()
    assert a.epoch != a.world_epoch
    # ...but the barrier still refuses to say "go" into the dead world
    res = a.enter_step(2, timeout=1)
    assert res["action"] == "reshape" and res["members"] == ["a"]
    a.accept_world(res["epoch"])       # caller reshaped for THIS view
    assert a.enter_step(2, timeout=1)["action"] == "go"


def test_zombie_verdict_rejected_by_master():
    """An expelled host's escalation (raised before its heartbeat
    latched the rejoin) must not become the cluster command."""
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=10.0, clock=clk)
    cm.join("a")
    cm.join("b")
    clk.advance(6.0)
    cm.heartbeat("b")
    clk.advance(6.0)                   # a's lease lapses
    with pytest.raises(ValueError, match="not a cluster member"):
        cm.propose_verdict("a", 7, "rollback", "nan")
    assert cm.stats()["active_command"] is None
    # a live member's verdict still arbitrates normally
    assert cm.propose_verdict("b", 7, "rollback", "nan")["origin"] == "b"


def test_saver_elections_are_per_step_not_single_slot():
    """Async writer threads of different hosts can lag steps apart: a
    request for ANOTHER step must not evict a live election — the
    single-slot design let two hosts both win the same step."""
    clk = FakeClock()
    cm = ClusterMaster(lease_timeout=1000.0, clock=clk,
                       save_block_secs=50.0)
    for h in ("a", "b", "c"):
        cm.join(h)
    assert cm.request_save("a", 5) is True
    assert cm.request_save("b", 3) is True     # older step: own election
    # c must NOT win step 5 (a's election survives b's step-3 request)
    assert cm.request_save("c", 5) is False
    assert cm.request_save("a", 5) is True
    # elections expire with their block window (leases stay live)
    clk.advance(51.0)
    assert cm.request_save("c", 5) is True


def test_trainer_rejects_plain_guardian_instance_with_cluster_member():
    cm = ClusterMaster(lease_timeout=30.0, clock=FakeClock())
    m = ClusterMember(cm, "a", auto_heartbeat=False,
                      register_local=False)

    def train_func():
        x = fluid.layers.data("x", shape=[8])
        return fluid.layers.mean(fluid.layers.fc(x, size=4))

    from paddle_tpu.contrib import Trainer

    t = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                guardian_config=guardian.Guardian(policy="rollback,abort"),
                cluster_member=m)
    with pytest.raises(ValueError, match="cluster-\\s*arbitrated|"
                                         "ClusterGuardian"):
        t._make_guardian()
    # a ClusterGuardian instance is the supported spelling
    t2 = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                 optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                 guardian_config=ClusterGuardian(
                     m, policy="rollback,abort"),
                 cluster_member=m)
    g = t2._make_guardian()
    try:
        assert isinstance(g, ClusterGuardian)
    finally:
        if t._set_guardian_flag or t2._set_guardian_flag:
            fluid.set_flags({"FLAGS_guardian": False})
