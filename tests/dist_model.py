"""Shared model + data for the distributed loss-parity tests.

Both the in-process reference run (test_dist_train.py) and the
subprocess trainers (dist_runner.py) import THIS module so the two
sides can never drift apart — the loss-equality assertion is only
meaningful if they build byte-identical programs and batches.
"""

import numpy as np

SEED = 21
BATCH = 16
STEPS = 6
IN_DIM = 32
HIDDEN = 64
CLASSES = 8
LR = 0.1


def build_model(fluid):
    """Emit the test model into the default programs; returns loss."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    img = fluid.layers.data("img", shape=[IN_DIM])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=HIDDEN, act="relu")
    pred = fluid.layers.fc(h, size=CLASSES, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches():
    """Deterministic global batches: [(x, y)] * STEPS."""
    rng = np.random.RandomState(0)
    proj = rng.rand(IN_DIM, CLASSES).astype("float32")
    out = []
    for _ in range(STEPS):
        x = rng.rand(BATCH, IN_DIM).astype("float32")
        y = (x @ proj).argmax(1).astype("int64").reshape(-1, 1)
        out.append((x, y))
    return out


# ---- sparse-embedding variant (the dist_ctr-style SelectedRows path) ------

EMB_V, EMB_D, IDS_PER = 128, 8, 4


def build_model_sparse(fluid):
    """Sparse-gradient model: embedding (SelectedRows grads) -> MLP.
    The multi-host subtlety this exists to test: sparse row-gradients
    from different processes' local batches must aggregate identically
    to the single-process dense run."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    ids = fluid.layers.data("ids", shape=[IDS_PER, 1], dtype="int64")
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[EMB_V, EMB_D], is_sparse=True)
    pooled = fluid.layers.reduce_mean(emb, dim=1)
    h = fluid.layers.fc(pooled, size=HIDDEN, act="relu")
    pred = fluid.layers.fc(h, size=CLASSES, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches_sparse():
    """Deterministic global feed dicts for the sparse model."""
    rng = np.random.RandomState(3)
    out = []
    for _ in range(STEPS):
        ids = rng.randint(0, EMB_V, (BATCH, IDS_PER, 1)).astype("int64")
        y = (ids.reshape(BATCH, IDS_PER).sum(1) % CLASSES) \
            .astype("int64").reshape(-1, 1)
        out.append({"ids": ids, "label": y})
    return out


# ---- text-classification variant (the dist_text_classification net) -------

TC_V, TC_T, TC_EMB, TC_FILTERS, TC_FC0, TC_CLASSES = 200, 8, 16, 32, 24, 2


def build_model_text_cls(fluid):
    """dist_text_classification workload (reference
    ``tests/unittests/dist_text_classification.py`` conv_net): embedding
    -> window-3 tanh sequence conv + max pool -> fc -> softmax fc,
    cross_entropy loss."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    words = fluid.layers.data("words", shape=[1], dtype="int64",
                              lod_level=1)
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(words, size=[TC_V, TC_EMB])
    conv = fluid.nets.sequence_conv_pool(emb, num_filters=TC_FILTERS,
                                         filter_size=3, act="tanh",
                                         pool_type="max")
    fc0 = fluid.layers.fc(conv, size=TC_FC0)
    pred = fluid.layers.fc(fc0, size=TC_CLASSES, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches_text_cls():
    rng = np.random.RandomState(5)
    out = []
    for _ in range(STEPS):
        w = rng.randint(0, TC_V, (BATCH, TC_T, 1)).astype("int64")
        lens = np.full(BATCH, TC_T, "int64")
        y = (w.reshape(BATCH, TC_T).max(1) % TC_CLASSES) \
            .astype("int64").reshape(-1, 1)
        out.append({"words": w, "words@LEN": lens, "label": y})
    return out


# ---- word2vec n-gram variant (dist_word2vec: shared sparse table) ---------

W2V_V, W2V_EMB, W2V_HID, W2V_N = 150, 12, 32, 5


def build_model_word2vec(fluid):
    """dist_word2vec workload (reference
    ``tests/unittests/dist_word2vec.py``): four context words through ONE
    shared sparse embedding table -> concat -> sigmoid fc -> softmax over
    the vocab.  The multi-host subtlety: every process contributes sparse
    row-grads to the SAME table rows (shared across the 4 slots)."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    words = [fluid.layers.data("w%d" % i, shape=[1], dtype="int64")
             for i in range(W2V_N - 1)]
    label = fluid.layers.data("nextw", shape=[1], dtype="int64")
    embs = [fluid.layers.embedding(
                w, size=[W2V_V, W2V_EMB], is_sparse=True,
                param_attr=fluid.ParamAttr(name="shared_w"))
            for w in words]
    concat = fluid.layers.concat(embs, axis=-1)
    concat = fluid.layers.reshape(
        concat, shape=[-1, W2V_EMB * (W2V_N - 1)])
    hidden = fluid.layers.fc(concat, size=W2V_HID, act="sigmoid")
    pred = fluid.layers.fc(hidden, size=W2V_V, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches_word2vec():
    rng = np.random.RandomState(7)
    out = []
    for _ in range(STEPS):
        ctx = rng.randint(0, W2V_V, (BATCH, W2V_N - 1)).astype("int64")
        nxt = (ctx.sum(1) % W2V_V).astype("int64").reshape(-1, 1)
        feed = {"w%d" % i: ctx[:, i:i + 1] for i in range(W2V_N - 1)}
        feed["nextw"] = nxt
        out.append(feed)
    return out


# name -> (builder, batches-of-feed-dicts); shared by dist_runner.py and
# the in-process reference runs in test_dist_train.py
MODELS = {
    "mlp": (build_model,
            lambda: [{"img": x, "label": y} for x, y in batches()]),
    "sparse": (build_model_sparse, batches_sparse),
    "text_cls": (build_model_text_cls, batches_text_cls),
    "word2vec": (build_model_word2vec, batches_word2vec),
}
