"""Shared model + data for the distributed loss-parity tests.

Both the in-process reference run (test_dist_train.py) and the
subprocess trainers (dist_runner.py) import THIS module so the two
sides can never drift apart — the loss-equality assertion is only
meaningful if they build byte-identical programs and batches.
"""

import numpy as np

SEED = 21
BATCH = 16
STEPS = 6
IN_DIM = 32
HIDDEN = 64
CLASSES = 8
LR = 0.1


def build_model(fluid):
    """Emit the test model into the default programs; returns loss."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    img = fluid.layers.data("img", shape=[IN_DIM])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=HIDDEN, act="relu")
    pred = fluid.layers.fc(h, size=CLASSES, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches():
    """Deterministic global batches: [(x, y)] * STEPS."""
    rng = np.random.RandomState(0)
    proj = rng.rand(IN_DIM, CLASSES).astype("float32")
    out = []
    for _ in range(STEPS):
        x = rng.rand(BATCH, IN_DIM).astype("float32")
        y = (x @ proj).argmax(1).astype("int64").reshape(-1, 1)
        out.append((x, y))
    return out


# ---- sparse-embedding variant (the dist_ctr-style SelectedRows path) ------

EMB_V, EMB_D, IDS_PER = 128, 8, 4


def build_model_sparse(fluid):
    """Sparse-gradient model: embedding (SelectedRows grads) -> MLP.
    The multi-host subtlety this exists to test: sparse row-gradients
    from different processes' local batches must aggregate identically
    to the single-process dense run."""
    fluid.default_main_program().random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    ids = fluid.layers.data("ids", shape=[IDS_PER, 1], dtype="int64")
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[EMB_V, EMB_D], is_sparse=True)
    pooled = fluid.layers.reduce_mean(emb, dim=1)
    h = fluid.layers.fc(pooled, size=HIDDEN, act="relu")
    pred = fluid.layers.fc(h, size=CLASSES, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
    return loss


def batches_sparse():
    """Deterministic global feed dicts for the sparse model."""
    rng = np.random.RandomState(3)
    out = []
    for _ in range(STEPS):
        ids = rng.randint(0, EMB_V, (BATCH, IDS_PER, 1)).astype("int64")
        y = (ids.reshape(BATCH, IDS_PER).sum(1) % CLASSES) \
            .astype("int64").reshape(-1, 1)
        out.append({"ids": ids, "label": y})
    return out
