"""contrib.decoder (InitState/StateCell/TrainingDecoder/BeamSearchDecoder)
— reference python/paddle/fluid/contrib/decoder/beam_search_decoder.py.

Train a copy-task seq2seq where the decoder cell is driven through
StateCell + TrainingDecoder, then generate with BeamSearchDecoder using
the SAME cell-step function and shared parameters, and check the top
beam reproduces the source."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.decoder import (
    InitState, StateCell, TrainingDecoder, BeamSearchDecoder)
from paddle_tpu.framework import program_guard
from paddle_tpu.param_attr import ParamAttr

V, D, H, TMAX = 8, 16, 64, 4
BOS, EOS = 1, 0


def _cell_updater(state_cell):
    """The shared RNN cell step: h = tanh(fc([x, h_pre]))."""
    x = state_cell.get_input('x')
    h_pre = state_cell.get_state('h')
    h = fluid.layers.fc(fluid.layers.concat([x, h_pre], axis=1),
                        size=H, act='tanh',
                        param_attr=ParamAttr(name='dec_fc_w'),
                        bias_attr=ParamAttr(name='dec_fc_b'))
    state_cell.set_state('h', h)


def _encoder(src):
    emb = fluid.layers.embedding(src, size=[V, D],
                                 param_attr=ParamAttr(name='src_emb_w'))
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(emb)
        h_pre = drnn.memory(shape=[H], value=0.0)
        h = fluid.layers.fc(fluid.layers.concat([x_t, h_pre], axis=1),
                            size=H, act='tanh',
                            param_attr=ParamAttr(name='enc_fc_w'),
                            bias_attr=ParamAttr(name='enc_fc_b'))
        drnn.update_memory(h_pre, h)
        drnn.output(h)
    return fluid.layers.sequence_pool(drnn(), 'last')     # [B, H]


def _build_train():
    src = fluid.layers.data('src', shape=[1], dtype='int64', lod_level=1)
    tgt = fluid.layers.data('tgt', shape=[1], dtype='int64', lod_level=1)
    lbl = fluid.layers.data('lbl', shape=[1], dtype='int64', lod_level=1)
    enc_last = _encoder(src)

    state_cell = StateCell(inputs={'x': None},
                           states={'h': InitState(init=enc_last)},
                           out_state='h')
    state_cell.state_updater(_cell_updater)

    temb = fluid.layers.embedding(tgt, size=[V, D],
                                  param_attr=ParamAttr(name='tgt_emb_w'))
    decoder = TrainingDecoder(state_cell)
    with decoder.block():
        e_t = decoder.step_input(temb)
        decoder.state_cell.compute_state(inputs={'x': e_t})
        h = decoder.state_cell.get_state('h')
        decoder.state_cell.update_states()
        decoder.output(fluid.layers.fc(
            h, size=V, act=None,
            param_attr=ParamAttr(name='out_fc_w'),
            bias_attr=ParamAttr(name='out_fc_b')))
    logits = decoder()                                    # [B, T, V]

    cost = fluid.layers.softmax_with_cross_entropy(logits, lbl)
    tgt_len = tgt.block._find_var_recursive(tgt._seq_len_name)
    mask = fluid.layers.padding_mask(tgt_len, logits)     # [B, T]
    masked = fluid.layers.elementwise_mul(
        cost, fluid.layers.unsqueeze(mask, axes=[2]))
    return fluid.layers.elementwise_div(
        fluid.layers.reduce_sum(masked), fluid.layers.reduce_sum(mask))


def _build_decode(beam_size):
    src = fluid.layers.data('src', shape=[1], dtype='int64', lod_level=1)
    enc_last = _encoder(src)                              # [B, H]

    state_cell = StateCell(inputs={'x': None},
                           states={'h': InitState(init=enc_last)},
                           out_state='h')
    state_cell.state_updater(_cell_updater)

    init_ids = fluid.layers.fill_constant_batch_size_like(
        input=enc_last, shape=[-1, 1], dtype='int64', value=BOS)
    init_scores = fluid.layers.fill_constant_batch_size_like(
        input=enc_last, shape=[-1, 1], dtype='float32', value=0.0)

    # the softmax projection must share out_fc_* with training: the
    # trained logits fc has no softmax, so score with softmax(logits)
    # via the same weights (fc act='softmax' composes exactly that)
    decoder = BeamSearchDecoder(
        state_cell=state_cell, init_ids=init_ids, init_scores=init_scores,
        target_dict_dim=V, word_dim=D, input_var_dict={}, topk_size=50,
        sparse_emb=False, max_len=TMAX, beam_size=beam_size, end_id=EOS,
        emb_param_attr=ParamAttr(name='tgt_emb_w'),
        score_param_attr=ParamAttr(name='out_fc_w'),
        score_bias_attr=ParamAttr(name='out_fc_b'))
    decoder.decode()
    return decoder()


def _copy_batch(rng, b):
    rows = []
    for _ in range(b):
        ln = rng.randint(2, TMAX + 1)
        seq = rng.randint(2, V, (ln,)).astype('int64')
        tgt = np.concatenate([[BOS], seq[:-1]]).astype('int64')
        rows.append((seq, tgt, seq))
    return rows


def test_contrib_decoder_train_and_beam_decode():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7

    loss = _build_train()
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    feeder = fluid.DataFeeder(
        feed_list=[
            fluid.default_main_program().global_block().var('src'),
            fluid.default_main_program().global_block().var('tgt'),
            fluid.default_main_program().global_block().var('lbl'),
        ], pad_to=TMAX)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(3)
    losses = []
    for _ in range(600):
        feed = feeder.feed(_copy_batch(rng, 16))
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.4, (losses[0], losses[-1])

    # ---- beam generation with the SAME params (shared scope) ----
    decode_prog, decode_startup = fluid.Program(), fluid.Program()
    with program_guard(decode_prog, decode_startup):
        sentences, scores = _build_decode(beam_size=3)

    batch = _copy_batch(rng, 8)
    src_pad = np.zeros((8, TMAX, 1), 'int64')
    src_len = np.zeros((8,), 'int32')
    for bi, (s, _, _) in enumerate(batch):
        src_pad[bi, :len(s), 0] = s
        src_len[bi] = len(s)

    sv, scv = exe.run(decode_prog,
                      feed={'src': src_pad, 'src@LEN': src_len},
                      fetch_list=[sentences, scores])
    sv = np.asarray(sv)                                   # [B, K, TMAX]
    scv = np.asarray(scv)
    assert sv.shape == (8, 3, TMAX)
    assert scv.shape == (8, 3)
    # beams come back best-first
    assert (np.diff(scv, axis=1) <= 1e-5).all(), scv

    correct = total = 0
    for bi, (s, _, _) in enumerate(batch):
        got = sv[bi, 0, :len(s)]
        correct += int((got == s).sum())
        total += len(s)
    assert correct / total > 0.7, (correct, total, sv[:2, 0])


def test_state_cell_validation():
    prog, start = fluid.Program(), fluid.Program()
    with program_guard(prog, start):
        boot = fluid.layers.data('b', shape=[4], dtype='float32')
        st = InitState(init_boot=boot, shape=[-1, 4], value=0.0)
        with pytest.raises(ValueError):
            StateCell(inputs={}, states={'h': st}, out_state='nope')
        with pytest.raises(ValueError):
            StateCell(inputs={}, states={'h': 3}, out_state='h')
        cell = StateCell(inputs={'x': None}, states={'h': st},
                         out_state='h')
        with pytest.raises(ValueError):
            cell.get_input('x')          # still a placeholder
        with pytest.raises(ValueError):
            cell.compute_state(inputs={'bogus': boot})


def test_beam_decode_with_attention_static_input():
    """input_var_dict carries a rank-3 encoder sequence [B, T, H] into
    the search: each beam attends over its sentence's encoder states
    (the reference reaches this via sequence_expand on LoD; the fixed-
    beam redesign tiles the input across the K lanes)."""
    prog, start = fluid.Program(), fluid.Program()
    with program_guard(prog, start):
        src = fluid.layers.data('src', shape=[1], dtype='int64',
                                lod_level=1)
        emb = fluid.layers.embedding(
            src, size=[V, D], param_attr=ParamAttr(name='att_emb'))
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(emb)
            hp = drnn.memory(shape=[H], value=0.0)
            h = fluid.layers.fc(fluid.layers.concat([x_t, hp], axis=1),
                                size=H, act='tanh',
                                param_attr=ParamAttr(name='att_enc_w'),
                                bias_attr=ParamAttr(name='att_enc_b'))
            drnn.update_memory(hp, h)
            drnn.output(h)
        enc_seq = drnn()                                  # [B, T, H]
        enc_last = fluid.layers.sequence_pool(enc_seq, 'last')

        def attn_updater(cell):
            x = cell.get_input('x')                       # [B*K, D]
            ctx_seq = cell.get_input('enc')               # [B*K, T, H]
            h_pre = cell.get_state('h')                   # [B*K, H]
            # dot-product attention of h_pre over the encoder states
            att = fluid.layers.matmul(
                ctx_seq, fluid.layers.unsqueeze(h_pre, axes=[2]))
            w = fluid.layers.softmax(
                fluid.layers.reshape(att, shape=[-1, TMAX]))
            ctx = fluid.layers.reshape(
                fluid.layers.matmul(
                    fluid.layers.unsqueeze(w, axes=[1]), ctx_seq),
                shape=[-1, H])                            # [B*K, H]
            h = fluid.layers.fc(
                fluid.layers.concat([x, h_pre, ctx], axis=1),
                size=H, act='tanh',
                param_attr=ParamAttr(name='att_dec_w'),
                bias_attr=ParamAttr(name='att_dec_b'))
            cell.set_state('h', h)

        cell = StateCell(inputs={'x': None, 'enc': None},
                         states={'h': InitState(init=enc_last)},
                         out_state='h')
        cell.state_updater(attn_updater)

        ii = fluid.layers.fill_constant_batch_size_like(
            enc_last, shape=[-1, 1], dtype='int64', value=BOS)
        sc = fluid.layers.fill_constant_batch_size_like(
            enc_last, shape=[-1, 1], dtype='float32', value=0.0)
        dec = BeamSearchDecoder(
            cell, ii, sc, target_dict_dim=V, word_dim=D,
            input_var_dict={'enc': enc_seq}, max_len=TMAX, beam_size=2,
            end_id=EOS, emb_param_attr=ParamAttr(name='att_emb2'))
        dec.decode()
        sent, scores = dec()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(start)
    rng = np.random.RandomState(1)
    sv, scv = exe.run(
        prog,
        feed={'src': rng.randint(2, V, (5, TMAX, 1)).astype('int64'),
              'src@LEN': np.array([4, 3, 2, 4, 3], 'int32')},
        fetch_list=[sent, scores])
    assert np.asarray(sv).shape == (5, 2, TMAX)
    assert np.isfinite(np.asarray(scv)).all()


def test_state_cell_serves_two_decoders():
    """A single StateCell may drive a TrainingDecoder and then a
    BeamSearchDecoder (the id(decoder)-keyed holder exists for this)."""
    prog, start = fluid.Program(), fluid.Program()
    with program_guard(prog, start):
        boot = fluid.layers.data('b', shape=[H], dtype='float32')
        cell = StateCell(inputs={'x': None},
                         states={'h': InitState(init=boot)},
                         out_state='h')
        cell.state_updater(_cell_updater)

        emb_seq = fluid.layers.data('seq', shape=[D], dtype='float32',
                                    lod_level=1)
        tdec = TrainingDecoder(cell)
        with tdec.block():
            e_t = tdec.step_input(emb_seq)
            tdec.state_cell.compute_state(inputs={'x': e_t})
            h = tdec.state_cell.get_state('h')
            tdec.state_cell.update_states()
            tdec.output(h)
        tdec()

        ii = fluid.layers.fill_constant_batch_size_like(
            boot, shape=[-1, 1], dtype='int64', value=BOS)
        sc = fluid.layers.fill_constant_batch_size_like(
            boot, shape=[-1, 1], dtype='float32', value=0.0)
        bdec = BeamSearchDecoder(cell, ii, sc, target_dict_dim=V,
                                 word_dim=D, max_len=2, beam_size=2,
                                 end_id=EOS)
        bdec.decode()            # raised KeyError before the holder fix
        sent, scores = bdec()
        assert tuple(sent.shape[-3:]) != ()


def test_training_decoder_block_discipline():
    prog, start = fluid.Program(), fluid.Program()
    with program_guard(prog, start):
        boot = fluid.layers.data('b', shape=[4], dtype='float32')
        cell = StateCell(inputs={'x': None},
                         states={'h': InitState(init=boot)},
                         out_state='h')
        dec = TrainingDecoder(cell)
        with pytest.raises(ValueError):
            dec.step_input(boot)         # outside block()
        with pytest.raises(ValueError):
            dec()                        # output before block closes
