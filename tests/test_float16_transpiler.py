"""Bfloat16 inference transpiler (reference
paddle/contrib/float16/float16_transpiler.py:21): an fp32 inference
program + scope is rewritten in place to compute in bf16 while the user
still feeds fp32 and fetches fp32."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib import Bfloat16Transpiler, Float16Transpiler


def _build_and_train(tmp_path, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(5, 16).astype("float32")
    ys = rng.randint(0, 5, 256)
    xs = (centers[ys] + 0.15 * rng.randn(256, 16)).astype("float32")

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        pred = fluid.layers.fc(h, size=5, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)

        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            for i in range(0, 256, 64):
                exe.run(feed={"x": xs[i:i + 64],
                              "label": ys[i:i + 64, None].astype("int64")},
                        fetch_list=[loss])
            fluid.io.save_inference_model(
                str(tmp_path / "m"), ["x"], [pred], exe)
    return xs, ys


def test_bf16_transpile_matches_fp32(tmp_path):
    xs, ys = _build_and_train(tmp_path)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        prog, feed_names, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path / "m"), exe)
        ref, = exe.run(prog, feed={"x": xs[:64]},
                       fetch_list=[fetch_vars[0].name])

        t = Bfloat16Transpiler()
        t.transpile(prog, fluid.CPUPlace(), scope=scope,
                    fetch_targets=fetch_vars)

        # params in the scope are bf16 now
        blk = prog.global_block()
        w_names = [p.name for p in blk.all_parameters()]
        assert w_names
        for n in w_names:
            assert str(np.asarray(scope.find_var(n)).dtype) == "bfloat16", n

        # user still feeds fp32 and fetches fp32
        out, = exe.run(prog, feed={"x": xs[:64]},
                       fetch_list=[fetch_vars[0].name])
        out = np.asarray(out)
        assert out.dtype == np.float32
        np.testing.assert_allclose(
            np.sum(out, axis=1), np.ones(64), rtol=2e-2)
        # bf16 has ~8 mantissa bits: probabilities close, argmax identical
        np.testing.assert_allclose(out, np.asarray(ref), atol=0.03)
        assert np.array_equal(np.argmax(out, 1), np.argmax(np.asarray(ref), 1))


def test_bf16_orphan_feed_var_not_required(tmp_path):
    """A feed var the pruned program keeps but no op consumes must not
    gain a cast op (it would turn an optional input into a required
    one)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4])
        fluid.layers.data("aux", shape=[4])  # never consumed
        pred = fluid.layers.fc(x, size=2, act="softmax")
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            fluid.io.save_inference_model(
                str(tmp_path / "m2"), ["x", "aux"], [pred], exe)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        prog, _, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path / "m2"), exe)
        Bfloat16Transpiler().transpile(
            prog, fluid.CPUPlace(), scope=scope, fetch_targets=fetch_vars)
        out, = exe.run(prog, feed={"x": np.zeros((3, 4), "float32")},
                       fetch_list=[fetch_vars[0].name])
        assert np.asarray(out).shape == (3, 2)


def test_bf16_fp32_islands_and_alias(tmp_path):
    """softmax (AMP black list) keeps fp32 inputs via inserted casts;
    Float16Transpiler is the reference-named alias."""
    assert Float16Transpiler is Bfloat16Transpiler
    xs, _ = _build_and_train(tmp_path, seed=1)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        prog, _, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path / "m"), exe)
        Bfloat16Transpiler().transpile(
            prog, fluid.CPUPlace(), scope=scope, fetch_targets=fetch_vars)
        blk = prog.global_block()
        sm = [op for op in blk.ops if op.type == "softmax"]
        assert sm, "model should contain softmax"
        for op in sm:
            for n in op.input_arg_names:
                v = blk._find_var_recursive(n)
                assert str(np.dtype(v.dtype)) != "bfloat16", \
                    "softmax input should be fp32-guarded, got bf16 %r" % n
        casts = [op for op in blk.ops if op.type == "cast"]
        assert len(casts) >= 2  # feed cast + fp32 guard at least
