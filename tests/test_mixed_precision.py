"""bf16 automatic-mixed-precision tests (contrib.mixed_precision — the
TPU rebuild of contrib/float16/float16_transpiler.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.contrib import mixed_precision as amp
from paddle_tpu.core import bfloat16


def test_whitelisted_matmul_computes_in_bf16(fresh_programs):
    x = fluid.layers.data("x", shape=[4])
    w = fluid.layers.data("w", shape=[4, 3], append_batch_size=False)
    y = fluid.layers.matmul(x, w)
    prog = fluid.default_main_program()
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.random.rand(2, 4).astype("float32")
    wv = np.random.rand(4, 3).astype("float32")
    feed = {"x": xv, "w": wv}

    (out_fp32,) = exe.run(feed=feed, fetch_list=[y], return_numpy=False)
    assert jnp.asarray(out_fp32).dtype == jnp.float32
    with amp.bf16_program_guard(prog):
        (out_bf16,) = exe.run(feed=feed, fetch_list=[y],
                              return_numpy=False)
    assert jnp.asarray(out_bf16).dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out_bf16, dtype=np.float32), np.asarray(out_fp32),
        rtol=2e-2)


def test_blacklisted_loss_stays_fp32(fresh_programs):
    x = fluid.layers.data("x", shape=[4])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    logits = fluid.layers.fc(x, size=3, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.SGD(learning_rate=0.0).minimize(loss)
    prog = fluid.default_main_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with amp.bf16_program_guard(prog):
        (lv,) = exe.run(
            feed={"x": np.random.rand(2, 4).astype("float32"),
                  "label": np.array([[0], [1]], "int64")},
            fetch_list=[loss], return_numpy=False)
    assert jnp.asarray(lv).dtype == jnp.float32


def test_decorated_optimizer_trains_and_keeps_fp32_master_weights(
        fresh_programs):
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    opt = amp.decorate(fluid.optimizer.Adam(learning_rate=1e-2))
    opt.minimize(loss)
    assert fluid.default_main_program()._amp_policy is not None

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    proj = rng.rand(8, 4).astype("float32")
    losses = []
    for _ in range(30):
        xv = rng.rand(32, 8).astype("float32")
        yv = (xv @ proj).argmax(1).astype("int64").reshape(-1, 1)
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.8
    # master weights stay fp32 in the scope
    scope = fluid.global_scope()
    for p in fluid.default_main_program().global_block().all_parameters():
        assert np.dtype(scope.var(p.name).dtype) == np.float32, p.name


def test_amp_matches_fp32_within_bf16_tolerance(fresh_programs):
    def build():
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax",
                               param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        return loss

    rng = np.random.RandomState(1)
    xv = rng.rand(16, 8).astype("float32")
    yv = rng.randint(0, 4, (16, 1)).astype("int64")

    results = {}
    for use_amp in (False, True):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            fluid.default_startup_program().random_seed = 7
            fluid.default_main_program().random_seed = 7
            loss = build()
            opt = fluid.optimizer.SGD(learning_rate=0.1)
            if use_amp:
                opt = amp.decorate(opt)
            opt.minimize(loss)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(fluid.default_startup_program())
                for _ in range(5):
                    (lv,) = exe.run(feed={"x": xv, "label": yv},
                                    fetch_list=[loss])
                results[use_amp] = float(np.asarray(lv).ravel()[0])
    assert results[True] == pytest.approx(results[False], rel=0.05)


def test_cast_parameters_to_bf16(fresh_programs):
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=2, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    amp.cast_parameters_to_bf16(fluid.default_main_program(), scope)
    params = fluid.default_main_program().global_block().all_parameters()
    assert params
    for p in params:
        assert jnp.asarray(scope.var(p.name)).dtype == jnp.bfloat16
    # inference still runs (gray ops follow input promotion)
    (out,) = exe.run(feed={"x": np.random.rand(2, 4).astype("float32")},
                     fetch_list=[y], return_numpy=False)
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


def test_amp_transformer_hlo_emits_bf16_dots(fresh_programs):
    """The AMP policy must change the compiled HLO, not just dtypes at the
    Python level: lower the real transformer train step under decorate()
    and assert the lowered module's dot_generals take bf16 operands
    (VERDICT r2: prove AMP isn't a no-op)."""
    import re

    import jax

    from paddle_tpu.executor import trace_program
    from paddle_tpu.models import transformer as tfm

    src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                            lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                            lod_level=1)
    lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                            lod_level=1)
    cost, _ = tfm.transformer(src, tgt, lbl, 8, 8, 32, 32, n_layer=1,
                              n_head=2, d_model=16, d_inner=32,
                              dropout_rate=0.1)
    opt = amp.decorate(fluid.optimizer.Adam(learning_rate=1e-3))
    opt.minimize(cost)
    prog = fluid.default_main_program()
    assert getattr(prog, "_amp_policy", None) is not None

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    b, t = 2, 8
    ids = np.random.RandomState(0).randint(2, 32, (b, t, 1)).astype("int64")
    lens = np.full((b,), t, "int32")
    feed = {"src_word": ids, "src_word@LEN": lens,
            "tgt_word": ids, "tgt_word@LEN": lens,
            "lbl_word": ids, "lbl_word@LEN": lens}
    feed_names = sorted(feed)
    state_names, writeback = exe._analyze(prog, feed_names, scope)
    fn, state_in, _ = trace_program(prog, feed_names, state_names,
                                    writeback, [cost.name])
    txt = jax.jit(fn).lower([feed[n] for n in feed_names],
                            [np.asarray(scope.var(n)) for n in state_in],
                            jax.random.key(0)).as_text()
    dots = re.findall(r"stablehlo\.dot_general.*", txt)
    assert dots, "no dot_general in lowered module"
    bf16_dots = [d for d in dots if "bf16" in d]
    # every fc/matmul/fused_attention dot (fwd + recomputed bwd) is
    # white-listed: the bf16 dots must dominate the module
    assert len(bf16_dots) >= len(dots) * 0.6, (
        "AMP left %d/%d dot_generals in fp32" %
        (len(dots) - len(bf16_dots), len(dots)))
