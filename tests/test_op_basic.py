"""Per-op golden tests (the reference's test_*_op.py pattern, SURVEY §4.2)."""

import numpy as np
import pytest

from op_test import OpTest


def _softmax_np(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


class TestMulOp(OpTest):
    op_type = "mul"

    def setup_method(self, method):
        rng = np.random.RandomState(1)
        self.inputs = {
            "X": rng.uniform(-1, 1, (4, 5)).astype("float32"),
            "Y": rng.uniform(-1, 1, (5, 3)).astype("float32"),
        }
        self.attrs = {}
        self.outputs = {"Out": self.inputs["X"] @ self.inputs["Y"]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["mul__X", "mul__Y"], "mul__Out",
                        max_relative_error=0.02)


class TestMulOpFlatten(OpTest):
    op_type = "mul"

    def setup_method(self, method):
        rng = np.random.RandomState(2)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        y = rng.uniform(-1, 1, (4, 5)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 2, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(6, 4) @ y).reshape(2, 3, 5)}

    def test_output(self):
        self.check_output()


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup_method(self, method):
        rng = np.random.RandomState(3)
        x = rng.uniform(-1, 1, (2, 4, 5)).astype("float32")
        y = rng.uniform(-1, 1, (2, 3, 5)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_Y": True}
        self.outputs = {"Out": x @ y.transpose(0, 2, 1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["matmul__X", "matmul__Y"], "matmul__Out",
                        max_relative_error=0.02)


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup_method(self, method):
        rng = np.random.RandomState(4)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        y = rng.uniform(-1, 1, (3,)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["elementwise_add__X", "elementwise_add__Y"],
                        "elementwise_add__Out", max_relative_error=0.02)


class TestElementwiseDiv(OpTest):
    op_type = "elementwise_div"

    def setup_method(self, method):
        rng = np.random.RandomState(5)
        x = rng.uniform(0.5, 2, (3, 4)).astype("float32")
        y = rng.uniform(0.5, 2, (3, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x / y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["elementwise_div__X", "elementwise_div__Y"],
                        "elementwise_div__Out", max_relative_error=0.02)


@pytest.mark.parametrize(
    "act,fn",
    [
        ("relu", lambda x: np.maximum(x, 0)),
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("exp", np.exp),
        ("square", lambda x: x * x),
        ("softsign", lambda x: x / (1 + np.abs(x))),
        ("softplus", lambda x: np.log1p(np.exp(x))),
        ("abs", np.abs),
    ],
)
def test_activation_forward(act, fn):
    class T(OpTest):
        op_type = act

    t = T()
    rng = np.random.RandomState(6)
    x = rng.uniform(-2, 2, (3, 7)).astype("float32")
    # keep away from relu/abs kink for numeric stability
    x[np.abs(x) < 0.05] = 0.5
    t.inputs = {"X": x}
    t.outputs = {"Out": fn(x)}
    t.check_output()
    t.check_grad(["%s__X" % act], "%s__Out" % act, max_relative_error=0.03)


class TestSoftmaxOp(OpTest):
    op_type = "softmax"

    def setup_method(self, method):
        rng = np.random.RandomState(7)
        x = rng.uniform(-1, 1, (5, 9)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": _softmax_np(x)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["softmax__X"], "softmax__Out",
                        max_relative_error=0.03)


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup_method(self, method):
        rng = np.random.RandomState(8)
        logits = rng.uniform(-1, 1, (6, 10)).astype("float32")
        label = rng.randint(0, 10, (6, 1)).astype("int64")
        sm = _softmax_np(logits)
        loss = -np.log(sm[np.arange(6), label.reshape(-1)]).reshape(6, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(
            ["softmax_with_cross_entropy__Logits"],
            "softmax_with_cross_entropy__Loss", max_relative_error=0.03,
        )


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup_method(self, method):
        rng = np.random.RandomState(9)
        x = _softmax_np(rng.uniform(-1, 1, (4, 6)).astype("float32"))
        label = rng.randint(0, 6, (4, 1)).astype("int64")
        y = -np.log(x[np.arange(4), label.reshape(-1)]).reshape(4, 1)
        self.inputs = {"X": x.astype("float32"), "Label": label}
        self.outputs = {"Y": y}

    def test_output(self):
        self.check_output()


class TestMeanOp(OpTest):
    op_type = "mean"

    def setup_method(self, method):
        rng = np.random.RandomState(10)
        x = rng.uniform(-1, 1, (4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray([x.mean()], dtype="float32")}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["mean__X"], "mean__Out", max_relative_error=0.02)


class TestSumOp(OpTest):
    op_type = "sum"

    def setup_method(self, method):
        rng = np.random.RandomState(11)
        a = rng.uniform(-1, 1, (3, 4)).astype("float32")
        b = rng.uniform(-1, 1, (3, 4)).astype("float32")
        c = rng.uniform(-1, 1, (3, 4)).astype("float32")
        self.inputs = {"X": [("sum_a", a), ("sum_b", b), ("sum_c", c)]}
        self.outputs = {"Out": a + b + c}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["sum_a", "sum_b"], "sum__Out",
                        max_relative_error=0.02)


@pytest.mark.parametrize(
    "op,np_fn",
    [
        ("reduce_sum", np.sum),
        ("reduce_mean", np.mean),
        ("reduce_max", np.max),
        ("reduce_min", np.min),
    ],
)
def test_reduce_ops(op, np_fn):
    class T(OpTest):
        op_type = op

    t = T()
    rng = np.random.RandomState(12)
    x = rng.uniform(-1, 1, (3, 4, 5)).astype("float32")
    t.inputs = {"X": x}
    t.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
    t.outputs = {"Out": np_fn(x, axis=1)}
    t.check_output()


class TestReshape(OpTest):
    op_type = "reshape"

    def setup_method(self, method):
        rng = np.random.RandomState(13)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, -1]}
        self.outputs = {"Out": x.reshape(2, 12)}

    def test_output(self):
        self.check_output()


class TestTranspose(OpTest):
    op_type = "transpose"

    def setup_method(self, method):
        rng = np.random.RandomState(14)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 0, 2]}
        self.outputs = {"Out": x.transpose(1, 0, 2)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["transpose__X"], "transpose__Out",
                        max_relative_error=0.02)


class TestConcat(OpTest):
    op_type = "concat"

    def setup_method(self, method):
        rng = np.random.RandomState(15)
        a = rng.uniform(-1, 1, (2, 3)).astype("float32")
        b = rng.uniform(-1, 1, (2, 5)).astype("float32")
        self.inputs = {"X": [("cat_a", a), ("cat_b", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["cat_a", "cat_b"], "concat__Out",
                        max_relative_error=0.02)


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def setup_method(self, method):
        rng = np.random.RandomState(16)
        w = rng.uniform(-1, 1, (10, 4)).astype("float32")
        ids = rng.randint(0, 10, (5, 1)).astype("int64")
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids.reshape(-1)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["lookup_table__W"], "lookup_table__Out",
                        max_relative_error=0.02)


class TestTopK(OpTest):
    op_type = "top_k"

    def setup_method(self, method):
        rng = np.random.RandomState(17)
        x = rng.uniform(-1, 1, (4, 8)).astype("float32")
        k = 3
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {
            "Out": vals, "Indices": idx.astype("int64"),
        }

    def test_output(self):
        self.check_output()


class TestSigmoidCrossEntropyWithLogits(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def setup_method(self, method):
        rng = np.random.RandomState(18)
        x = rng.uniform(-2, 2, (4, 5)).astype("float32")
        label = rng.uniform(0, 1, (4, 5)).astype("float32")
        loss = np.maximum(x, 0) - x * label + np.log1p(np.exp(-np.abs(x)))
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Out": loss}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["sigmoid_cross_entropy_with_logits__X"],
                        "sigmoid_cross_entropy_with_logits__Out",
                        max_relative_error=0.03)


class TestScale(OpTest):
    op_type = "scale"

    def setup_method(self, method):
        rng = np.random.RandomState(19)
        x = rng.uniform(-1, 1, (3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 0.5}
        self.outputs = {"Out": x * 2.5 + 0.5}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["scale__X"], "scale__Out", max_relative_error=0.02)
