"""TrainState exact-resume checkpoints (ISSUE 6 tentpole): full-state
capture/apply round trips (params + optimizer slots + LR counter +
executor PRNG counter + reader position), atomic commit + checksum
manifest, corruption fallback, async overlap (checkpoint/save monitor
span, non-blocking save), and in-process exact-resume loss parity."""

import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.parallel import checkpoint as ck
from paddle_tpu.reader import checkpointable
from paddle_tpu.scope import global_scope


def _build(seed=7, lr_decay=False, dropout=0.0):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    if dropout:
        h = fluid.layers.dropout(h, dropout_prob=dropout)
    pred = fluid.layers.fc(h, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    lr = fluid.layers.exponential_decay(1e-2, decay_steps=3,
                                        decay_rate=0.7) if lr_decay \
        else 1e-2
    fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return loss


def _batch(rng, n=4):
    return {"x": rng.rand(n, 8).astype("float32"),
            "label": rng.randint(0, 4, (n, 1)).astype("int64")}


def _persist_snap(scope, program):
    # copy=True: np.asarray of a CPU jax.Array is a zero-copy VIEW and
    # a later step donates the buffer (the exact tear the snapshot
    # itself guards against — see capture_train_state)
    return {v.name: np.array(scope.var(v.name), copy=True)
            for v in program.global_block().vars.values()
            if v.persistable and scope.has_var(v.name)}


def test_capture_covers_full_train_state(fresh_programs):
    """The snapshot holds params AND optimizer slot vars AND the LR /
    in-graph step-counter vars AND the executor PRNG counter — the
    exact set whose silent reset the old params-only path caused."""
    loss = _build(lr_decay=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    train_exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    for _ in range(3):
        train_exe.run(feed=_batch(rng), fetch_list=[loss])
    ts = ck.capture_train_state(
        3, program=fluid.default_main_program(),
        executors={"train": train_exe})
    names = set(ts.arrays)
    assert any("moment" in n for n in names), names      # Adam slots
    assert any("beta" in n for n in names), names        # Adam powers
    # the LR schedule is an in-graph function of the persistable
    # step-counter var — the counter IS the restorable LR state
    assert any("LR_DECAY_COUNTER" in n for n in names), names
    assert ts.host["executors"]["train"]["run_counter"] == 3
    assert ts.step == 3


def test_save_load_roundtrip_and_atomic_layout(tmp_path, fresh_programs):
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    exe.run(feed=_batch(rng), fetch_list=[loss])
    ts = ck.capture_train_state(1, program=fluid.default_main_program())
    d = str(tmp_path / "one")
    ck.save_train_state(d, ts)
    # artifact layout: arrays + host state + manifest, no tmp leftovers
    assert sorted(os.listdir(d)) == ["MANIFEST.json", "arrays.npz",
                                     "train_state.json"]
    assert not [e for e in os.listdir(str(tmp_path))
                if e.startswith(".tmp.")]
    got = ck.load_train_state(d)
    assert got.step == 1
    assert set(got.arrays) == set(ts.arrays)
    for n in ts.arrays:
        np.testing.assert_array_equal(got.arrays[n], ts.arrays[n])
        assert got.arrays[n].dtype == ts.arrays[n].dtype


def test_nonnative_dtype_roundtrip(tmp_path):
    """bfloat16 state (AMP master runs) survives the npz round trip via
    the raw-view encoding (npy itself degrades it to void)."""
    import ml_dtypes

    a = np.arange(12, dtype=ml_dtypes.bfloat16).reshape(3, 4)
    ts = ck.TrainState(0, {"w": a, "b": np.ones(3, "float32")}, {
        "format": ck.TRAIN_STATE_FORMAT, "step": 0,
        "executors": {}, "readers": {}, "extra": {}})
    d = str(tmp_path / "bf16")
    ck.save_train_state(d, ts)
    got = ck.load_train_state(d)
    assert got.arrays["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        got.arrays["w"].astype(np.float32), a.astype(np.float32))
    assert got.arrays["b"].dtype == np.float32


def test_corrupt_artifact_detected_and_restore_falls_back(
        tmp_path, fresh_programs):
    """Acceptance: corrupt-latest -> restore falls back to the previous
    step without crashing; missing manifest and torn tmp dirs are also
    non-fatal."""
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "m"),
                                         async_save=False)
    exe.run(feed=_batch(rng), fetch_list=[loss])
    mgr.save(1, program=fluid.default_main_program())
    want = _persist_snap(global_scope(), fluid.default_main_program())
    exe.run(feed=_batch(rng), fetch_list=[loss])
    mgr.save(2, program=fluid.default_main_program())
    assert mgr.all_steps() == [1, 2]

    # garble the latest artifact's arrays payload
    victim = os.path.join(str(tmp_path / "m"), "step_%010d" % 2,
                          "arrays.npz")
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef" * 4)
    with pytest.raises(ck.CheckpointCorruptError):
        ck.load_train_state(os.path.dirname(victim))

    # a torn tmp dir (kill mid-save) must also be ignored
    os.makedirs(os.path.join(str(tmp_path / "m"), ".tmp.step_junk.123"))
    with pytest.warns(UserWarning, match="corrupt"):
        step = mgr.restore(program=fluid.default_main_program())
    assert step == 1
    got = _persist_snap(global_scope(), fluid.default_main_program())
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])

    # explicit step restore of the corrupt artifact DOES raise
    with pytest.raises(ck.CheckpointCorruptError):
        mgr.restore(program=fluid.default_main_program(), step=2)


def test_restore_with_no_valid_checkpoint_returns_none(tmp_path,
                                                       fresh_programs):
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "empty"))
    assert mgr.restore(program=fluid.default_main_program()) is None


def test_strict_restore_rejects_model_mismatch(tmp_path, fresh_programs):
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    ts = ck.capture_train_state(0, program=fluid.default_main_program())
    del ts.arrays[sorted(ts.arrays)[0]]        # drop one var
    with pytest.raises(ck.CheckpointCorruptError, match="lacks"):
        ck.apply_train_state(ts, program=fluid.default_main_program())
    # strict=False restores the intersection
    ck.apply_train_state(ts, program=fluid.default_main_program(),
                         strict=False)


def test_rotation_and_interval_gating(tmp_path, fresh_programs):
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    mgr = ck.TrainStateCheckpointManager(
        str(tmp_path / "rot"), max_to_keep=2, save_interval_steps=3,
        async_save=False)
    saved = [s for s in range(1, 11) if mgr.save(s, program=prog)]
    assert saved == [1, 4, 7, 10]              # gated on the interval
    assert mgr.all_steps() == [7, 10]          # rotated to max_to_keep
    assert mgr.latest_step() == 10
    # a fresh manager over the same dir resumes the gating from disk
    mgr2 = ck.TrainStateCheckpointManager(
        str(tmp_path / "rot"), max_to_keep=2, save_interval_steps=3)
    assert mgr2.save(11, program=prog) is False
    assert mgr2.save(13, program=prog) is True
    mgr2.close()


def test_async_save_overlaps_and_publishes_span(tmp_path, monkeypatch,
                                                fresh_programs):
    """Acceptance: the write runs in the background (save() returns
    before a deliberately slowed write lands) and shows up as a
    checkpoint/save monitor span — overlap, not step time."""
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    prog = fluid.default_main_program()

    real = ck.save_train_state
    delay = 0.5

    def slow_save(dirname, ts):
        time.sleep(delay)
        return real(dirname, ts)

    monkeypatch.setattr(ck, "save_train_state", slow_save)
    monitor.registry().reset()
    monitor.enable()
    try:
        mgr = ck.TrainStateCheckpointManager(str(tmp_path / "a"),
                                             async_save=True)
        t0 = time.perf_counter()
        assert mgr.save(1, program=prog)
        returned_in = time.perf_counter() - t0
        assert returned_in < delay / 2, (
            "async save blocked the caller for %.3fs" % returned_in)
        assert threading.active_count() >= 2
        mgr.wait_until_finished()
        assert (time.perf_counter() - t0) >= delay
        assert mgr.all_steps() == [1]
        text = monitor.expose_text()     # names sanitized for Prometheus
        assert "span_checkpoint_save" in text
        assert "span_checkpoint_snapshot" in text
        assert "mark_checkpoint_saved" in text
    finally:
        monitor.disable()
        monitor.registry().reset()


def test_async_write_failure_surfaces_on_next_call(tmp_path, monkeypatch,
                                                   fresh_programs):
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    prog = fluid.default_main_program()

    def boom(dirname, ts):
        raise OSError("disk full")

    monkeypatch.setattr(ck, "save_train_state", boom)
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "f"),
                                         async_save=True)
    assert mgr.save(1, program=prog)
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        mgr.wait_until_finished()


def test_checkpointable_reader_position_roundtrip():
    src = lambda: iter(range(10))
    r = checkpointable(src)
    it = r()
    assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
    st = r.state_dict()
    assert st == {"epoch": 0, "offset": 4}

    r2 = checkpointable(src)
    r2.load_state_dict(st)
    assert list(r2()) == [4, 5, 6, 7, 8, 9]    # fast-forwarded
    assert r2.state_dict() == {"epoch": 1, "offset": 0}
    assert list(r2())[:3] == [0, 1, 2]         # next epoch from the top

    with pytest.raises(TypeError, match="CREATOR"):
        checkpointable([1, 2, 3])


def test_exact_resume_loss_parity_in_process(tmp_path, fresh_programs):
    """The tentpole guarantee, in-process: train 10 steps straight vs
    train 6 / checkpoint / rebuild everything / restore / train 4 —
    the two loss trajectories are BIT-identical (dropout + LR decay +
    Adam slots + reader position all restored)."""

    def data_reader():
        rng = np.random.RandomState(42)
        for _ in range(64):
            yield _batch(rng)

    def run(steps, reader, mgr=None, resume=False, save_at=None):
        # each leg builds the net under its own name guard so the
        # persistable var names line up across save/restore legs
        with fluid.unique_name.guard(), \
                fluid.program_guard(fluid.Program(), fluid.Program()), \
                fluid.scope_guard(fluid.Scope()):
            losses = []
            loss = _build(lr_decay=True, dropout=0.3)
            fluid.Executor(fluid.CPUPlace()).run(
                fluid.default_startup_program())
            exe = fluid.Executor(fluid.CPUPlace())
            step = 0
            if resume:
                step = mgr.restore(program=fluid.default_main_program(),
                                   executors={"train": exe},
                                   readers={"train": reader}) or 0
            it = iter(reader())
            while step < steps:
                (lv,) = exe.run(feed=next(it), fetch_list=[loss])
                step += 1
                losses.append(np.asarray(lv).tobytes())
                if save_at == step:
                    mgr.save_now(step,
                                 program=fluid.default_main_program(),
                                 executors={"train": exe},
                                 readers={"train": reader})
            return step, losses

    # uninterrupted reference
    _, ref = run(10, checkpointable(data_reader))

    # interrupted at step 6, then resumed in a fresh world
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "e"),
                                         async_save=False)
    _, first = run(6, checkpointable(data_reader), mgr=mgr, save_at=6)
    _, rest = run(10, checkpointable(data_reader), mgr=mgr, resume=True)
    assert first + rest == ref


def test_strict_executor_name_mismatch_leaves_scope_untouched(
        tmp_path, fresh_programs):
    """A checkpoint rejected for an executor-name mismatch must not
    half-apply: the scope keeps its pre-restore values (review fix)."""
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    train_exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(3)
    train_exe.run(feed=_batch(rng), fetch_list=[loss])
    ts = ck.capture_train_state(1, program=prog,
                                executors={"train": train_exe})
    train_exe.run(feed=_batch(rng), fetch_list=[loss])
    after_step2 = _persist_snap(global_scope(), prog)
    with pytest.raises(ck.CheckpointCorruptError, match="executor"):
        ck.apply_train_state(ts, program=prog,
                             executors={"other_name": train_exe})
    now = _persist_snap(global_scope(), prog)
    for k in after_step2:       # scope still holds the step-2 state
        np.testing.assert_array_equal(now[k], after_step2[k])


def test_same_step_resave_and_save_now_noop(tmp_path, fresh_programs):
    """Re-saving an existing step keeps a valid artifact (rename-aside
    commit), and save_now of an already-committed step is a no-op
    rather than a redundant rewrite (review fixes)."""
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "rs"),
                                        async_save=False)
    assert mgr.save(1, program=prog)
    first_mtime = os.path.getmtime(
        os.path.join(str(tmp_path / "rs"), "step_%010d" % 1,
                     "MANIFEST.json"))
    # flush of the same committed step: no rewrite
    assert mgr.save_now(1, program=prog)
    assert os.path.getmtime(
        os.path.join(str(tmp_path / "rs"), "step_%010d" % 1,
                     "MANIFEST.json")) == first_mtime
    # an explicit re-save of the same step (fresh manager, same dir)
    # overwrites through the rename-aside path and stays valid
    mgr2 = ck.TrainStateCheckpointManager(str(tmp_path / "rs"),
                                         async_save=False,
                                         save_interval_steps=1)
    ts = ck.capture_train_state(1, program=prog)
    ck.save_train_state(os.path.join(str(tmp_path / "rs"),
                                     "step_%010d" % 1), ts)
    got = ck.load_train_state(os.path.join(str(tmp_path / "rs"),
                                           "step_%010d" % 1))
    assert got.step == 1 and mgr2.latest_step() == 1


def test_restore_reseeds_save_cadence_past_corrupt_latest(
        tmp_path, fresh_programs):
    """After falling back past a corrupt latest artifact, the save
    cadence restarts from the RESTORED step, so the skipped index is
    re-saved (overwriting the corrupt dir) instead of warned forever
    (review fix)."""
    loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    rng = np.random.RandomState(4)
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "c"),
                                        async_save=False,
                                        save_interval_steps=5)
    exe.run(feed=_batch(rng), fetch_list=[loss])
    mgr.save(1, program=prog)
    for _ in range(5):
        exe.run(feed=_batch(rng), fetch_list=[loss])
    mgr.save(6, program=prog)
    # corrupt step 6, restore -> 1, next save must land at 6 again
    victim = os.path.join(str(tmp_path / "c"), "step_%010d" % 6,
                          "arrays.npz")
    with open(victim, "r+b") as f:
        f.seek(8)
        f.write(b"\x00" * 32)
    mgr2 = ck.TrainStateCheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         save_interval_steps=5)
    with pytest.warns(UserWarning, match="corrupt"):
        assert mgr2.restore(program=prog) == 1
    assert mgr2.save(3, program=prog) is False      # 3 < 1 + 5
    assert mgr2.save(6, program=prog) is True       # overwrites corrupt 6
    assert ck.load_train_state(os.path.dirname(victim)).step == 6


def test_restore_surfaces_model_mismatch_instead_of_fresh_start(
        tmp_path, fresh_programs):
    """A structural misfit (model changed) must RAISE from restore(),
    not be skipped as 'corrupt' all the way down to a silent fresh
    start (review fix: CheckpointMismatchError stops the fallback)."""
    _build()
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    mgr = ck.TrainStateCheckpointManager(str(tmp_path / "mm"),
                                        async_save=False)
    mgr.save(1, program=fluid.default_main_program())

    with fluid.unique_name.guard(), \
            fluid.program_guard(fluid.Program(), fluid.Program()), \
            fluid.scope_guard(fluid.Scope()):
        # a DIFFERENT model over the same checkpoint dir
        x = fluid.layers.data("x", shape=[8])
        h = fluid.layers.fc(x, size=32, act="relu")   # extra layer
        pred = fluid.layers.fc(h, size=2)
        fluid.optimizer.SGD(0.1).minimize(
            fluid.layers.mean(fluid.layers.square(pred)))
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        mgr2 = ck.TrainStateCheckpointManager(str(tmp_path / "mm"))
        with pytest.raises(ck.CheckpointMismatchError, match="model"):
            mgr2.restore(program=fluid.default_main_program())
