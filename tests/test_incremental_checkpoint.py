"""Incremental (Check-N-Run-style) table checkpoints — ISSUE 15:
per-interval touched-row deltas against a periodic full base, bitwise
replay, chain-aware rotation, restore-seeded chains, the per-host
sharded delta leg, and the exact-resume acceptance drill."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import program_guard
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.parallel.checkpoint import (
    CheckpointCorruptError, TrainStateCheckpointManager,
    capture_train_state, commit_sharded_train_state, load_train_state,
    partition_shards, row_delta, sparse_table_state_vars,
    write_train_state_shards)

V, D, B = 64, 8, 8


def _build(vocab=V, seed=9):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[vocab, D], is_sparse=True,
        param_attr=ParamAttr(name="table"))
    pred = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=1,
                           param_attr=ParamAttr(name="fc_w"),
                           bias_attr=ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return loss


def _batches(n, seed=0, vocab=V):
    rng = np.random.RandomState(seed)
    return [{"ids": rng.randint(0, vocab, (B, 4, 1)).astype("int64"),
             "y": rng.rand(B, 1).astype("float32")} for _ in range(n)]


def _mgr(tmp_path, **kw):
    kw.setdefault("async_save", False)
    kw.setdefault("incremental", "auto")
    kw.setdefault("incremental_full_every", 4)
    kw.setdefault("max_to_keep", None)
    return TrainStateCheckpointManager(str(tmp_path / "ck"), **kw)


# ---------------------------------------------------------------------------
# unit layer
# ---------------------------------------------------------------------------

def test_row_delta_is_bitwise():
    rng = np.random.RandomState(2)
    base = rng.rand(16, 4).astype("float32")
    new = base.copy()
    new[3] += 1.0
    new[11, 2] = np.nextafter(new[11, 2], np.inf)   # one-ULP move
    new[5] = base[5]                                 # untouched
    rows, values = row_delta(base, new)
    assert rows.tolist() == [3, 11]
    out = base.copy()
    out[rows] = values
    np.testing.assert_array_equal(out, new)
    # NaN that stays bit-identical is NOT re-written
    base[7, 0] = new[7, 0] = np.nan
    rows, _ = row_delta(base, new)
    assert 7 not in rows.tolist()


def test_sparse_table_state_vars_detects_tables_and_slots():
    loss = _build()   # noqa: F841 — builds into the default program
    main = fluid.default_main_program()
    names = ["table", "table_moment1_0", "table_moment2_0",
             "fc_w", "table_beta1_pow_acc_0", "table_out_w_0",
             "table_projection"]
    out = sparse_table_state_vars(main, names)
    assert out.get("table") == V
    assert out.get("table_moment1_0") == V
    assert out.get("table_moment2_0") == V
    assert "fc_w" not in out
    # only known ROW-WISE accumulator names match: the scalar beta pow
    # accumulator and user params that merely share the table's name
    # prefix (a same-height 'table_out_w_0' projection would otherwise
    # be delta-encoded despite its dense gradient touching every row)
    assert "table_beta1_pow_acc_0" not in out
    assert "table_out_w_0" not in out
    assert "table_projection" not in out


# ---------------------------------------------------------------------------
# manager: delta encode / replay / rotation / restore-seeded chain
# ---------------------------------------------------------------------------

def _train_and_save(tmp_path, steps, mgr=None, seed=0, start=1):
    """Train `steps` steps saving after each; returns (losses, mgr,
    final live arrays of the delta vars)."""
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.unique_name.guard(), \
            program_guard(main, startup):
        loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        mgr = mgr or _mgr(tmp_path)
        losses = []
        for i, f in enumerate(_batches(steps, seed=seed)):
            losses.append(float(np.asarray(
                exe.run(main, feed=f, fetch_list=[loss])[0]).ravel()[0]))
            mgr.save_now(start + i, scope=scope, program=main,
                         executors=exe)
        live = {n: np.array(np.asarray(scope.var(n)), copy=True)
                for n in scope.local_var_names()
                if n == "table" or (n.startswith("table_")
                                    and "moment" in n)}
    return losses, mgr, live


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """ONE 8-step sparse training run with per-step incremental saves,
    shared (read-only; mutating tests copy the dir) by the chain tests
    below — 4 separate retrains collapsed to keep tier-1 inside the
    870s budget."""
    root = tmp_path_factory.mktemp("incr")
    losses, mgr, live = _train_and_save(root, steps=8)
    return {"losses": losses, "dir": str(root / "ck"), "live": live,
            "mgr": mgr}


def _copy_ck(trained, tmp_path):
    import shutil
    dst = str(tmp_path / "ck")
    shutil.copytree(trained["dir"], dst)
    return dst


def test_incremental_cadence_and_bitwise_replay(trained):
    mgr, live = trained["mgr"], trained["live"]
    # step 1 = full base; 2..4 deltas; 5 = full (full_every=4); 6-8 delta
    kinds = {}
    for s in mgr.all_steps():
        ts = load_train_state(mgr._step_dir(s))
        kinds[s] = "delta" if ts.host.get("incremental") else "full"
    assert kinds == {1: "full", 2: "delta", 3: "delta", 4: "delta",
                     5: "full", 6: "delta", 7: "delta", 8: "delta"}
    # delta artifacts carry only the touched rows for the table vars
    ts4 = load_train_state(mgr._step_dir(4))
    assert "table" in ts4.delta
    (kind, rows, values), = ts4.delta["table"]
    assert kind == "rows" and 0 < rows.shape[0] < V
    # chain replay returns FULL arrays, bit-identical to the live state
    out = mgr.load(8)
    assert out.delta is None or not out.delta
    for n, a in live.items():
        np.testing.assert_array_equal(out.arrays[n], a)
    # and bytes: a delta artifact is smaller than the full base
    full_b = sum(os.path.getsize(os.path.join(mgr._step_dir(1), f))
                 for f in os.listdir(mgr._step_dir(1)))
    delta_b = sum(os.path.getsize(os.path.join(mgr._step_dir(4), f))
                  for f in os.listdir(mgr._step_dir(4)))
    assert delta_b < full_b


def test_rotation_keeps_load_bearing_chain(tmp_path):
    _, mgr, live = _train_and_save(
        tmp_path, steps=6,
        mgr=_mgr(tmp_path, max_to_keep=2))
    steps = mgr.all_steps()
    # kept: {5 (full), 6 (delta)} — 6's chain only needs 5, so 1..4 go
    assert steps == [5, 6]
    out = mgr.load(6)
    for n, a in live.items():
        np.testing.assert_array_equal(out.arrays[n], a)


def test_rotation_never_drops_a_needed_base(tmp_path):
    # full_every large: every artifact after step 1 is a delta, so the
    # whole chain back to step 1 is load-bearing for the kept tail
    _, mgr, live = _train_and_save(
        tmp_path, steps=5,
        mgr=_mgr(tmp_path, max_to_keep=2, incremental_full_every=100))
    assert mgr.all_steps() == [1, 2, 3, 4, 5]   # chain kept alive
    out = mgr.load(5)
    for n, a in live.items():
        np.testing.assert_array_equal(out.arrays[n], a)


def test_corrupt_chain_is_loud(trained, tmp_path):
    import shutil
    ck = _copy_ck(trained, tmp_path)
    mgr = TrainStateCheckpointManager(ck, async_save=False,
                                      incremental="auto",
                                      max_to_keep=None)
    shutil.rmtree(os.path.join(ck, os.path.basename(
        trained["mgr"]._step_dir(5))))      # the kept tail's full base
    with pytest.raises(CheckpointCorruptError):
        mgr.load(8)


def test_restore_seeds_chain_and_next_save_is_delta(trained, tmp_path):
    ck = _copy_ck(trained, tmp_path)
    # fresh process-analog: new manager over the (copied) dir
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.unique_name.guard(), \
            program_guard(main, startup):
        loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        mgr2 = _mgr(tmp_path)
        restored = mgr2.restore(scope=scope, program=main, executors=exe)
        assert restored == 8
        f = _batches(9, seed=0)[8]
        exe.run(main, feed=f, fetch_list=[loss])
        mgr2.save_now(9, scope=scope, program=main, executors=exe)
        live = {n: np.array(np.asarray(scope.var(n)), copy=True)
                for n in ("table",)}
    ts9 = load_train_state(mgr2._step_dir(9))
    assert ts9.host.get("incremental"), (
        "post-restore save paid a full write instead of continuing "
        "the delta chain")
    out = mgr2.load(9)
    np.testing.assert_array_equal(out.arrays["table"], live["table"])


# ---------------------------------------------------------------------------
# sharded (per-host) delta leg
# ---------------------------------------------------------------------------

def test_sharded_incremental_writes_local_touched_rows(tmp_path):
    """4 virtual writers each diff ONLY their own shard: delta entries
    carry global row ids, mixed full/delta artifacts reassemble, and
    the manager's chain replay is bit-identical."""
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.unique_name.guard(), \
            program_guard(main, startup):
        loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feeds = _batches(2)
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        ts1 = capture_train_state(1, scope=scope, program=main,
                                  executors=exe, sharded=True)
        exe.run(main, feed=feeds[1], fetch_list=[loss])
        ts2 = capture_train_state(2, scope=scope, program=main,
                                  executors=exe, sharded=True)
        live = {"table": np.array(np.asarray(scope.var("table")),
                                  copy=True)}
        names = sparse_table_state_vars(
            main, [e["name"] for e in ts1.shards])

    mgr = _mgr(tmp_path)
    writers = 4
    for ts in (ts1, ts2):
        ts.shards = [e for p in partition_shards(ts, writers) for e in p]
        ts._incr_names = names
        mgr._encode_incremental_shards(ts, names)

    # ts2's table entries became per-writer row deltas
    table_entries = [e for e in ts2.shards if e["name"] == "table"]
    assert table_entries and all(
        e.get("rows") is not None for e in table_entries)
    for e in table_entries:
        lo, hi = e["index"][0]
        assert all(lo <= r < hi for r in e["rows"].tolist()), (
            "delta rows are not global ids inside the writer's range")

    # write both artifacts (writer entries grouped by original writer)
    for ts in (ts1, ts2):
        by_writer = {}
        for e in ts.shards:
            lo = int(e["index"][0][0])
            by_writer.setdefault(lo, []).append(e)
        d = mgr._step_dir(ts.step)
        for w, (lo, entries) in enumerate(sorted(by_writer.items())):
            write_train_state_shards(d, ts, w, entries=entries)
        commit_sharded_train_state(d, ts, len(by_writer))

    out = mgr.load(2)
    np.testing.assert_array_equal(out.arrays["table"], live["table"])


# ---------------------------------------------------------------------------
# acceptance: exact-resume drill (base+delta == uninterrupted)
# ---------------------------------------------------------------------------

def test_exact_resume_from_delta_chain_is_bit_identical(trained, tmp_path):
    """The PR-4 drill predicate on the incremental path: restore from a
    DELTA artifact mid-run and the continued trajectory (losses and the
    table) is bit-identical to the uninterrupted run."""
    losses_a, live_a = trained["losses"], trained["live"]
    ck = _copy_ck(trained, tmp_path)

    # resume at step 6 (a delta artifact: 5 was the full base)
    assert load_train_state(os.path.join(ck, os.path.basename(
        trained["mgr"]._step_dir(6)))).host.get("incremental")
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.unique_name.guard(), \
            program_guard(main, startup):
        loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        mgr2 = _mgr(tmp_path)
        restored = mgr2.restore(scope=scope, program=main,
                                executors=exe, step=6)
        assert restored == 6
        losses_b = []
        for f in _batches(8, seed=0)[6:]:
            losses_b.append(float(np.asarray(
                exe.run(main, feed=f, fetch_list=[loss])[0]).ravel()[0]))
        live_b = {n: np.array(np.asarray(scope.var(n)), copy=True)
                  for n in live_a}
    assert losses_b == losses_a[6:], (losses_a[6:], losses_b)
    for n, a in live_a.items():
        np.testing.assert_array_equal(live_b[n], a)
