"""Native C ABI (reference paddle/capi/capi.h + train/demo/
demo_trainer.cc): the shared library is loaded in-process via ctypes
(live-interpreter path) and the two C++ demo binaries run as separate
OS processes (embedded-interpreter path), proving a pure-native
deployment/training surface over the jit executor."""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.capi as capi

pytestmark = pytest.mark.skipif(
    not capi.native_available(), reason="no native toolchain")


class PdTensor(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("dtype", ctypes.c_int),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("rank", ctypes.c_int32),
        ("data", ctypes.c_void_p),
        ("data_size", ctypes.c_int64),
    ]


def _load_lib():
    lib = ctypes.CDLL(capi.lib_path())
    lib.pd_init.restype = ctypes.c_int
    lib.pd_init.argtypes = [ctypes.c_char_p]
    lib.pd_last_error.restype = ctypes.c_char_p
    lib.pd_predictor_create.restype = ctypes.c_void_p
    lib.pd_predictor_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.pd_predictor_io_json.restype = ctypes.c_void_p
    lib.pd_predictor_io_json.argtypes = [ctypes.c_void_p]
    lib.pd_predictor_run.restype = ctypes.c_int
    lib.pd_predictor_run.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(PdTensor), ctypes.c_int32,
        ctypes.POINTER(ctypes.POINTER(PdTensor)),
        ctypes.POINTER(ctypes.c_int32)]
    lib.pd_predictor_destroy.argtypes = [ctypes.c_void_p]
    lib.pd_tensor_release.argtypes = [ctypes.POINTER(PdTensor)]
    lib.pd_free.argtypes = [ctypes.c_void_p]
    return lib


def _save_model(tmp_path):
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 16).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[16])
        h = fluid.layers.fc(x, size=8, act="relu")
        pred = fluid.layers.fc(h, size=3, act="softmax")
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            fluid.io.save_inference_model(
                str(tmp_path / "m"), ["x"], [pred], exe)
            ref, = exe.run(
                fluid.default_main_program().clone(for_test=True),
                feed={"x": xs[:4]}, fetch_list=[pred.name])
    return xs, np.asarray(ref)


def test_capi_predictor_in_process(tmp_path):
    xs, _ = _save_model(tmp_path)
    lib = _load_lib()
    assert lib.pd_init(None) == 0  # live interpreter -> no-op

    p = lib.pd_predictor_create(
        str(tmp_path / "m").encode(), b"cpu")
    assert p, lib.pd_last_error()

    js = lib.pd_predictor_io_json(p)
    meta = ctypes.string_at(js).decode()
    lib.pd_free(js)
    assert '"name": "x"' in meta and '"fetches"' in meta

    batch = np.ascontiguousarray(xs[:4])
    shape = (ctypes.c_int64 * 2)(4, 16)
    t = PdTensor(
        name=b"x", dtype=0, shape=shape, rank=2,
        data=batch.ctypes.data_as(ctypes.c_void_p),
        data_size=batch.nbytes)
    outs = ctypes.POINTER(PdTensor)()
    n_out = ctypes.c_int32(0)
    rc = lib.pd_predictor_run(p, (PdTensor * 1)(t), 1,
                              ctypes.byref(outs), ctypes.byref(n_out))
    assert rc == 0, lib.pd_last_error()
    assert n_out.value == 1
    out = outs[0]
    out_shape = [out.shape[i] for i in range(out.rank)]
    assert out_shape == [4, 3]
    vals = np.frombuffer(
        ctypes.string_at(out.data, out.data_size), "float32"
    ).reshape(4, 3)
    np.testing.assert_allclose(vals.sum(axis=1), np.ones(4), rtol=1e-4)
    lib.pd_tensor_release(ctypes.byref(outs[0]))
    lib.pd_free(outs)
    lib.pd_predictor_destroy(p)


def _demo_env():
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + ":" + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def test_demo_predictor_binary(tmp_path):
    _save_model(tmp_path)
    exe = capi.build_demo("demo_predictor",
                          out_path=str(tmp_path / "demo_predictor"))
    r = subprocess.run(
        [exe, str(tmp_path / "m"), sys.executable],
        capture_output=True, text=True, env=_demo_env(), timeout=600)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    assert "shape=[4,3]" in r.stdout, r.stdout


def test_demo_trainer_binary(tmp_path):
    """The reference demo_trainer flow: a C++ process trains from a
    saved program and the loss falls.

    The program seeds are PINNED (they serialize with the program):
    an unseeded program draws its init auto-seed from numpy's global
    RNG, whose state depends on which tests ran before — the
    convergence margin then flips under the full suite while passing
    in isolation (the PR-11 flake)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 1234
        fluid.default_startup_program().random_seed = 1234
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        fluid.io.save_train_program(
            str(tmp_path / "t"), loss_name=loss.name,
            feed_names=["x", "y"])

    exe = capi.build_demo("demo_trainer",
                          out_path=str(tmp_path / "demo_trainer"))
    save_dir = str(tmp_path / "trained")
    r = subprocess.run(
        [exe, str(tmp_path / "t"), "30", save_dir, sys.executable],
        capture_output=True, text=True, env=_demo_env(), timeout=600)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    lines = [l for l in r.stdout.splitlines() if l.startswith("step:")]
    assert len(lines) == 30
    final = [l for l in r.stdout.splitlines()
             if l.startswith("first_loss:")][0].split()
    first_loss, last_loss = float(final[1]), float(final[3])
    assert last_loss < first_loss * 0.9, r.stdout

    # the C++ process saved persistables a python process can restore
    assert os.path.isdir(save_dir) and os.listdir(save_dir)
    main, startup, loss_name, feeds = fluid.io.load_train_program(
        str(tmp_path / "t"))
    scope = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        fluid.io.load_persistables(exe2, save_dir, main)
    w = [np.asarray(scope.find_var(p.name))
         for p in main.global_block().all_parameters()]
    assert w and all(np.all(np.isfinite(a)) for a in w)
