"""tools/bench_history.py: cross-run bench regression tracking over the
committed driver wrappers (BENCH_r*.json) and fresh bench.py artifacts —
legacy-methodology gating, noise-band verdicts, the +20% synthetic
perturbation gate, and bare-artifact (schema v2) ingestion."""

import copy
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(ROOT, "tools", "bench_history.py")

sys.path.insert(0, os.path.join(ROOT, "tools"))

import bench_history  # noqa: E402


def _wrapper(n, parsed, rc=0):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


def _rung(metric, value, step_s=None, mfu=None, goodput=None,
          informational=False, **extra):
    out = dict({"metric": metric, "value": value, "unit": "items/sec",
                "vs_baseline": 1.0}, **extra)
    if step_s is not None:
        out["min_step_s"] = step_s
        out["n_windows"] = 3
    if mfu is not None:
        out["mfu"] = mfu
    if goodput is not None:
        out["goodput"] = {"goodput_ratio": goodput,
                          "buckets": {}, "wall_seconds": 1.0}
    if informational:
        out["informational"] = True
    return out


def _write(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_committed_artifact_evolution_passes(tmp_path):
    """The r01->r04 history: r01/r02 predate the fetch-sync methodology
    (legacy, never baselines), r04 is an rc=124 timeout with no parsed
    line (incomplete), r03 is the first comparable run — the evolution
    PASSes."""
    paths = [os.path.join(ROOT, "BENCH_r%02d.json" % i)
             for i in (1, 2, 3, 4)]
    runs = [bench_history.load_artifact(p, i) for i, p in
            enumerate(paths)]
    by = {r["run"]: r for r in runs}
    assert by["r01"]["status"] == "legacy_methodology"
    assert by["r02"]["status"] == "legacy_methodology"
    assert by["r03"]["status"] == "ok"
    assert by["r04"]["status"] == "incomplete" and by["r04"]["rc"] == 124
    report = bench_history.compare(runs)
    assert report["overall"] == "PASS"
    assert report["latest"] == "r03"


def test_synthetic_perturbation_regresses(tmp_path):
    """A +20% step-time copy of r03 (value scaled down accordingly)
    must come back REGRESSED against the committed history — the CI
    gate's self-check."""
    with open(os.path.join(ROOT, "BENCH_r03.json")) as f:
        r03 = json.load(f)
    bad = copy.deepcopy(r03)
    bad["n"] = 5
    bad["parsed"]["min_step_s"] = round(
        r03["parsed"]["min_step_s"] * 1.2, 6)
    bad["parsed"]["value"] = round(r03["parsed"]["value"] / 1.2, 2)
    p = _write(tmp_path, "BENCH_r05.json", bad)
    out = subprocess.run(
        [sys.executable, TOOL] +
        [os.path.join(ROOT, "BENCH_r%02d.json" % i)
         for i in (1, 2, 3, 4)] + [p, "--json"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    assert out.returncode == 1, out.stderr
    report = json.loads(out.stdout)
    assert report["overall"] == "REGRESSED"
    latest = [r for r in report["runs"] if r["run"] == "r05"][0]
    fields = {(c["metric"], c["field"]): c["verdict"]
              for c in latest["comparisons"]}
    assert fields[("resnet50_images_per_sec_bf16",
                   "min_step_s")] == "REGRESSED"
    assert fields[("resnet50_images_per_sec_bf16",
                   "value")] == "REGRESSED"


def test_noise_band_tolerates_small_deltas(tmp_path):
    """Deltas inside the noise band PASS in either direction."""
    a = _wrapper(1, _rung("m", 100.0, step_s=0.100, mfu=0.2,
                          goodput=0.9))
    b = _wrapper(2, _rung("m", 97.0, step_s=0.103, mfu=0.195,
                          goodput=0.87))
    runs = [bench_history.load_artifact(
        _write(tmp_path, "a%d.json" % w["n"], w), i)
        for i, w in enumerate((a, b))]
    report = bench_history.compare(runs, noise=0.05)
    assert report["overall"] == "PASS"
    # ...and a goodput collapse beyond the band is a regression even
    # when throughput holds
    c = _wrapper(3, _rung("m", 100.0, step_s=0.100, mfu=0.2,
                          goodput=0.70))
    runs.append(bench_history.load_artifact(
        _write(tmp_path, "a3.json", c), 2))
    report = bench_history.compare(runs, noise=0.05)
    assert report["overall"] == "REGRESSED"
    regs = report["runs"][-1]["regressions"]
    assert [r["field"] for r in regs] == ["goodput"]


def test_baseline_is_best_prior_not_last(tmp_path):
    """Comparisons run against the BEST prior value, so a slow run
    does not lower the bar for the one after it."""
    ws = [_wrapper(1, _rung("m", 100.0, step_s=0.100)),
          _wrapper(2, _rung("m", 80.0, step_s=0.125)),   # slow run
          _wrapper(3, _rung("m", 90.0, step_s=0.111))]   # still slow
    runs = [bench_history.load_artifact(
        _write(tmp_path, "w%d.json" % w["n"], w), i)
        for i, w in enumerate(ws)]
    report = bench_history.compare(runs, noise=0.05)
    assert report["runs"][1]["verdict"] == "REGRESSED"
    assert report["runs"][2]["verdict"] == "REGRESSED"   # vs r1's best


def test_informational_and_error_rungs_do_not_gate(tmp_path):
    parsed = dict(_rung("scored", 100.0, step_s=0.1),
                  extra_metrics=[
                      _rung("era_rung", 50.0, step_s=0.2,
                            informational=True),
                      dict(_rung("broken_error", 0.0), unit="error",
                           error="boom")])
    a = _wrapper(1, parsed)
    worse = copy.deepcopy(parsed)
    worse["extra_metrics"][0]["min_step_s"] = 0.4   # era rung 2x slower
    b = _wrapper(2, worse)
    runs = [bench_history.load_artifact(
        _write(tmp_path, "i%d.json" % w["n"], w), i)
        for i, w in enumerate((a, b))]
    report = bench_history.compare(runs, noise=0.05)
    # the informational regression is VISIBLE but does not gate
    comps = report["runs"][1]["comparisons"]
    assert any(c["metric"] == "era_rung"
               and c["verdict"] == "REGRESSED" for c in comps)
    assert report["overall"] == "PASS"
    # error rungs are never judged
    assert not any(c["metric"] == "broken_error" for c in comps)


def test_trace_stage_fields_index_without_gating(tmp_path):
    """ISSUE 17: p99_queue_wait_ms / p99_decode_ms are indexed and
    judged against history, but NEVER gate — even inside a gating
    (non-informational) rung, a 10x stage regression stays
    informational while a real p99_ms regression still gates."""
    assert "p99_queue_wait_ms" in bench_history.INFORMATIONAL_FIELDS
    assert "p99_decode_ms" in bench_history.INFORMATIONAL_FIELDS
    base = _rung("serving_requests_per_sec", 100.0, step_s=0.1,
                 p99_ms=20.0, p99_queue_wait_ms=5.0, p99_decode_ms=2.0)
    worse = dict(base, p99_queue_wait_ms=50.0, p99_decode_ms=20.0)
    runs = [bench_history.load_artifact(
        _write(tmp_path, "t%d.json" % i, _wrapper(i + 1, r)), i)
        for i, r in enumerate((base, worse))]
    report = bench_history.compare(runs, noise=0.05)
    comps = report["runs"][1]["comparisons"]
    # both stage fields are indexed, judged REGRESSED, and marked
    # informational despite riding a gating rung
    for f in ("p99_queue_wait_ms", "p99_decode_ms"):
        c = next(c for c in comps if c["field"] == f)
        assert c["verdict"] == "REGRESSED" and c["informational"], c
    assert report["runs"][1]["verdict"] == "PASS"
    assert report["overall"] == "PASS"
    # control: the same delta on p99_ms itself DOES gate
    gated = dict(base, p99_ms=200.0)
    runs = [bench_history.load_artifact(
        _write(tmp_path, "g%d.json" % i, _wrapper(i + 1, r)), i)
        for i, r in enumerate((base, gated))]
    assert bench_history.compare(
        runs, noise=0.05)["runs"][1]["verdict"] == "REGRESSED"


def test_fleet_fields_index_without_gating(tmp_path):
    """ISSUE 18: aggregate_rps / reroute_latency_ms (the serving-fleet
    scaling and failover-latency pair) are indexed and judged against
    history but NEVER gate — multi-process drill numbers move with
    host load."""
    assert "aggregate_rps" in bench_history.INFORMATIONAL_FIELDS
    assert "reroute_latency_ms" in bench_history.INFORMATIONAL_FIELDS
    base = _rung("serving_fleet", 390.0, step_s=0.1,
                 aggregate_rps=390.0, reroute_latency_ms=270.0)
    worse = dict(base, aggregate_rps=100.0, reroute_latency_ms=2000.0)
    runs = [bench_history.load_artifact(
        _write(tmp_path, "f%d.json" % i, _wrapper(i + 1, r)), i)
        for i, r in enumerate((base, worse))]
    report = bench_history.compare(runs, noise=0.05)
    comps = report["runs"][1]["comparisons"]
    for f in ("aggregate_rps", "reroute_latency_ms"):
        c = next(c for c in comps if c["field"] == f)
        assert c["verdict"] == "REGRESSED" and c["informational"], c
    assert report["overall"] == "PASS"


def test_fleet_telemetry_fields_index_without_gating(tmp_path):
    """ISSUE 19: digest_build_us / straggler_detect_windows (the fleet
    telemetry rung's digest-cost and detection-latency pair) are
    indexed and judged against history but NEVER gate — microsecond
    timings swing with CI host load."""
    assert "digest_build_us" in bench_history.INFORMATIONAL_FIELDS
    assert "straggler_detect_windows" in bench_history.INFORMATIONAL_FIELDS
    base = _rung("fleet_telemetry", 40.0, step_s=0.1,
                 digest_build_us=40.0, straggler_detect_windows=1)
    worse = dict(base, digest_build_us=300.0, straggler_detect_windows=8)
    runs = [bench_history.load_artifact(
        _write(tmp_path, "t%d.json" % i, _wrapper(i + 1, r)), i)
        for i, r in enumerate((base, worse))]
    report = bench_history.compare(runs, noise=0.05)
    comps = report["runs"][1]["comparisons"]
    for f in ("digest_build_us", "straggler_detect_windows"):
        c = next(c for c in comps if c["field"] == f)
        assert c["verdict"] == "REGRESSED" and c["informational"], c
    assert report["overall"] == "PASS"


def test_bare_schema_v2_artifact_ingests_with_goodput(tmp_path):
    """A fresh bench.py artifact (bare JSON line, schema_version 2,
    run_id, embedded goodput) ingests as a comparable run keyed after
    the wrapper history."""
    bare = dict(_rung("m", 100.0, step_s=0.1, goodput=0.93),
                schema_version=2, run_id="abcd1234-0001",
                ladder_complete=True)
    run = bench_history.load_artifact(
        _write(tmp_path, "fresh.json", bare), 7)
    assert run["status"] == "ok"
    assert run["schema_version"] == 2
    assert run["run_id"] == "abcd1234-0001"
    assert run["rungs"][0]["goodput"] == pytest.approx(0.93)
    # a ladder --out file is the reprinted LAST line of a JSONL stream
    stream = "\n".join(["not json", json.dumps(bare)])
    p = tmp_path / "stream.json"
    p.write_text(stream)
    run2 = bench_history.load_artifact(str(p), 8)
    assert run2["status"] == "ok"


def test_index_written_atomically(tmp_path):
    a = _write(tmp_path, "x1.json",
               _wrapper(1, _rung("m", 100.0, step_s=0.1)))
    idx = str(tmp_path / "history.json")
    rc = bench_history.main([a, "--index", idx, "--json"])
    assert rc == 0
    with open(idx) as f:
        saved = json.load(f)
    assert saved["overall"] == "PASS"
    assert saved["runs"][0]["run"] == "r01"


def test_serving_rung_slo_fields_indexed_but_non_gating(tmp_path):
    """The serving rung's {throughput_rps, p99_ms} SLO pair is indexed
    and judged, but the rung is informational — a serving regression
    never flips the overall verdict (non-gating at first)."""
    def serving(rps, p99):
        return _rung("serving_requests_per_sec", rps,
                     informational=True, throughput_rps=rps,
                     p99_ms=p99, min_step_s=0.01, n_windows=1)

    r1 = {"metric": "resnet", "value": 100.0, "unit": "img/s",
          "vs_baseline": 1.0, "min_step_s": 0.5, "n_windows": 3,
          "extra_metrics": [serving(3000.0, 25.0)]}
    # next run: scored rung steady, serving MUCH worse
    r2 = copy.deepcopy(r1)
    r2["extra_metrics"] = [serving(1000.0, 400.0)]
    paths = [_write(tmp_path, "a.json", _wrapper(1, r1)),
             _write(tmp_path, "b.json", _wrapper(2, r2))]
    report = bench_history.compare(
        [bench_history.load_artifact(p, i)
         for i, p in enumerate(paths)])
    runs = {r["run"]: r for r in report["runs"]}
    rec = [g for g in runs["r02"]["rungs"]
           if g["metric"] == "serving_requests_per_sec"][0]
    assert rec["throughput_rps"] == 1000.0 and rec["p99_ms"] == 400.0
    judged = {c["field"]: c for c in runs["r02"]["comparisons"]
              if c["metric"] == "serving_requests_per_sec"}
    assert judged["throughput_rps"]["verdict"] == "REGRESSED"
    assert judged["p99_ms"]["verdict"] == "REGRESSED"
    assert judged["throughput_rps"]["informational"]
    # ...but the run (and the report) still PASS
    assert runs["r02"]["verdict"] == "PASS"
    assert report["overall"] == "PASS"


def test_longctx_ring_rung_indexes_informational(tmp_path):
    """ISSUE 12: the T>=32k ring-attention rung indexes (value +
    min_step_s + goodput tracked against prior history) but never
    gates — a collapsed tokens/sec flags the comparison as
    informational while the run verdict stays PASS."""
    ring = {"metric": "longctx_ring_tokens_per_sec", "value": 5000.0,
            "unit": "tokens/sec", "vs_baseline": 0.0, "seq_len": 32768,
            "sp": 8, "min_step_s": 6.5, "n_windows": 2,
            "informational": True, "virtual_mesh": True,
            "goodput": {"goodput_ratio": 0.4,
                        "buckets": {"compute": 2.0}}}
    base = _wrapper(1, {"metric": "resnet50_images_per_sec_bf16",
                        "value": 100.0, "unit": "images/sec",
                        "vs_baseline": 1.0, "min_step_s": 0.5,
                        "n_windows": 3, "schema_version": 2,
                        "extra_metrics": [ring]})
    worse_ring = copy.deepcopy(ring)
    worse_ring["value"] = 1000.0          # 5x throughput collapse
    worse_ring["goodput"]["goodput_ratio"] = 0.05
    nxt = _wrapper(2, {"metric": "resnet50_images_per_sec_bf16",
                       "value": 100.0, "unit": "images/sec",
                       "vs_baseline": 1.0, "min_step_s": 0.5,
                       "n_windows": 3, "schema_version": 2,
                       "extra_metrics": [worse_ring]})
    p1 = tmp_path / "BENCH_r01.json"
    p2 = tmp_path / "BENCH_r02.json"
    p1.write_text(json.dumps(base))
    p2.write_text(json.dumps(nxt))
    report = bench_history.compare(
        [bench_history.load_artifact(str(p1), 0),
         bench_history.load_artifact(str(p2), 1)])
    last = report["runs"][-1]
    ring_cmp = [c for c in last["comparisons"]
                if c["metric"] == "longctx_ring_tokens_per_sec"]
    assert ring_cmp, "longctx rung not indexed"
    assert any(c["field"] == "value" and c["verdict"] == "REGRESSED"
               for c in ring_cmp)
    assert all(c["informational"] for c in ring_cmp)
    assert last["verdict"] == "PASS"      # informational: never gates
    assert report["overall"] == "PASS"


def test_ckpt_sharded_rung_save_wall_indexed_but_non_gating(tmp_path):
    """ISSUE 13: the per-host sharded checkpoint rung's save wall-clock
    is indexed and judged against prior history (lower is better), but
    the rung is informational (disk-bound) — a slower save never flips
    the overall verdict."""
    def ckpt(wall):
        return _rung("ckpt_sharded_per_host_save", wall,
                     informational=True, save_wall_s=wall,
                     state_bytes=50_000_000,
                     per_host={"4": {"wall_s": wall}})

    r1 = {"metric": "resnet", "value": 100.0, "unit": "img/s",
          "vs_baseline": 1.0, "min_step_s": 0.5, "n_windows": 3,
          "extra_metrics": [ckpt(0.09)]}
    r2 = copy.deepcopy(r1)
    r2["extra_metrics"] = [ckpt(0.50)]       # 5x slower per-host save
    paths = [_write(tmp_path, "a.json", _wrapper(1, r1)),
             _write(tmp_path, "b.json", _wrapper(2, r2))]
    report = bench_history.compare(
        [bench_history.load_artifact(p, i)
         for i, p in enumerate(paths)])
    runs = {r["run"]: r for r in report["runs"]}
    rec = [g for g in runs["r02"]["rungs"]
           if g["metric"] == "ckpt_sharded_per_host_save"][0]
    assert rec["save_wall_s"] == 0.50
    judged = {c["field"]: c for c in runs["r02"]["comparisons"]
              if c["metric"] == "ckpt_sharded_per_host_save"}
    assert judged["save_wall_s"]["verdict"] == "REGRESSED"
    assert judged["save_wall_s"]["informational"]
    assert runs["r02"]["verdict"] == "PASS"
    assert report["overall"] == "PASS"


def test_quantized_rung_accuracy_delta_indexed_but_non_gating(tmp_path):
    """ISSUE 14: the quantized rung's {tok_s, accuracy_delta} index and
    judge against prior history (value higher-better, delta
    lower-better), but the rung is informational while it accumulates
    history — a worse delta never flips the overall verdict."""
    def quant(tok_s, delta):
        return _rung("quantized_tok_per_sec", tok_s, step_s=1.0 / tok_s,
                     informational=True, accuracy_delta=delta,
                     bf16_tok_s=tok_s / 1.5, gate_pass=True)

    r1 = {"metric": "resnet", "value": 100.0, "unit": "img/s",
          "vs_baseline": 1.0, "min_step_s": 0.5, "n_windows": 3,
          "extra_metrics": [quant(420.0, 0.009)]}
    r2 = copy.deepcopy(r1)
    r2["extra_metrics"] = [quant(400.0, 0.019)]   # worse delta + tok/s
    paths = [_write(tmp_path, "qa.json", _wrapper(1, r1)),
             _write(tmp_path, "qb.json", _wrapper(2, r2))]
    report = bench_history.compare(
        [bench_history.load_artifact(p, i)
         for i, p in enumerate(paths)])
    runs = {r["run"]: r for r in report["runs"]}
    rec = [g for g in runs["r02"]["rungs"]
           if g["metric"] == "quantized_tok_per_sec"][0]
    assert rec["accuracy_delta"] == 0.019
    judged = {c["field"]: c for c in runs["r02"]["comparisons"]
              if c["metric"] == "quantized_tok_per_sec"}
    assert judged["accuracy_delta"]["verdict"] == "REGRESSED"
    assert judged["accuracy_delta"]["informational"]
    assert judged["value"]["current"] == 400.0
    assert runs["r02"]["verdict"] == "PASS"
    assert report["overall"] == "PASS"


def test_rec_sparse_rung_fields_indexed_but_non_gating(tmp_path):
    """ISSUE 15: the rec_sparse rung's vocab-scaling fields
    (sparse_step_s / dense_step_s / incr_ckpt_bytes) are indexed and
    judged against prior history (all lower is better), but the rung is
    informational — a regression in any of them never flips the overall
    verdict (the ckpt_sharded precedent)."""
    def rec(sp, dn, incr):
        return _rung("rec_sparse_vocab_scaling", dn / sp,
                     informational=True, sparse_step_s=sp,
                     dense_step_s=dn, incr_ckpt_bytes=incr,
                     per_vocab={"1000000": {"sparse_step_s": sp}})

    r1 = {"metric": "resnet", "value": 100.0, "unit": "img/s",
          "vs_baseline": 1.0, "min_step_s": 0.5, "n_windows": 3,
          "extra_metrics": [rec(0.006, 0.09, 230_000)]}
    r2 = copy.deepcopy(r1)
    # sparse step 10x slower, incremental bytes 50x fatter: the exact
    # regressions the index must surface
    r2["extra_metrics"] = [rec(0.060, 0.09, 12_000_000)]
    paths = [_write(tmp_path, "a.json", _wrapper(1, r1)),
             _write(tmp_path, "b.json", _wrapper(2, r2))]
    report = bench_history.compare(
        [bench_history.load_artifact(p, i)
         for i, p in enumerate(paths)])
    runs = {r["run"]: r for r in report["runs"]}
    rec2 = [g for g in runs["r02"]["rungs"]
            if g["metric"] == "rec_sparse_vocab_scaling"][0]
    assert rec2["sparse_step_s"] == 0.060
    assert rec2["incr_ckpt_bytes"] == 12_000_000
    judged = {c["field"]: c for c in runs["r02"]["comparisons"]
              if c["metric"] == "rec_sparse_vocab_scaling"}
    assert judged["sparse_step_s"]["verdict"] == "REGRESSED"
    assert judged["incr_ckpt_bytes"]["verdict"] == "REGRESSED"
    assert judged["dense_step_s"]["verdict"] == "PASS"
    assert all(judged[f]["informational"]
               for f in ("sparse_step_s", "dense_step_s",
                         "incr_ckpt_bytes"))
    assert runs["r02"]["verdict"] == "PASS"   # informational: no gate
    assert report["overall"] == "PASS"


def test_decode_paged_rung_fields_indexed_but_non_gating(tmp_path):
    """ISSUE 16: the decode_paged rung's triple (sessions_at_fixed_hbm /
    spec_tok_s / prefix_hit_rate — all higher is better) is indexed and
    judged against prior history, but the rung is informational while
    it accumulates history — a collapse in any of them surfaces in the
    comparisons without flipping the overall verdict."""
    def paged(sess, spec_ts, hit):
        return _rung("decode_sessions_at_fixed_hbm", sess,
                     informational=True, sessions_at_fixed_hbm=sess,
                     spec_tok_s=spec_ts, prefix_hit_rate=hit,
                     spec_outputs_match=True)

    r1 = {"metric": "resnet", "value": 100.0, "unit": "img/s",
          "vs_baseline": 1.0, "min_step_s": 0.5, "n_windows": 3,
          "extra_metrics": [paged(10.2, 37.0, 0.75)]}
    r2 = copy.deepcopy(r1)
    # HBM ratio halved, spec tok/s collapsed, prefix cache cold: the
    # exact decode-path regressions the index must surface
    r2["extra_metrics"] = [paged(4.8, 12.0, 0.10)]
    paths = [_write(tmp_path, "a.json", _wrapper(1, r1)),
             _write(tmp_path, "b.json", _wrapper(2, r2))]
    report = bench_history.compare(
        [bench_history.load_artifact(p, i)
         for i, p in enumerate(paths)])
    runs = {r["run"]: r for r in report["runs"]}
    rec = [g for g in runs["r02"]["rungs"]
           if g["metric"] == "decode_sessions_at_fixed_hbm"][0]
    assert rec["sessions_at_fixed_hbm"] == 4.8
    assert rec["spec_tok_s"] == 12.0
    assert rec["prefix_hit_rate"] == 0.10
    judged = {c["field"]: c for c in runs["r02"]["comparisons"]
              if c["metric"] == "decode_sessions_at_fixed_hbm"}
    assert judged["sessions_at_fixed_hbm"]["verdict"] == "REGRESSED"
    assert judged["spec_tok_s"]["verdict"] == "REGRESSED"
    assert judged["prefix_hit_rate"]["verdict"] == "REGRESSED"
    assert all(judged[f]["informational"]
               for f in ("sessions_at_fixed_hbm", "spec_tok_s",
                         "prefix_hit_rate"))
    assert runs["r02"]["verdict"] == "PASS"   # informational: no gate
    assert report["overall"] == "PASS"
