"""Parser tests for the second dataset batch (wmt14, wmt16, conll05,
movielens, flowers, voc2012, sentiment) on synthetic fixtures — no
network."""

import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest


def _add(tf, name, blob):
    info = tarfile.TarInfo(name)
    info.size = len(blob)
    tf.addfile(info, io.BytesIO(blob))


def test_wmt14_parser(tmp_path):
    from paddle_tpu.dataset import wmt14

    tar = tmp_path / "wmt14.tgz"
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = (b"hello world\tbonjour monde\n"
             b"hello unknownword\tbonjour\n"
             b"badline\n")
    with tarfile.open(tar, "w:gz") as tf:
        _add(tf, "wmt14/train/src.dict", src_dict)
        _add(tf, "wmt14/train/trg.dict", trg_dict)
        _add(tf, "wmt14/train/train", train)
    samples = list(wmt14.reader_creator(str(tar), "train/train", 100)())
    assert len(samples) == 2          # bad line dropped
    src_ids, trg_ids, trg_next = samples[0]
    assert src_ids == [0, 3, 4, 1]    # <s> hello world <e>
    assert trg_ids == [0, 3, 4]       # <s> bonjour monde
    assert trg_next == [3, 4, 1]      # bonjour monde <e>
    # unknown word maps to UNK_IDX=2
    assert samples[1][0] == [0, 3, 2, 1]


def test_wmt16_dict_build_and_reader(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common, wmt16

    tar = tmp_path / "wmt16.tar.gz"
    train = (b"the cat\tdie katze\n"
             b"the dog\tder hund\n")
    with tarfile.open(tar, "w:gz") as tf:
        _add(tf, "wmt16/train", train)
        _add(tf, "wmt16/test", b"the cat\tdie katze\n")
        _add(tf, "wmt16/val", b"the dog\tder hund\n")
    monkeypatch.setattr(wmt16.common, "download",
                        lambda *a, **k: str(tar))
    monkeypatch.setattr(wmt16.common, "DATA_HOME", str(tmp_path))

    en = wmt16.get_dict("en", 100)
    assert en["<s>"] == 0 and en["<e>"] == 1 and en["<unk>"] == 2
    assert en["the"] == 3              # most frequent first
    samples = list(wmt16.test(100, 100, "en")())
    assert len(samples) == 1
    src_ids, trg_ids, trg_next = samples[0]
    de = wmt16.get_dict("de", 100)
    assert src_ids == [0, en["the"], en["cat"], 1]
    assert trg_ids == [0, de["die"], de["katze"]]
    assert trg_next == [de["die"], de["katze"], 1]


def test_conll05_bracket_expansion_and_reader(tmp_path):
    from paddle_tpu.dataset import conll05

    # two-predicate sentence in the conll prop format
    words = b"The\ncat\nsat\n\n"
    props = (b"-  (A0*\n"
             b"-  *)\n"
             b"sit  (V*)\n"
             b"\n")
    tar = tmp_path / "c.tgz"
    with tarfile.open(tar, "w:gz") as tf:
        _add(tf, "rel/words.gz", gzip.compress(words))
        _add(tf, "rel/props.gz", gzip.compress(props))

    corpus = conll05.corpus_reader(str(tar), "rel/words.gz",
                                   "rel/props.gz")
    got = list(corpus())
    assert len(got) == 1
    sentence, verb, labels = got[0]
    assert sentence == ["The", "cat", "sat"]
    assert verb == "sit"
    assert labels == ["B-A0", "I-A0", "B-V"]

    word_dict = {"The": 1, "cat": 2, "sat": 3, "bos": 4, "eos": 5}
    verb_dict = {"sit": 1}
    label_map = {"B-A0": 0, "I-A0": 1, "B-V": 2, "O": 3}
    rdr = conll05.reader_creator(corpus, word_dict, verb_dict, label_map)
    (sample,) = list(rdr())
    word_idx, n2, n1, c0, p1, p2, pred, mark, label_idx = sample
    assert word_idx == [1, 2, 3]
    assert pred == [1, 1, 1]
    assert mark == [0, 1, 1]          # window around verb at index 2
    assert label_idx == [0, 1, 2]
    assert c0 == [3, 3, 3]            # ctx_0 = 'sat'
    assert p1 == [word_dict["eos"]] * 3


def test_conll05_label_dict_loader(tmp_path):
    from paddle_tpu.dataset import conll05

    f = tmp_path / "target.txt"
    f.write_text("B-A0\nI-A0\nB-V\nI-V\nO\n")
    d = conll05.load_label_dict(str(f))
    assert d["O"] == max(d.values())
    assert set(d) == {"B-A0", "I-A0", "B-V", "I-V", "O"}
    # B-x and I-x adjacent
    assert d["I-A0"] == d["B-A0"] + 1


def test_movielens_parser(tmp_path, monkeypatch):
    from paddle_tpu.dataset import movielens

    zp = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(zp, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Jumanji (1995)::Adventure\n")
        z.writestr("ml-1m/users.dat",
                   "1::M::25::15::12345\n2::F::35::7::67890\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::3::978302109\n"
                   "1::2::4::978301968\n")
    monkeypatch.setattr(movielens.common, "download",
                        lambda *a, **k: str(zp))
    movielens._meta_cache.clear()
    try:
        all_samples = list(movielens.train()()) + \
            list(movielens.test()())
        assert len(all_samples) == 3
        s = all_samples[0]
        # [uid, gender, age_idx, job, mid, categories, title_words, [r]]
        assert len(s) == 8
        # ratings normalized r*2-5: raw 3..5 -> 1..5
        assert all(-3.0 <= smp[-1][0] <= 5.0 for smp in all_samples)
        assert {smp[-1][0] for smp in all_samples} == {5.0, 1.0, 3.0}
        assert movielens.max_user_id() == 2
        assert movielens.max_movie_id() == 2
        assert movielens.max_job_id() == 15
        cats = movielens.movie_categories()
        assert set(cats) == {"Animation", "Comedy", "Adventure"}
        titles = movielens.get_movie_title_dict()
        assert "toy" in titles and "jumanji" in titles
        m = movielens.movie_info()[1]
        assert "Toy Story" in m.title
    finally:
        movielens._meta_cache.clear()


def test_flowers_parser(tmp_path):
    import scipy.io
    from PIL import Image
    from paddle_tpu.dataset import flowers

    n = 4
    tar = tmp_path / "102flowers.tgz"
    with tarfile.open(tar, "w:gz") as tf:
        rng = np.random.RandomState(0)
        for i in range(1, n + 1):
            img = Image.fromarray(
                rng.randint(0, 255, (20, 30, 3), dtype=np.uint8))
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            _add(tf, "jpg/image_%05d.jpg" % i, buf.getvalue())
    labels = np.array([[5, 6, 7, 8]])
    setid = {"trnid": np.array([[1, 3]]), "tstid": np.array([[2]]),
             "valid": np.array([[4]])}
    scipy.io.savemat(tmp_path / "imagelabels.mat", {"labels": labels})
    scipy.io.savemat(tmp_path / "setid.mat", setid)

    # TRAIN_FLAG is 'tstid' (reference's deliberate swap)
    assert flowers.TRAIN_FLAG == "tstid" and flowers.TEST_FLAG == "trnid"
    rdr = flowers.reader_creator(
        str(tar), str(tmp_path / "imagelabels.mat"),
        str(tmp_path / "setid.mat"), "trnid", resize=16)
    samples = list(rdr())
    assert len(samples) == 2
    img, lbl = samples[0]
    assert img.shape == (3, 16, 16) and img.dtype == np.float32
    assert 0.0 <= img.min() and img.max() <= 1.0
    assert lbl == 4                   # label 5 -> zero-based 4
    tst = list(flowers.reader_creator(
        str(tar), str(tmp_path / "imagelabels.mat"),
        str(tmp_path / "setid.mat"), "tstid", resize=16)())
    assert len(tst) == 1 and tst[0][1] == 5


def test_voc2012_parser(tmp_path):
    from PIL import Image
    from paddle_tpu.dataset import voc2012

    tar = tmp_path / "voc.tar"
    with tarfile.open(tar, "w") as tf:
        _add(tf, voc2012.SET_FILE.format("trainval"), b"img1\nimg2\n")
        rng = np.random.RandomState(1)
        for name in ("img1", "img2"):
            im = Image.fromarray(
                rng.randint(0, 255, (12, 10, 3), dtype=np.uint8))
            buf = io.BytesIO()
            im.save(buf, format="JPEG")
            _add(tf, voc2012.DATA_FILE.format(name), buf.getvalue())
            mask = Image.fromarray(
                rng.randint(0, 20, (12, 10), dtype=np.uint8), mode="P")
            buf2 = io.BytesIO()
            mask.save(buf2, format="PNG")
            _add(tf, voc2012.LABEL_FILE.format(name), buf2.getvalue())
    samples = list(voc2012.reader_creator(str(tar), "trainval")())
    assert len(samples) == 2
    img, lbl = samples[0]
    assert img.shape == (12, 10, 3) and img.dtype == np.uint8
    assert lbl.shape == (12, 10) and lbl.max() < 21


def test_sentiment_pipeline_with_injected_corpus():
    from paddle_tpu.dataset import sentiment

    docs = [(["good", "movie", "good"], "pos"),
            (["bad", "movie"], "neg"),
            (["good"], "pos")]
    wd = sentiment.build_word_dict(docs)
    assert wd["good"] == 0            # most frequent
    samples = sentiment.build_samples(docs, wd)
    assert len(samples) == 3
    labels = sorted(lbl for _, lbl in samples)
    assert labels == [0, 1, 1]        # neg=0 (x1), pos=1 (x2)
    ids, _ = samples[0]
    assert all(isinstance(i, int) for i in ids)
    # deterministic shuffle
    assert samples == sentiment.build_samples(docs, wd)


def test_mq2007_letor_parsing_and_generators(tmp_path):
    from paddle_tpu.dataset import mq2007

    lines = [
        "2 qid:10 1:0.1 2:0.5 3:0.0 #docid = GX1",
        "0 qid:10 1:0.0 2:0.2 3:0.4 #docid = GX2",
        "1 qid:10 1:0.3 2:0.1 3:0.9 #docid = GX3",
        "0 qid:11 1:0.0 2:0.0 3:0.0 #docid = GX4",   # all-zero: filtered
        "not a letor line",
        "1 qid:12 1:0.7 2:0.7 3:0.7",
        "0 qid:12 1:0.1 2:0.2 3:0.3",
    ]
    f = tmp_path / "train.txt"
    f.write_text("\n".join(lines))
    qls = mq2007.load_from_text(str(f))
    assert [ql.query_id for ql in qls] == [10, 11, 12]
    assert len(qls[0]) == 3
    kept = mq2007.query_filter(qls)
    assert [ql.query_id for ql in kept] == [10, 12]

    # pointwise: ranked by relevance descending; vectors are fixed-width
    # (LETOR's 46 features) with missing slots filled with -1
    pts = list(mq2007.gen_point(qls[0]))
    assert [p[0] for p in pts] == [2, 1, 0]
    assert pts[0][1].shape == (mq2007.FEATURE_DIM,)
    np.testing.assert_allclose(pts[0][1][:3], [0.1, 0.5, 0.0])
    np.testing.assert_allclose(pts[0][1][3:], -1.0)

    # pairwise: all differing-relevance pairs, higher doc first
    pairs = list(mq2007.gen_pair(qls[0]))
    assert len(pairs) == 3
    for label, hi, lo in pairs:
        assert label == np.array([1])
    # listwise: one (labels, features) matrix per query
    lbl, feats = next(mq2007.gen_list(qls[2]))
    assert lbl.tolist() == [[1], [0]]
    assert feats.shape == (2, mq2007.FEATURE_DIM)

    # ragged lines (trailing features omitted) still stack uniformly
    q = mq2007.Query.parse("1 qid:5 2:0.5")
    assert len(q.feature_vector) == mq2007.FEATURE_DIM
    assert q.feature_vector[:3] == [-1, 0.5, -1]


def test_image_transform_pipeline(tmp_path):
    from paddle_tpu.dataset import image as dimage

    rng = np.random.RandomState(3)
    im = rng.randint(0, 255, (40, 60, 3), dtype=np.uint8)
    r = dimage.resize_short(im, 20)
    assert min(r.shape[:2]) == 20 and r.shape[1] == 30
    c = dimage.center_crop(r, 16)
    assert c.shape[:2] == (16, 16)
    rc = dimage.random_crop(r, 16)
    assert rc.shape[:2] == (16, 16)
    fl = dimage.left_right_flip(c)
    np.testing.assert_array_equal(fl[:, 0], c[:, -1])
    chw = dimage.to_chw(c)
    assert chw.shape == (3, 16, 16)
    out = dimage.simple_transform(im, 24, 16, is_train=True,
                                  mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 16, 16) and out.dtype == np.float32

    # encode/decode round-trip + batch_images_from_tar over a tiny tar
    import io
    import pickle
    import tarfile

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(im[:, :, ::-1]).save(buf, format="PNG")
    decoded = dimage.load_image_bytes(buf.getvalue())
    assert decoded.shape == (40, 60, 3)
    np.testing.assert_array_equal(decoded, im)   # PNG is lossless

    tar = tmp_path / "imgs.tar"
    with tarfile.open(str(tar), "w") as tf:
        for name in ("a.png", "b.png"):
            ti = tarfile.TarInfo(name)
            ti.size = len(buf.getvalue())
            tf.addfile(ti, io.BytesIO(buf.getvalue()))
    meta = dimage.batch_images_from_tar(str(tar), "train",
                                        {"a.png": 0, "b.png": 1},
                                        num_per_batch=1)
    batches = [ln.strip() for ln in open(meta)]
    assert len(batches) == 2
    blob = pickle.load(open(batches[0], "rb"))
    assert blob["label"] in ([0], [1])
    assert dimage.load_image_bytes(blob["data"][0]).shape == (40, 60, 3)
