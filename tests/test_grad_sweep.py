"""Gradient sweep: numeric-vs-analytic grad checks for differentiable
ops whose backward path no other test executed (found by a dynamic
compute_op audit of the suite).  The generic auto-vjp grad maker makes
most gradients correct by construction — what this sweep catches is the
per-op plumbing: slot wiring, multiple outputs, integer side inputs
(no_grad), and kernels whose forward isn't smoothly differentiable at
the sampled points (inputs are chosen away from kinks, the reference
op_test.py convention).
"""

import numpy as np
import pytest

from op_test import OpTest


def _r(shape, lo, hi, seed):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype("float32")


def _away_from(x, kinks, margin=0.15):
    """Nudge values within `margin` of any kink point outward."""
    for k in kinks:
        close = np.abs(x - k) < margin
        x = np.where(close, k + np.sign(x - k + 1e-9) * margin, x)
    return x.astype("float32")


# (op_type, inputs, attrs, grad_inputs, output_slot_name_suffix, no_grad)
def ACT(op, attrs, lo, hi, kinks=()):
    x = _away_from(_r((3, 4), lo, hi, abs(hash(op)) % 1000), kinks)
    return (op, {"X": x}, attrs, ["X"], "Out", None)


CASES = [
    ACT("cos", {}, -3, 3),
    ACT("sin", {}, -3, 3),
    ACT("gelu", {}, -3, 3),
    ACT("elu", {"alpha": 0.8}, -3, 3, kinks=(0.0,)),
    ACT("reciprocal", {}, 0.5, 2),
    ACT("rsqrt", {}, 0.5, 2),
    ACT("sqrt", {}, 0.5, 2),
    ACT("pow", {"factor": 2.0}, 0.5, 2),
    ACT("tanh_shrink", {}, -3, 3),
    ACT("hard_sigmoid", {"slope": 0.2, "offset": 0.5}, -1.4, 1.4),
    ACT("leaky_relu", {"alpha": 0.1}, -3, 3, kinks=(0.0,)),
    ACT("brelu", {"t_min": -1.0, "t_max": 2.0}, -0.6, 1.6),
    ACT("relu6", {"threshold": 6.0}, 0.5, 5.0),
    ACT("hard_shrink", {"threshold": 0.5}, -3, 3, kinks=(-0.5, 0.5)),
    ACT("softshrink", {"lambda": 0.5}, -3, 3, kinks=(-0.5, 0.5)),
    ACT("thresholded_relu", {"threshold": 1.0}, -3, 3, kinks=(1.0,)),
    ACT("logsigmoid", {}, -3, 3),
    # ---- losses / norms --------------------------------------------------
    ("hinge_loss",
     {"Logits": _away_from(_r((5, 1), -2, 2, 40), (1.0, -1.0)),
      "Labels": np.array([[1], [0], [1], [0], [1]], "float32")},
     {}, ["Logits"], "Loss", {"hinge_loss__Labels"}),
    ("huber_loss",
     {"X": np.zeros((4, 1), "float32"),
      "Y": np.array([[0.3], [-0.4], [2.0], [-3.0]], "float32")},
     {"delta": 1.0}, ["X"], "Out", {"huber_loss__Y"}),
    ("log_loss",
     {"Predicted": _r((4, 1), 0.2, 0.8, 41),
      "Labels": np.array([[1], [0], [1], [0]], "float32")},
     {"epsilon": 1e-4}, ["Predicted"], "Loss", {"log_loss__Labels"}),
    ("rank_loss",
     {"Label": np.array([[1.0], [0.0], [1.0]], "float32"),
      "Left": _r((3, 1), -1, 1, 42), "Right": _r((3, 1), -1, 1, 43)},
     {}, ["Left", "Right"], "Out", {"rank_loss__Label"}),
    ("squared_l2_norm", {"X": _r((3, 3), -2, 2, 44)}, {}, ["X"], "Out",
     None),
    ("l1_norm", {"X": _away_from(_r((3, 3), -2, 2, 45), (0.0,))}, {},
     ["X"], "Out", None),
    ("clip_by_norm", {"X": _r((3, 3), 1, 2, 46)}, {"max_norm": 1.0},
     ["X"], "Out", None),
    # ---- manipulation ----------------------------------------------------
    ("gather",
     {"X": _r((5, 3), -2, 2, 47), "Index": np.array([4, 0, 2], "int64")},
     {}, ["X"], "Out", {"gather__Index"}),
    ("scatter",
     {"X": _r((5, 3), -2, 2, 48), "Ids": np.array([1, 3], "int64"),
      "Updates": _r((2, 3), -2, 2, 49)},
     {"overwrite": False}, ["X", "Updates"], "Out", {"scatter__Ids"}),
    ("flatten", {"X": _r((2, 3, 2), -2, 2, 50)}, {"axis": 2}, ["X"],
     "Out", None),
    ("pad", {"X": _r((2, 3), -2, 2, 51)},
     {"paddings": [1, 0, 0, 1], "pad_value": 0.0}, ["X"], "Out", None),
    ("reverse", {"X": _r((2, 4), -2, 2, 52)}, {"axis": [1]}, ["X"],
     "Out", None),
    ("cumsum", {"X": _r((2, 4), -2, 2, 53)}, {"axis": 1}, ["X"], "Out",
     None),
    ("minus", {"X": _r((2, 4), -2, 2, 54), "Y": _r((2, 4), -2, 2, 55)},
     {}, ["X", "Y"], "Out", None),
    ("label_smooth", {"X": _r((2, 5), 0, 1, 56)}, {"epsilon": 0.1},
     ["X"], "Out", None),
    ("cast", {"X": _r((2, 4), -2, 2, 57)},
     {"in_dtype": "float32", "out_dtype": "float32"}, ["X"], "Out", None),
    ("expand", {"X": _r((2, 3), -2, 2, 58)}, {"expand_times": [2, 1]},
     ["X"], "Out", None),
    ("norm", {"X": _r((3, 4), 0.5, 2, 59)}, {"axis": 1}, ["X"], "Out",
     None),
    ("elementwise_pow",
     {"X": _r((2, 3), 0.5, 2, 60), "Y": _r((2, 3), 0.5, 2, 61)},
     {}, ["X", "Y"], "Out", None),
    ("multiplex",
     {"Ids": np.array([[1], [0]], "int64"),
      "X": [("mx0", _r((2, 3), -2, 2, 62)),
            ("mx1", _r((2, 3), -2, 2, 63))]},
     {}, ["mx0", "mx1"], "Out", {"multiplex__Ids"}),
    ("reduce_prod", {"X": _r((2, 3), 0.5, 1.5, 64)}, {"dim": [1]},
     ["X"], "Out", None),
    # ---- conv / interp / pooling ----------------------------------------
    ("conv2d_transpose",
     {"Input": _r((1, 2, 3, 3), -1, 1, 65),
      "Filter": _r((2, 2, 2, 2), -1, 1, 66)},
     {"strides": [1, 1], "paddings": [0, 0]},
     ["Input", "Filter"], "Output", None),
    ("depthwise_conv2d",
     {"Input": _r((1, 2, 4, 4), -1, 1, 67),
      "Filter": _r((2, 1, 2, 2), -1, 1, 68)},
     {"strides": [1, 1], "paddings": [0, 0], "groups": 2},
     ["Input", "Filter"], "Output", None),
    ("conv3d",
     {"Input": _r((1, 1, 2, 3, 3), -1, 1, 69),
      "Filter": _r((1, 1, 2, 2, 2), -1, 1, 70)},
     {"strides": [1, 1, 1], "paddings": [0, 0, 0]},
     ["Input", "Filter"], "Output", None),
    ("pool3d", {"X": _r((1, 1, 2, 3, 3), -1, 1, 71)},
     {"ksize": [2, 2, 2], "strides": [1, 1, 1], "paddings": [0, 0, 0],
      "pooling_type": "avg"}, ["X"], "Out", None),
    ("nearest_interp", {"X": _r((1, 1, 2, 2), -1, 1, 72)},
     {"out_h": 4, "out_w": 4}, ["X"], "Out", None),
    ("bilinear_interp", {"X": _r((1, 1, 2, 2), -1, 1, 73)},
     {"out_h": 3, "out_w": 3}, ["X"], "Out", None),
    ("bilinear_tensor_product",
     {"X": _r((2, 3), -1, 1, 74), "Y": _r((2, 2), -1, 1, 75),
      "Weight": _r((2, 3, 2), -1, 1, 76)},
     {}, ["X", "Y", "Weight"], "Out", None),
    ("im2sequence", {"X": _r((1, 1, 4, 4), -1, 1, 77)},
     {"kernels": [2, 2], "strides": [2, 2], "paddings": [0, 0, 0, 0]},
     ["X"], "Out", None),
    # ---- sequence family (Length is an integer no-grad input) ------------
    ("sequence_reverse",
     {"X": _r((2, 3, 2), -1, 1, 78),
      "Length": [("srl", np.array([3, 2], "int32"))]},
     {}, ["X"], "Out", {"srl"}),
    ("sequence_expand",
     {"X": _r((2, 4), -1, 1, 79), "Y": _r((2, 3, 2), -1, 1, 80),
      "Length": [("sel", np.array([3, 2], "int32"))]},
     {}, ["X"], "Out", {"sel", "sequence_expand__Y"}),
    ("sequence_concat",
     {"X": [("sca", _r((2, 2, 2), -1, 1, 81)),
            ("scb", _r((2, 2, 2), -1, 1, 82))],
      "Length": [("scla", np.array([2, 1], "int32")),
                 ("sclb", np.array([1, 2], "int32"))]},
     {}, ["sca", "scb"], "Out", {"scla", "sclb"}),
    ("sequence_unpad",
     {"X": _r((2, 3, 2), -1, 1, 83),
      "Length": [("sul", np.array([3, 2], "int32"))]},
     {}, ["X"], "Out", {"sul"}),
    ("row_conv",
     {"X": _r((2, 3, 2), -1, 1, 84), "Filter": _r((2, 2), -1, 1, 85),
      "Length": [("rcl", np.array([3, 2], "int32"))]},
     {}, ["X", "Filter"], "Out", {"rcl"}),
]


@pytest.mark.parametrize(
    "op_type,inputs,attrs,grad_inputs,out_slot,no_grad",
    CASES, ids=[c[0] for c in CASES])
def test_grad(op_type, inputs, attrs, grad_inputs, out_slot, no_grad):
    import paddle_tpu as fluid
    import paddle_tpu.registry as registry

    t = OpTest()
    t.op_type = op_type
    t.inputs = inputs
    t.attrs = dict(attrs)
    # forward probe: declare every output slot (placeholder arrays — the
    # probe only needs names; infer assigns real shapes), run once, and
    # make the real output arrays the expected outputs for check_grad's
    # cotangent shapes
    slots = registry.OPS[op_type].output_slots
    t.outputs = {s: np.zeros(1, "float32") for s in slots}
    program, startup, feed, outs = t._build(stop_gradient_all=True)
    names = {s: pairs[0][0] for s, pairs in t._canon(t.outputs).items()}
    exe = fluid.Executor(fluid.CPUPlace())
    vals = exe.run(program, feed=feed, fetch_list=list(names.values()))
    t.outputs = {s: np.asarray(v) for s, v in zip(names, vals)}

    # grad targets: single-array inputs are canonicalized to
    # "<op>__<slot>"; list inputs keep their explicit names
    list_slots = {k for k, v in inputs.items()
                  if isinstance(v, list) and v and isinstance(v[0], tuple)}
    targets = [g if any(g == n for s in list_slots
                        for n, _ in inputs[s])
               else "%s__%s" % (op_type, g) for g in grad_inputs]
    t.check_grad(targets, names[out_slot], no_grad_set=no_grad,
                 max_relative_error=8e-3, delta=2e-3)


# ---- third wave: grouped norms, shifted convs, sequence reshapes ----------

CASES2 = [
    ("group_norm",
     {"X": _r((2, 4, 3, 3), -1, 1, 90), "Scale": _r((4,), 0.5, 1.5, 91),
      "Bias": _r((4,), -0.5, 0.5, 92)},
     {"groups": 2, "epsilon": 1e-5}, ["X", "Scale", "Bias"], "Y", None),
    ("conv_shift",
     {"X": _r((2, 6), -1, 1, 93), "Y": _r((2, 3), -1, 1, 94)},
     {}, ["X", "Y"], "Out", None),
    ("sequence_reshape",
     {"X": _r((2, 4, 2), -1, 1, 95),
      "Length": [("srsl", np.array([4, 2], "int32"))]},
     {"new_dim": 4}, ["X"], "Out", {"srsl"}),
    ("sequence_expand_as",
     {"X": _r((2, 3), -1, 1, 96), "Y": _r((2, 4, 2), -1, 1, 97),
      "YLength": [("seal", np.array([4, 2], "int32"))]},
     {}, ["X"], "Out", {"seal", "sequence_expand_as__Y"}),
    ("sequence_scatter",
     {"X": _r((2, 5), -1, 1, 98),
      "Ids": np.array([[1, 2, 0], [0, 3, 0]], "int64"),
      "Updates": _r((2, 3), -1, 1, 99),
      "Length": [("sscl", np.array([3, 2], "int32"))]},
     {}, ["X", "Updates"], "Out", {"sscl", "sequence_scatter__Ids"}),
    ("lod_reset",
     {"X": _r((2, 3, 2), -1, 1, 100),
      "Y": [("lrl", np.array([0, 2, 5], "int64"))]},
     {}, ["X"], "Out", {"lrl"}),
    ("spp", {"X": _r((1, 2, 4, 4), -1, 1, 101)},
     {"pyramid_height": 2, "pooling_type": "avg"}, ["X"], "Out", None),
]


@pytest.mark.parametrize(
    "op_type,inputs,attrs,grad_inputs,out_slot,no_grad",
    CASES2, ids=[c[0] for c in CASES2])
def test_grad_wave3(op_type, inputs, attrs, grad_inputs, out_slot,
                    no_grad):
    test_grad(op_type, inputs, attrs, grad_inputs, out_slot, no_grad)


def test_max_pool_with_index_unpool_chain_grad():
    """max_pool2d_with_index -> unpool roundtrip gradient: the unpool
    scatter must route cotangents back exactly to the argmax positions
    (reference max_pool_with_index_op.cc + unpool_op.cc custom grads)."""
    import paddle_tpu as fluid

    rng = np.random.RandomState(102)
    # distinct values => unique argmax (numeric diff stays off ties)
    xv = rng.permutation(64).astype("float32").reshape(1, 1, 8, 8) / 64.0

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[1, 8, 8])
        x.stop_gradient = False
        block = fluid.default_main_program().current_block()
        pooled = block.create_var(name="pooled", dtype="float32")
        mask = block.create_var(name="mask", dtype="int64")
        block.append_op(
            type="max_pool2d_with_index", inputs={"X": [x]},
            outputs={"Out": [pooled], "Mask": [mask]},
            attrs={"ksize": [2, 2], "strides": [2, 2],
                   "paddings": [0, 0]})
        up = block.create_var(name="up", dtype="float32")
        block.append_op(
            type="unpool", inputs={"X": [pooled], "Indices": [mask]},
            outputs={"Out": [up]},
            attrs={"unpool_size": [8, 8], "ksize": [2, 2],
                   "strides": [2, 2]})
        loss = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(up, up))
        (gx,) = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            (g,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    # d(sum(up^2))/dx = 2*x at argmax positions, 0 elsewhere
    want = np.zeros_like(xv)
    for i in range(4):
        for j in range(4):
            win = xv[0, 0, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
            a, b = np.unravel_index(win.argmax(), (2, 2))
            want[0, 0, 2 * i + a, 2 * j + b] = 2 * win.max()
    np.testing.assert_allclose(g, want, rtol=1e-5)


def test_print_backward_passthrough_with_fanout(capfd):
    """print's gradient is the SUMMED cotangent when the printed var has
    multiple downstream consumers (the GRAD:: wiring materializes the
    accumulation before the pass-through reads it; reference
    print_op.cc backward)."""
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[3])
        x.stop_gradient = False
        p = fluid.layers.Print(x, message="probe",
                               print_phase="BACKWARD")
        # two consumers -> two grad contributions to sum
        a = fluid.layers.scale(p, scale=2.0)
        b = fluid.layers.scale(p, scale=5.0)
        loss = fluid.layers.reduce_sum(
            fluid.layers.elementwise_add(a, b))
        (gx,) = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            (gv,) = exe.run(feed={"x": np.ones((2, 3), "float32")},
                            fetch_list=[gx])
    np.testing.assert_allclose(gv, 7.0 * np.ones((2, 3)), rtol=1e-6)
