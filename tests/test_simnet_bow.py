"""SimNet-BOW pairwise ranker (models/simnet_bow.py — reference
dist_simnet_bow.py workload): twin towers with a shared sparse
embedding train under margin_rank_loss until positive titles outrank
negatives."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.simnet_bow import simnet_bow

V, T, B = 500, 6, 32


def _batches(steps, seed=0):
    """Positive titles share words with the query; negatives are random
    — rankable purely from the shared embedding space."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        q = rng.randint(0, V, (B, T, 1)).astype("int64")
        pos = q.copy()
        # positive keeps half the query words, rest resampled
        mask = rng.rand(B, T, 1) < 0.5
        pos[mask] = rng.randint(0, V, int(mask.sum()))
        neg = rng.randint(0, V, (B, T, 1)).astype("int64")
        lens = np.full(B, T, "int64")
        out.append({"q": q, "q@LEN": lens, "p": pos, "p@LEN": lens,
                    "n": neg, "n@LEN": lens})
    return out


def test_simnet_bow_learns_to_rank():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 11
        fluid.default_startup_program().random_seed = 11
        q = fluid.layers.data("q", shape=[1], dtype="int64", lod_level=1)
        p = fluid.layers.data("p", shape=[1], dtype="int64", lod_level=1)
        n = fluid.layers.data("n", shape=[1], dtype="int64", lod_level=1)
        cost, ps, ns = simnet_bow(q, p, n, dict_size=V, margin=0.3)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            losses = []
            for feed in _batches(80):
                lv, pv, nv = exe.run(feed=feed,
                                     fetch_list=[cost, ps, ns])
                losses.append(float(np.asarray(lv)))
            # ranking accuracy on fresh data
            correct = total = 0
            for feed in _batches(5, seed=99):
                _, pv, nv = exe.run(feed=feed, fetch_list=[cost, ps, ns])
                correct += int((np.asarray(pv) > np.asarray(nv)).sum())
                total += B
    # BOW word overlap ranks many pairs from init; training tightens
    # the margin until held-out ranking accuracy is high (the loss
    # plateau is the irreducible tail: positives that kept no query
    # words are unrankable by construction)
    assert np.mean(losses[-10:]) < 0.08, np.mean(losses[-10:])
    assert correct / total > 0.93, correct / total
