"""API-stability gate (reference tools/print_signatures.py +
tools/diff_api.py CI pattern): the public surface must match the golden
list; intentional changes run ``python tools/print_signatures.py
--update`` and commit the diff."""

import os
import subprocess
import sys


def test_public_api_matches_golden():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tools_dir = os.path.join(root, "tools")
    sys.path.insert(0, tools_dir)
    try:
        import print_signatures
        current = print_signatures.collect()
        golden = open(print_signatures.GOLDEN).read().splitlines()
    finally:
        # remove by value: importing print_signatures inserts the repo
        # root at index 0, so pop(0) would evict the wrong entry
        sys.path.remove(tools_dir)
    cur_set, gold_set = set(current), set(golden)
    removed = sorted(gold_set - cur_set)
    added = sorted(cur_set - gold_set)
    assert not removed and not added, (
        "public API drifted; run `python tools/print_signatures.py "
        "--update` if intentional.\nremoved: %s\nadded: %s"
        % (removed[:10], added[:10]))
