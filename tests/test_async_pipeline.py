"""Step-overlap pipeline: async dispatch, device prefetch, compile cache.

The contract under test (ISSUE 1 acceptance): the overlapped path —
``DevicePrefetcher`` staging feeds ahead + ``return_numpy=False`` with a
bounded dispatch window — must be *bit-identical* in loss trajectory to
the fully synchronous path, the prefetcher must drain cleanly on early
shutdown and surface producer exceptions after the good batches, and a
second executor over the same program+signature must perform zero new
lowerings (process-global trace cache).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import compile_cache
from paddle_tpu.reader import DevicePrefetcher


def _mlp_program(seed=7):
    prog, sprog = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, sprog):
        img = fluid.layers.data("img", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, size=8, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog.random_seed = seed
    sprog.random_seed = seed
    return prog, sprog, loss


def _feeds(n, batch=4):
    rng = np.random.RandomState(0)
    return [{"img": rng.rand(batch, 8).astype("float32"),
             "label": rng.randint(0, 4, (batch, 1)).astype("int64")}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# loss-trajectory parity
# ---------------------------------------------------------------------------

def test_overlap_loss_parity_bit_identical():
    """Seeded program run synchronously vs through the full overlapped
    pipeline (prefetcher + async dispatch window) produces bit-identical
    per-step losses: overlap must never change numerics."""
    prog, sprog, loss = _mlp_program()
    feeds = _feeds(6)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        sync_losses = [
            exe.run(prog, feed=f, fetch_list=[loss])[0].item()
            for f in feeds
        ]

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(sprog)
        handles = []
        with DevicePrefetcher(iter(feeds), place=fluid.CPUPlace(),
                              capacity=2) as pf:
            for f in pf:
                handles.append(exe2.run(prog, feed=f, fetch_list=[loss],
                                        return_numpy=False))
        exe2.sync()
        overlap_losses = [np.asarray(h[0]).item() for h in handles]

    assert sync_losses == overlap_losses


def test_async_dispatch_window_bounds_inflight():
    """The dispatch window never holds more than max_inflight steps and
    drain() empties it."""
    from paddle_tpu.executor import AsyncDispatchQueue

    q = AsyncDispatchQueue(max_inflight=3)
    for i in range(10):
        q.push([np.float32(i)])
        assert len(q) <= 3
    q.drain()
    assert len(q) == 0


def test_async_dispatch_window_skips_donated_buffers():
    """A window entry whose buffers were donated away by a later step
    (fetch-less steps push new_state; donate_argnums reuses it) must be
    skipped, not block_until_ready-ed into 'Array has been deleted'."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.executor import AsyncDispatchQueue

    q = AsyncDispatchQueue(max_inflight=4)
    a = jnp.arange(4.0)
    jax.block_until_ready(a)
    a.delete()                           # what donation does on TPU
    q.push([a])
    q.push([jnp.arange(2.0)])
    q.drain()                            # must not raise
    assert len(q) == 0
    # an all-donated oldest entry must still produce a real bound:
    # _sync_oldest falls through to the oldest live leaf of a younger
    # in-flight step rather than skipping the sync outright
    b, c = jnp.arange(3.0), jnp.arange(5.0)
    jax.block_until_ready([b, c])
    b.delete()
    q.push([b])
    q.push([c])
    assert q._live_leaves([b]) == []
    q._sync_oldest()                     # pops [b], blocks via [c]
    assert len(q) == 1
    q.drain()


def test_async_dispatch_empty_fetch_list():
    """return_numpy=False with an empty fetch_list still bounds and
    drains the window (handles are the donated new_state)."""
    prog, sprog, loss = _mlp_program(seed=19)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        for f in _feeds(12):             # > FLAGS_max_inflight_steps
            exe.run(prog, feed=f, fetch_list=[], return_numpy=False)
        # the window holds tiny derived tokens, not the donated
        # new_state buffers themselves (which the next step deletes on
        # real accelerators) — so the bound survives donation
        assert exe._dispatch_queue._inflight[-1][0].size == 1
        exe.sync()
        assert len(exe._dispatch_queue) == 0


def test_executor_sync_retires_inflight():
    prog, sprog, loss = _mlp_program()
    feeds = _feeds(4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        for f in feeds:
            exe.run(prog, feed=f, fetch_list=[loss], return_numpy=False)
        assert len(exe._dispatch_queue) > 0
        exe.sync()
        assert len(exe._dispatch_queue) == 0


# ---------------------------------------------------------------------------
# prefetcher lifecycle
# ---------------------------------------------------------------------------

def test_prefetcher_exception_after_good_batches():
    """A producer exception surfaces at the consumer AFTER every
    already-produced batch — not as a silent end-of-data, not before the
    good batches."""
    def source():
        yield {"x": np.zeros(2, "float32")}
        yield {"x": np.ones(2, "float32")}
        raise RuntimeError("decode failed")

    pf = DevicePrefetcher(source, capacity=4)
    it = iter(pf)
    got = [next(it), next(it)]
    assert [g["x"][0] for g in got] == [0.0, 1.0]
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_prefetcher_close_midstream_joins_producer():
    """close() while the producer is blocked on a full queue stops and
    joins the thread (no daemon-thread leak, no hang)."""
    def source():
        for i in range(1000):
            yield {"x": np.full(2, i, "float32")}

    pf = DevicePrefetcher(source, capacity=1)
    it = iter(pf)
    first = next(it)
    assert first["x"][0] == 0.0
    time.sleep(0.05)           # let the producer block on the full queue
    pf.close()
    assert not pf._thread.is_alive()
    # close is idempotent
    pf.close()


def test_prefetcher_context_manager_abandoned_iteration():
    consumed = []
    with DevicePrefetcher(iter(_feeds(50)), capacity=2) as pf:
        for f in pf:
            consumed.append(f)
            if len(consumed) == 3:
                break
    assert len(consumed) == 3
    assert not pf._thread.is_alive()


def test_prefetcher_abandoned_iterator_stops_producer():
    """Dropping the iterator (the facades keep no other handle) stops
    the producer thread via GeneratorExit — no busy-polling leak."""
    pf = DevicePrefetcher(iter(_feeds(1000)), capacity=1)
    it = iter(pf)
    next(it)
    it.close()
    assert not pf._thread.is_alive()


def test_prefetcher_partial_shardings_dict_still_stages_rest():
    """Feeds missing from a partial shardings dict fall back to plain
    device placement instead of silently staying host arrays."""
    import jax
    from jax.sharding import SingleDeviceSharding

    sh = SingleDeviceSharding(jax.devices("cpu")[0])
    feeds = [{"img": np.zeros((2, 4), "float32"),
              "label": np.zeros((2, 1), "int64")}]
    with DevicePrefetcher(iter(feeds), place=fluid.CPUPlace(),
                          shardings={"img": sh}) as pf:
        out = next(iter(pf))
    assert isinstance(out["img"], jax.Array)
    assert isinstance(out["label"], jax.Array)   # the unlisted feed


def test_prefetcher_reiterable_with_callable_source():
    """A callable source makes the prefetcher re-iterable (the PyReader
    multi-epoch contract): each epoch sees the full fresh stream."""
    def source():
        return iter(_feeds(4))

    with DevicePrefetcher(source, capacity=2) as pf:
        epochs = [len(list(pf)), len(list(pf))]
    assert epochs == [4, 4]
    assert not pf._thread.is_alive()


def test_prefetcher_fresh_iter_supersedes_live_stream():
    """iter() over a live stream (callable source) restarts from the
    top — the fresh epoch never shares the half-consumed stream, and a
    stale superseded iterator can neither steal its batches nor kill it
    when dropped/GC'd."""
    import gc

    def source():
        return iter(_feeds(5))

    pf = DevicePrefetcher(source, capacity=2)
    it1 = iter(pf)
    first = next(it1)
    epoch2 = [f for f in pf]            # fresh iter() mid-stream
    assert len(epoch2) == 5
    assert np.array_equal(epoch2[0]["img"], first["img"])  # from the top
    del it1
    gc.collect()                         # stale iterator GC: no effect
    assert len(list(pf)) == 5
    pf.close()


def test_prefetcher_enter_is_lazy_no_batch_loss():
    """__enter__ must not pre-start a producer the first iter() then
    restarts: a callable source over a shared underlying stream sees
    every batch exactly once."""
    stream = iter(_feeds(5))
    with DevicePrefetcher(lambda: stream, capacity=2) as pf:
        got = list(pf)
    assert len(got) == 5


def test_prefetcher_second_live_iter_over_plain_iterator_raises():
    """A second iter() while a plain-iterator epoch is live raises
    instead of silently competing for (and truncating) the stream."""
    pf = DevicePrefetcher(iter(_feeds(5)), capacity=2)
    it1 = iter(pf)
    next(it1)
    with pytest.raises(RuntimeError, match="active iterator"):
        iter(pf)
    pf.close()


def test_prefetcher_exhausted_iterator_raises():
    """Re-iterating over a consumed one-shot-iterator source raises
    instead of silently yielding an empty epoch."""
    pf = DevicePrefetcher(iter(_feeds(2)), capacity=2)
    assert len(list(pf)) == 2
    with pytest.raises(RuntimeError, match="exhausted"):
        iter(pf)


def test_prefetcher_reiterable_with_list_source():
    """A re-iterable container source (list of feed dicts) supports
    multi-epoch iteration like a reader creator."""
    pf = DevicePrefetcher(_feeds(3), capacity=2)
    assert [len(list(pf)) for _ in range(3)] == [3, 3, 3]
    pf.close()


def test_prefetcher_two_unadvanced_iters_do_not_share_epoch():
    """A second iter() before the first is ever advanced must supersede
    (callable source) or raise (one-shot iterator) — never silently
    hand out two consumers over one epoch's queue."""
    import gc

    pf = DevicePrefetcher(lambda: iter(_feeds(6)), capacity=2)
    it1 = iter(pf)
    it2 = iter(pf)                   # supersedes it1 pre-advance
    assert len(list(it2)) == 6       # full epoch, nothing stolen
    assert list(it1) == []           # superseded: cleanly empty
    pf.close()

    pf2 = DevicePrefetcher(iter(_feeds(3)), capacity=2)
    it1 = iter(pf2)
    with pytest.raises(RuntimeError, match="active iterator"):
        iter(pf2)
    del it1
    gc.collect()                     # a dropped unadvanced consumer...
    assert len(list(pf2)) == 3       # ...doesn't block recovery


def test_prefetcher_unadvanced_iterator_leaks_no_thread():
    """iter() alone must not spawn a producer: a created-but-never-
    advanced generator's finally never runs, so an eager thread would
    leak (busy-polling, pinning staged batches) for the process life."""
    import gc

    pf = DevicePrefetcher(iter(_feeds(50)), capacity=1)
    it = iter(pf)
    assert pf._thread is None        # producer starts on first next()
    del it
    gc.collect()
    assert pf._thread is None
    assert len(list(pf)) == 50       # still consumable afterwards


def test_prefetcher_threads_do_not_leak():
    before = threading.active_count()
    for _ in range(5):
        with DevicePrefetcher(iter(_feeds(10)), capacity=2) as pf:
            next(iter(pf))
    assert threading.active_count() <= before + 1


# ---------------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------------

def test_compile_cache_second_executor_zero_lowerings():
    """A fresh Executor over the same program+signature reuses the
    process-global trace cache: zero new lowerings on the second run."""
    prog, sprog, loss = _mlp_program()
    feeds = _feeds(2)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        exe.run(prog, feed=feeds[0], fetch_list=[loss])
    baseline = compile_cache.stats()

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(sprog)
        exe2.run(prog, feed=feeds[1], fetch_list=[loss])
    after = compile_cache.stats()

    assert after["lowerings"] == baseline["lowerings"]
    assert after["trace_hits"] >= baseline["trace_hits"] + 2

    # structural mutation invalidates the fingerprint: appending an op
    # must NOT serve the stale trace
    fp_before = compile_cache.program_fingerprint(prog)
    with fluid.program_guard(prog, sprog):
        fluid.layers.scale(loss, scale=2.0)
    assert compile_cache.program_fingerprint(prog) != fp_before


def test_parallel_executor_return_numpy_false_async():
    """ParallelExecutor honors return_numpy=False: device arrays come
    back without a per-step sync, and the values match the numpy path."""
    import jax

    prog, sprog, loss = _mlp_program()
    feeds = _feeds(3)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        pe = fluid.ParallelExecutor(use_cuda=False, main_program=prog,
                                    loss_name=loss.name)
        dev_losses = []
        for f in feeds:
            out = pe.run(feed=f, fetch_list=[loss], return_numpy=False)
            assert isinstance(out[0], jax.Array)
            dev_losses.append(out[0])
        pe.sync()
        np_vals = [np.asarray(d).item() for d in dev_losses]

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(sprog)
        pe2 = fluid.ParallelExecutor(use_cuda=False, main_program=prog,
                                     loss_name=loss.name)
        ref = [pe2.run(feed=f, fetch_list=[loss])[0].item() for f in feeds]

    assert np_vals == ref


def test_parallel_executor_check_nan_inf_keeps_device_arrays():
    """FLAGS_check_nan_inf adds a per-step sync but must not change the
    return_numpy=False type contract: fetches stay jax Arrays."""
    import jax

    prog, sprog, loss = _mlp_program(seed=17)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(sprog)
            pe = fluid.ParallelExecutor(use_cuda=False, main_program=prog,
                                        loss_name=loss.name)
            out = pe.run(feed=_feeds(1)[0], fetch_list=[loss],
                         return_numpy=False)
            assert isinstance(out[0], jax.Array)
            pe.sync()
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_persistent_cache_dir_populated(tmp_path):
    """FLAGS_compile_cache_dir points jax's on-disk executable cache at
    the directory; a compile writes at least one entry."""
    cache_dir = str(tmp_path / "xla_cache")
    fluid.set_flags({"FLAGS_compile_cache_dir": cache_dir})
    try:
        prog, sprog, loss = _mlp_program(seed=11)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(sprog)
            exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
        entries = []
        for root, _, files in os.walk(cache_dir):
            entries.extend(files)
        assert entries, "persistent compilation cache wrote no entries"
    finally:
        fluid.set_flags({"FLAGS_compile_cache_dir": ""})


# ---------------------------------------------------------------------------
# profiler observability
# ---------------------------------------------------------------------------

def test_profiler_records_pipeline_spans():
    """h2d_transfer / dispatch / fetch_sync / compile spans and the
    compile_cache hit/miss marks are visible in the captured events."""
    from paddle_tpu import profiler

    prog, sprog, loss = _mlp_program(seed=13)
    feeds = _feeds(3)
    profiler.reset_profiler()
    profiler.start_profiler()
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(sprog)
            exe.run(prog, feed=feeds[0], fetch_list=[loss])          # compile
            exe.run(prog, feed=feeds[1], fetch_list=[loss])          # dispatch
            exe.run(prog, feed=feeds[2], fetch_list=[loss],
                    return_numpy=False)
            exe.sync()                                               # window
        names = {e["name"] for e in profiler._events}
    finally:
        profiler.stop_profiler()
        profiler.reset_profiler()
    for expected in ("executor/h2d_transfer", "executor/compile",
                     "executor/dispatch", "executor/fetch_sync"):
        assert expected in names, (expected, sorted(names))
    assert "compile_cache/hit" in names or "compile_cache/miss" in names


# ---------------------------------------------------------------------------
# bench ladder smoke (slow: excluded from the tier-1 gate)
# ---------------------------------------------------------------------------

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.mark.slow
def test_bench_smoke_ladder(tmp_path):
    """`bench.py --smoke` exercises the real ladder machinery (subprocess
    rungs, budget gate, partial-artifact emit) in ~30s: exit 0, valid
    JSON lines, final line ladder_complete, artifact file written."""
    out = str(tmp_path / "BENCH_smoke.json")
    cache_dir = str(tmp_path / "xla_cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--device", "cpu",
         "--budget-seconds", "240", "--out", out,
         "--compile_cache_dir", cache_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=420, env=env)
    assert res.returncode == 0, res.stderr[-2000:]
    # rung subprocesses inherit the persistent cache dir via the env: a
    # second invocation starts warm (the VERDICT r4 wall-clock lever)
    cached = [f for _, _, fs in os.walk(cache_dir) for f in fs]
    assert cached, "ladder rungs wrote no persistent-cache entries"
    lines = [l for l in res.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, res.stdout
    final = json.loads(lines[-1])
    assert final["ladder_complete"] is True
    assert final["metric"].startswith("mnist_mlp")
    assert final["value"] > 0
    # one per-rung reprint + the final line
    assert len(lines) >= 2
    with open(out) as f:
        assert json.load(f)["ladder_complete"] is True


@pytest.mark.slow
def test_bench_budget_skips_rungs_exit_zero(tmp_path):
    """An exhausted --budget-seconds records remaining rungs as omitted
    and still exits 0 with a valid artifact (the rc=124 fix)."""
    out = str(tmp_path / "BENCH_budget.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--device", "cpu",
         "--budget-seconds", "1", "--out", out],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120, env=env)
    assert res.returncode == 0, res.stderr[-2000:]
    final = json.loads(res.stdout.strip().splitlines()[-1])
    assert final["ladder_complete"] is True
    assert len(final.get("omitted", [])) == 2
