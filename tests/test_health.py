"""Model-health telemetry + NaN provenance (ISSUE 20): the in-graph
probe publishes per-layer stats without perturbing the trajectory
(bit-parity flag on/off), the disabled path performs zero health calls,
the guardian's quarantine sidecar names the exact first non-finite op,
the replay is deterministic, and check_nan_inf names the offending
variables."""

import glob
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import compile_cache, fault, guardian, monitor
from paddle_tpu.monitor import alerts, health

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    yield
    fault.clear()
    fault.clear_injections()
    guardian.uninstall()
    fluid.set_flags({
        "FLAGS_health": False,
        "FLAGS_health_every": 10,
        "FLAGS_guardian": False,
        "FLAGS_guardian_policy": "skip,rollback,abort",
        "FLAGS_check_nan_inf": False,
    })
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()
    health._clear_for_tests()


def _build_mlp(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(bs, 8).astype("float32"),
             "label": rng.randint(0, 4, (bs, 1)).astype("int64")}
            for _ in range(n)]


def _run(steps=6, fetch_extra=(), **run_kw):
    """Fresh seeded program + scope, `steps` executor steps; returns
    the per-step loss bytes (bit-comparable) and the scope."""
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    out = []
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        for feed in _batches(steps):
            vals = exe.run(main, feed=feed,
                           fetch_list=[loss] + list(fetch_extra),
                           **run_kw)
            out.append(np.asarray(vals[0], "float32").tobytes())
    return out, scope


# ---------------------------------------------------------------------------
# tentpole: the probe publishes per-layer stats as one extra fetch
# ---------------------------------------------------------------------------

def test_probe_publishes_per_layer_stats(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 2})
    _run(steps=4)
    snap = health.last_snapshot()
    assert snap is not None and snap["step"] == 2   # steps 0..3, cadence 2
    layers = snap["layers"]
    assert layers, "no layer classes published"
    for d in layers.values():
        assert np.isfinite(d["grad_norm"])
        assert d["param_norm"] > 0
        assert d["nonfinite"] == 0
    # at least one layer actually moved (Adam update)
    assert any(d["update_ratio"] > 0 for d in layers.values())
    # gauges: health/<layer>/<stat> normalized to health_<layer>_<stat>
    text = monitor.registry().expose_text()
    label = sorted(layers)[0]
    assert ("health_%s_grad_norm" % label) in text
    # JSONL: model_health records at the decimated cadence (steps 2, 4)
    recs = []
    for f in glob.glob(str(tmp_path / "*.jsonl")):
        with open(f) as fh:
            recs += [json.loads(ln) for ln in fh if "model_health" in ln]
    recs = [r for r in recs if r.get("event") == "model_health"]
    assert [r["step"] for r in recs] == [0, 2]
    assert recs[-1]["layers"][label]["param_norm"] > 0
    # the compact one-liner used by abort messages / stall dumps
    line = health.format_snapshot()
    assert line.startswith("step 2:") and label in line


def test_off_cadence_steps_do_not_publish():
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 100})
    _run(steps=3)
    # only step 0 is on-cadence; steps 1-2 never sync the stats fetch
    assert health.last_snapshot()["step"] == 0
    # but the replay ring still has every step (provenance readiness)
    assert len(health._REPLAY) == 3


# ---------------------------------------------------------------------------
# disabled-is-free: zero health calls per step (raising monkeypatch)
# ---------------------------------------------------------------------------

def test_disabled_path_performs_zero_health_calls(monkeypatch):
    def _boom(*a, **k):
        raise AssertionError("health call on the disabled path")
    monkeypatch.setattr(health, "build_probe", _boom)
    monkeypatch.setattr(health, "wrap_step_probe", _boom)
    monkeypatch.setattr(health, "note_step", _boom)
    out, _ = _run(steps=2)
    assert len(out) == 2
    assert health.last_snapshot() is None


# ---------------------------------------------------------------------------
# bit-parity: the probe never perturbs the trajectory
# ---------------------------------------------------------------------------

def test_seeded_trajectory_bit_identical_health_on_off():
    off, _ = _run(steps=6)
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 1})
    on, _ = _run(steps=6)
    assert off == on
    fluid.set_flags({"FLAGS_health_every": 3})
    decimated, _ = _run(steps=6)
    assert off == decimated   # cadence is host-side only


def test_flag_flip_rekeys_the_trace():
    base = compile_cache.trace_flag_values()
    fluid.set_flags({"FLAGS_health": True})
    probed = compile_cache.trace_flag_values()
    assert base != probed
    # cadence is NOT trace-shaping: same key at any FLAGS_health_every
    fluid.set_flags({"FLAGS_health_every": 7})
    assert compile_cache.trace_flag_values() == probed


# ---------------------------------------------------------------------------
# NaN provenance: quarantine sidecar names the exact first bad op
# ---------------------------------------------------------------------------

def _poisoned_guardian_run(tmp_path, steps=8, poison_step=3, **gkw):
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 1,
                     "FLAGS_guardian": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    qdir = str(tmp_path / "q")
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        g = guardian.install(guardian.Guardian(quarantine_dir=qdir, **gkw))
        # poison a PARAM: the next step's very first op (fc_0's mul)
        # consumes it, so provenance must name that op
        fault.inject_nan("fc_0.w_0",
                         fault.FaultSchedule(steps=[poison_step]))
        exe = fluid.Executor(fluid.CPUPlace())
        err = None
        try:
            for feed in _batches(steps):
                exe.run(main, feed=feed, fetch_list=[loss])
            g.flush()
        except guardian.GuardianAbortError as e:
            err = e
        stats = g.stats()
        guardian.uninstall()
    return qdir, stats, err


def test_quarantine_sidecar_carries_op_provenance(tmp_path):
    monitor.enable(log_dir=str(tmp_path / "mon"))
    qdir, stats, _ = _poisoned_guardian_run(tmp_path)
    assert stats["quarantined"] >= 1
    sidecars = sorted(glob.glob(os.path.join(qdir, "*.json")))
    assert sidecars
    prov = json.load(open(sidecars[0]))["provenance"]
    assert prov["found"] is True
    assert prov["op_type"] == "mul"
    assert prov["out_var"] == "fc_0.tmp_0"
    assert prov["op_index"] == 0
    assert "fc_0.w_0" in prov["in_vars"]
    assert prov["layer"]
    assert prov["replay_ms"] >= 0
    # reproducibility fields: the PRNG key data rides in the record
    assert prov["key_data"]
    # the JSONL twin landed too
    evs = []
    for f in glob.glob(str(tmp_path / "mon" / "*.jsonl")):
        with open(f) as fh:
            evs += [json.loads(ln) for ln in fh
                    if "guardian_nan_provenance" in ln]
    evs = [e for e in evs if e.get("event") == "guardian_nan_provenance"]
    assert evs and evs[0]["out_var"] == "fc_0.tmp_0"


def test_provenance_replay_is_deterministic(tmp_path):
    qdir, _, _ = _poisoned_guardian_run(tmp_path)
    sidecars = sorted(glob.glob(os.path.join(qdir, "*.json")))
    rec = json.load(open(sidecars[0]))
    prov = rec["provenance"]
    # replay the SAME quarantined step again from the stashed context
    # and the guardian's quarantined feed artifact: identical attribution
    names = rec["feed_names"]
    with np.load(rec["path"]) as z:
        vals = [z["arr_%d" % i] for i in range(len(names))]
    again = health.nan_provenance(rec["step"], feed=(names, vals))
    for k in ("op_index", "op_type", "out_var", "layer", "in_vars"):
        assert again[k] == prov[k], k
    third = health.nan_provenance(rec["step"], feed=(names, vals))
    assert third["op_index"] == again["op_index"]
    assert third["out_var"] == again["out_var"]


def test_abort_message_carries_health_and_provenance(tmp_path):
    _, stats, err = _poisoned_guardian_run(
        tmp_path, policy="skip,abort", max_skips=1)
    assert err is not None, stats
    msg = str(err)
    assert "[health " in msg
    assert "grad_norm" in msg
    assert "first non-finite op: mul -> 'fc_0.tmp_0'" in msg


def test_guard_skip_parity_probe_on_vs_off(tmp_path):
    """The guard watches only the user fetches (n_watch): the probe's
    stats fetch never influences skip decisions, and the recovered
    trajectory is bit-identical with the probe on or off."""
    def run(on, sub):
        fluid.set_flags({"FLAGS_health": on, "FLAGS_guardian": True})
        main, startup, loss = _build_mlp()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            fluid.Executor(fluid.CPUPlace()).run(startup)
            g = guardian.install(guardian.Guardian(
                quarantine_dir=str(tmp_path / sub)))
            fault.poison_batch("x", fault.FaultSchedule(steps=[4]))
            exe = fluid.Executor(fluid.CPUPlace())
            out = [np.asarray(exe.run(main, feed=feed,
                                      fetch_list=[loss])[0],
                              "float32").tobytes()
                   for feed in _batches(9)]
            g.flush()
            stats = g.stats()
            guardian.uninstall()
        fault.clear_injections()
        return out, stats

    off_losses, off_stats = run(False, "q_off")
    on_losses, on_stats = run(True, "q_on")
    assert off_losses == on_losses
    assert off_stats["skipped_steps"] == on_stats["skipped_steps"] == 1


# ---------------------------------------------------------------------------
# parallel executor: same probe, same parity (8 virtual CPU devices)
# ---------------------------------------------------------------------------

def _pe_run(steps=4, bs=16):
    main, startup, loss = _build_mlp()
    rng = np.random.RandomState(0)
    out = []
    with fluid.scope_guard(fluid.Scope()), \
            fluid.program_guard(main, startup):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        pe = fluid.ParallelExecutor(loss_name=loss.name)
        for _ in range(steps):
            feed = {"x": rng.rand(bs, 8).astype("float32"),
                    "label": rng.randint(0, 4, (bs, 1)).astype("int64")}
            (lv,) = pe.run(feed=feed, fetch_list=[loss])
            out.append(np.asarray(lv, "float32").tobytes())
    return out


def test_parallel_executor_probe_publishes_and_keeps_parity():
    off = _pe_run()
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 1})
    on = _pe_run()
    assert off == on
    snap = health.last_snapshot()
    assert snap["executor"] == "parallel_executor"
    assert snap["layers"]
    assert all(np.isfinite(d["grad_norm"])
               for d in snap["layers"].values())


# ---------------------------------------------------------------------------
# check_nan_inf names the first bad variable (+ summary of the rest)
# ---------------------------------------------------------------------------

def test_check_nan_inf_names_first_and_remaining_vars():
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[1]))
        exe = fluid.Executor(fluid.CPUPlace())
        with pytest.raises(RuntimeError) as ei:
            for feed in _batches(4):
                exe.run(main, feed=feed, fetch_list=[loss, pred])
    msg = str(ei.value)
    assert "check_nan_inf: variable " in msg
    assert "contains nan" in msg
    assert "more non-finite" in msg   # both fetches went bad, one named


def test_check_nan_inf_gains_provenance_when_probed():
    fluid.set_flags({"FLAGS_check_nan_inf": True, "FLAGS_health": True})
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[1]))
        exe = fluid.Executor(fluid.CPUPlace())
        with pytest.raises(RuntimeError) as ei:
            for feed in _batches(4):
                exe.run(main, feed=feed, fetch_list=[loss])
    assert "first non-finite op: mul -> 'fc_0.tmp_0'" in str(ei.value)


# ---------------------------------------------------------------------------
# satellites: stall dumps, fleet summary + alert rules, report tool
# ---------------------------------------------------------------------------

def test_stall_probe_includes_last_health_snapshot():
    fluid.set_flags({"FLAGS_health": True, "FLAGS_health_every": 1})
    _run(steps=2)
    probe = monitor._stall_probe()
    assert probe["health"] is not None
    assert probe["health"]["layers"]


def test_health_alert_rules_fire_on_synthetic_view():
    rules = {r.name: r for r in alerts.default_rules()}
    assert "grad_norm_explosion" in rules
    assert "update_ratio_collapse" in rules
    view = {"hosts": {
        "h0": {"health": {"grad_norm_max": 5e6,
                          "update_ratio_min": 1e-9,
                          "nonfinite_total": 3}},
        "h1": {"health": {"grad_norm_max": 2.0,
                          "update_ratio_min": 1e-3,
                          "nonfinite_total": 0}},
        "h2": {}}}
    assert rules["grad_norm_explosion"].resolve(view) == {
        "h0": 5e6, "h1": 2.0}
    eng = alerts.AlertEngine([rules["grad_norm_explosion"],
                              rules["update_ratio_collapse"]])
    evs = eng.evaluate(view, now=100.0)
    fired = {(e["rule"], e["member_id"]) for e in evs
             if e["state"] == "firing"}
    assert ("grad_norm_explosion", "h0") in fired
    assert ("update_ratio_collapse", "h0") in fired
    assert not any(k == "h1" for _, k in fired)


def test_health_report_tool_renders_table(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    _, _, _ = _poisoned_guardian_run(tmp_path)
    monitor.disable()
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import health_report
        from program_report import load_records
    finally:
        sys.path.pop(0)
    recs = load_records(str(tmp_path))
    report = health_report.health_from_records(recs)
    assert report["layers"]
    assert report["provenance"]
    assert report["provenance"][0]["out_var"] == "fc_0.tmp_0"
    text = health_report.render_table(report)
    assert "grad_norm" in text
    assert "nan provenance" in text
    assert "fc_0.tmp_0" in text
