"""Paged KV cache + prefix reuse + int8 KV + speculative decoding
(ISSUE 16).

Four layers, matching the subsystem's own split:

* the :class:`PageAllocator` as a PURE unit — alloc/extend/release
  refcounting, content-hashed prefix sharing, copy-on-write splits,
  exhaustion that allocates NOTHING, and the leak check;
* the scheduler's resource-aware admission gate (fake clock, no
  device): a refused request stays QUEUED, never fails — page
  exhaustion is back-pressure, not a crash;
* the :class:`PagedKVCacheStore` geometry: page-aligned validation and
  the int8-vs-f32 bytes accounting the sessions-at-fixed-HBM claim
  rides on;
* the engine end to end (slow-marked): paged greedy decode reproduces
  the fixed-region engine's tokens AND logits (2e-4), both paths
  compile ONE decode signature, a timeout evicted mid-decode frees its
  pages immediately (the leak regression), pool exhaustion queues and
  completes, and speculative decoding with a weight-synced draft
  reproduces plain greedy token-for-token while accepting draft
  tokens.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import (ContinuousBatchingScheduler,
                                GenerationEngine, build_decoder_lm)
from paddle_tpu.serving.decoder import sync_draft_weights
from paddle_tpu.serving.kv_cache import (OutOfPagesError, PageAllocator,
                                         PagedKVCacheStore)
from paddle_tpu.serving.scheduler import RequestTimeoutError


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# page allocator: pure host-side unit
# ---------------------------------------------------------------------------

def test_pages_needed_rounds_up_to_page_granularity():
    a = PageAllocator(num_pages=16, page_size=8)
    assert a.pages_needed(1, 0) == 1
    assert a.pages_needed(8, 0) == 1
    assert a.pages_needed(8, 1) == 2
    assert a.pages_needed(9, 7) == 2
    assert a.pages_needed(9, 8) == 3


def test_alloc_release_refcount_and_leak_check():
    a = PageAllocator(num_pages=8, page_size=4)
    pages, shared = a.alloc_for_prompt(0, [1, 2, 3, 4, 5], max_new=3)
    assert len(pages) == 2 and shared == 0
    assert a.pages_in_use() == 2 and a.free_pages() == 6
    assert all(a.refcount(p) == 1 for p in pages)
    assert a.check_leaks() == []
    freed = a.release(0)
    assert freed == 2
    assert a.pages_in_use() == 0 and a.free_pages() == 8
    # double release is a no-op, not a double-free
    assert a.release(0) == 0


def test_extend_grows_a_slot_one_page_at_a_time():
    a = PageAllocator(num_pages=4, page_size=4)
    a.alloc_for_prompt(0, [1, 2], max_new=0)
    assert len(a.slot_pages(0)) == 1
    a.extend(0, 2)
    assert len(a.slot_pages(0)) == 3
    assert a.free_pages() == 1


def test_prefix_sharing_aliases_full_prompt_pages():
    a = PageAllocator(num_pages=8, page_size=4)
    system = [7, 7, 3, 9]                       # exactly one full page
    p0, s0 = a.alloc_for_prompt(0, system + [1], max_new=0)
    p1, s1 = a.alloc_for_prompt(1, system + [2], max_new=0)
    assert s0 == 0 and s1 == 1
    assert p1[0] == p0[0]                       # the system page aliased
    assert p1[1] != p0[1]                       # tails stay private
    assert a.refcount(p0[0]) == 2
    assert a.prefix_hits == 1 and a.prefix_misses >= 1
    # releasing one holder keeps the shared page live for the other
    a.release(0)
    assert a.refcount(p1[0]) == 1
    assert a.holds(1) and not a.holds(0)
    assert p1[0] in a.slot_pages(1)
    a.release(1)
    assert a.pages_in_use() == 0 and a.check_leaks() == []


def test_prefix_sharing_is_chain_hashed_not_per_page():
    """A page is shared only when the WHOLE prefix up to it matches —
    identical content at page 2 after divergent page 1 must not alias
    (the chain hash encodes the causal dependence of KV on history)."""
    a = PageAllocator(num_pages=16, page_size=4)
    common = [5, 6, 7, 8]
    pa, _ = a.alloc_for_prompt(0, [1, 1, 1, 1] + common, max_new=0)
    pb, sb = a.alloc_for_prompt(1, [2, 2, 2, 2] + common, max_new=0)
    assert sb == 0
    assert pb[1] != pa[1]


def test_cow_split_shared_and_exclusive():
    a = PageAllocator(num_pages=8, page_size=4)
    system = [7, 7, 3, 9]
    p0, _ = a.alloc_for_prompt(0, system, max_new=4)
    p1, s1 = a.alloc_for_prompt(1, system, max_new=4)
    assert s1 == 1 and p1[0] == p0[0]
    old, new = a.cow_split(1, 0)
    assert old == p0[0] and new != old
    assert a.refcount(old) == 1 and a.refcount(new) == 1
    assert a.slot_pages(1)[0] == new
    # an exclusively held page needs no copy: split returns it as-is
    old2, new2 = a.cow_split(0, 0)
    assert old2 == new2 == p0[0]
    a.release(0)
    a.release(1)
    assert a.check_leaks() == []


def test_exhaustion_raises_and_allocates_nothing():
    a = PageAllocator(num_pages=3, page_size=4)
    a.alloc_for_prompt(0, [1, 2, 3, 4, 5], max_new=0)     # 2 pages
    free_before = a.free_pages()
    with pytest.raises(OutOfPagesError):
        a.alloc_for_prompt(1, [9] * 6, max_new=4)          # needs 3
    # the failed allocation held NOTHING back
    assert a.free_pages() == free_before
    assert a.slot_pages(1) == []
    assert a.check_leaks() == []


def test_released_prefix_entries_leave_the_index():
    a = PageAllocator(num_pages=4, page_size=4)
    system = [7, 7, 3, 9]
    a.alloc_for_prompt(0, system, max_new=0)
    a.release(0)
    # the page went back to the pool, so the index entry died with it:
    # a fresh prompt re-misses instead of aliasing a recycled page
    _, shared = a.alloc_for_prompt(1, system, max_new=0)
    assert shared == 0
    a.release(1)


# ---------------------------------------------------------------------------
# scheduler: the resource-aware admission gate (pure, fake clock)
# ---------------------------------------------------------------------------

def test_admission_gate_keeps_refused_requests_queued():
    clk = FakeClock()
    capacity = {"free": 2}

    def gate(req, picked):
        return len(picked) + 1 <= capacity["free"]

    s = ContinuousBatchingScheduler(4, clock=clk, admission_gate=gate)
    reqs = [s.submit(i) for i in range(4)]
    plan, _ = s.admit()
    assert [r.id for r in plan.requests] == [reqs[0].id, reqs[1].id]
    # the refused tail is QUEUED, in order — not failed, not dropped
    assert s.queue_depth() == 2
    assert reqs[2].status == "queued" and reqs[3].status == "queued"
    # capacity freed -> the same requests admit on the next pass
    for r in plan.requests:
        s.complete(r, None)
    plan2, _ = s.admit()
    assert [r.id for r in plan2.requests] == [reqs[2].id, reqs[3].id]


# ---------------------------------------------------------------------------
# paged store geometry
# ---------------------------------------------------------------------------

def test_paged_store_validates_alignment_and_counts_bytes():
    with pytest.raises(ValueError, match="page"):
        PagedKVCacheStore(2, 4, 2, 30, 8, num_pages=16, page_size=8)
    f32 = PagedKVCacheStore(2, 4, 2, 32, 8, num_pages=16, page_size=8)
    q8 = PagedKVCacheStore(2, 4, 2, 32, 8, num_pages=16, page_size=8,
                           kv_dtype="int8")
    assert q8.quantized and not f32.quantized
    # int8 pages stay well under half the f32 cost even carrying
    # their f32 per-row scales (exactly 4x leaner as head_dim grows)
    assert q8.bytes_per_page() * 2 < f32.bytes_per_page()
    # a short session costs pages at its OWN length, not max_len
    assert f32.bytes_per_session(8) < f32.bytes_per_session(32)


# ---------------------------------------------------------------------------
# engine end to end (slow: compiles the decode programs)
# ---------------------------------------------------------------------------

_DIMS = dict(n_layer=1, n_head=2, d_model=16, d_inner=32)


@pytest.mark.slow
def test_paged_greedy_parity_with_fixed_region():
    """The tentpole contract: paged decode (page-table gather/scatter
    KV) reproduces the fixed-region engine's greedy stream exactly and
    its per-step logits to 2e-4 — and BOTH engines compile exactly one
    decode signature (zero extra warm-path lowerings)."""
    prompts = [[3, 5, 7], [2, 9, 4, 6, 8], [1, 2]]
    outs = {}
    for paged in (False, True):
        spec = build_decoder_lm(23, 32, 2, paged=paged, page_size=8,
                                prefix="pgp" if paged else "pgf",
                                **_DIMS)
        eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                               max_new_tokens=5, record_logits=True,
                               timeout_s=300.0)
        try:
            outs[paged] = [r.result(600) for r in
                           [eng.submit(p) for p in prompts]]
            assert len(eng._exe_decode._cache) == 1
            if paged:
                assert eng._alloc.check_leaks() == []
                assert eng._alloc.pages_in_use() == 0
        finally:
            eng.close()
    for fixed, paged in zip(outs[False], outs[True]):
        assert paged["tokens"] == fixed["tokens"]
        for a, b in zip(paged["logits"], fixed["logits"]):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_int8_kv_decode_completes_with_prefix_hits_and_snapshot():
    """int8 KV pages decode end to end; the shared system prompt
    aliases pages (hit-rate telemetry > 0) and the completion snapshot
    carries the paged counters."""
    system = list(range(2, 10))                 # one full page (ps=8)
    prompts = [system + [11 + i] for i in range(4)]
    spec = build_decoder_lm(23, 32, 4, paged=True, page_size=8,
                            kv_dtype="int8", prefix="pgq", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=4, timeout_s=300.0)
    try:
        outs = [r.result(600) for r in [eng.submit(p) for p in prompts]]
        assert all(len(o["tokens"]) == 4 for o in outs)
        snap = eng.metrics.paged_snapshot()
        assert snap["prefix_hits"] > 0
        assert snap["prefix_hit_rate"] > 0
        counts = eng.metrics.summary()["counts"]
        assert counts["prefix_hits"] == snap["prefix_hits"]
        assert eng._alloc.check_leaks() == []
    finally:
        eng.close()


@pytest.mark.slow
def test_page_exhaustion_queues_and_completes():
    """A pool sized for ONE session at a time: concurrent submits
    serialize through the admission gate (queued-not-crashed) and all
    complete."""
    spec = build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                            num_pages=2, prefix="pgx", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=4, timeout_s=300.0)
    try:
        # each request needs 2 pages (prompt 4 + new 4 -> ceil(8/8)=1,
        # but the bucket pads prefill to 8 -> worst case 2) — the pool
        # holds exactly one at a time
        reqs = [eng.submit([1 + i, 2, 3, 4], max_new_tokens=8)
                for i in range(3)]
        outs = [r.result(600) for r in reqs]
        assert all(len(o["tokens"]) == 8 for o in outs)
        assert eng._alloc.pages_in_use() == 0
        assert eng._alloc.check_leaks() == []
    finally:
        eng.close()


@pytest.mark.slow
def test_timeout_mid_decode_frees_pages():
    """The leak regression: a request evicted mid-decode on its timeout
    budget releases its pages (and any prefix refs) IMMEDIATELY — a
    wedged or slow generation must not pin pool pages it will never
    use."""
    import time as _time

    spec = build_decoder_lm(23, 64, 2, paged=True, page_size=8,
                            prefix="pgt", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=48, timeout_s=300.0)
    try:
        # a long generation with a budget far below its decode time
        # (but comfortably above the admission latency, so the request
        # is evicted RUNNING, pages held — the path under test)
        req = eng.submit([1, 2, 3], timeout_s=0.3)
        with pytest.raises(RequestTimeoutError):
            req.result(60)
        # eviction frees on the loop thread; bounded wait, no sleep-race
        deadline = _time.monotonic() + 30
        while (eng._alloc.pages_in_use()
               and _time.monotonic() < deadline):
            _time.sleep(0.02)
        assert eng._alloc.pages_in_use() == 0
        assert eng._alloc.check_leaks() == []
        # the table row went back to the OOB sentinel: a recycled slot
        # cannot write through stale page translations
        assert (eng._table == spec.cache.num_pages).all()
    finally:
        eng.close()


@pytest.mark.slow
def test_speculative_decode_matches_greedy_and_accepts():
    """Speculative decoding with a weight-synced draft (the perfect-
    draft rig) reproduces plain greedy token-for-token, accepts draft
    tokens (> 0), and still compiles one decode signature for the
    Tq=1 path."""
    prompts = [[3, 5, 7], [2, 9, 4, 6], [8, 1]]
    spec = build_decoder_lm(23, 32, 2, prefix="spf", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=6, timeout_s=300.0)
    try:
        base = [r.result(600)["tokens"] for r in
                [eng.submit(p) for p in prompts]]
    finally:
        eng.close()

    tgt = build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                           spec_k=3, prefix="spp", **_DIMS)
    draft = build_decoder_lm(23, 32, 2, prefix="spd", **_DIMS)
    eng = GenerationEngine(tgt, place=fluid.CPUPlace(),
                           max_new_tokens=6, timeout_s=300.0,
                           draft_spec=draft, start=False)
    try:
        assert sync_draft_weights(eng._scope, tgt, draft) > 0
        eng.start()
        outs = [r.result(600)["tokens"] for r in
                [eng.submit(p) for p in prompts]]
        snap = eng.metrics.paged_snapshot()
        assert outs == base
        assert snap["spec_accepted"] > 0
        assert snap["spec_rounds"] > 0
        assert eng._alloc.check_leaks() == []
    finally:
        eng.close()


@pytest.mark.slow
def test_draft_spec_validation():
    """A draft without a verify program, a paged draft, and a
    mismatched draft all refuse at construction — not mid-decode."""
    tgt = build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                           spec_k=3, prefix="dvt", **_DIMS)
    no_verify = build_decoder_lm(23, 32, 2, prefix="dvn", **_DIMS)
    with pytest.raises(ValueError, match="verify"):
        GenerationEngine(no_verify, place=fluid.CPUPlace(),
                         draft_spec=no_verify, start=False)
    paged_draft = build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                                   prefix="dvp", **_DIMS)
    with pytest.raises(ValueError, match="fixed-region"):
        GenerationEngine(tgt, place=fluid.CPUPlace(),
                         draft_spec=paged_draft, start=False)
    short = build_decoder_lm(23, 16, 2, prefix="dvs", **_DIMS)
    with pytest.raises(ValueError, match="slots/vocab"):
        GenerationEngine(tgt, place=fluid.CPUPlace(),
                         draft_spec=short, start=False)


@pytest.mark.slow
def test_tune_kv_quantization_rides_the_accuracy_gate():
    """int8 KV admits only under the eval-delta budget
    (``FLAGS_quantize_accuracy_budget`` by default); an impossible
    budget keeps f32 KV and records the rejection as evidence — the
    r15 quantization-gate discipline applied to the KV pool."""
    from paddle_tpu import autotune

    def build(kv_dtype):
        return build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                                kv_dtype=kv_dtype, prefix="kvg",
                                **_DIMS)

    prompts = [[3, 5, 7], [2, 9, 4, 6]]
    cfg = autotune.TunedConfig()
    d = autotune.tune_kv_quantization(build, prompts,
                                      max_new_tokens=4, config=cfg)
    assert d["knob"] == "kv_quantization"
    assert d["chosen"] == "kv_int8"              # tiny delta admits
    cand = d["candidates"][0]
    assert cand["accuracy_delta"] < d["accuracy_budget"]
    assert cand["greedy_tokens_match"] is True
    assert cfg.get("kv_quantization") is not None

    # the same candidate under an impossible budget: f32 KV kept,
    # rejection IS the evidence
    d2 = autotune.tune_kv_quantization(build, prompts,
                                       max_new_tokens=4, budget=1e-12)
    assert d2["chosen"] is None
    assert d2["candidates"][0]["status"] == "rejected_accuracy"
