"""tools/kube_gen_job.py — k8s job generator for multi-host training
(reference benchmark/fluid/kube_gen_job.py), emitting the
PADDLE_COORDINATOR/TRAINERS/TRAINER_ID env contract
parallel.distributed.init_distributed reads."""

import importlib.util
import os
import subprocess
import sys

import yaml


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "kube_gen_job.py")
    spec = importlib.util.spec_from_file_location("kube_gen_job", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, path


def test_manifests_are_valid_yaml_with_env_contract():
    mod, path = _load()
    out = subprocess.run(
        [sys.executable, path, "--name", "mnist", "--image", "repo/img",
         "--entry", "python train.py --flag=1", "--hosts", "4",
         "--tpu_count", "4"],
        stdout=subprocess.PIPE, text=True, check=True).stdout
    svc, job = [yaml.safe_load(d) for d in out.split("---")]
    # headless service: the k8s API's ClusterIP is a string field whose
    # headless value is the literal string "None"
    assert svc["kind"] == "Service" and svc["spec"]["clusterIP"] == "None"
    assert job["kind"] == "Job"
    spec = job["spec"]
    assert spec["completions"] == 4 and spec["completionMode"] == "Indexed"
    pod = spec["template"]["spec"]
    assert pod["subdomain"] == "mnist"
    c = pod["containers"][0]
    env = {e["name"]: e for e in c["env"]}
    # the runtime's env contract (parallel/distributed.py)
    assert env["PADDLE_COORDINATOR"]["value"] == "mnist-0.mnist:7164"
    assert env["PADDLE_TRAINERS"]["value"] == "4"
    assert "job-completion-index" in str(env["PADDLE_TRAINER_ID"])
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    assert c["command"][-1] == "python train.py --flag=1"


def test_gen_job_direct_api():
    mod, _ = _load()
    job = mod.gen_job("t", "img", "cmd", hosts=2, tpu_resource=None)
    assert "limits" not in \
        job["spec"]["template"]["spec"]["containers"][0]["resources"]
