"""Predictor API tests (paddle_inference_api.h parity): save -> load via
NativeConfig/AnalysisConfig, Run with PaddleTensor and dict inputs,
clone-per-thread, sequence inputs with lod lengths."""

import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (AnalysisConfig, NativeConfig,
                                  PaddleTensor, create_paddle_predictor)


@pytest.fixture
def saved_model(tmp_path, fresh_programs):
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[6])
    h = fluid.layers.fc(x, size=8, act="relu")
    h = fluid.layers.dropout(h, dropout_prob=0.5)
    pred = fluid.layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(str(tmp_path / "model"), ["x"],
                                      [pred], exe)
    return str(tmp_path / "model")


def test_native_predictor_runs(saved_model):
    pred = create_paddle_predictor(NativeConfig(model_dir=saved_model))
    assert pred.feed_names == ["x"]
    xv = np.random.RandomState(0).rand(4, 6).astype("float32")
    (out,) = pred.run([PaddleTensor(name="x", data=xv)])
    assert out.shape == (4, 3)
    np.testing.assert_allclose(np.asarray(out.data).sum(1),
                               np.ones(4), rtol=1e-5)
    # dict input form
    (out2,) = pred.run({"x": xv})
    np.testing.assert_array_equal(out.data, out2.data)


def test_analysis_predictor_deterministic_dropout(saved_model):
    """Saved inference models are inference-mode (for_test at save
    time): dropout is disabled, so repeated runs agree exactly.
    AnalysisConfig is API parity — same behavior as NativeConfig."""
    pred = create_paddle_predictor(AnalysisConfig(model_dir=saved_model))
    xv = np.random.RandomState(1).rand(2, 6).astype("float32")
    a = pred.run({"x": xv})[0].data
    b = pred.run({"x": xv})[0].data
    np.testing.assert_array_equal(a, b)


def test_predictor_clone_shares_weights_and_is_threadsafe(saved_model):
    base = create_paddle_predictor(AnalysisConfig(model_dir=saved_model))
    xv = np.random.RandomState(2).rand(3, 6).astype("float32")
    want = base.run({"x": xv})[0].data
    results = {}

    def worker(i):
        p = base.clone()
        results[i] = p.run({"x": xv})[0].data

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(4):
        np.testing.assert_array_equal(results[i], want)


def test_predictor_input_validation(saved_model):
    pred = create_paddle_predictor(NativeConfig(model_dir=saved_model))
    with pytest.raises(ValueError, match="not a feed target"):
        pred.run({"bogus": np.zeros((1, 6), "float32")})
    with pytest.raises(ValueError, match="missing inputs"):
        pred.run([])


def test_predictor_sequence_input_with_lod(tmp_path, fresh_programs):
    fluid.default_startup_program().random_seed = 3
    ids = fluid.layers.data("ids", shape=[1], dtype="int64", lod_level=1)
    emb = fluid.layers.embedding(ids, size=[20, 4])
    pooled = fluid.layers.sequence_pool(emb, "sum")
    out = fluid.layers.fc(pooled, size=2, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(
            str(tmp_path / "m2"), ["ids", "ids@LEN"], [out], exe)
    pred = create_paddle_predictor(
        NativeConfig(model_dir=str(tmp_path / "m2")))
    idv = np.random.RandomState(4).randint(0, 20, (2, 5, 1)).astype(
        "int64")
    (o,) = pred.run([PaddleTensor(name="ids", data=idv, lod=[5, 3])])
    assert o.shape == (2, 2)


def test_inference_transpiler_folds_bn_into_conv():
    """BN folding: the optimized program has NO batch_norm ops and
    produces the same outputs as the un-optimized inference program."""
    import numpy as np

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c1 = fluid.layers.conv2d(img, 8, 3, padding=1, bias_attr=False)
        b1 = fluid.layers.batch_norm(c1, act="relu")
        c2 = fluid.layers.conv2d(b1, 4, 1, bias_attr=False)
        b2 = fluid.layers.batch_norm(c2, act=None)
        out = fluid.layers.reduce_mean(b2, dim=[2, 3])

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # make running stats non-trivial so the fold is a real test
        for op in main.global_block().ops:
            if op.type == "batch_norm":
                rng = np.random.RandomState(1)
                scope.set_var(op.inputs["Mean"][0],
                              rng.rand(*np.asarray(
                                  scope.var(op.inputs["Mean"][0])).shape
                                       ).astype("float32"))
                scope.set_var(op.inputs["Variance"][0],
                              (rng.rand(*np.asarray(scope.var(
                                  op.inputs["Variance"][0])).shape)
                               + 0.5).astype("float32"))
        infer = main.clone(for_test=True)
        rng = np.random.RandomState(0)
        xv = rng.rand(2, 3, 8, 8).astype("float32")
        (ref,) = exe.run(infer, feed={"img": xv}, fetch_list=[out.name])

        t = fluid.InferenceTranspiler()
        opt = t.transpile(infer, fluid.CPUPlace(), scope)
        types = [op.type for op in opt.global_block().ops]
        assert "batch_norm" not in types, types
        # the input program is untouched (use the return value)
        assert any(op.type == "batch_norm"
                   for op in infer.global_block().ops)
        (got,) = exe.run(opt, feed={"img": xv}, fetch_list=[out.name])
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        # a TRAIN program transpiles too (is_test flip happens inside)
        opt2 = t.transpile(main, fluid.CPUPlace(), scope)
        assert not any(op.type == "batch_norm"
                       for op in opt2.global_block().ops)


def test_clone_concurrency_separate_caches_shared_weights(saved_model):
    """Clone() hardening: each clone owns its executor cache (no lock
    contention on compiled entries), all clones share the ONE immutable
    weight scope, and concurrent Runs are bit-identical to the base."""
    base = create_paddle_predictor(AnalysisConfig(model_dir=saved_model))
    xv = np.random.RandomState(5).rand(4, 6).astype("float32")
    want = base.run({"x": xv})[0].data
    clones = [base.clone() for _ in range(2)]
    for c in clones:
        # separate executors and compiled-program caches...
        assert c._exe is not base._exe
        assert c._exe._cache is not base._exe._cache
        # ...over the same shared weight scope and program
        assert c._scope is base._scope
        assert c._program is base._program
    results = {}

    def worker(i, p):
        results[i] = p.run({"x": xv})[0].data

    threads = [threading.Thread(target=worker, args=(i, c))
               for i, c in enumerate(clones)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(len(clones)):
        np.testing.assert_array_equal(results[i], want)
        # each clone compiled through its own cache
        assert len(clones[i]._exe._cache) == 1


def test_second_run_same_signature_zero_new_lowerings(saved_model):
    """Warm-path regression gate: a second Run with the same input
    signature is a pure dispatch — zero new jit/pmap lowerings."""
    from jax._src import test_util as jtu

    pred = create_paddle_predictor(NativeConfig(model_dir=saved_model))
    xv = np.random.RandomState(6).rand(3, 6).astype("float32")
    pred.run({"x": xv})                      # cold: trace + compile
    with jtu.count_jit_and_pmap_lowerings() as n:
        out2 = pred.run({"x": xv})
        out3 = pred.run({"x": xv})
    assert n[0] == 0, n[0]
    np.testing.assert_array_equal(out2[0].data, out3[0].data)


def test_predictor_serving_delegation_matches_direct(saved_model):
    """enable_serving: Run splits the batch through the shared
    continuous-batching engine and reassembles — outputs identical to
    the direct dispatch, clones share ONE engine."""
    direct = create_paddle_predictor(AnalysisConfig(model_dir=saved_model))
    xv = np.random.RandomState(7).rand(5, 6).astype("float32")
    want = direct.run({"x": xv})[0].data

    cfg = AnalysisConfig(model_dir=saved_model).enable_serving(
        slots=4, timeout_s=60.0)
    pred = create_paddle_predictor(cfg)
    try:
        got = pred.run({"x": xv})[0].data
        np.testing.assert_array_equal(got, want)
        clone = pred.clone()
        got2 = clone.run({"x": xv})[0].data
        np.testing.assert_array_equal(got2, want)
        assert clone.serving_engine() is pred.serving_engine()
        summ = pred.serving_engine().metrics.summary()
        # each 5-row Run splits into ceil(5/4) slot-capacity requests
        assert summ["counts"]["completed"] == 4
    finally:
        pred.serving_engine().close()
