"""Subprocess entry for distributed tests (the reference's
``test_dist_base.py`` trainer-process body).  Each process joins the
jax.distributed world, builds the same model, feeds its LOCAL half of
every global batch through the ParallelExecutor, and prints the losses.

Run: python dist_runner.py <process_id> <num_processes> <coordinator>
         [ckpt_dir]

With ``ckpt_dir`` the run is preemption-aware: it resumes from the
latest sharded checkpoint, and on SIGTERM all processes agree on a
flush step via the preemption vote (distributed.any_process_flagged),
write a collective checkpoint, and exit 0 — the fault-injection
protocol of the checkpoint-on-signal test.
"""

import json
import os
import signal
import sys


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    coordinator = sys.argv[3]
    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu as fluid
    from paddle_tpu.parallel import distributed

    distributed.init_distributed(
        coordinator_address=coordinator, num_processes=nproc,
        process_id=pid)
    assert jax.process_count() == nproc

    import numpy as np
    import dist_model

    # same model + data as the single-process reference run in the test
    # (DIST_MODEL selects the workload from dist_model.MODELS)
    model_name = os.environ.get("DIST_MODEL", "mlp")
    build_fn, batches_fn = dist_model.MODELS[model_name]
    loss = build_fn(fluid)

    # the transpiler-produced sharding plan drives the PE
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=pid, trainers=nproc)
    mesh = fluid.make_mesh()            # all 8 global devices
    bs = t.build_strategy(mesh)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    mgr = None
    start = 0
    if ckpt_dir:
        from paddle_tpu.parallel.checkpoint import ShardedCheckpointManager

        mgr = ShardedCheckpointManager(ckpt_dir, async_save=False)
        restored = mgr.restore()
        if restored is not None:
            start = restored
            print("RESUMED", start, flush=True)

    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                mesh=mesh)

    flagged = []

    def on_term(signum, frame):
        flagged.append(signum)

    if ckpt_dir:
        signal.signal(signal.SIGTERM, on_term)

    losses = []
    data = batches_fn()
    for i in range(start, len(data)):
        if mgr is not None and distributed.any_process_flagged(flagged):
            # collective flush: every process saves its shards for the
            # agreed step, then exits cleanly (preemption drain)
            mgr.save_now(i)
            print("CKPT_SAVED", i, flush=True)
            print("DIST_LOSSES", json.dumps(losses), flush=True)
            return
        lo = pid * (dist_model.BATCH // nproc)
        hi = lo + dist_model.BATCH // nproc
        (lv,) = pe.run(feed={k: v[lo:hi] for k, v in data[i].items()},
                       fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
        print("STEP", i, flush=True)
    print("DIST_LOSSES", json.dumps(losses), flush=True)


if __name__ == "__main__":
    main()
