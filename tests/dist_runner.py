"""Subprocess entry for distributed tests (the reference's
``test_dist_base.py`` trainer-process body).  Each process joins the
jax.distributed world, builds the same model, feeds its LOCAL half of
every global batch through the ParallelExecutor, and prints the losses.

Run: python dist_runner.py <process_id> <num_processes> <coordinator>
"""

import json
import os
import sys


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    coordinator = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu as fluid
    from paddle_tpu.parallel import distributed

    distributed.init_distributed(
        coordinator_address=coordinator, num_processes=nproc,
        process_id=pid)
    assert jax.process_count() == nproc

    import numpy as np
    import dist_model

    # same model + data as the single-process reference run in the test
    loss = dist_model.build_model(fluid)

    # the transpiler-produced sharding plan drives the PE
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=pid, trainers=nproc)
    mesh = fluid.make_mesh()            # all 8 global devices
    bs = t.build_strategy(mesh)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                mesh=mesh)

    losses = []
    for x, y in dist_model.batches():
        # local slice: this trainer's share of the global batch
        lo = pid * (dist_model.BATCH // nproc)
        hi = lo + dist_model.BATCH // nproc
        (lv,) = pe.run(feed={"img": x[lo:hi], "label": y[lo:hi]},
                       fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    print("DIST_LOSSES", json.dumps(losses))


if __name__ == "__main__":
    main()
