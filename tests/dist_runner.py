"""Subprocess entry for distributed tests (the reference's
``test_dist_base.py`` trainer-process body).  Each process joins the
jax.distributed world, builds the same model, feeds its LOCAL half of
every global batch through the ParallelExecutor, and prints the losses.

Run: python dist_runner.py <process_id> <num_processes> <coordinator>
"""

import json
import os
import sys


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    coordinator = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu as fluid
    from paddle_tpu.parallel import distributed

    distributed.init_distributed(
        coordinator_address=coordinator, num_processes=nproc,
        process_id=pid)
    assert jax.process_count() == nproc

    import numpy as np

    # same model + data as the single-process reference run in the test
    fluid.default_main_program().random_seed = 21
    fluid.default_startup_program().random_seed = 21
    img = fluid.layers.data("img", shape=[32])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=64, act="relu")
    pred = fluid.layers.fc(h, size=8, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    # the transpiler-produced sharding plan drives the PE
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=pid, trainers=nproc)
    mesh = fluid.make_mesh()            # all 8 global devices
    bs = t.build_strategy(mesh)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                mesh=mesh)

    rng = np.random.RandomState(0)
    proj = rng.rand(32, 8).astype("float32")
    losses = []
    for _ in range(6):
        x = rng.rand(16, 32).astype("float32")
        y = (x @ proj).argmax(1).astype("int64").reshape(-1, 1)
        # local slice: this trainer's half of the global batch
        lo = pid * (16 // nproc)
        hi = lo + 16 // nproc
        (lv,) = pe.run(feed={"img": x[lo:hi], "label": y[lo:hi]},
                       fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    print("DIST_LOSSES", json.dumps(losses))


if __name__ == "__main__":
    main()
