"""Whole-trunk NHWC layout pass (transpiler.layout.convert_to_nhwc).

The reference transforms layouts at kernel boundaries
(``paddle/fluid/framework/data_layout_transform.cc:1``); here a program
pass flips the conv trunk to NHWC so the [M, C]-tiled fused conv+BN
Pallas kernels see their native layout with no boundary transposes.

Covers: structural rewrite (conv/pool/bn attrs, single entry transpose,
boundary transpose before the fc head), multi-step training parity on a
residual CNN (NCHW vs NHWC vs NHWC+fuse_conv_bn), the NHWC Pallas
kernel pair's numerics vs jax.vjp of the reference math (interpret
mode), and pool2d NHWC semantics (max/avg/exclusive padding).
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _build(mode, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[8, 6, 6])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        c1 = fluid.layers.conv2d(img, num_filters=16, filter_size=1,
                                 bias_attr=False)
        b1 = fluid.layers.batch_norm(c1, act="relu")
        c2 = fluid.layers.conv2d(b1, num_filters=8, filter_size=1,
                                 bias_attr=False)
        b2 = fluid.layers.batch_norm(c2, act="relu")
        c3 = fluid.layers.conv2d(b2, num_filters=8, filter_size=3,
                                 padding=1, bias_attr=False)
        b3 = fluid.layers.batch_norm(c3, act=None)
        res = fluid.layers.elementwise_add(x=b3, y=img, act="relu")
        pool = fluid.layers.pool2d(res, pool_size=6, pool_type="avg",
                                   global_pooling=True)
        pred = fluid.layers.fc(pool, size=5, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        if "nhwc" in mode:
            n = fluid.transpiler.convert_to_nhwc(main)
            assert n == 3, "expected 3 convs converted, got %d" % n
        if "fuse" in mode:
            n = fluid.transpiler.fuse_conv_bn(main)
            assert n == 3, "expected 3 BNs decomposed, got %d" % n
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def _run(mode, steps=4):
    main, startup, loss = _build(mode)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(steps):
            f = {"img": rng.rand(4, 8, 6, 6).astype("float32"),
                 "label": rng.randint(0, 5, (4, 1)).astype("int64")}
            l, = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    return losses


def test_structural_rewrite():
    main, _, _ = _build("nhwc")
    block = main.global_block()
    convs = [op for op in block.ops if op.type == "conv2d"]
    assert convs and all(
        op.attrs.get("data_format") == "NHWC" for op in convs)
    bns = [op for op in block.ops if op.type == "batch_norm"]
    assert bns and all(op.attrs.get("data_layout") == "NHWC" for op in bns)
    pools = [op for op in block.ops if op.type == "pool2d"]
    assert pools and all(
        op.attrs.get("data_format") == "NHWC" for op in pools)
    # exactly one entry transpose (the fed image) and one exit boundary
    # (the global-pool output feeding fc); trunk interior has none
    transposes = [op for op in block.ops if op.type == "transpose"]
    entry = [op for op in transposes if op.attrs["axis"] == [0, 2, 3, 1]]
    exits = [op for op in transposes if op.attrs["axis"] == [0, 3, 1, 2]]
    assert len(entry) == 1, [op.inputs for op in entry]
    assert len(exits) == 1, [op.inputs for op in exits]
    # trunk var metadata flipped: conv outputs are [B, H, W, C]
    out = block._find_var_recursive(convs[0].outputs["Output"][0])
    assert out.shape[-1] == 16, out.shape
    # weights stay OIHW (checkpoint parity)
    w = block._find_var_recursive(convs[0].inputs["Filter"][0])
    assert tuple(w.shape) == (16, 8, 1, 1), w.shape


def test_training_parity_nhwc():
    base = _run("plain")
    nhwc = _run("nhwc")
    np.testing.assert_allclose(nhwc, base, rtol=2e-3, atol=2e-4)


def test_training_parity_nhwc_fused():
    base = _run("plain")
    fused = _run("nhwc_fuse")
    np.testing.assert_allclose(fused, base, rtol=2e-3, atol=2e-4)


def test_nhwc_fusion_emits_nhwc_fused_ops():
    main, _, _ = _build("nhwc_fuse")
    types = [op.type for op in main.global_block().ops]
    assert types.count("bn_act_conv2d") == 2
    for op in main.global_block().ops:
        if op.type == "bn_act_conv2d":
            assert op.attrs.get("data_format") == "NHWC"
        if op.type in ("batch_stats", "bn_apply", "stats_finalize"):
            assert op.attrs.get("data_layout") == "NHWC"


def test_imagenet_bottleneck_parity():
    """Strided bottleneck + projection shortcut (the resnet_imagenet
    shapes the bench runs) track NCHW over several steps."""
    def build(nhwc, seed=11):
        from paddle_tpu.models.resnet import resnet_imagenet
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = seed
        startup.random_seed = seed
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[3, 32, 32])
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            pred = resnet_imagenet(img, class_dim=10, depth=18)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            if nhwc:
                assert fluid.transpiler.convert_to_nhwc(main) > 0
            fluid.optimizer.Momentum(learning_rate=0.01,
                                     momentum=0.9).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(3)
    feeds = [{"img": rng.rand(4, 3, 32, 32).astype("float32"),
              "label": rng.randint(0, 10, (4, 1)).astype("int64")}
             for _ in range(3)]
    out = []
    for nhwc in (False, True):
        main, startup, loss = build(nhwc)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ls = []
            for f in feeds:
                l, = exe.run(main, feed=f, fetch_list=[loss])
                ls.append(float(np.asarray(l).ravel()[0]))
            out.append(ls)
    np.testing.assert_allclose(out[1], out[0], rtol=2e-3, atol=2e-4)


def test_pool2d_nhwc_semantics():
    """pool2d NHWC == transposed pool2d NCHW for max/avg, strided with
    asymmetric (ceil-extended) padding and exclusive avg counting."""
    rng = np.random.RandomState(0)
    x = rng.rand(2, 5, 7, 7).astype("float32")
    for ptype in ("max", "avg"):
        for ceil in (False, True):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                a = fluid.layers.data("a", shape=[5, 7, 7])
                o1 = fluid.layers.pool2d(a, pool_size=3, pool_stride=2,
                                         pool_padding=1, pool_type=ptype,
                                         ceil_mode=ceil)
                b = fluid.layers.transpose(a, perm=[0, 2, 3, 1])
                helper = fluid.layer_helper.LayerHelper("pool2d")
                out = helper.create_variable_for_type_inference(b.dtype)
                helper.append_op(
                    type="pool2d", inputs={"X": [b]},
                    outputs={"Out": [out]},
                    attrs={"ksize": [3, 3], "strides": [2, 2],
                           "paddings": [1, 1], "pooling_type": ptype,
                           "ceil_mode": ceil, "data_format": "NHWC"})
                o2 = fluid.layers.transpose(out, perm=[0, 3, 1, 2])
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                r1, r2 = exe.run(main, feed={"a": x},
                                 fetch_list=[o1, o2])
            np.testing.assert_allclose(np.asarray(r2), np.asarray(r1),
                                       rtol=1e-6, atol=1e-6)


def test_nhwc_pallas_kernels_vs_reference():
    """bn_act_matmul_nhwc fwd + single-kernel bwd == jax.vjp of the
    reference math (interpret mode; partial last block exercised)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import conv_bn

    rng = np.random.RandomState(0)
    m, c, o = 1300, 64, 128
    x = jnp.asarray(rng.randn(m, c).astype("float32"))
    w = jnp.asarray(rng.randn(c, o).astype("float32") * 0.1)
    mean = jnp.asarray(rng.randn(c).astype("float32"))
    var = jnp.asarray(np.abs(rng.randn(c)).astype("float32") + 0.5)
    gamma = jnp.asarray(rng.randn(c).astype("float32"))
    beta = jnp.asarray(rng.randn(c).astype("float32"))
    shift = jnp.asarray(rng.randn(o).astype("float32"))
    eps = 1e-5

    def ref_fn(x, w, mean, var, gamma, beta):
        rstd = jax.lax.rsqrt(var + eps)
        xn = jnp.maximum((x - mean) * (rstd * gamma) + beta, 0.0)
        z = xn @ w
        zc = z - shift
        return z, jnp.sum(zc, axis=0), jnp.sum(zc * zc, axis=0)

    def fused(x, w, mean, var, gamma, beta):
        return conv_bn.bn_act_matmul_nhwc(
            x, w, mean, var, gamma, beta, shift, eps, "relu", True, True,
            True)

    assert conv_bn.supported(1, c, o, m, jnp.float32)
    zf, vjp_f = jax.vjp(fused, x, w, mean, var, gamma, beta)
    zr, vjp_r = jax.vjp(ref_fn, x, w, mean, var, gamma, beta)
    for a, b in zip(zf, zr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-4)
    cts = (jnp.asarray(rng.randn(m, o).astype("float32")),
           jnp.asarray(rng.randn(o).astype("float32")),
           jnp.asarray(rng.randn(o).astype("float32")))
    for name, a, b in zip(("dx", "dw", "dmean", "dvar", "dgamma",
                           "dbeta"), vjp_f(cts), vjp_r(cts)):
        denom = np.abs(np.asarray(b)).max() + 1e-9
        rel = np.abs(np.asarray(a - b)).max() / denom
        assert rel < 1e-4, (name, rel)
