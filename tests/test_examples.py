"""The examples/ scripts run end-to-end as real user programs (one per
API dialect) — subprocess-isolated like the reference's book tests."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("script", ["fluid_mnist.py", "v2_mnist.py",
                                    "v1_config_mnist.py"])
def test_example_runs(script):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=ROOT + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script)],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout
