"""In-program evaluators (reference evaluator.py): cross-batch counter
accumulation, reset, and final-metric computation."""

import numpy as np

import paddle_tpu as fluid


def test_edit_distance_evaluator_accumulates_and_resets():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = fluid.layers.data("hyp", shape=[1], dtype="int64",
                                lod_level=1)
        ref = fluid.layers.data("ref", shape=[1], dtype="int64",
                                lod_level=1)
        ev = fluid.evaluator.EditDistance(hyp, ref)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ev.reset(exe)

            def feed(h, r):
                return {
                    "hyp": np.array(h, "int64").reshape(1, -1, 1),
                    "hyp@LEN": np.array([len(h)], "int32"),
                    "ref": np.array(r, "int64").reshape(1, -1, 1),
                    "ref@LEN": np.array([len(r)], "int32"),
                }

            # batch 1: distance 1 (one substitution); batch 2: exact
            exe.run(main, feed=feed([1, 2, 3], [1, 9, 3]),
                    fetch_list=[ev.metrics[0]])
            exe.run(main, feed=feed([4, 5], [4, 5]),
                    fetch_list=[ev.metrics[0]])
            dist, err = ev.eval(exe)
            # normalized distances (reference default): (1/3 + 0) / 2
            np.testing.assert_allclose(dist, [1 / 6], rtol=1e-5)
            np.testing.assert_allclose(err, [0.5])    # 1 of 2 wrong

            ev.reset(exe)
            dist, err = ev.eval(exe)
            np.testing.assert_allclose(dist, [0.0])


def test_chunk_evaluator_accumulates():
    # IOB with 1 chunk type: B=0, I=1, O=2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = fluid.layers.data("inf", shape=[1], dtype="int64",
                                lod_level=1)
        lab = fluid.layers.data("lab", shape=[1], dtype="int64",
                                lod_level=1)
        ev = fluid.evaluator.ChunkEvaluator(inf, lab, chunk_scheme="IOB",
                                            num_chunk_types=1)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ev.reset(exe)

            def feed(i, l):
                return {"inf": np.array(i, "int64").reshape(1, -1, 1),
                        "inf@LEN": np.array([len(i)], "int32"),
                        "lab": np.array(l, "int64").reshape(1, -1, 1),
                        "lab@LEN": np.array([len(l)], "int32")}

            # one perfectly-predicted chunk
            exe.run(main, feed=feed([0, 1, 2], [0, 1, 2]),
                    fetch_list=[ev.metrics[0]])
            # one missed chunk (predict O everywhere)
            exe.run(main, feed=feed([2, 2, 2], [0, 1, 2]),
                    fetch_list=[ev.metrics[0]])
            p, r, f1 = ev.eval(exe)
            np.testing.assert_allclose(p, [1.0])      # 1 inferred, 1 right
            np.testing.assert_allclose(r, [0.5])      # 2 labeled, 1 found
            np.testing.assert_allclose(f1, [2 / 3], rtol=1e-6)
