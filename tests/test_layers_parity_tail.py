"""Parity-tail layer fns (reference nn.py/tensor.py/io.py names added
late): elementwise_max/min/pow, flatten, sum, multiplex, rank_loss,
sigmoid_cross_entropy_with_logits, gaussian_random, mean_iou, dice_loss,
image_resize_short, lstm_unit, gru_unit, autoincreased_step_counter,
create_parameter, has_inf/has_nan, append_LARS, the
layer_function_generator utilities, and the host-side reader-handle
family (py_reader/open_files/read_file/shuffle/batch/double_buffer/
random_data_generator/load/Preprocessor)."""

import os
import pickle

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import program_guard

L = fluid.layers


def _run(fetches, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetches)]


def test_elementwise_max_min_pow_and_flatten():
    x = L.data("x", shape=[2, 3])
    y = L.data("y", shape=[2, 3])
    outs = _run([L.elementwise_max(x, y), L.elementwise_min(x, y),
                 L.elementwise_pow(x, y), L.flatten(x, axis=2)],
                {"x": np.full((4, 2, 3), 2.0, "float32"),
                 "y": np.full((4, 2, 3), 3.0, "float32")})
    assert (outs[0] == 3.0).all() and (outs[1] == 2.0).all()
    np.testing.assert_allclose(outs[2], np.full((4, 2, 3), 8.0), rtol=1e-6)
    assert outs[3].shape == (8, 3)      # flatten axis=2 on [4,2,3]


def test_sum_multiplex_rank_loss_sigmoid_ce():
    x = L.data("x", shape=[3])
    p = L.data("p", shape=[3])
    q = L.data("q", shape=[3])
    ids = L.data("ids", shape=[1], dtype="int32")
    lbl = L.data("lbl", shape=[1])
    left = L.data("lf", shape=[1])
    right = L.data("rt", shape=[1])
    z = L.data("z", shape=[3])
    t = L.data("t", shape=[3])

    a = np.arange(12, dtype="float32").reshape(4, 3)
    zv = np.tile(np.array([[1.0, -1.0, 0.0]], "float32"), (4, 1))
    tv = np.tile(np.array([[1.0, 0.0, 1.0]], "float32"), (4, 1))
    s, m, rl, ce = _run(
        [L.sum([x, x, x]), L.multiplex([p, q], ids),
         L.rank_loss(lbl, left, right),
         L.sigmoid_cross_entropy_with_logits(z, t)],
        {"x": a,
         "p": np.zeros((4, 3), "float32"),
         "q": np.ones((4, 3), "float32"),
         "ids": np.array([[0], [1], [0], [1]], "int32"),
         "lbl": np.ones((4, 1), "float32"),
         "lf": np.full((4, 1), 2.0, "float32"),
         "rt": np.zeros((4, 1), "float32"),
         "z": zv, "t": tv})
    np.testing.assert_allclose(s, 3 * a, rtol=1e-6)
    np.testing.assert_allclose(m[:, 0], [0, 1, 0, 1])
    # C(o) = o*(1-label) + log(1+exp(-o)), o = left-right = 2, label=1
    np.testing.assert_allclose(rl, np.log1p(np.exp(-2.0)) *
                               np.ones((4, 1)), rtol=1e-5)
    want = np.maximum(zv, 0) - zv * tv + np.log1p(np.exp(-np.abs(zv)))
    np.testing.assert_allclose(ce, want, rtol=1e-5)


def test_gaussian_random_moments_and_mean_iou():
    g = L.gaussian_random([2000, 8], mean=1.0, std=2.0)
    gv, = _run([g], {})
    assert abs(gv.mean() - 1.0) < 0.1 and abs(gv.std() - 2.0) < 0.1

    pred = L.data("pr", shape=[6], dtype="int64", append_batch_size=False)
    lab = L.data("lb", shape=[6], dtype="int64", append_batch_size=False)
    iou, _, _ = L.mean_iou(pred, lab, num_classes=2)
    got, = _run([iou], {"pr": np.array([0, 0, 1, 1, 0, 1], "int64"),
                        "lb": np.array([0, 1, 1, 1, 0, 0], "int64")})
    # class0: inter 2, union 4 -> .5 ; class1: inter 2, union 4 -> .5
    np.testing.assert_allclose(got, [0.5], rtol=1e-5)


def test_dice_loss_and_image_resize_short():
    probs = L.data("p", shape=[2])
    lbl = L.data("l", shape=[1], dtype="int64")
    d, = _run([L.dice_loss(probs, lbl)],
              {"p": np.array([[1.0, 0.0], [0.0, 1.0]], "float32"),
               "l": np.array([[0], [1]], "int64")})
    assert d[0] < 1e-4   # perfect prediction -> ~0 loss

    img = L.data("img", shape=[3, 12, 8])
    out = L.image_resize_short(img, 4)
    assert tuple(out.shape[2:]) == (6, 4)   # short side 8 -> 4, keep AR


def test_lstm_gru_units_step_math():
    x = L.data("x", shape=[5])
    h = L.data("h", shape=[6])
    c = L.data("c", shape=[6])
    h1, c1 = L.lstm_unit(x, h, c)
    gin = L.data("gi", shape=[9])      # 3 * hidden(3)
    gh = L.data("gh", shape=[3])
    nh, rhp, gate = L.gru_unit(gin, gh, 9)
    hv, cv, nv = _run(
        [h1, c1, nh],
        {"x": np.random.rand(3, 5).astype("float32"),
         "h": np.zeros((3, 6), "float32"),
         "c": np.ones((3, 6), "float32"),
         "gi": np.random.rand(3, 9).astype("float32"),
         "gh": np.zeros((3, 3), "float32")})
    assert hv.shape == (3, 6) and cv.shape == (3, 6)
    assert np.isfinite(hv).all()
    assert nv.shape == (3, 3)


def test_has_inf_has_nan_and_create_parameter():
    x = L.data("x", shape=[3])
    hi = L.has_inf(x)
    hn = L.has_nan(x)
    w = L.create_parameter(shape=[3, 2], dtype="float32")
    o = L.matmul(x, w)
    a, b, ov = _run([hi, hn, o],
                    {"x": np.array([[1.0, np.inf, 0.0]], "float32")})
    assert bool(a[0]) is True and bool(b[0]) is False
    assert ov.shape == (1, 2)


def test_autoincreased_step_counter_advances():
    ctr = L.autoincreased_step_counter(begin=1)
    loss = L.mean(L.fc(L.data("x", shape=[4]), 2))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.zeros((2, 4), "float32")}
    vals = [int(np.asarray(exe.run(feed=feed,
                                   fetch_list=[ctr, loss])[0])[0])
            for _ in range(3)]
    assert vals == [1, 2, 3], vals


def test_append_LARS_scales_updates():
    x = L.data("x", shape=[4])
    y = L.data("y", shape=[1])
    pred = L.fc(x, 1, bias_attr=False)
    loss = L.mean(L.square_error_cost(pred, y))
    params_grads = fluid.append_backward(loss)
    # a plain float learning_rate is accepted (materialized in-graph)
    decayed = fluid.layers.append_LARS(params_grads, 0.1,
                                       weight_decay=0.01)
    assert len(decayed) == len(params_grads)
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.apply_gradients(params_grads, loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 4).astype("float32"),
            "y": rng.rand(8, 1).astype("float32")}
    l0 = float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])[0])
    for _ in range(20):
        lv, = exe.run(feed=feed, fetch_list=[loss])
    assert float(np.asarray(lv)[0]) < l0   # LARS-scaled SGD still learns


def test_layer_function_generator():
    gen = L.generate_layer_fn("cos_sim")
    x = L.data("x", shape=[4])
    y = L.data("y", shape=[4])
    outs = gen(x, y)
    assert len(outs) == 3                  # Out, XNorm, YNorm
    sig = L.generate_layer_fn_noattr("sigmoid")
    s = sig(x)
    o, = _run([s], {"x": np.zeros((2, 4), "float32"),
                    "y": np.zeros((2, 4), "float32")})
    np.testing.assert_allclose(o, 0.5 * np.ones((2, 4)), rtol=1e-6)

    @L.templatedoc(op_type="relu")
    def doc_holder():
        """${comment}"""
    assert doc_holder.__doc__ and "${comment}" not in doc_holder.__doc__


def test_py_reader_training_flow():
    pr = L.py_reader(capacity=4, shapes=[[-1, 8], [-1, 1]],
                     dtypes=["float32", "int64"])
    img, lbl = L.read_file(pr)
    pred = L.fc(img, 4, act="softmax")
    loss = L.mean(L.cross_entropy(pred, lbl))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(32):
            yield (rng.rand(8).astype("float32"),
                   rng.randint(0, 4, (1,)).astype("int64"))

    pr.decorate_paddle_reader(samples)
    handle = L.double_buffer(L.batch(L.shuffle(pr, 16), 8))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    steps = 0
    for feed in handle:
        lv, = exe.run(feed=feed, fetch_list=[loss])
        steps += 1
    assert steps == 4
    assert np.isfinite(np.asarray(lv)).all()
    # unbatched iteration is refused with guidance
    with pytest.raises(RuntimeError):
        iter(pr).__next__()


def test_open_files_and_preprocessor(tmp_path):
    from paddle_tpu import recordio as rio
    path = str(tmp_path / "d.rio")
    with rio.Writer(path) as w:
        for i in range(20):
            w.write(pickle.dumps((np.full((4,), i, "float32"),
                                  np.array([i % 3], "int64"))))
    of = L.open_files([path], shapes=[[-1, 4], [-1, 1]],
                      lod_levels=[0, 0], dtypes=["float32", "int64"],
                      thread_num=2, pass_num=2)
    xv, yv = L.read_file(of)
    assert xv.shape[-1] == 4
    h = L.batch(of, 5)
    batches = list(h)
    assert len(batches) == 8               # 20 samples x 2 passes / 5

    pre = L.Preprocessor(reader=h)
    with pre.block():
        xi, yi = pre.inputs()
        pre.outputs(L.scale(xi, scale=2.0), yi)
    out_batches = list(pre)
    assert len(out_batches) == 8
    raw = np.sort(np.concatenate(
        [b[xv.name][:, 0] for b in batches]))
    cooked = np.sort(np.concatenate(
        [b[xv.name][:, 0] for b in out_batches]))
    np.testing.assert_allclose(cooked, 2.0 * raw, rtol=1e-6)


def test_tensor_provider_and_reader_var_ranks():
    pr = L.py_reader(capacity=2, shapes=[[-1, 3], [-1, 1]],
                     dtypes=["float32", "int64"])
    xv, yv = L.read_file(pr)

    def tensors():
        for i in range(3):
            yield (np.full((5, 3), i, "float32"),
                   np.zeros((5, 1), "int64"))

    pr.decorate_tensor_provider(tensors)
    feeds = list(pr)
    assert len(feeds) == 3
    assert feeds[1][xv.name].shape == (5, 3)
    assert (feeds[1][xv.name] == 1).all()

    # inner -1 dims keep their rank (only the LEADING batch dim strips)
    seq = L.py_reader(capacity=2, shapes=[[-1, -1, 16]],
                      dtypes=["float32"])
    sv = L.read_file(seq)
    assert len(sv.shape) == 3 and sv.shape[-1] == 16

    # slot-count mismatch in a tensor provider is a loud error
    bad = L.py_reader(capacity=2, shapes=[[-1, 3], [-1, 1]],
                      dtypes=["float32", "int64"])
    bad.decorate_tensor_provider(lambda: iter([(np.zeros((5, 3)),)]))
    with pytest.raises(ValueError):
        next(iter(bad))


def test_preprocessor_output_count_mismatch_is_loud(tmp_path):
    from paddle_tpu import recordio as rio
    path = str(tmp_path / "d.rio")
    with rio.Writer(path) as w:
        for i in range(4):
            w.write(pickle.dumps((np.zeros((2,), "float32"),
                                  np.array([0], "int64"))))
    of = L.open_files([path], shapes=[[-1, 2], [-1, 1]],
                      lod_levels=[0, 0], dtypes=["float32", "int64"])
    h = L.batch(of, 2)
    pre = L.Preprocessor(reader=h)
    with pytest.raises(ValueError):
        with pre.block():
            xi, yi = pre.inputs()
            pre.outputs(xi)          # 1 output for a 2-slot reader


def test_chunk_evaluator_and_init_on_cpu():
    m = fluid.metrics.ChunkEvaluator()
    m.update(10, 8, 6)
    m.update(np.array([5]), 7, 4)
    p, r, f1 = m.eval()
    assert abs(p - 10 / 15) < 1e-9 and abs(r - 10 / 15) < 1e-9
    assert abs(f1 - 2 * p * r / (p + r)) < 1e-9
    with pytest.raises(ValueError):
        m.update("nan", 1, 1)

    assert not fluid.initializer.force_init_on_cpu()
    with fluid.initializer.init_on_cpu():
        assert fluid.initializer.force_init_on_cpu()
    assert not fluid.initializer.force_init_on_cpu()

    from paddle_tpu.reader import ComposeNotAligned
    import paddle_tpu.reader as rd
    r1 = lambda: iter([1, 2, 3])        # noqa: E731
    r2 = lambda: iter([4, 5])           # noqa: E731
    with pytest.raises(ComposeNotAligned):
        list(rd.compose(r1, r2)())


def test_random_data_generator_and_load(tmp_path):
    # reference contract: per-sample shapes, no batch dim
    rdg = L.random_data_generator(-1.0, 1.0, shapes=[[4], [2, 3]],
                                  lod_levels=[0, 0])
    xv, yv = L.read_file(rdg)
    assert len(xv.shape) == 2 and len(yv.shape) == 3   # batch prepended
    b = L.batch(rdg, 6)
    feed = next(iter(b))
    assert feed[xv.name].shape == (6, 4)
    assert feed[yv.name].shape == (6, 2, 3)
    arr = feed[xv.name]
    assert (-1 <= arr).all() and (arr <= 1).all()
    with pytest.raises(ValueError):
        L.random_data_generator(0.0, 1.0, shapes=[[-1, 4]],
                                lod_levels=[0])

    w = np.arange(6, dtype="float32").reshape(2, 3)
    np.save(str(tmp_path / "w.npy"), w)
    out = L.create_tensor(dtype="float32")
    L.load(out, str(tmp_path / "w"))
    got, = _run([out], {})
    np.testing.assert_allclose(got, w)
