"""Detection op tests vs numpy oracles: iou, prior_box, anchor_generator,
box_coder encode/decode round-trip, bipartite_match, target_assign,
multiclass_nms, roi_pool, polygon_box_transform."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(build, feed):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fetches = build()
        exe = fluid.Executor(fluid.CPUPlace())
        return exe.run(feed=feed, fetch_list=list(fetches))


def _np_iou(a, b):
    out = np.zeros((len(a), len(b)))
    for i, x in enumerate(a):
        for j, y in enumerate(b):
            ix1, iy1 = max(x[0], y[0]), max(x[1], y[1])
            ix2, iy2 = min(x[2], y[2]), min(x[3], y[3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            ua = ((x[2] - x[0]) * (x[3] - x[1]) +
                  (y[2] - y[0]) * (y[3] - y[1]) - inter)
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


def test_iou_similarity():
    rng = np.random.RandomState(0)
    a = np.sort(rng.rand(5, 4).astype("float32"), -1)[:, [0, 1, 2, 3]]
    a = np.stack([a[:, 0], a[:, 1], a[:, 0] + a[:, 2] + 0.1,
                  a[:, 1] + a[:, 3] + 0.1], -1).astype("float32")
    b = np.stack([a[:, 0] + 0.05, a[:, 1] + 0.05, a[:, 2] + 0.05,
                  a[:, 3] + 0.05], -1)[:3].astype("float32")

    def build():
        x = fluid.layers.data("x", shape=[4], append_batch_size=False)
        x.shape = (-1, 4)
        y = fluid.layers.data("y", shape=[4], append_batch_size=False)
        y.shape = (-1, 4)
        return (fluid.layers.iou_similarity(x, y),)

    (got,) = _run(build, {"x": a, "y": b})
    np.testing.assert_allclose(got, _np_iou(a, b), atol=1e-5)


def test_prior_box_layout_and_values():
    img = np.zeros((1, 3, 32, 32), "float32")
    fmap = np.zeros((1, 8, 4, 4), "float32")

    def build():
        i = fluid.layers.data("img", shape=[3, 32, 32])
        f = fluid.layers.data("fmap", shape=[8, 4, 4])
        boxes, variances = fluid.layers.prior_box(
            f, i, min_sizes=[8.0], max_sizes=[16.0],
            aspect_ratios=[2.0], flip=True, clip=True)
        return boxes, variances

    boxes, variances = _run(build, {"img": img, "fmap": fmap})
    # priors: ars {1, 2, 0.5} x 1 min_size + 1 max_size = 4
    assert boxes.shape == (4, 4, 4, 4)
    assert variances.shape == boxes.shape
    np.testing.assert_allclose(variances[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
    # first prior at (0,0): center (0.5*8, 0.5*8)=(4,4), ar=1 size 8
    np.testing.assert_allclose(
        boxes[0, 0, 0], [0.0, 0.0, 8.0 / 32, 8.0 / 32], atol=1e-6)
    # max_size prior: sqrt(8*16)/2 half-size
    hs = np.sqrt(8 * 16) / 2
    np.testing.assert_allclose(
        boxes[0, 0, 3], [max(0, (4 - hs) / 32), max(0, (4 - hs) / 32),
                         (4 + hs) / 32, (4 + hs) / 32], atol=1e-6)
    assert boxes.min() >= 0 and boxes.max() <= 1  # clipped


def test_anchor_generator_matches_reference_formula():
    fmap = np.zeros((1, 8, 2, 3), "float32")

    def build():
        f = fluid.layers.data("fmap", shape=[8, 2, 3])
        anchors, variances = fluid.layers.anchor_generator(
            f, anchor_sizes=[32.0], aspect_ratios=[1.0, 2.0],
            stride=[16.0, 16.0])
        return anchors, variances

    anchors, variances = _run(build, {"fmap": fmap})
    assert anchors.shape == (2, 3, 2, 4)
    # reference formula for ar=1, size=32, stride 16: base=16, scale=2
    # -> w=h=32; center at offset*(stride-1)=7.5
    np.testing.assert_allclose(
        anchors[0, 0, 0], [7.5 - 15.5, 7.5 - 15.5, 7.5 + 15.5,
                           7.5 + 15.5], atol=1e-5)
    # ar=2: base_w=round(sqrt(256/2))=11, base_h=22 -> w=22, h=44
    np.testing.assert_allclose(
        anchors[0, 0, 1],
        [7.5 - 0.5 * 21, 7.5 - 0.5 * 43, 7.5 + 0.5 * 21, 7.5 + 0.5 * 43],
        atol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(1)
    priors = np.abs(rng.rand(6, 4)).astype("float32")
    priors[:, 2:] = priors[:, :2] + 0.2 + priors[:, 2:] * 0.3
    pvar = np.full((6, 4), 0.1, "float32")
    targets = np.abs(rng.rand(3, 4)).astype("float32")
    targets[:, 2:] = targets[:, :2] + 0.15 + targets[:, 2:] * 0.2

    def build():
        p = fluid.layers.data("p", shape=[4], append_batch_size=False)
        p.shape = (-1, 4)
        pv = fluid.layers.data("pv", shape=[4], append_batch_size=False)
        pv.shape = (-1, 4)
        t = fluid.layers.data("t", shape=[4], append_batch_size=False)
        t.shape = (-1, 4)
        enc = fluid.layers.box_coder(p, pv, t, "encode_center_size")
        dec = fluid.layers.box_coder(p, pv, enc, "decode_center_size")
        return enc, dec

    enc, dec = _run(build, {"p": priors, "pv": pvar, "t": targets})
    assert enc.shape == (3, 6, 4)
    # decoding the encoding recovers each target against every prior
    for j in range(6):
        np.testing.assert_allclose(dec[:, j, :], targets, atol=1e-4)


def test_bipartite_match_greedy_and_per_prediction():
    dist = np.array([[[0.9, 0.2, 0.0, 0.6],
                      [0.8, 0.7, 0.0, 0.1]]], "float32")  # [1, 2, 4]

    def build():
        d = fluid.layers.data("d", shape=[2, 4], append_batch_size=False)
        d.shape = (-1, 2, 4)
        m, md = fluid.layers.bipartite_match(d)
        m2, md2 = fluid.layers.bipartite_match(
            d, match_type="per_prediction", dist_threshold=0.5)
        return m, md, m2, md2

    m, md, m2, md2 = _run(build, {"d": dist})
    # greedy: global max 0.9 -> col0=row0; next best unused 0.7 -> col1=row1
    assert m[0, 0] == 0 and m[0, 1] == 1
    # pure bipartite mode leaves remaining columns unmatched
    assert m[0, 2] == -1 and m[0, 3] == -1
    np.testing.assert_allclose(md[0], [0.9, 0.7, 0.0, 0.0], atol=1e-6)
    # per_prediction fills col3 (best dist 0.6 >= 0.5) but NOT col2 (0.0)
    assert m2[0, 3] == 0 and m2[0, 2] == -1
    np.testing.assert_allclose(md2[0], [0.9, 0.7, 0.0, 0.6], atol=1e-6)


def test_target_assign_scatter():
    x = np.arange(12, dtype="float32").reshape(1, 3, 4)  # 3 gt rows
    match = np.array([[1, -1, 2, 0]], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[3, 4], append_batch_size=False)
        xi.shape = (-1, 3, 4)
        mi = fluid.layers.data("m", shape=[4], dtype="int32",
                               append_batch_size=False)
        mi.shape = (-1, 4)
        out, w = fluid.layers.target_assign(xi, mi, mismatch_value=-7)
        return out, w

    out, w = _run(build, {"x": x, "m": match})
    np.testing.assert_allclose(out[0, 0], x[0, 1])
    np.testing.assert_allclose(out[0, 1], [-7] * 4)
    np.testing.assert_allclose(out[0, 2], x[0, 2])
    np.testing.assert_allclose(w[0, :, 0], [1, 0, 1, 1])


def test_multiclass_nms_suppresses_overlaps():
    # 4 boxes: 0 and 1 overlap heavily; 2 is separate; 3 low score
    boxes = np.array([[[0.0, 0.0, 0.4, 0.4],
                       [0.02, 0.02, 0.42, 0.42],
                       [0.6, 0.6, 0.9, 0.9],
                       [0.0, 0.6, 0.2, 0.9]]], "float32")
    scores = np.zeros((1, 2, 4), "float32")
    scores[0, 1] = [0.9, 0.8, 0.7, 0.01]   # class 1 (class 0 = bg)

    def build():
        b = fluid.layers.data("b", shape=[4, 4], append_batch_size=False)
        b.shape = (-1, 4, 4)
        s = fluid.layers.data("s", shape=[2, 4], append_batch_size=False)
        s.shape = (-1, 2, 4)
        out = fluid.layers.multiclass_nms(
            b, s, score_threshold=0.05, nms_threshold=0.5, keep_top_k=4)
        ln = fluid.layers.sequence_length(out)
        return out, ln

    out, ln = _run(build, {"b": boxes, "s": scores})
    assert ln[0] == 2                       # box1 suppressed, box3 cut
    np.testing.assert_allclose(out[0, 0, :2], [1, 0.9], atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 2:], boxes[0, 0], atol=1e-6)
    np.testing.assert_allclose(out[0, 1, :2], [1, 0.7], atol=1e-6)
    assert (out[0, 2:, 0] == -1).all()      # padding rows labeled -1


def test_roi_pool_max_pooling():
    x = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    rois = np.array([[0.0, 0.0, 3.0, 3.0],
                     [4.0, 4.0, 7.0, 7.0]], "float32")

    def build():
        xi = fluid.layers.data("x", shape=[1, 8, 8])
        r = fluid.layers.data("rois", shape=[4], append_batch_size=False)
        r.shape = (-1, 4)
        out = fluid.layers.roi_pool(xi, r, pooled_height=2,
                                    pooled_width=2)
        return (out,)

    (out,) = _run(build, {"x": x, "rois": rois})
    assert out.shape == (2, 1, 2, 2)
    img = x[0, 0]
    # roi 0 covers rows/cols 0..3, 2x2 bins of 2x2 pixels each: max =
    # bottom-right element of each bin
    np.testing.assert_allclose(out[0, 0],
                               [[img[1, 1], img[1, 3]],
                                [img[3, 1], img[3, 3]]])
    np.testing.assert_allclose(out[1, 0],
                               [[img[5, 5], img[5, 7]],
                                [img[7, 5], img[7, 7]]])


def test_polygon_box_transform():
    x = np.zeros((1, 8, 2, 2), "float32")
    x[0, 0, 0, 1] = 1.0    # channel 0 (x-offset), pixel (0,1)
    x[0, 1, 1, 0] = 2.0    # channel 1 (y-offset), pixel (1,0)

    def build():
        xi = fluid.layers.data("x", shape=[8, 2, 2])
        return (fluid.layers.polygon_box_transform(xi),)

    (out,) = _run(build, {"x": x})
    # reference (polygon_box_transform_op.cc:43-48): even ch -> col - in,
    # odd ch -> row - in
    assert out[0, 0, 0, 1] == pytest.approx(1 - 1.0)
    assert out[0, 1, 1, 0] == pytest.approx(1 - 2.0)
    assert out[0, 0, 1, 1] == pytest.approx(1.0)   # col 1, offset 0
    assert out[0, 1, 0, 0] == pytest.approx(0.0)   # row 0, offset 0
