"""CTR DNN (models/ctr_dnn.py — reference dist_ctr.py workload):
sparse-embedding click model trains single-device, and the same program
runs EP-sharded on a (dp, ep) mesh with loss parity — the pserver
sparse-table capability on the mesh runtime (SURVEY §7 stage 8).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models.ctr_dnn import ctr_dnn

DNN_V, LR_V, T, BATCH = 1000, 100, 5, 32


def _build(is_distributed=False, seed=7):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    dnn = fluid.layers.data("dnn_ids", shape=[1], dtype="int64",
                            lod_level=1)
    lr = fluid.layers.data("lr_ids", shape=[1], dtype="int64",
                           lod_level=1)
    label = fluid.layers.data("click", shape=[1], dtype="int64")
    cost, predict, auc = ctr_dnn(dnn, lr, label, DNN_V, LR_V,
                                 is_distributed=is_distributed)
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
    return cost, predict, auc


def _batches(steps, seed=0):
    """Click depends on whether any dnn id falls in the 'hot' range —
    learnable from the embeddings alone."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        ids = rng.randint(50, DNN_V, (BATCH, T, 1)).astype("int64")
        hot = rng.rand(BATCH) < 0.5
        ids[hot, 0, 0] = rng.randint(0, 50, hot.sum())
        lens = np.full(BATCH, T, "int64")
        lr_ids = rng.randint(0, LR_V, (BATCH, 2, 1)).astype("int64")
        out.append({"dnn_ids": ids, "dnn_ids@LEN": lens,
                    "lr_ids": lr_ids,
                    "lr_ids@LEN": np.full(BATCH, 2, "int64"),
                    "click": hot.astype("int64").reshape(-1, 1)})
    return out


def test_ctr_dnn_trains_and_auc_rises():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        cost, _pred, auc = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            losses, aucs = [], []
            for feed in _batches(120):
                lv, av = exe.run(feed=feed, fetch_list=[cost, auc])
                losses.append(float(np.asarray(lv)))
                aucs.append(float(np.asarray(av)))
    assert min(losses[-20:]) < losses[0] * 0.5, (losses[0], losses[-1])
    assert aucs[-1] > 0.85, aucs[-1]  # streaming AUC after 120 batches


def test_ctr_dnn_ep_sharded_loss_parity():
    """is_distributed tables row-shard over ep; the sharded run's losses
    match the single-device run (GSPMD changes layout, not math)."""
    batches = _batches(4)

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        cost, _p, _a = _build(is_distributed=False)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            single = [float(np.asarray(exe.run(feed=f,
                                               fetch_list=[cost])[0]))
                      for f in batches]

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        cost, _p, _a = _build(is_distributed=True)
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=1)
        mesh = fluid.make_mesh((4, 2), ("dp", "ep"))
        bs = t.build_strategy(mesh)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            pe = fluid.ParallelExecutor(loss_name=cost.name, mesh=mesh,
                                        build_strategy=bs, scope=scope)
            sharded = [float(np.asarray(pe.run(feed=f,
                                               fetch_list=[cost])[0]))
                       for f in batches]

    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=1e-5)
