"""Attention seq2seq NMT model (reference
benchmark/fluid/models/machine_translation.py:53 seq_to_seq_net):
bi-LSTM encoder + Bahdanau attention decoder trains end-to-end, masks
padded source positions in the attention softmax, and handles
variable-length batches."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.machine_translation import seq_to_seq_net

V = 16


def _feed(rng, B, T, lens=None):
    feed = {}
    lens = np.asarray(lens if lens is not None else [T] * B, "int32")
    for name in ("src", "tgt", "lbl"):
        feed[name] = rng.randint(1, V, (B, T, 1)).astype("int64")
        feed[name + "@LEN"] = lens
    # copy task: label = source, target = source (teacher forcing input)
    feed["tgt"] = feed["src"].copy()
    feed["lbl"] = feed["src"].copy()
    return feed


def test_seq2seq_attention_trains():
    rng = np.random.RandomState(0)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64",
                                lod_level=1)
        cost, logits = seq_to_seq_net(src, tgt, lbl, V, V,
                                      embedding_dim=16, encoder_size=16,
                                      decoder_size=16)
        fluid.optimizer.AdamOptimizer(learning_rate=0.02).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            feed = _feed(rng, B=8, T=6)
            losses = []
            for _ in range(40):
                l, = exe.run(feed=feed, fetch_list=[cost])
                losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_seq2seq_attention_masks_padding():
    """Padded source positions must not receive attention: the loss on
    a short-sequence batch is invariant to garbage in the padding."""
    rng = np.random.RandomState(1)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64",
                                lod_level=1)
        cost, _ = seq_to_seq_net(src, tgt, lbl, V, V, embedding_dim=8,
                                 encoder_size=8, decoder_size=8)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            feed = _feed(rng, B=4, T=6, lens=[3, 4, 2, 6])
            a, = exe.run(feed=feed, fetch_list=[cost])
            # scribble over source padding beyond each length
            for i, l in enumerate(feed["src@LEN"]):
                feed["src"][i, l:] = (feed["src"][i, l:] + 7) % V
            b, = exe.run(feed=feed, fetch_list=[cost])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)
