"""Attention seq2seq NMT model (reference
benchmark/fluid/models/machine_translation.py:53 seq_to_seq_net):
bi-LSTM encoder + Bahdanau attention decoder trains end-to-end, masks
padded source positions in the attention softmax, and handles
variable-length batches."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.machine_translation import seq_to_seq_net

V = 16


def _feed(rng, B, T, lens=None):
    feed = {}
    lens = np.asarray(lens if lens is not None else [T] * B, "int32")
    for name in ("src", "tgt", "lbl"):
        feed[name] = rng.randint(1, V, (B, T, 1)).astype("int64")
        feed[name + "@LEN"] = lens
    # copy task: label = source, target = source (teacher forcing input)
    feed["tgt"] = feed["src"].copy()
    feed["lbl"] = feed["src"].copy()
    return feed


def test_seq2seq_attention_trains():
    rng = np.random.RandomState(0)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64",
                                lod_level=1)
        cost, logits = seq_to_seq_net(src, tgt, lbl, V, V,
                                      embedding_dim=16, encoder_size=16,
                                      decoder_size=16)
        fluid.optimizer.AdamOptimizer(learning_rate=0.02).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            feed = _feed(rng, B=8, T=6)
            losses = []
            for _ in range(40):
                l, = exe.run(feed=feed, fetch_list=[cost])
                losses.append(float(np.asarray(l).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_seq2seq_attention_masks_padding():
    """Padded source positions must not receive attention: the loss on
    a short-sequence batch is invariant to garbage in the padding."""
    rng = np.random.RandomState(1)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64",
                                lod_level=1)
        cost, _ = seq_to_seq_net(src, tgt, lbl, V, V, embedding_dim=8,
                                 encoder_size=8, decoder_size=8)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            feed = _feed(rng, B=4, T=6, lens=[3, 4, 2, 6])
            a, = exe.run(feed=feed, fetch_list=[cost])
            # scribble over source padding beyond each length
            for i, l in enumerate(feed["src@LEN"]):
                feed["src"][i, l:] = (feed["src"][i, l:] + 7) % V
            b, = exe.run(feed=feed, fetch_list=[cost])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


def test_nets_attention_numerics():
    """nets.simple_attention / dot_product_attention: masked softmax
    weighting of values (reference trainer_config_helpers/networks.py
    simple_attention, dot_product_attention)."""
    import numpy as np
    import paddle_tpu as fluid

    B, T, D = 2, 4, 3
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        enc = fluid.layers.data("enc", shape=[D], lod_level=1)
        query = fluid.layers.data("q", shape=[D])
        ctx = fluid.nets.dot_product_attention(
            enc, enc, query, length=fluid.layers.sequence_length(enc))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            rng = np.random.RandomState(0)
            ev = rng.randn(B, T, D).astype("float32")
            qv = rng.randn(B, D).astype("float32")
            lens = np.array([2, 4], "int64")
            out, = exe.run(feed={"enc": ev, "enc@LEN": lens, "q": qv},
                           fetch_list=[ctx])
    # numpy oracle: masked softmax over scores, weighted value sum
    for b in range(B):
        s = ev[b] @ qv[b]
        s[lens[b]:] = -np.inf
        w = np.exp(s - s.max()); w /= w.sum()
        np.testing.assert_allclose(out[b], w @ ev[b], rtol=1e-4, atol=1e-5)

    # simple_attention: trains end-to-end (params inside) — shape check
    # + gradient existence via a tiny minimize
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        enc = fluid.layers.data("enc", shape=[D], lod_level=1)
        proj = fluid.layers.fc(enc, size=D, num_flatten_dims=2,
                               bias_attr=False)
        state = fluid.layers.data("st", shape=[D])
        ctx = fluid.nets.simple_attention(
            enc, proj, state, D, length=fluid.layers.sequence_length(enc))
        loss = fluid.layers.mean(ctx)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            out, = exe.run(feed={"enc": np.ones((B, T, D), "float32"),
                                 "enc@LEN": np.array([2, 4], "int64"),
                                 "st": np.ones((B, D), "float32")},
                           fetch_list=[loss])
    assert np.isfinite(out).all()
