"""Program printer + graphviz export tests (reference debugger.py /
graphviz.py parity)."""

import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import debugger


def _build():
    x = fluid.layers.data("x", shape=[4])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = fluid.layers.fc(x, size=3, act="softmax",
                           param_attr=fluid.ParamAttr(name="dbg_w"))
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_pprint_program(fresh_programs):
    _build()
    text = debugger.pprint_program_codes()
    assert "param dbg_w" in text
    assert "mul(" in text and "softmax" in text
    assert "_grad" not in text              # backward hidden by default
    full = debugger.pprint_program_codes(show_backward=True)
    assert "_grad" in full and "sgd" in full


def test_draw_block_graphviz(tmp_path, fresh_programs):
    _build()
    path = str(tmp_path / "g.dot")
    out = debugger.draw_block_graphviz(
        fluid.default_main_program().global_block(),
        highlights=["dbg_w"], path=path)
    assert out == path and os.path.exists(path)
    dot = open(path).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert '"var_dbg_w"' in dot and "orange" in dot
    assert '[label="mul" shape=box' in dot
    # edges connect vars to ops
    assert '"var_dbg_w" -> "op_' in dot
