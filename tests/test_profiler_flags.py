"""Profiler wiring + numeric-debugging flag tests (SURVEY §5: tracing,
race/numeric debugging).  The reference wraps every op run in RecordEvent
(operator.cc:153) and exports chrome traces (tools/timeline.py); here the
executor step/compile and trainer step are the spanned units, and
FLAGS_check_nan_inf raises on non-finite step outputs (operator.cc:717)."""

import json

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler


def _build_mlp():
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=3, act="relu")
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_executor_spans_appear_in_chrome_trace(tmp_path, fresh_programs):
    loss = _build_mlp()
    path = str(tmp_path / "trace.json")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.rand(8, 4).astype("float32")
    with profiler.profiler("All", profile_path=path):
        for _ in range(3):
            exe.run(feed={"x": x}, fetch_list=[loss])
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "executor/compile" in names
    assert names.count("executor/run") == 3
    for e in trace["traceEvents"]:
        # spans are X-phase with real durations; the only other phase
        # is the M-phase process/thread-name metadata
        assert e["ph"] in ("X", "M")
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_record_event_outside_profiler_is_dropped(fresh_programs):
    profiler.reset_profiler()
    with profiler.RecordEvent("unprofiled"):
        pass
    with profiler._events_lock:
        assert not profiler._events


def test_span_straddling_stop_profiler_is_kept(fresh_programs):
    """__enter__ latches the enabled state: a span started under the
    session is recorded even if stop_profiler lands before __exit__
    (previously __exit__ decided post-hoc and dropped it)."""
    profiler.reset_profiler()
    profiler.start_profiler("CPU")
    ev = profiler.RecordEvent("straddle")
    ev.__enter__()
    profiler.stop_profiler(profile_path=None)
    ev.__exit__(None, None, None)
    with profiler._events_lock:
        names = [e["name"] for e in profiler._events]
    assert "straddle" in names
    # and the inverse: started while disabled, exited under a session
    ev2 = profiler.RecordEvent("pre_session")
    ev2.__enter__()
    profiler.start_profiler("CPU")
    ev2.__exit__(None, None, None)
    profiler.stop_profiler(profile_path=None)
    with profiler._events_lock:
        names = [e["name"] for e in profiler._events]
    assert "pre_session" not in names


def _fabricate_events():
    """Deterministic event set: 'a' called 3x (total 3ms, max 1.5ms),
    'b' called once (total 10ms)."""
    profiler.reset_profiler()
    with profiler._events_lock:
        for dur in (500.0, 1000.0, 1500.0):
            profiler._events.append({"name": "a", "ts": 0.0, "dur": dur,
                                     "ph": "X", "pid": 1, "tid": 1})
        profiler._events.append({"name": "b", "ts": 0.0, "dur": 10000.0,
                                 "ph": "X", "pid": 1, "tid": 1})


def test_print_summary_sorted_key_variants(fresh_programs, capsys):
    _fabricate_events()
    first_row = {}
    for key in (None, "total", "calls", "ave", "max"):
        profiler._print_summary(key)
        out = capsys.readouterr().out.splitlines()
        assert out[0].startswith("Event")
        first_row[key] = out[1].split()[0]
    # total/avg/max rank the long single span first; calls ranks 'a'
    assert first_row[None] == "b"
    assert first_row["total"] == "b"
    assert first_row["ave"] == "b"
    assert first_row["max"] == "b"
    assert first_row["calls"] == "a"
    # summarize_events is the same formatter the offline CLI prints
    with profiler._events_lock:
        events = list(profiler._events)
    profiler._print_summary("total")
    assert capsys.readouterr().out.strip() == \
        profiler.summarize_events(events, "total")
    profiler.reset_profiler()


def test_mark_event_counting(fresh_programs, capsys):
    profiler.reset_profiler()
    profiler.mark_event("cache/hit")          # outside a session: dropped
    profiler.start_profiler("CPU")
    for _ in range(3):
        profiler.mark_event("cache/hit")
    profiler.mark_event("cache/miss")
    profiler.stop_profiler(profile_path=None)
    out = capsys.readouterr().out
    row = [ln for ln in out.splitlines() if ln.startswith("cache/hit")]
    assert row and row[0].split()[2] == "3"   # calls column counts marks
    with profiler._events_lock:
        marks = [e for e in profiler._events if e["name"] == "cache/hit"]
    assert len(marks) == 3 and all(e["dur"] == 0.0 for e in marks)
    profiler.reset_profiler()


def test_chrome_trace_thread_metadata(tmp_path, fresh_programs):
    """export_chrome_tracing labels worker threads with M-phase
    process_name/thread_name metadata instead of raw tids."""
    import threading

    profiler.reset_profiler()
    profiler.start_profiler("CPU")

    def worker():
        with profiler.RecordEvent("worker_span"):
            pass

    t = threading.Thread(target=worker, name="prefetch-producer-0")
    t.start()
    t.join()
    with profiler.RecordEvent("main_span"):
        pass
    path = str(tmp_path / "trace.json")
    profiler.stop_profiler(profile_path=path)
    trace = json.load(open(path))["traceEvents"]
    meta = [e for e in trace if e["ph"] == "M"]
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "paddle_tpu" for e in meta)
    tnames = {e["args"]["name"] for e in meta
              if e["name"] == "thread_name"}
    assert "prefetch-producer-0" in tnames
    # every span's tid has a thread_name metadata entry
    span_tids = {e["tid"] for e in trace if e["ph"] == "X"}
    meta_tids = {e["tid"] for e in meta if e["name"] == "thread_name"}
    assert span_tids <= meta_tids
    profiler.reset_profiler()


def test_check_nan_inf_catches_injected_nan(fresh_programs):
    x = fluid.layers.data("x", shape=[2])
    out = fluid.layers.log(x)          # log(-1) -> nan
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        exe.run(feed={"x": np.ones((2, 2), "float32")}, fetch_list=[out])
        with pytest.raises(RuntimeError, match="contains nan"):
            exe.run(feed={"x": -np.ones((2, 2), "float32")},
                    fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    # flag off: silently returns the nan (reference default behavior)
    (v,) = exe.run(feed={"x": -np.ones((2, 2), "float32")},
                   fetch_list=[out])
    assert np.isnan(v).all()


def test_check_nan_inf_names_state_var(fresh_programs):
    x = fluid.layers.data("x", shape=[2])
    h = fluid.layers.fc(x, size=2, act=None)
    loss = fluid.layers.mean(fluid.layers.log(h))
    fluid.optimizer.SGD(learning_rate=1e30).minimize(loss)  # diverges
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="check_nan_inf"):
            for _ in range(5):
                exe.run(feed={"x": np.random.rand(4, 2).astype("float32")},
                        fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_api_roundtrip_and_unknown():
    fluid.set_flags({"FLAGS_benchmark": True})
    assert fluid.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
    fluid.set_flags({"benchmark": False})   # bare spelling accepted
    assert fluid.get_flags(["benchmark"])["benchmark"] is False
    with pytest.raises(KeyError):
        fluid.set_flags({"FLAGS_no_such_flag": 1})
    with pytest.raises(KeyError):
        fluid.get_flags("nope")


def test_trace_summary_cli_offline(tmp_path, fresh_programs):
    """tools/trace_summary.py summarizes an exported chrome trace
    offline, printing the same per-name table stop_profiler prints."""
    import os
    import subprocess
    import sys

    loss = _build_mlp()
    path = str(tmp_path / "trace.json")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.rand(8, 4).astype("float32")
    with profiler.profiler("CPU", profile_path=path):
        for _ in range(2):
            exe.run(feed={"x": x}, fetch_list=[loss])
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_summary.py"),
         path, "--sorted_key", "calls"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120, check=True).stdout
    lines = out.splitlines()
    # the export's correlation id leads, then the live-format table
    assert lines[0].startswith("run_id ")
    assert lines[1].startswith("Event")
    assert any(ln.startswith("executor/run") for ln in lines)
    # row format matches the live summary: name total calls avg max
    row = [ln for ln in lines if ln.startswith("executor/run")][0]
    assert row.split()[2] == "2"
    # marks are tallied as counter totals, not zero-ms span rows
    assert any(ln.startswith("mark/compile_cache/") for ln in lines)
    assert not any(ln.startswith("compile_cache/") for ln in lines)


def test_trace_summary_cli_top_and_metadata_only(tmp_path):
    """--top caps the table; a trace whose threads carry only M-phase
    metadata events (or events missing dur) must not crash."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(root, "tools", "trace_summary.py")

    many = {"traceEvents": [
        {"name": "span%d" % i, "ph": "X", "ts": 0.0, "dur": 10.0 + i,
         "pid": 1, "tid": 1} for i in range(10)]}
    p1 = str(tmp_path / "many.json")
    json.dump(many, open(p1, "w"))
    out = subprocess.run(
        [sys.executable, tool, p1, "--top", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120, check=True).stdout
    rows = [ln for ln in out.splitlines() if ln.startswith("span")]
    assert len(rows) == 3

    meta_only = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "paddle_tpu"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 7,
         "args": {"name": "prefetch-producer"}},
        {"ph": "X", "ts": 0.0, "pid": 1, "tid": 7},   # nameless stray
    ]}
    p2 = str(tmp_path / "meta.json")
    json.dump(meta_only, open(p2, "w"))
    res = subprocess.run(
        [sys.executable, tool, p2],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120)
    assert res.returncode == 0, res.stderr
    assert "metadata-only" in res.stdout


def test_trainer_step_spans(tmp_path, fresh_programs):
    from paddle_tpu.contrib import Trainer

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=2, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.01)

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(4):
            yield rng.rand(4).astype("float32"), np.array([1], "int64")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=optimizer_func)
    path = str(tmp_path / "t.json")
    with profiler.profiler(profile_path=path):
        trainer.train(num_epochs=1, event_handler=lambda e: None,
                      reader=fluid.batch(reader, batch_size=2),
                      feed_order=["x", "label"])
    names = [e["name"] for e in json.load(open(path))["traceEvents"]]
    assert names.count("trainer/step") == 2
    assert "executor/run" in names
