"""Profiler wiring + numeric-debugging flag tests (SURVEY §5: tracing,
race/numeric debugging).  The reference wraps every op run in RecordEvent
(operator.cc:153) and exports chrome traces (tools/timeline.py); here the
executor step/compile and trainer step are the spanned units, and
FLAGS_check_nan_inf raises on non-finite step outputs (operator.cc:717)."""

import json

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler


def _build_mlp():
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=3, act="relu")
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_executor_spans_appear_in_chrome_trace(tmp_path, fresh_programs):
    loss = _build_mlp()
    path = str(tmp_path / "trace.json")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.rand(8, 4).astype("float32")
    with profiler.profiler("All", profile_path=path):
        for _ in range(3):
            exe.run(feed={"x": x}, fetch_list=[loss])
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "executor/compile" in names
    assert names.count("executor/run") == 3
    for e in trace["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0


def test_record_event_outside_profiler_is_dropped(fresh_programs):
    profiler.reset_profiler()
    with profiler.RecordEvent("unprofiled"):
        pass
    with profiler._events_lock:
        assert not profiler._events


def test_check_nan_inf_catches_injected_nan(fresh_programs):
    x = fluid.layers.data("x", shape=[2])
    out = fluid.layers.log(x)          # log(-1) -> nan
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        exe.run(feed={"x": np.ones((2, 2), "float32")}, fetch_list=[out])
        with pytest.raises(RuntimeError, match="contains nan"):
            exe.run(feed={"x": -np.ones((2, 2), "float32")},
                    fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    # flag off: silently returns the nan (reference default behavior)
    (v,) = exe.run(feed={"x": -np.ones((2, 2), "float32")},
                   fetch_list=[out])
    assert np.isnan(v).all()


def test_check_nan_inf_names_state_var(fresh_programs):
    x = fluid.layers.data("x", shape=[2])
    h = fluid.layers.fc(x, size=2, act=None)
    loss = fluid.layers.mean(fluid.layers.log(h))
    fluid.optimizer.SGD(learning_rate=1e30).minimize(loss)  # diverges
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="check_nan_inf"):
            for _ in range(5):
                exe.run(feed={"x": np.random.rand(4, 2).astype("float32")},
                        fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_api_roundtrip_and_unknown():
    fluid.set_flags({"FLAGS_benchmark": True})
    assert fluid.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
    fluid.set_flags({"benchmark": False})   # bare spelling accepted
    assert fluid.get_flags(["benchmark"])["benchmark"] is False
    with pytest.raises(KeyError):
        fluid.set_flags({"FLAGS_no_such_flag": 1})
    with pytest.raises(KeyError):
        fluid.get_flags("nope")


def test_trainer_step_spans(tmp_path, fresh_programs):
    from paddle_tpu.contrib import Trainer

    def train_func():
        x = fluid.layers.data("x", shape=[4])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=2, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.01)

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(4):
            yield rng.rand(4).astype("float32"), np.array([1], "int64")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=optimizer_func)
    path = str(tmp_path / "t.json")
    with profiler.profiler(profile_path=path):
        trainer.train(num_epochs=1, event_handler=lambda e: None,
                      reader=fluid.batch(reader, batch_size=2),
                      feed_order=["x", "label"])
    names = [e["name"] for e in json.load(open(path))["traceEvents"]]
    assert names.count("trainer/step") == 2
    assert "executor/run" in names
