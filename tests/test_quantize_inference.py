"""Quantized execution (ISSUE 14): the ``quantize_inference`` program
pass, the ``dequant_matmul`` kernels, the accuracy-gated
``tune_quantization`` decision procedure, and the serving wiring.

CPU-testable by design: gate logic and pass semantics run on the XLA
int8 fallback; the Pallas kernels verify in interpreter mode."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import autotune
from paddle_tpu.transpiler import quantize_inference
from paddle_tpu.transpiler.quantize_pass import QUANT_SUFFIX, SCALE_SUFFIX


def _fc_program(seed=7, d_in=64, d_h=128, d_out=16):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[d_in])
        h = fluid.layers.fc(x, size=d_h, act="relu")
        pred = fluid.layers.fc(h, size=d_out, act="softmax")
    return main, startup, pred


def _init(startup, scope):
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return exe


# ---------------------------------------------------------------------------
# pass semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["weight_only", "dynamic"])
def test_pass_rewrites_weights_and_matches_fp(mode):
    main, startup, pred = _fc_program()
    scope = fluid.Scope()
    exe = _init(startup, scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 64).astype("float32")}
    with fluid.scope_guard(scope):
        (ref,) = exe.run(main, feed=feed, fetch_list=[pred])
        q = quantize_inference(main, scope=scope, mode=mode)
        types = [op.type for op in q.global_block().ops]
        assert types.count("dequant_matmul") == 2, types
        assert "mul" not in types
        # the original program is untouched
        assert "dequant_matmul" not in [
            op.type for op in main.global_block().ops]
        # int8 weights + per-output-channel f32 scales in the scope
        w8 = np.asarray(scope.var("fc_0.w_0" + QUANT_SUFFIX))
        sw = np.asarray(scope.var("fc_0.w_0" + SCALE_SUFFIX))
        assert w8.dtype == np.int8 and w8.shape == (64, 128)
        assert sw.dtype == np.float32 and sw.shape == (128,)
        # per-channel grid: each column's dequant error is bounded by
        # ITS OWN scale, not the global max
        w = np.asarray(scope.var("fc_0.w_0"))
        np.testing.assert_allclose(w8 * sw, w, atol=float(sw.max()))
        (out,) = exe.run(q, feed=feed, fetch_list=[pred.name],
                         scope=scope)
        delta = autotune.eval_delta([ref], [out])
        assert delta < 0.02, delta
        # distinct fingerprint: the goodput/program-profile stack
        # attributes the quantized program separately for free
        from paddle_tpu import compile_cache

        assert compile_cache.program_fingerprint(q) != \
            compile_cache.program_fingerprint(main)


def test_pass_skips_unquantizable_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[4, 8])
        b = fluid.layers.data("b", shape=[8, 4])
        # non-persistable Y: not a weight, must not be rewritten
        out = fluid.layers.matmul(a, b)
        fluid.layers.mean(out)
    scope = fluid.Scope()
    _init(startup, scope)
    q = quantize_inference(main, scope=scope)
    assert [op.type for op in q.global_block().ops] == \
        [op.type for op in main.global_block().ops]


def test_dequant_matmul_xla_fallback_numerics():
    from paddle_tpu.ops.quantize import xla_dequant_matmul

    rng = np.random.RandomState(1)
    x = rng.randn(6, 96).astype(np.float32)
    w = (rng.randn(96, 160) * 0.05).astype(np.float32)
    sw = (np.abs(w).max(axis=0) / 127.0).astype(np.float32)
    qw = np.clip(np.round(w / sw), -127, 127).astype(np.int8)
    import jax.numpy as jnp

    wo = np.asarray(xla_dequant_matmul(jnp.asarray(x), jnp.asarray(qw),
                                       jnp.asarray(sw)))
    np.testing.assert_allclose(wo, x @ (qw.astype(np.float32) * sw),
                               rtol=1e-5, atol=1e-5)
    dyn = np.asarray(xla_dequant_matmul(jnp.asarray(x), jnp.asarray(qw),
                                        jnp.asarray(sw), mode="dynamic"))
    sx = np.maximum(np.abs(x).max(axis=1, keepdims=True), 1e-12) / 127.0
    qx = np.clip(np.round(x / sx), -127, 127).astype(np.int64)
    ref = (qx @ qw.astype(np.int64)).astype(np.float64) * sx * sw
    np.testing.assert_allclose(dyn, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pallas_kernel_parity_interpret():
    """Pallas fused kernels vs the XLA fallback, interpreter mode (the
    CPU-drivable half of the kernel contract; slow-marked per the
    ISSUE's budget allowance — the XLA int8 fallback is the tier-1
    CPU coverage via test_dequant_matmul_xla_fallback_numerics and
    every pass/serving test)."""
    from paddle_tpu.ops.pallas import quant_matmul as qm

    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    x = rng.randn(5, 130).astype(np.float32)     # ragged everything
    w = (rng.randn(130, 200) * 0.05).astype(np.float32)
    sw = (np.abs(w).max(axis=0) / 127.0).astype(np.float32)
    qw = np.clip(np.round(w / sw), -127, 127).astype(np.int8)
    wo = np.asarray(qm.dequant_matmul(jnp.asarray(x), jnp.asarray(qw),
                                      jnp.asarray(sw), interpret=True))
    np.testing.assert_allclose(wo, x @ (qw.astype(np.float32) * sw),
                               rtol=1e-5, atol=1e-5)
    dyn = np.asarray(qm.dequant_matmul(jnp.asarray(x), jnp.asarray(qw),
                                       jnp.asarray(sw), mode="dynamic",
                                       interpret=True))
    sx = np.maximum(np.abs(x).max(axis=1, keepdims=True), 1e-12) / 127.0
    qx = np.clip(np.round(x / sx), -127, 127).astype(np.int64)
    ref = (qx @ qw.astype(np.int64)).astype(np.float64) * sx * sw
    np.testing.assert_allclose(dyn, ref, rtol=1e-5, atol=1e-5)
    # bf16 activations: int8 values are exact in bf16's mantissa? No —
    # the kernel upcasts to f32 BEFORE the dot, so bf16 x only loses
    # its own input precision
    xb = jnp.asarray(x, jnp.bfloat16)
    wob = np.asarray(qm.dequant_matmul(xb, jnp.asarray(qw),
                                       jnp.asarray(sw), interpret=True))
    ref_b = np.asarray(xb, np.float32) @ (qw.astype(np.float32) * sw)
    np.testing.assert_allclose(wob, ref_b, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# per-channel fake-quant (QAT grid parity satellite)
# ---------------------------------------------------------------------------

def test_fake_quantize_abs_max_per_channel():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[-1, 6],
                              append_batch_size=False)
        block = main.global_block()
        out = block.create_var(name="q", dtype="float32")
        scale = block.create_var(name="qs", dtype="float32")
        block.append_op(
            type="fake_quantize_abs_max", inputs={"X": [x]},
            outputs={"Out": [out], "OutScale": [scale]},
            attrs={"bit_length": 8, "quant_axis": 0})
    assert block.var("qs").shape == (-1,) or block.var("qs").shape[0] in \
        (-1, 6)   # -1 rows: channel count resolves at run time
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    xv = np.array([[0.5, -1.0, 2.0, 0.1, -0.2, 4.0],
                   [0.25, 0.5, -1.0, 0.05, 0.1, -2.0]], "float32")
    with fluid.scope_guard(sc):
        exe.run(startup)
        q, s = exe.run(main, feed={"x": xv}, fetch_list=["q", "qs"])
    # per-row (axis 0) grids: each row's scale is its own abs max
    np.testing.assert_allclose(np.asarray(s),
                               np.abs(xv).max(axis=1), rtol=1e-6)
    ref = np.round(xv / np.asarray(s)[:, None] * 127) \
        * np.asarray(s)[:, None] / 127
    np.testing.assert_allclose(np.asarray(q), ref, rtol=1e-5, atol=1e-6)


def test_qat_per_channel_weight_grid_matches_pass():
    """QuantizeTranspiler(weight_quant_axis='auto') trains against the
    SAME per-output-channel grid quantize_inference deploys."""
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    qt = QuantizeTranspiler(weight_quant_axis="auto")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        pred = fluid.layers.fc(x, size=8, act="softmax")
        n = qt.training_transpile(main, startup)
        assert n >= 2
    fq = [op for op in main.global_block().ops
          if op.type == "fake_quantize_abs_max"
          and op.inputs["X"][0] == "fc_0.w_0"]
    assert fq and fq[0].attrs.get("quant_axis") == 1
    scale_var = main.global_block().var(fq[0].outputs["OutScale"][0])
    assert scale_var.shape == (8,)     # one grid per output channel
    scope = fluid.Scope()
    exe = _init(startup, scope)
    with fluid.scope_guard(scope):
        (p,) = exe.run(main, feed={"x": np.random.RandomState(0)
                                   .rand(4, 16).astype("float32")},
                       fetch_list=[pred])
        assert np.isfinite(np.asarray(p)).all()
        # convert_to_int8 honors the per-channel axis
        conv = qt.convert_to_int8(main, scope=scope)
        q8 = np.asarray(scope.var("fc_0.w_0.int8"))
        s8 = np.asarray(scope.var("fc_0.w_0.int8_scale"))
        assert q8.dtype == np.int8 and s8.shape == (8,)
        w = np.asarray(scope.var("fc_0.w_0"))
        np.testing.assert_allclose(q8 * (s8 / 127.0), w,
                                   atol=float(s8.max()) / 100)
        assert "fc_0.w_0" in conv


def test_pass_consumes_qat_out_scale_as_calibration():
    """A frozen QAT program deploys on the TRAINED running envelope —
    the pass consumes it instead of re-measuring, and the weight-side
    fake-quant op disappears from the rewritten program."""
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    qt = QuantizeTranspiler(weight_quantize_type="range_abs_max",
                            activation_quantize_type="range_abs_max")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax")
        qt.training_transpile(main, startup)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    scope = fluid.Scope()
    exe = _init(startup, scope)
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        for _ in range(3):
            exe.run(main, feed={
                "x": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 4, (8, 1)).astype("int64")},
                fetch_list=[loss])
        trained = float(np.asarray(scope.var("fc_0.w_0.scale"))[0])
        assert trained > 0
        # the inference subgraph (freeze + prune, what
        # save_inference_model ships) is what the pass quantizes
        frozen = qt.freeze_program(main, fluid.CPUPlace(), scope=scope) \
            .prune_feed_fetch(["x"], [pred.name])
        q = quantize_inference(frozen, scope=scope, mode="weight_only")
        info = q._quantize_info
        assert info["weights"]["fc_0.w_0"]["calibration"] == \
            "qat_out_scale"
        # deployed grid == trained envelope / 127 (broadcast)
        sw = np.asarray(scope.var("fc_0.w_0" + SCALE_SUFFIX))
        np.testing.assert_allclose(sw, trained / 127.0, rtol=1e-6)
        # the weight-side fake-quant is consumed; activation-side stays
        fq_inputs = [op.inputs["X"][0]
                     for op in q.global_block().ops
                     if op.type.startswith("fake_quantize")]
        assert "fc_0.w_0" not in fq_inputs
        feed = {"x": rng.rand(4, 16).astype("float32"),
                "label": np.zeros((4, 1), "int64")}
        (ref,) = exe.run(frozen, feed=feed, fetch_list=[pred.name],
                         scope=scope)
        (out,) = exe.run(q, feed=feed, fetch_list=[pred.name],
                         scope=scope)
        assert autotune.eval_delta([ref], [out]) < 0.05


def test_dynamic_mode_consumes_qat_activation_scale():
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    qt = QuantizeTranspiler(activation_quantize_type="range_abs_max")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax")
        qt.training_transpile(main, startup)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    scope = fluid.Scope()
    exe = _init(startup, scope)
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(main, feed={
            "x": rng.rand(8, 16).astype("float32"),
            "label": rng.randint(0, 4, (8, 1)).astype("int64")},
            fetch_list=[loss])
        frozen = qt.freeze_program(main, fluid.CPUPlace(), scope=scope) \
            .prune_feed_fetch(["x"], [pred.name])
        q = quantize_inference(frozen, scope=scope, mode="dynamic")
        dq = [op for op in q.global_block().ops
              if op.type == "dequant_matmul"]
        assert dq and dq[0].inputs.get("XScale") == ["x.scale"]
        (out,) = exe.run(q, feed={"x": rng.rand(4, 16).astype(
            "float32")}, fetch_list=[pred.name], scope=scope)
        assert np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# save/load round trip + warm-path lowerings
# ---------------------------------------------------------------------------

def test_save_load_round_trip_cold_and_zero_warm_lowerings(tmp_path):
    from jax._src import test_util as jtu

    main, startup, pred = _fc_program()
    scope = fluid.Scope()
    exe = _init(startup, scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 64).astype("float32")}
    d = str(tmp_path / "model")
    with fluid.scope_guard(scope):
        (ref,) = exe.run(main, feed=feed, fetch_list=[pred])
        q = quantize_inference(main, scope=scope, mode="weight_only")
        fluid.io.save_inference_model(
            d, ["x"], [q.global_block().var(pred.name)], exe,
            main_program=q)
    # the artifact ships int8 persistables and DROPS the fp masters
    import json

    mm = json.load(open(os.path.join(d, "__model__")))
    names = [v["name"] for b in mm["program"]["blocks"]
             for v in b["vars"]]
    assert any(n.endswith(QUANT_SUFFIX) for n in names)
    assert "fc_0.w_0" not in names
    # cold load runs quantized with no re-calibration
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog2, feeds, fetches = fluid.io.load_inference_model(d, exe)
        assert "dequant_matmul" in [op.type
                                    for op in prog2.global_block().ops]
        (out,) = exe.run(prog2, feed=feed, fetch_list=fetches)
        assert autotune.eval_delta([ref], [out]) < 0.02
        # warm serving path: a second dispatch of the same signature
        # performs ZERO lowerings
        with jtu.count_jit_and_pmap_lowerings() as n:
            (out2,) = exe.run(prog2, feed=feed, fetch_list=fetches)
        assert n[0] == 0, n[0]
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ---------------------------------------------------------------------------
# the accuracy gate
# ---------------------------------------------------------------------------

def test_tune_quantization_picks_mode_and_records_evidence():
    main, startup, pred = _fc_program()
    scope = fluid.Scope()
    _init(startup, scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 64).astype("float32")}
    cfg = autotune.TunedConfig(meta={"model": "test"})
    with fluid.scope_guard(scope):
        d = autotune.tune_quantization(
            main, scope, feed, [pred], fluid.CPUPlace(),
            probe_steps=2, min_speedup=0.0, config=cfg)
    assert d["chosen"] in ("weight_only", "dynamic")
    assert d["accuracy_delta"] <= d["accuracy_budget"]
    assert {c["mode"] for c in d["candidates"]} == \
        {"weight_only", "dynamic"}
    for c in d["candidates"]:
        assert "accuracy_delta" in c and "step_s" in c
    # evidence landed in the TunedConfig artifact
    got = cfg.get("quantization")
    assert got is not None and got["chosen"] == d["chosen"]
    assert got["evidence"] == "measured_ab_window+eval_delta"


def test_tune_quantization_rejects_corrupted_scales_keeps_fp():
    """Acceptance drill: a deliberately accuracy-broken quantization
    (injected scale corruption) is rejected and full precision kept,
    with the rejection recorded as TunedConfig evidence."""
    main, startup, pred = _fc_program()
    scope = fluid.Scope()
    _init(startup, scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 64).astype("float32")}
    cfg = autotune.TunedConfig(meta={"model": "test"})
    with fluid.scope_guard(scope):
        qbad = quantize_inference(main, scope=scope, mode="weight_only")
        sname = "fc_0.w_0" + SCALE_SUFFIX
        scope.set_var(sname, np.asarray(scope.var(sname)) * 100.0)
        d = autotune.tune_quantization(
            main, scope, feed, [pred], fluid.CPUPlace(),
            probe_steps=2, min_speedup=0.0,
            candidates=[("weight_only", qbad)], config=cfg)
    assert d["chosen"] is None          # full precision kept
    (cand,) = d["candidates"]
    assert cand["status"] == "rejected_accuracy"
    assert cand["accuracy_delta"] > d["accuracy_budget"]
    got = cfg.get("quantization")
    assert got["chosen"] is None
    assert got["candidates"][0]["status"] == "rejected_accuracy"


def test_tune_quantization_pinned_mode_wins():
    main, startup, pred = _fc_program()
    scope = fluid.Scope()
    _init(startup, scope)
    feed = {"x": np.random.RandomState(0).rand(4, 64).astype("float32")}
    from paddle_tpu import flags as _flags

    was_pinned = _flags.pinned("quantize_mode")
    fluid.set_flags({"FLAGS_quantize_mode": "off"})   # pins
    try:
        cfg = autotune.TunedConfig(meta={})
        with fluid.scope_guard(scope):
            d = autotune.tune_quantization(
                main, scope, feed, [pred], fluid.CPUPlace(), config=cfg)
        assert d["chosen"] is None and d["evidence"] == "pinned"
        assert cfg.get("quantization")["source"] == "pinned"
    finally:
        _flags.set_flags({"quantize_mode": ""}, pin=False)
        _flags._restore_pins({"quantize_mode": was_pinned})


def test_decide_quantization_pure_policy():
    cands = [
        {"mode": "weight_only", "accuracy_delta": 0.001, "step_s": 0.5},
        {"mode": "dynamic", "accuracy_delta": 0.5, "step_s": 0.2},
        {"mode": "broken", "rejected": "error: boom"},
    ]
    d = autotune.decide_quantization(1.0, cands, budget=0.02,
                                     min_speedup=1.0, batch=10)
    assert d["chosen"] == "weight_only"
    by_mode = {c["mode"]: c for c in d["candidates"]}
    assert by_mode["dynamic"]["status"] == "rejected_accuracy"
    assert by_mode["weight_only"]["status"] == "ok"
    assert "status" not in by_mode["broken"]
    assert d["chosen_tok_s"] == 20.0 and d["fp_tok_s"] == 10.0
    # a candidate under budget but SLOWER than fp is rejected too
    d2 = autotune.decide_quantization(
        1.0, [{"mode": "weight_only", "accuracy_delta": 0.001,
               "step_s": 1.5}], budget=0.02)
    assert d2["chosen"] is None
    assert d2["candidates"][0]["status"] == "rejected_slower"


# ---------------------------------------------------------------------------
# kernel decision table
# ---------------------------------------------------------------------------

def test_quant_kernel_table_and_choice(tmp_path):
    from paddle_tpu import flags as _flags

    autotune.reset_quant_kernel_table()
    # earlier suite tests may have left FLAGS_pallas_kernels PINNED
    # (set_flags defaults to pin=True); choice semantics under a pin
    # are asserted explicitly below, so start unpinned
    entry_pin = _flags.pinned("pallas_kernels")
    _flags._restore_pins({"pallas_kernels": False})
    try:
        table = autotune.AttentionDecisionTable(
            dirname=str(tmp_path), filename=autotune.QUANT_FILENAME)
        tok0 = autotune.trace_token()
        d = autotune.tune_quant_kernel(8, 128, 128, "float32",
                                       fluid.CPUPlace(), table=table)
        assert d["knob"] == "quant_kernel" and "pallas" in d
        key = autotune.quant_shape_key(8, 128, 128, "float32")
        assert table.lookup("", key) is not None
        # warm: the second call serves from the table, no measuring
        d2 = autotune.tune_quant_kernel(8, 128, 128, "float32",
                                        fluid.CPUPlace(), table=table)
        assert d2.get("cached") is True and d2["pallas"] == d["pallas"]
        # the ruling lives in the process table consulted at trace time
        autotune.quant_kernel_table().record("", key, True)
        assert autotune.quant_kernel_choice(8, 128, 128,
                                            "float32") is True
        # a mutated table re-keys the trace caches
        assert autotune.trace_token() != tok0
        # a pinned FLAGS_pallas_kernels beats the table
        was = _flags.pinned("pallas_kernels")
        fluid.set_flags({"FLAGS_pallas_kernels": False})
        try:
            assert autotune.quant_kernel_choice(8, 128, 128,
                                                "float32") is None
        finally:
            _flags.set_flags({"pallas_kernels": False}, pin=False)
            _flags._restore_pins({"pallas_kernels": was})
    finally:
        autotune.reset_quant_kernel_table()
        _flags._restore_pins({"pallas_kernels": entry_pin})


def test_tuned_config_applies_quant_kernel_rulings():
    from paddle_tpu import flags as _flags

    autotune.reset_quant_kernel_table()
    entry_pin = _flags.pinned("pallas_kernels")
    _flags._restore_pins({"pallas_kernels": False})
    try:
        key = autotune.quant_shape_key(16, 256, 256, "bfloat16")
        cfg = autotune.TunedConfig(decisions=[
            {"knob": "quant_kernel", "shape": key, "pallas": True},
            {"knob": "quantization", "chosen": "weight_only"}])
        outcomes = dict(cfg.apply())
        assert outcomes["quant_kernel"] == "applied"
        assert outcomes["quantization"] == "advisory"
        assert autotune.quant_kernel_choice(16, 256, 256,
                                            "bfloat16") is True
    finally:
        autotune.reset_quant_kernel_table()
        _flags._restore_pins({"pallas_kernels": entry_pin})


# ---------------------------------------------------------------------------
# serving wiring
# ---------------------------------------------------------------------------

def test_inference_engine_quantized_matches_fp(tmp_path):
    from paddle_tpu.serving import InferenceEngine

    main, startup, pred = _fc_program(d_in=32, d_h=64, d_out=8)
    scope = fluid.Scope()
    exe = _init(startup, scope)
    d = str(tmp_path / "model")
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            d, ["x"], [pred], exe, main_program=main)
        feed = {"x": rng.rand(4, 32).astype("float32")}
        (ref,) = exe.run(main, feed=feed, fetch_list=[pred])
    eng = InferenceEngine(model_dir=d, slots=4, timeout_s=60.0,
                          quantize="weight_only")
    try:
        assert eng.quantize_mode == "weight_only"
        assert "dequant_matmul" in [
            op.type for op in eng._program.global_block().ops]
        outs = np.stack([np.asarray(eng.run({"x": feed["x"][i]})[0])
                         for i in range(4)])
        assert autotune.eval_delta([np.asarray(ref)], [outs]) < 0.02
    finally:
        eng.close()


def test_inference_engine_consumes_tuned_quantization_ruling(tmp_path):
    from paddle_tpu.serving import InferenceEngine

    main, startup, pred = _fc_program(d_in=32, d_h=64, d_out=8)
    scope = fluid.Scope()
    exe = _init(startup, scope)
    d = str(tmp_path / "model")
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            d, ["x"], [pred], exe, main_program=main)
    cfg = autotune.TunedConfig(decisions=[
        {"knob": "quantization", "chosen": "weight_only"}])
    eng = InferenceEngine(model_dir=d, slots=4, timeout_s=60.0,
                          tuned_config=cfg)
    try:
        assert eng.quantize_mode == "weight_only"
    finally:
        eng.close()
    # a gate that KEPT full precision must not quantize
    cfg2 = autotune.TunedConfig(decisions=[
        {"knob": "quantization", "chosen": None}])
    eng2 = InferenceEngine(model_dir=d, slots=4, timeout_s=60.0,
                           tuned_config=cfg2)
    try:
        assert eng2.quantize_mode is None
    finally:
        eng2.close()


@pytest.mark.slow
def test_generation_engine_quantized_decode():
    """Slow-marked for the tier-1 wall budget (the serving decode
    parity precedent); the DecoderSpec.quantize rewrite itself is
    cheap and the InferenceEngine wiring stays tier-1."""
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.serving.decoder import build_decoder_lm

    spec = build_decoder_lm(vocab_size=32, max_len=32, slots=4,
                            n_layer=1, n_head=2, d_model=16, d_inner=32,
                            seed=11, prefix="qlm")
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=4, record_logits=True,
                           quantize="weight_only", start=True)
    try:
        assert eng.quantize_mode == "weight_only"
        types = [op.type
                 for op in eng.spec.decode_program.global_block().ops]
        assert "dequant_matmul" in types
        r = eng.generate([3, 5, 7], timeout=120)
        assert len(r["tokens"]) == 4
        assert all(np.isfinite(row).all() for row in r["logits"])
        # int8 decode working set: the quantized weights really are
        # 1/4 the bytes of the f32 masters
        info = eng.spec.decode_program._quantize_info
        assert info["weights"]
        for w in info["weights"].values():
            assert w["bytes_int8"] * 4 == w["bytes_fp"]
    finally:
        eng.close()


def test_predictor_enable_quantization(tmp_path):
    from paddle_tpu.inference import (AnalysisConfig,
                                      create_paddle_predictor)

    main, startup, pred = _fc_program(d_in=32, d_h=64, d_out=8)
    scope = fluid.Scope()
    exe = _init(startup, scope)
    d = str(tmp_path / "model")
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            d, ["x"], [pred], exe, main_program=main)
    base = create_paddle_predictor(
        AnalysisConfig(model_dir=d, use_gpu=False))
    quant = create_paddle_predictor(
        AnalysisConfig(model_dir=d,
                       use_gpu=False).enable_quantization())
    xv = rng.rand(2, 32).astype("float32")
    (ref,) = base.run({"x": xv})
    (out,) = quant.run({"x": xv})
    assert autotune.eval_delta([ref.data], [out.data]) < 0.02
    # clones share the quantized program
    clone = quant.clone()
    (outc,) = clone.run({"x": xv})
    np.testing.assert_array_equal(out.data, outc.data)


# ---------------------------------------------------------------------------
# the bench rung acceptance: quantized beats bf16 at accuracy parity
# ---------------------------------------------------------------------------

def test_bench_quantized_rung_beats_bf16_under_budget():
    """ISSUE 14 acceptance: the quantized forward rung's tok/s beats
    the bf16 rung's with the accuracy delta under the configured
    budget — the gate predicate itself is the assertion."""
    import argparse
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    args = argparse.Namespace(model="quantized", device="cpu",
                              batch_size=0, iterations=3,
                              skip_batch_num=2)
    old_windows = bench.N_WINDOWS
    bench.N_WINDOWS = 2   # tier-1 wall-clock: 2 interleaved A/B windows
    try:
        r = bench.bench_quantized(args)
    finally:
        bench.N_WINDOWS = old_windows
    assert r["unit"] == "tokens/sec" and r["value"] > 0
    # the acceptance predicate: faster than bf16 AND delta under budget
    assert r["value"] > r["bf16_tok_s"], (r["value"], r["bf16_tok_s"])
    assert r["accuracy_delta"] <= r["accuracy_budget"], r
    assert r["gate_pass"] is True
    # evidence: the TunedConfig trail is embedded, weight bytes shrank
    knobs = [d["knob"] for d in r["autotune"]["decisions"]]
    assert "quantization" in knobs
    assert r["weight_bytes_int8"] * 4 == r["weight_bytes_fp"]
    assert r["min_step_s"] < r["bf16_min_step_s"]
