"""Profile-guided auto-configuration (ISSUE 9): the decision functions
as pure functions of synthetic measurements, the TunedConfig artifact,
pin semantics, probe-accounting exclusion, and the CPU-drivable tuner
loops (the batch ladder's rejection mechanism is the compiled module's
own peak-HBM estimate against a fake ``FLAGS_autotune_hbm_bytes``
ceiling — never an OOM — which is exactly what makes these tests
hardware-free)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import autotune, compile_cache, flags, monitor
from paddle_tpu.monitor import program_profile


@pytest.fixture(autouse=True)
def _clean_autotune_state():
    autotune.reset_attention_table()
    prev_pins = {n: flags.pinned(n)
                 for n in ("pallas_kernels", "pallas_attention_max_seq",
                           "autotune_hbm_bytes", "autotune_dir")}
    yield
    fluid.set_flags({"FLAGS_autotune_hbm_bytes": 0,
                     "FLAGS_autotune_dir": "",
                     "FLAGS_pallas_kernels": False}, pin=False)
    flags._restore_pins(prev_pins)
    autotune.reset_attention_table()
    program_profile.reset()
    if monitor.enabled():
        monitor.disable()
        monitor.registry().reset()


# ---------------------------------------------------------------------------
# batch-size ladder (pure)
# ---------------------------------------------------------------------------

def test_batch_ladder_geometric():
    assert autotune.batch_ladder(32, 256) == [32, 64, 128, 256]
    assert autotune.batch_ladder(48, 100) == [48, 96]


def test_ladder_stops_at_projected_hbm_ceiling():
    """Once two rungs' probed peaks fit a line, an over-ceiling
    projection stops the ladder WITHOUT spending that rung's compile."""
    probed, measured = [], []

    def probe(b):
        probed.append(b)
        return 1000 * b          # affine in batch

    def measure(b):
        measured.append(b)
        return 0.001 * b ** 0.9  # s/example improves monotonically

    d = autotune.run_batch_ladder([16, 32, 64, 128], hbm_limit=70000,
                                  probe_fn=probe, measure_fn=measure,
                                  headroom=0.9)
    # 16k/32k probed fine; 64's projection (64k > 63k ceiling) stops it
    assert probed == [16, 32]
    assert measured == [16, 32]
    assert d["chosen"] == 32
    last = d["candidates"][-1]
    assert last["status"] == "rejected_projected_hbm"
    assert last["batch"] == 64
    assert last["projected_peak_hbm_bytes"] == pytest.approx(64000, rel=.01)
    # the projection rejection spent neither a compile nor a window
    assert "step_s" not in last


def test_ladder_rejects_probed_peak_before_any_dispatch():
    """A rung whose PROBED estimate exceeds the ceiling never gets a
    measurement window — rejection is the estimate, not an OOM."""
    measured = []
    # a nonlinear peak curve defeats the projection, forcing the probe
    peaks = {16: 10_000, 32: 80_000}

    d = autotune.run_batch_ladder(
        [16, 32], hbm_limit=70_000, probe_fn=lambda b: peaks[b],
        measure_fn=lambda b: measured.append(b) or 0.0001 * b,
        headroom=1.0)
    assert measured == [16]
    assert d["candidates"][-1]["status"] == "rejected_hbm"
    assert d["chosen"] == 16


def test_ladder_throughput_regression_stop():
    """The PERF.md b512-not-b1024 shape: seconds-per-example improves,
    plateaus, then regresses — the ladder stops at the regression and
    picks the best measured rung."""
    spe = {16: 10.0, 32: 6.0, 64: 4.0, 128: 4.1, 256: 6.0, 512: 9.9}
    d = autotune.run_batch_ladder(
        sorted(spe), hbm_limit=None, probe_fn=lambda b: None,
        measure_fn=lambda b: spe[b] * b, regress_tol=0.05)
    assert d["chosen"] == 64
    statuses = [c["status"] for c in d["candidates"]]
    # 128 is within tolerance of 64 (measured, kept); 256 regresses
    assert statuses == ["ok", "ok", "ok", "ok", "regressed"]
    assert d["candidates"][-1]["batch"] == 256


def test_ladder_no_limit_measures_every_rung():
    d = autotune.run_batch_ladder(
        [8, 16], hbm_limit=None, probe_fn=lambda b: 100 * b,
        measure_fn=lambda b: 0.001 * b)
    assert [c["status"] for c in d["candidates"]] == ["ok", "ok"]
    # equal seconds-per-example: the tie keeps the SMALLER batch (same
    # throughput, less memory headroom consumed)
    assert d["chosen"] == 8
    assert d["hbm_limit_bytes"] is None


# ---------------------------------------------------------------------------
# attention kernel + bucket bounds (pure)
# ---------------------------------------------------------------------------

def test_decide_attention_kernel_thresholds():
    assert autotune.decide_attention_kernel(0.010, 0.006)["pallas"]
    # a tie (or anything under min_speedup) goes to XLA
    assert not autotune.decide_attention_kernel(0.010, 0.010)["pallas"]
    assert not autotune.decide_attention_kernel(0.010, 0.0099)["pallas"]
    d = autotune.decide_attention_kernel(0.012, 0.004, min_speedup=1.1)
    assert d["pallas"] and d["speedup"] == pytest.approx(3.0)


def _wmt16_like_lengths():
    """The bench's realistic skewed mix: lognormal lengths clipped to
    [4, 64] (bench_transformer_realdist's distribution)."""
    rng = np.random.RandomState(7)
    return np.clip(rng.lognormal(3.2, 0.55, size=4000), 4,
                   64).astype(int).tolist()


def test_token_fill_and_4_not_6_outcome():
    """The PERF.md r4 ruling reproduced: six finer-but-ragged bounds
    have HIGHER fill than the four MXU-friendly ones, yet the chooser —
    hardware-friendly multiples first — returns the four."""
    lengths = _wmt16_like_lengths()
    friendly = [16, 32, 48, 64]
    ragged6 = [12, 20, 28, 36, 48, 64]
    assert autotune.token_fill(lengths, ragged6) > \
        autotune.token_fill(lengths, friendly)
    d = autotune.choose_bucket_bounds(lengths, k=6, multiple=16)
    assert d["chosen"] == friendly
    assert d["fill"] == pytest.approx(
        autotune.token_fill(lengths, friendly), abs=1e-3)
    # and the 4 bounds beat pad-to-max decisively (the 1.94x shape)
    assert d["fill"] > 1.5 * d["pad_to_max_fill"]


def test_choose_bucket_bounds_k_subsets():
    # mass only near 16 and 64: two bounds suffice, the chooser finds
    # the right pair out of the candidate multiples
    lengths = {14: 100, 16: 100, 60: 10, 64: 10}
    d = autotune.choose_bucket_bounds(lengths, k=2, multiple=16)
    assert d["chosen"] == [16, 64]
    # top bound always covers the max length, rounded up to a multiple
    d = autotune.choose_bucket_bounds({5: 3, 33: 1}, k=1, multiple=16)
    assert d["chosen"] == [48]


# ---------------------------------------------------------------------------
# checkpoint interval (pure)
# ---------------------------------------------------------------------------

def test_checkpoint_interval_monotone_in_save_cost():
    """The formula is monotone non-decreasing in every measured cost —
    the ISSUE's stated unit property."""
    prev = 0
    for save_s in (0.01, 0.1, 0.5, 2.0, 5.0):
        d = autotune.decide_checkpoint_interval(
            step_s=0.1, snapshot_s=0.01, save_s=save_s, budget=0.035)
        assert d["chosen"] >= prev
        prev = d["chosen"]
    prev = 0
    for snap_s in (0.001, 0.01, 0.05, 0.2):
        d = autotune.decide_checkpoint_interval(
            step_s=0.1, snapshot_s=snap_s, save_s=0.0, budget=0.035)
        assert d["chosen"] >= prev
        assert d["overhead_frac"] <= 0.035 + 1e-9
        prev = d["chosen"]


def test_checkpoint_interval_drain_and_sync_modes():
    # async: the on-step cost is the snapshot only, but the write must
    # drain inside the interval
    d = autotune.decide_checkpoint_interval(
        step_s=0.1, snapshot_s=0.001, save_s=2.0, budget=0.035)
    assert d["chosen"] == 20 and d["drain_bound_steps"] == 20
    # sync: the whole write lands on the step path
    d_sync = autotune.decide_checkpoint_interval(
        step_s=0.1, snapshot_s=0.001, save_s=2.0, budget=0.035,
        async_save=False)
    assert d_sync["chosen"] > 500
    assert d_sync["overhead_frac"] <= 0.035 + 1e-9
    with pytest.raises(ValueError):
        autotune.decide_checkpoint_interval(0.0, 0.01, 0.01)


# ---------------------------------------------------------------------------
# TunedConfig artifact + pinning
# ---------------------------------------------------------------------------

def test_tuned_config_round_trip(tmp_path):
    cfg = autotune.TunedConfig(meta={"model": "t"})
    cfg.add({"knob": "batch_size", "chosen": 512,
             "candidates": [{"batch": 512, "status": "ok"}]},
            fingerprint="abcdef012345")
    cfg.add(autotune.decide_checkpoint_interval(0.02, 0.002, 0.01))
    path = cfg.save(str(tmp_path / "tuned.json"))
    loaded = autotune.TunedConfig.load(path)
    assert loaded.value("batch_size") == 512
    assert loaded.value("checkpoint_interval") == cfg.value(
        "checkpoint_interval")
    assert loaded.meta["model"] == "t"
    assert loaded.get("batch_size")["fingerprint"] == "abcdef012345"
    # latest-wins on duplicate knobs
    loaded.add({"knob": "batch_size", "chosen": 256})
    assert loaded.value("batch_size") == 256
    # the raw artifact is plain JSON (the report tool's contract)
    doc = json.loads(open(path).read())
    assert doc["meta"]["version"] == autotune.TunedConfig.VERSION


def test_pinned_flag_beats_tuned_attention_decision():
    """A user-set FLAGS_pallas_kernels always wins over the decision
    table: attention_choice returns None (flag rules), and apply()
    records the pin instead of installing."""
    q = k = (2, 2, 32, 16)
    key = autotune.attention_shape_key(q, k, "float32")
    autotune.attention_table().record("fp", key, True, persist=False)
    assert autotune.attention_choice(q, k, "float32") is True
    # the user pins the flag: the table is ignored
    fluid.set_flags({"FLAGS_pallas_kernels": False})     # pin=True
    assert flags.pinned("pallas_kernels")
    assert autotune.attention_choice(q, k, "float32") is None
    cfg = autotune.TunedConfig()
    cfg.decisions.append({"knob": "attention_kernel", "shape": key,
                          "pallas": True})
    assert ("attention_kernel", "pinned") in cfg.apply()
    # unpinned again: the ruling applies
    flags._restore_pins({"pallas_kernels": False})
    assert autotune.attention_choice(q, k, "float32") is True
    assert ("attention_kernel", "applied") in cfg.apply()


def test_attention_table_persists_and_rekeys_traces(tmp_path):
    fluid.set_flags({"FLAGS_autotune_dir": str(tmp_path)}, pin=False)
    t0 = compile_cache.trace_flag_values()
    key = autotune.attention_shape_key((1, 1, 64, 16), (1, 1, 64, 16),
                                       "float32")
    autotune.attention_table().record("fp1", key, True)
    # a new ruling re-keys every trace/AOT cache entry
    assert compile_cache.trace_flag_values() != t0
    assert os.path.exists(
        str(tmp_path / autotune.AttentionDecisionTable.FILENAME))
    # a cold process (fresh table) reads the persisted ruling
    autotune.reset_attention_table()
    e = autotune.attention_table().lookup("fp1", key)
    assert e is not None and e["pallas"] is True
    # shape-level fallback: another program's same shape gets the ruling
    assert autotune.attention_table().lookup("other", key)["pallas"]
    # and the OP-level chooser lazily activates the persisted table off
    # the dir flag alone — a fresh process with FLAGS_autotune_dir set
    # serves warm rulings without ever invoking the tuner
    autotune.reset_attention_table()
    assert autotune.attention_choice((1, 1, 64, 16), (1, 1, 64, 16),
                                     "float32") is True


# ---------------------------------------------------------------------------
# probe accounting (satellite bugfix)
# ---------------------------------------------------------------------------

def test_probe_accounting_excluded_from_report():
    program_profile.reset()
    with program_profile.probe_accounting():
        assert program_profile.probe_active()
        program_profile.note_step("probefp", 5.0, 32)
    program_profile.note_step("steadyfp", 1.0, 32)
    rows = {(r["fingerprint"], bool(r.get("probe"))): r
            for r in program_profile.report_rows() if r["steps"]}
    assert rows[("probefp", True)]["wall_share"] == 0.0
    assert rows[("probefp", True)]["mfu"] is None
    # the steady row owns 100% of the (non-probe) wall clock even
    # though the probe burned 5x its time
    assert rows[("steadyfp", False)]["wall_share"] == 1.0
    table = program_profile.render_table(
        program_profile.report_rows())
    assert "probe:" in table


def test_probe_work_never_blends_into_steady_row():
    """A tuner probing the SAME fingerprint the run then trains: probe
    wall clock lands in its own flagged row — the steady row's share
    and step count exclude it entirely."""
    program_profile.reset()
    with program_profile.probe_accounting():
        for _ in range(5):
            program_profile.note_step("fp", 2.0, 8)      # 10s of probes
    program_profile.note_step("fp", 1.0, 8)              # 1s steady
    rows = [r for r in program_profile.report_rows() if r["steps"]]
    assert len(rows) == 2
    steady = next(r for r in rows if not r.get("probe"))
    probe = next(r for r in rows if r.get("probe"))
    assert steady["fingerprint"] == probe["fingerprint"] == "fp"
    assert steady["steps"] == 1 and steady["wall_s"] == 1.0
    assert steady["wall_share"] == 1.0
    assert probe["steps"] == 5 and probe["wall_s"] == 10.0
    assert probe["wall_share"] == 0.0 and probe["mfu"] is None


# ---------------------------------------------------------------------------
# CPU-driven tuner loops
# ---------------------------------------------------------------------------

def _toy_mlp():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    img = fluid.layers.data("img", shape=[784])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=64, act="relu")
    pred = fluid.layers.fc(h, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)

    def make_feed(b):
        return {"img": rng.rand(b, 784).astype("float32"),
                "label": rng.randint(0, 10, (b, 1)).astype("int64")}
    return loss, make_feed


def test_tune_batch_size_fake_hbm_limit_and_zero_extra_compiles():
    """The CPU-drivable ladder: a fake FLAGS_autotune_hbm_bytes ceiling
    rejects by ESTIMATE (the documented mechanism), probe compiles are
    exactly the declared ladder (one per probed rung, trace-cache
    counted), and re-measuring the chosen rung afterwards performs zero
    further lowerings (the window dispatches the seeded executable)."""
    from jax._src import test_util as jtu

    from paddle_tpu.executor import Executor
    from paddle_tpu.scope import Scope, scope_guard

    loss, make_feed = _toy_mlp()
    fluid.set_flags({"FLAGS_autotune_hbm_bytes": 2_000_000}, pin=False)
    # warm the one-time machinery OUTSIDE the count (startup lowering,
    # jax.random key jits, device_put paths) — and the start rung's own
    # profile, which the tuner then serves from the registry for free
    warm_scope = Scope()
    with scope_guard(warm_scope):
        exe = Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program(), scope=warm_scope)
        exe.cost_analysis(fluid.default_main_program(), make_feed(16),
                          [loss], scope=warm_scope)
        autotune.measure_step_window(
            exe, fluid.default_main_program(), make_feed(16), [loss],
            steps=1, scope=warm_scope)
    cfg = autotune.TunedConfig()
    # regress_tol effectively off: step timing on a loaded CI box is
    # noisy enough to fire the (pure-function-tested) regression stop
    # before the ladder reaches the ceiling — this test pins the MEMORY
    # path, so the ladder must climb until the estimate rejects
    with jtu.count_jit_and_pmap_lowerings() as n:
        d = autotune.tune_batch_size(
            fluid.default_main_program(),
            fluid.default_startup_program(), make_feed, loss,
            fluid.CPUPlace(), start=16, max_batch=4096, probe_steps=2,
            regress_tol=1e9, config=cfg)
    probed = [c for c in d["candidates"] if "peak_hbm_bytes" in c]
    rejected = [c for c in d["candidates"]
                if str(c["status"]).startswith("rejected")]
    # the fake 2 MB ceiling stopped the ladder before max_batch
    assert rejected, d["candidates"]
    assert d["chosen"] is not None
    assert d["hbm_limit_bytes"] == 2_000_000
    # every rejection happened via the estimate, never a dispatch
    for c in rejected:
        assert "step_s" not in c
    # zero compiles beyond the declared probe ladder: one lowering per
    # NEW probed rung (the cost_analysis explicit compile, whose
    # executable the measured window then dispatches); the pre-warmed
    # b16 rung and the startup program re-lower nothing
    assert n[0] == len(probed) - 1, (n[0], d)
    # warm re-measure of the chosen batch in a fresh scope/executor:
    # the trace cache + seeded AOT slot serve it, zero new lowerings
    from paddle_tpu.executor import Executor
    from paddle_tpu.scope import Scope, scope_guard

    scope = Scope()
    with scope_guard(scope):
        exe = Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program(), scope=scope)
        with jtu.count_jit_and_pmap_lowerings() as n2:
            autotune.measure_step_window(
                exe, fluid.default_main_program(),
                make_feed(d["chosen"]), [loss], steps=2, scope=scope)
    assert n2[0] == 0, n2[0]
    # the decision landed in the config with provenance
    assert cfg.value("batch_size") == d["chosen"]
    assert cfg.get("batch_size")["fingerprint"]


def test_tune_batch_size_twice_warm_registry_same_peaks():
    """Second tune in one process: probes are served from the warm
    profile registry, and each rung must get ITS OWN signature's peak —
    not the newest-captured profile (which would be the first run's
    largest rung, instantly mis-rejecting the ladder's base)."""
    loss, make_feed = _toy_mlp()
    fluid.set_flags({"FLAGS_autotune_hbm_bytes": 2_000_000}, pin=False)
    kw = dict(start=16, max_batch=4096, probe_steps=1, regress_tol=1e9)
    d1 = autotune.tune_batch_size(
        fluid.default_main_program(), fluid.default_startup_program(),
        make_feed, loss, fluid.CPUPlace(), **kw)
    d2 = autotune.tune_batch_size(
        fluid.default_main_program(), fluid.default_startup_program(),
        make_feed, loss, fluid.CPUPlace(), **kw)
    peaks1 = {c["batch"]: c.get("peak_hbm_bytes")
              for c in d1["candidates"]}
    peaks2 = {c["batch"]: c.get("peak_hbm_bytes")
              for c in d2["candidates"]}
    assert peaks2 == peaks1
    assert d2["chosen"] is not None
    assert [c["status"] for c in d2["candidates"]] \
        == [c["status"] for c in d1["candidates"]]


def test_tune_attention_kernel_ab_and_warm_table(tmp_path):
    """The measured A/B picks XLA at tiny shapes on CPU (the Pallas
    kernel runs interpreted there), persists the ruling, and a warm
    tuner call serves it with zero compiles."""
    fluid.set_flags({"FLAGS_autotune_dir": str(tmp_path)}, pin=False)
    n_head, T, dh, b = 2, 32, 16, 4
    q = fluid.layers.data("q", shape=[n_head, T, dh])
    k = fluid.layers.data("k", shape=[n_head, T, dh])
    v = fluid.layers.data("v", shape=[n_head, T, dh])
    att = fluid.layers.fused_attention(q, k, v, causal=True)
    loss = fluid.layers.reduce_mean(att)
    fluid.optimizer.SGD(learning_rate=0.0).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {n: rng.rand(b, n_head, T, dh).astype("float32")
            for n in "qkv"}
    shape = ((b, n_head, T, dh), (b, n_head, T, dh), "float32")
    from jax._src import test_util as jtu

    cfg = autotune.TunedConfig()
    d = autotune.tune_attention_kernel(
        fluid.default_main_program(), fluid.default_startup_program(),
        feed, loss, fluid.CPUPlace(), shape=shape, probe_steps=2,
        config=cfg)
    # both arms really ran, and the ruling IS the measured comparison
    # (which kernel wins at toy CPU shapes is timing noise, not the
    # contract — the contract is measured-A/B-decides)
    assert d["xla_step_s"] > 0 and d["pallas_step_s"] > 0
    assert d["pallas"] == (
        d["xla_step_s"] / d["pallas_step_s"] >= d["min_speedup"])
    # the A/B restored the flags unpinned
    assert not flags.pinned("pallas_kernels")
    assert flags.flag("pallas_kernels") is False
    # warm process: fresh table object reads the persisted ruling and
    # the tuner pays nothing — zero lowerings, zero measurement
    autotune.reset_attention_table()
    with jtu.count_jit_and_pmap_lowerings() as n:
        d2 = autotune.tune_attention_kernel(
            fluid.default_main_program(),
            fluid.default_startup_program(), feed, loss,
            fluid.CPUPlace(), shape=shape, probe_steps=2)
    assert d2.get("cached") and d2["pallas"] == d["pallas"]
    assert n[0] == 0
    # and the op-level chooser serves the tuned ruling
    assert autotune.attention_choice(*shape) == d["pallas"]


def test_trainer_consumes_tuned_config(tmp_path):
    """Trainer(autotune=path): the tuned checkpoint interval re-gates
    the manager — unless the user pinned step_interval explicitly."""
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    from paddle_tpu.reader import checkpointable

    cfg = autotune.TunedConfig()
    cfg.add(autotune.decide_checkpoint_interval(
        step_s=0.02, snapshot_s=0.002, save_s=0.01, async_save=False))
    path = cfg.save(str(tmp_path / "tuned.json"))
    expect = cfg.value("checkpoint_interval")
    assert expect and expect != 10       # would mask the default

    def train_func():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = fluid.layers.fc(x, size=4, act="softmax")
        return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(16):
            yield (rng.rand(8).astype("float32"),
                   rng.randint(0, 4, (1,)).astype("int64"))

    losses = []

    def handler(ev):
        if hasattr(ev, "metrics"):
            losses.append(float(np.ravel(ev.metrics[0])[0]))

    # unpinned CheckpointConfig: the tuned cadence applies
    tr = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                 optimizer_func=lambda: fluid.optimizer.Adam(1e-3),
                 checkpoint_config=CheckpointConfig(
                     checkpoint_dir=str(tmp_path / "ck1"),
                     async_save=False),
                 autotune=path)
    assert tr.checkpoint_cfg.step_interval == expect
    assert tr._ckpt_mgr.save_interval_steps == expect
    tr.train(num_epochs=1, event_handler=handler,
             reader=checkpointable(fluid.batch(samples, batch_size=8)),
             feed_order=["x", "label"])
    assert losses and np.isfinite(losses[-1])

    # pinned step_interval: the user's cadence survives
    tr2 = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-3),
                  checkpoint_config=CheckpointConfig(
                      checkpoint_dir=str(tmp_path / "ck2"),
                      step_interval=5, async_save=False),
                  autotune=path)
    assert tr2.checkpoint_cfg.step_interval == 5
    assert tr2._ckpt_mgr.save_interval_steps == 5


def test_manager_measured_costs_and_tune(tmp_path):
    """The checkpoint manager's own cost samples feed the interval
    tuner (measured evidence, not a guess)."""
    from paddle_tpu.parallel.checkpoint import (
        TrainStateCheckpointManager)

    x = fluid.layers.data("x", shape=[4])
    loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    mgr = TrainStateCheckpointManager(str(tmp_path), async_save=False)
    assert mgr.measured_costs() == {}
    mgr.save(1, program=fluid.default_main_program(),
             executors={"train": exe})
    costs = mgr.measured_costs()
    assert costs["n"] == 1
    assert costs["snapshot_s"] > 0 and costs["save_s"] > 0
    d = autotune.tune_checkpoint_interval(step_s=0.05, manager=mgr,
                                          async_save=False)
    assert d["chosen"] >= 1 and d["measured_saves"] == 1
    mgr.set_interval(7)
    assert mgr.save_interval_steps == 7
    with pytest.raises(ValueError):
        autotune.tune_checkpoint_interval(manager=mgr)   # no step time


@pytest.mark.slow
def test_acceptance_tuner_matches_best_grid_point():
    """Acceptance: the tuner's chosen batch has measured
    step-time/example within tolerance of the best exhaustive grid
    point (the tuner finds what a full sweep finds, cheaper)."""
    loss, make_feed = _toy_mlp()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    place = fluid.CPUPlace()
    grid = [32, 64, 128, 256, 512]
    d = autotune.tune_batch_size(main, startup, make_feed, loss, place,
                                 ladder=list(grid), probe_steps=6,
                                 warmup_steps=2)
    assert d["chosen"] is not None
    # exhaustive grid sweep with the same measurement machinery
    from paddle_tpu.executor import Executor
    from paddle_tpu.scope import Scope, scope_guard

    sweep = {}
    scope = Scope()
    with scope_guard(scope), program_profile.probe_accounting():
        exe = Executor(place)
        exe.run(startup, scope=scope)
        for b in grid:
            feed = make_feed(b)
            exe.cost_analysis(main, feed, [loss], scope=scope)
            sweep[b] = autotune.measure_step_window(
                exe, main, feed, [loss], steps=6, warmup=2,
                scope=scope) / b
    best = min(sweep.values())
    # generous tolerance: CPU step timing under concurrent test load is
    # noisy; the claim is "the tuner lands in the right neighborhood",
    # not microbenchmark equality
    assert sweep[d["chosen"]] <= best * 1.6, (d["chosen"], sweep)


# ---------------------------------------------------------------------------
# pipeline schedule + microbatch tuning (ISSUE 12)
# ---------------------------------------------------------------------------

def test_decide_pipeline_fast_then_low_bubble():
    """Fastest wins outright; near-ties (within tol) settle by the
    schedule table's bubble fraction, then the memory bound."""
    cands = [
        {"schedule": "gpipe", "microbatches": 4, "step_s": 0.100,
         "bubble_fraction": 0.20, "in_flight": 7},
        {"schedule": "interleaved", "microbatches": 4, "step_s": 0.102,
         "bubble_fraction": 0.10, "in_flight": 11},
        {"schedule": "1f1b", "microbatches": 16, "step_s": 0.200,
         "bubble_fraction": 0.15, "in_flight": 7},
    ]
    d = autotune.decide_pipeline(cands, tol=0.05)
    assert d["chosen"] == {"schedule": "interleaved", "microbatches": 4}
    assert d["evidence"] == "measured_step_window"
    assert len(d["candidates"]) == 3
    # a decisive speed gap beats a nicer schedule table
    cands[0]["step_s"] = 0.05
    d2 = autotune.decide_pipeline(cands, tol=0.05)
    assert d2["chosen"]["schedule"] == "gpipe"
    # rejected/unmeasured candidates never win; all-rejected raises
    with pytest.raises(ValueError, match="no measured candidate"):
        autotune.decide_pipeline(
            [{"schedule": "gpipe", "microbatches": 2,
              "rejected": "peak_hbm"}])


def _pipelined_fc_program(stages=2, microbatches=2, size=8):
    x = fluid.layers.data("x", shape=[size])
    pipe = fluid.layers.Pipeline(microbatches=microbatches)
    for i in range(stages):
        with pipe.stage():
            c = pipe.carry(x if i == 0 else None)
            c = fluid.layers.fc(c, size=size, act="tanh")
            pipe.emit(c)
    out = pipe()
    loss = fluid.layers.mean(fluid.layers.square(out))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def test_tune_pipeline_pinned_schedule_skips_probes():
    """An explicit BuildStrategy.pipeline_schedule is the user's pin:
    recorded as such, zero candidates measured."""
    from paddle_tpu.parallel import make_mesh

    loss = _pipelined_fc_program()
    mesh = make_mesh((1, 2), ("dp", "pp"))
    bs = fluid.BuildStrategy()
    bs.pipeline_schedule = "1f1b"
    bs.pipeline_microbatches = 4
    cfg = autotune.TunedConfig()
    d = autotune.tune_pipeline(
        fluid.default_main_program(), fluid.default_startup_program(),
        {"x": np.zeros((8, 8), "float32")}, loss, mesh,
        build_strategy=bs, config=cfg)
    assert d["evidence"] == "pinned"
    assert d["chosen"] == {"schedule": "1f1b", "microbatches": 4}
    assert d["candidates"] == []
    assert cfg.get("pipeline")["source"] == "pinned"


def test_tune_pipeline_requires_pipelined_program():
    from paddle_tpu.parallel import make_mesh

    x = fluid.layers.data("x", shape=[4])
    loss = fluid.layers.mean(fluid.layers.fc(x, size=4))
    with pytest.raises(ValueError, match="no pipeline_region"):
        autotune.tune_pipeline(
            fluid.default_main_program(),
            fluid.default_startup_program(),
            {"x": np.zeros((4, 4), "float32")}, loss,
            make_mesh((1, 2), ("dp", "pp")))


def test_tune_pipeline_measures_and_records():
    """The measured path: one compile per candidate, decision +
    per-candidate evidence (step_s, bubble fraction, memory bound) in
    the TunedConfig artifact; probe steps ride the probe accounting."""
    from paddle_tpu.parallel import make_mesh

    loss = _pipelined_fc_program(stages=2, microbatches=2)
    mesh = make_mesh((1, 2), ("dp", "pp"))
    cfg = autotune.TunedConfig()
    rng = np.random.RandomState(0)
    d = autotune.tune_pipeline(
        fluid.default_main_program(), fluid.default_startup_program(),
        {"x": rng.rand(8, 8).astype("float32")}, loss, mesh,
        microbatch_candidates=[2, 4], probe_steps=1, warmup_steps=1,
        config=cfg)
    assert d["chosen"]["schedule"] in ("gpipe", "1f1b")
    assert d["chosen"]["microbatches"] in (2, 4)
    measured = [c for c in d["candidates"] if c.get("step_s")]
    assert len(measured) == 4        # 2 schedules x 2 microbatch counts
    for c in measured:
        assert 0.0 < c["bubble_fraction"] < 1.0
        assert c["in_flight"] >= 1
    rec = cfg.get("pipeline")
    assert rec["chosen"] == d["chosen"]
    assert rec["evidence"] == "measured_step_window"
    assert rec["mesh_pp"] == 2


def test_tune_pipeline_hbm_gate_rejects_all(monkeypatch):
    """A fake 1-byte ceiling (FLAGS_autotune_hbm_bytes) rejects every
    candidate from the compiled peak estimate before any measured
    window — the CPU-testable rejection path."""
    from paddle_tpu.parallel import make_mesh

    loss = _pipelined_fc_program(stages=2, microbatches=2)
    mesh = make_mesh((1, 2), ("dp", "pp"))
    fluid.set_flags({"FLAGS_autotune_hbm_bytes": 1,
                     "FLAGS_preflight_oom": "warn"})
    try:
        with pytest.raises(ValueError, match="no measured candidate"):
            autotune.tune_pipeline(
                fluid.default_main_program(),
                fluid.default_startup_program(),
                {"x": np.zeros((8, 8), "float32")}, loss, mesh,
                microbatch_candidates=[2], schedules=["gpipe"],
                probe_steps=1)
    finally:
        fluid.set_flags({"FLAGS_autotune_hbm_bytes": 0,
                         "FLAGS_preflight_oom": "auto"})
