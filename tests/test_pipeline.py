"""GPipe-style pipeline parallelism tests on the 8-device virtual mesh:
parity with sequential stage folding, gradients, microbatch counts."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import pipeline
from paddle_tpu.parallel.mesh import make_mesh


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _sequential(params, x):
    w, b = params
    for i in range(w.shape[0]):
        x = _stage_fn((w[i], b[i]), x)
    return x


@pytest.mark.parametrize("microbatches", [8, 16])
def test_pipeline_matches_sequential(microbatches):
    rng = np.random.RandomState(0)
    s, d, batch = 8, 6, 32
    w = rng.randn(s, d, d).astype("float32") * 0.3
    b = rng.randn(s, d).astype("float32") * 0.1
    x = rng.randn(batch, d).astype("float32")
    mesh = make_mesh((8,), ("pp",))
    out = pipeline(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                   jnp.asarray(x), mesh, microbatches=microbatches)
    want = _sequential((w, b), x)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


def test_pipeline_on_sub_axis():
    """pp composes inside a 2-axis mesh (dp x pp)."""
    rng = np.random.RandomState(1)
    s, d, batch = 4, 5, 8
    w = rng.randn(s, d, d).astype("float32") * 0.3
    b = rng.randn(s, d).astype("float32") * 0.1
    x = rng.randn(batch, d).astype("float32")
    mesh = make_mesh((2, 4), ("dp", "pp"))
    out = pipeline(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                   jnp.asarray(x), mesh, axis="pp", microbatches=4)
    np.testing.assert_allclose(np.asarray(out), _sequential((w, b), x),
                               atol=1e-5)


def test_pipeline_gradients_match_sequential():
    rng = np.random.RandomState(2)
    s, d, batch = 4, 4, 8
    w = jnp.asarray(rng.randn(s, d, d).astype("float32") * 0.3)
    b = jnp.asarray(rng.randn(s, d).astype("float32") * 0.1)
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    mesh = make_mesh((4,), ("pp",))

    def piped_loss(w_, b_):
        return jnp.sum(pipeline(_stage_fn, (w_, b_), x, mesh,
                                microbatches=4) ** 2)

    def seq_loss(w_, b_):
        return jnp.sum(_sequential((w_, b_), x) ** 2)

    gp = jax.grad(piped_loss, argnums=(0, 1))(w, b)
    gs = jax.grad(seq_loss, argnums=(0, 1))(w, b)
    for a, b_ in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4)


def test_pipeline_rejects_bad_axis_and_batch():
    mesh = make_mesh((8,), ("dp",))
    with pytest.raises(ValueError, match="no axis"):
        pipeline(_stage_fn, (jnp.zeros((8, 2, 2)), jnp.zeros((8, 2))),
                 jnp.zeros((4, 2)), mesh, axis="pp")
    pp = make_mesh((4,), ("pp",))
    with pytest.raises(ValueError, match="must divide"):
        pipeline(_stage_fn, (jnp.zeros((4, 2, 2)), jnp.zeros((4, 2))),
                 jnp.zeros((10, 2)), pp, microbatches=4)


def test_pipeline_bf16_activations_fp32_params():
    """Mixed dtypes: carries follow the stage output dtype."""
    rng = np.random.RandomState(3)
    s, d, batch = 4, 4, 8
    w = jnp.asarray(rng.randn(s, d, d).astype("float32") * 0.3)
    b = jnp.asarray(rng.randn(s, d).astype("float32") * 0.1)
    x = jnp.asarray(rng.randn(batch, d), jnp.bfloat16)
    mesh = make_mesh((4,), ("pp",))
    out = pipeline(_stage_fn, (w, b), x, mesh, microbatches=4)
    assert out.dtype == jnp.float32        # promoted by fp32 params
    want = _sequential((np.asarray(w), np.asarray(b)),
                       np.asarray(x, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(out), want, atol=0.05)
