"""Pipeline-parallelism tests on the 8-device virtual mesh: schedule
equivalence (GPipe vs 1F1B vs interleaved) against sequential stage
folding, gradients, microbatch counts, and the per-tick schedule
accounting the goodput ledger's ``pipeline_bubble`` bucket is built
from."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.parallel import pipeline
from paddle_tpu.parallel.mesh import make_mesh, shard_map_norep
from paddle_tpu.parallel.pipeline import SCHEDULES, schedule_stats


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _sequential(params, x):
    w, b = params
    for i in range(w.shape[0]):
        x = _stage_fn((w[i], b[i]), x)
    return x


@pytest.mark.parametrize("microbatches", [8, 16])
def test_pipeline_matches_sequential(microbatches):
    rng = np.random.RandomState(0)
    s, d, batch = 8, 6, 32
    w = rng.randn(s, d, d).astype("float32") * 0.3
    b = rng.randn(s, d).astype("float32") * 0.1
    x = rng.randn(batch, d).astype("float32")
    mesh = make_mesh((8,), ("pp",))
    out = pipeline(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                   jnp.asarray(x), mesh, microbatches=microbatches)
    want = _sequential((w, b), x)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


def test_pipeline_on_sub_axis():
    """pp composes inside a 2-axis mesh (dp x pp)."""
    rng = np.random.RandomState(1)
    s, d, batch = 4, 5, 8
    w = rng.randn(s, d, d).astype("float32") * 0.3
    b = rng.randn(s, d).astype("float32") * 0.1
    x = rng.randn(batch, d).astype("float32")
    mesh = make_mesh((2, 4), ("dp", "pp"))
    out = pipeline(_stage_fn, (jnp.asarray(w), jnp.asarray(b)),
                   jnp.asarray(x), mesh, axis="pp", microbatches=4)
    np.testing.assert_allclose(np.asarray(out), _sequential((w, b), x),
                               atol=1e-5)


def test_pipeline_gradients_match_sequential():
    rng = np.random.RandomState(2)
    s, d, batch = 4, 4, 8
    w = jnp.asarray(rng.randn(s, d, d).astype("float32") * 0.3)
    b = jnp.asarray(rng.randn(s, d).astype("float32") * 0.1)
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    mesh = make_mesh((4,), ("pp",))

    def piped_loss(w_, b_):
        return jnp.sum(pipeline(_stage_fn, (w_, b_), x, mesh,
                                microbatches=4) ** 2)

    def seq_loss(w_, b_):
        return jnp.sum(_sequential((w_, b_), x) ** 2)

    gp = jax.grad(piped_loss, argnums=(0, 1))(w, b)
    gs = jax.grad(seq_loss, argnums=(0, 1))(w, b)
    for a, b_ in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4)


def test_pipeline_rejects_bad_axis_and_batch():
    mesh = make_mesh((8,), ("dp",))
    with pytest.raises(ValueError, match="no axis"):
        pipeline(_stage_fn, (jnp.zeros((8, 2, 2)), jnp.zeros((8, 2))),
                 jnp.zeros((4, 2)), mesh, axis="pp")
    pp = make_mesh((4,), ("pp",))
    with pytest.raises(ValueError, match="must divide"):
        pipeline(_stage_fn, (jnp.zeros((4, 2, 2)), jnp.zeros((4, 2))),
                 jnp.zeros((10, 2)), pp, microbatches=4)


def _stage_arrays(s_total, d, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(s_total, d, d).astype("float32") * 0.3)
    b = jnp.asarray(rng.randn(s_total, d).astype("float32") * 0.1)
    return w, b


@pytest.mark.parametrize("schedule,s_total,mesh_s,microbatches", [
    ("1f1b", 4, 4, 8),
    ("1f1b", 4, 4, 2),          # M < 2S-1: the stash-guard regime
    ("interleaved", 8, 4, 8),   # v=2
    ("interleaved", 8, 4, 4),   # v=2, one ring group
])
def test_schedule_matches_sequential(schedule, s_total, mesh_s,
                                     microbatches):
    """Schedule equivalence: every schedule computes the same function
    as folding the stages sequentially."""
    d, batch = 5, 16
    w, b = _stage_arrays(s_total, d)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    mesh = make_mesh((mesh_s,), ("pp",))
    out = pipeline(_stage_fn, (w, b), x, mesh,
                   microbatches=microbatches, schedule=schedule)
    want = _sequential((np.asarray(w), np.asarray(b)), np.asarray(x))
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


@pytest.mark.parametrize("schedule,s_total", [
    ("1f1b", 4), ("interleaved", 8)])
def test_schedule_gradients_match_sequential(schedule, s_total):
    """The 1F1B custom-vjp (bounded stash + per-stage recompute) and
    the interleaved loop's autodiff both reproduce sequential grads."""
    d, batch, m = 4, 16, 8
    w, b = _stage_arrays(s_total, d, seed=2)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    mesh = make_mesh((4,), ("pp",))

    def piped_loss(w_, b_):
        return jnp.sum(pipeline(_stage_fn, (w_, b_), x, mesh,
                                microbatches=m, schedule=schedule) ** 2)

    def seq_loss(w_, b_):
        return jnp.sum(_sequential((w_, b_), x) ** 2)

    gp = jax.grad(piped_loss, argnums=(0, 1))(w, b)
    gs = jax.grad(seq_loss, argnums=(0, 1))(w, b)
    for a, b_ in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4)


def test_gpipe_matches_old_psum_lowering():
    """Satellite: the slice-out single-source broadcast (plus the
    dropped wrap edge and the skipped final-tick rotation) computes
    BIT-identical outputs to the original masked-psum GPipe lowering,
    inlined here as the reference."""
    s, d, batch, m = 4, 5, 16, 8
    w, b = _stage_arrays(s, d, seed=4)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    mesh = make_mesh((s,), ("pp",))

    def old_shard(params, xx, axis_name):
        n = lax.psum(1, axis_name)
        stage = lax.axis_index(axis_name)
        my = jax.tree_util.tree_map(lambda p: p[0], params)
        mb = batch // m
        x_mb = xx.reshape((m, mb) + xx.shape[1:])
        perm = [(j, (j + 1) % n) for j in range(n)]

        def tick(t, carry):
            cur, outs = carry
            cur = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, m - 1)], cur)
            out = _stage_fn(my, cur)
            done = t - (n - 1)
            take = (stage == n - 1) & (done >= 0) & (done < m)
            upd = lax.dynamic_update_index_in_dim(
                outs, out, jnp.clip(done, 0, m - 1), 0)
            outs = jnp.where(take, upd, outs)
            return lax.ppermute(out, axis_name, perm), outs

        outs0 = jnp.zeros((m, mb) + xx.shape[1:], xx.dtype)
        cur0 = jnp.zeros((mb,) + xx.shape[1:], xx.dtype)
        _, outs = lax.fori_loop(0, m + n - 1, tick, (cur0, outs0))
        mask = (stage == n - 1).astype(outs.dtype)
        return lax.psum(outs * mask, axis_name).reshape(xx.shape)

    from jax.sharding import NamedSharding, PartitionSpec as P
    old_fn = shard_map_norep(
        functools.partial(old_shard, axis_name="pp"), mesh,
        in_specs=((P("pp"), P("pp")), P()), out_specs=P())
    wj = jax.device_put(w, NamedSharding(mesh, P("pp")))
    bj = jax.device_put(b, NamedSharding(mesh, P("pp")))
    old = old_fn((wj, bj), x)
    new = pipeline(_stage_fn, (w, b), x, mesh, microbatches=m)
    np.testing.assert_array_equal(np.asarray(old), np.asarray(new))


def test_schedule_stats_accounting():
    """The per-tick stage-idle tables behind the pipeline_bubble
    bucket: gpipe matches the closed form, interleaved shrinks the
    fraction at equal (S, M), 1f1b's stash bound is M-independent."""
    s, m = 4, 8
    g = schedule_stats("gpipe", s, m)
    assert g["bubble_fraction"] == pytest.approx(
        (s - 1) / (m + s - 1))
    i2 = schedule_stats("interleaved", s, m, virtual=2)
    assert i2["bubble_fraction"] == pytest.approx(
        (s - 1) / (2 * m + s - 1))
    assert i2["bubble_fraction"] < g["bubble_fraction"]
    f = schedule_stats("1f1b", s, m)
    assert f["in_flight"] == min(m, 2 * s - 1)
    assert schedule_stats("1f1b", s, 64)["in_flight"] == 2 * s - 1
    assert schedule_stats("gpipe", s, 64)["in_flight"] == 64 + s - 1
    assert f["remat_units"] == m
    # None normalizes to the gpipe default; junk raises
    assert schedule_stats(None, s, m)["schedule"] == "gpipe"
    with pytest.raises(ValueError, match="unknown"):
        schedule_stats("zigzag", s, m)
    assert set(SCHEDULES) == {"gpipe", "1f1b", "interleaved"}


def test_schedule_validation_errors():
    mesh = make_mesh((4,), ("pp",))
    w, b = _stage_arrays(6, 3)
    with pytest.raises(ValueError, match="multiple"):
        pipeline(_stage_fn, (w, b), jnp.zeros((8, 3)), mesh,
                 microbatches=4, schedule="interleaved")
    w8, b8 = _stage_arrays(8, 3)
    with pytest.raises(ValueError, match="multiple"):
        pipeline(_stage_fn, (w8, b8), jnp.zeros((12, 3)), mesh,
                 microbatches=6, schedule="interleaved")
    w4, b4 = _stage_arrays(4, 3)
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        pipeline(_stage_fn, (w4, b4), jnp.zeros((8, 3)), mesh,
                 schedule="zigzag")


@pytest.mark.slow
def test_1f1b_backward_memory_m_independent():
    """The 1F1B memory claim, measured on the compiled module: growing
    M grows the GPipe backward's temp footprint (per-tick residual
    stashes) while 1F1B's stays bounded (min(M, 2S-1) input-activation
    slots + per-stage recompute)."""
    s, d, batch_per_m = 4, 32, 4
    w, b = _stage_arrays(s, d, seed=6)
    mesh = make_mesh((4,), ("pp",))

    def temp_bytes(schedule, m):
        x = jnp.zeros((batch_per_m * m, d), jnp.float32)

        def loss(w_, b_):
            return jnp.sum(pipeline(_stage_fn, (w_, b_), x, mesh,
                                    microbatches=m,
                                    schedule=schedule) ** 2)

        compiled = jax.jit(jax.grad(loss, argnums=(0, 1))).lower(w, b)\
            .compile()
        ma = compiled.memory_analysis()
        return getattr(ma, "temp_size_in_bytes", None)

    g4, g32 = temp_bytes("gpipe", 4), temp_bytes("gpipe", 32)
    f4, f32 = temp_bytes("1f1b", 4), temp_bytes("1f1b", 32)
    if None in (g4, g32, f4, f32):  # backend without memory analysis
        pytest.skip("compiled memory_analysis unavailable")
    # gpipe's backward temp grows with M; 1f1b's grows strictly slower
    # (the stash is capped at 2S-1 slots; growth comes only from the
    # M-sized in/out buffers both schedules share)
    assert g32 > g4
    assert (f32 - f4) < 0.5 * (g32 - g4), (f4, f32, g4, g32)


def test_pipeline_bf16_activations_fp32_params():
    """Mixed dtypes: carries follow the stage output dtype."""
    rng = np.random.RandomState(3)
    s, d, batch = 4, 4, 8
    w = jnp.asarray(rng.randn(s, d, d).astype("float32") * 0.3)
    b = jnp.asarray(rng.randn(s, d).astype("float32") * 0.1)
    x = jnp.asarray(rng.randn(batch, d), jnp.bfloat16)
    mesh = make_mesh((4,), ("pp",))
    out = pipeline(_stage_fn, (w, b), x, mesh, microbatches=4)
    assert out.dtype == jnp.float32        # promoted by fp32 params
    want = _sequential((np.asarray(w), np.asarray(b)),
                       np.asarray(x, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(out), want, atol=0.05)
