"""Framework-behavior tests (reference test_program.py /
test_operator_desc.py pattern, SURVEY §4.3)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import Program


def _build_mlp():
    img = fluid.layers.data("img", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=4, act="relu")
    pred = fluid.layers.fc(h, size=3, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    return img, label, pred, loss


def test_program_build_and_shapes():
    img, label, pred, loss = _build_mlp()
    main = fluid.default_main_program()
    assert pred.shape == (-1, 3)
    assert loss.shape == (1,)
    op_types = [op.type for op in main.global_block().ops]
    assert "mul" in op_types and "cross_entropy" in op_types
    params = main.global_block().all_parameters()
    assert len(params) == 4  # 2 weights + 2 biases


def test_program_serialization_roundtrip():
    _build_mlp()
    main = fluid.default_main_program()
    restored = Program.from_json(main.to_json())
    assert [op.type for op in restored.global_block().ops] == [
        op.type for op in main.global_block().ops
    ]
    for name, v in main.global_block().vars.items():
        rv = restored.global_block().var(name)
        assert rv.shape == v.shape
        assert rv.persistable == v.persistable


def test_clone_for_test_disables_dropout():
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.dropout(x, dropout_prob=0.5)
    main = fluid.default_main_program()
    test_prog = main.clone(for_test=True)
    (dropout_op,) = [
        op for op in test_prog.global_block().ops if op.type == "dropout"
    ]
    assert dropout_op.attrs["is_test"] is True
    # original untouched
    (orig_op,) = [
        op for op in main.global_block().ops if op.type == "dropout"
    ]
    assert orig_op.attrs.get("is_test", False) is False


def test_prune_feed_fetch():
    img, label, pred, loss = _build_mlp()
    main = fluid.default_main_program()
    pruned = main.prune_feed_fetch(["img"], [pred.name])
    types = [op.type for op in pruned.global_block().ops]
    assert "cross_entropy" not in types
    assert "mul" in types


def test_executor_runs_pruned_inference():
    img, label, pred, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    infer = fluid.default_main_program().prune_feed_fetch(["img"], [pred.name])
    x = np.random.RandomState(0).rand(5, 8).astype("float32")
    (out,) = exe.run(infer, feed={"img": x}, fetch_list=[pred.name])
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(5), rtol=1e-5)


def test_scope_hierarchy():
    s = fluid.Scope()
    s.set_var("a", np.ones(3))
    kid = s.new_scope()
    assert kid.find_var("a") is not None
    kid.set_var("b", np.zeros(2))
    assert s.find_var("b") is None


def test_operator_repr_and_io():
    x = fluid.layers.data("x", shape=[4])
    out = fluid.layers.fc(x, size=2)
    main = fluid.default_main_program()
    mul_op = [op for op in main.global_block().ops if op.type == "mul"][0]
    assert mul_op.input("X") == [x.name]
    assert len(mul_op.output("Out")) == 1


def test_program_guard_isolation():
    p1 = fluid.Program()
    s1 = fluid.Program()
    with fluid.program_guard(p1, s1):
        fluid.layers.data("z", shape=[2])
        assert fluid.default_main_program() is p1
    assert fluid.default_main_program() is not p1
    assert "z" in p1.global_block().vars
