"""Sharded (orbax) checkpoint tests on the 8-device virtual mesh:
round-trip with sharded params, cross-topology restore, manager
rotation + interval gating (paddle_tpu.parallel.checkpoint)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.parallel import checkpoint as ck


def _build_and_train(steps=2, seed=5):
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = fluid.layers.fc(x, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    for _ in range(steps):
        exe.run(feed={"x": rng.rand(4, 8).astype("float32"),
                      "label": rng.randint(0, 4, (4, 1)).astype("int64")},
                fetch_list=[loss])
    return loss


def _snap(scope, program):
    return {v.name: np.asarray(scope.var(v.name))
            for v in program.global_block().vars.values()
            if v.persistable and scope.has_var(v.name)}


def test_sharded_roundtrip_with_mesh_shardings(tmp_path, fresh_programs):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _build_and_train()
        prog = fluid.default_main_program()
        # place a param sharded over the dp axis before saving
        mesh = fluid.make_mesh()
        w_name = prog.global_block().all_parameters()[0].name
        w = scope.var(w_name)
        sharded = jax.device_put(
            np.asarray(w), NamedSharding(mesh, P("dp")))
        scope.set_var(w_name, sharded)
        before = _snap(scope, prog)
        names = ck.save_sharded(str(tmp_path / "ck"), scope, prog)
        assert w_name in names and "fc_0.b_0" in str(names)

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        shardings = {w_name: NamedSharding(fluid.make_mesh(), P("dp"))}
        ck.load_sharded(str(tmp_path / "ck"), scope2,
                        fluid.default_main_program(),
                        shardings=shardings)
        after = _snap(scope2, fluid.default_main_program())
        restored_w = scope2.var(w_name)
        assert isinstance(restored_w, jax.Array)
        assert len(restored_w.sharding.device_set) == 8
    assert before.keys() == after.keys()
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])


def test_restore_onto_different_topology(tmp_path, fresh_programs):
    """Save replicated, restore sharded over a 2-axis mesh (elastic
    resume onto a different mesh shape)."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _build_and_train()
        prog = fluid.default_main_program()
        ck.save_sharded(str(tmp_path / "ck2"), scope, prog)
        w_name = prog.global_block().all_parameters()[0].name
        want = np.asarray(scope.var(w_name))

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))
        ck.load_sharded(str(tmp_path / "ck2"), scope2,
                        fluid.default_main_program(),
                        shardings={w_name: NamedSharding(mesh, P("a"))})
        got = scope2.var(w_name)
        assert len(got.sharding.device_set) == 4
        np.testing.assert_array_equal(np.asarray(got), want)


def test_manager_rotation_and_interval(tmp_path, fresh_programs):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _build_and_train(steps=1)
        prog = fluid.default_main_program()
        mgr = ck.ShardedCheckpointManager(
            str(tmp_path / "mgr"), max_to_keep=2, save_interval_steps=2,
            async_save=False)
        saved = [s for s in range(6) if mgr.save(s, scope, prog)]
        mgr.wait_until_finished()
        # interval=2 -> steps 0,2,4 saved; max_to_keep=2 -> {2,4} kept
        assert saved == [0, 2, 4]
        assert mgr.all_steps() == [2, 4]
        assert mgr.latest_step() == 4

        w_name = prog.global_block().all_parameters()[0].name
        want = np.asarray(scope.var(w_name))
        scope.set_var(w_name, np.zeros_like(want))
        step = mgr.restore(scope, prog)
        assert step == 4
        np.testing.assert_array_equal(np.asarray(scope.var(w_name)), want)
        mgr.close()


def test_restore_before_startup_raises(tmp_path, fresh_programs):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _build_and_train(steps=0)
        ck.save_sharded(str(tmp_path / "ck3"), scope)
    empty = fluid.Scope()
    with fluid.scope_guard(empty):
        with pytest.raises(ValueError, match="startup"):
            ck.load_sharded(str(tmp_path / "ck3"), empty)


def test_save_now_bypasses_interval(tmp_path):
    """save_now flushes regardless of save_interval_steps (the
    preemption path); restore picks it up."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel.checkpoint import ShardedCheckpointManager

    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=2)
    fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    mgr = ShardedCheckpointManager(str(tmp_path / "m"), async_save=False,
                                   save_interval_steps=100)
    assert mgr.save(step=0)                   # first save always lands
    assert mgr.save(step=3) is False          # interval-gated
    assert mgr.save_now(step=3)               # forced flush
    assert mgr.latest_step() == 3
    assert mgr.restore() == 3
    mgr.close()
