"""Deterministic fault-injection harness (ISSUE 8): schedules are pure
functions of (seed, step), drills install through the public
``paddle_tpu.fault`` API (registry, helpers, or the FLAGS_fault_spec
string), and two runs with the same schedule inject at identical
points — the property that makes a fault drill a regression test.
The mid-save kill family is additionally drilled end-to-end (subprocess
SIGKILL) by tests/test_elastic_drill.py."""

import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import fault


@pytest.fixture(autouse=True)
def _clean_fault_registry():
    fault.clear()
    fault.clear_injections()
    yield
    fault.clear()
    fault.clear_injections()


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------

def test_schedule_forms_and_determinism():
    s = fault.FaultSchedule(steps=[3, 7])
    assert [i for i in range(10) if s.fires(i)] == [3, 7]

    p = fault.FaultSchedule(every=4, start=2)
    assert [i for i in range(12) if p.fires(i)] == [2, 6, 10]

    # probabilistic form: a pure function of (seed, step) — two
    # instances with the same seed agree everywhere, a different seed
    # gives a different (still deterministic) pattern
    a = fault.FaultSchedule(prob=0.3, seed=42)
    b = fault.FaultSchedule(prob=0.3, seed=42)
    pat_a = [a.fires(i) for i in range(300)]
    assert pat_a == [b.fires(i) for i in range(300)]
    assert 30 < sum(pat_a) < 160          # roughly 30%
    c = fault.FaultSchedule(prob=0.3, seed=43)
    assert pat_a != [c.fires(i) for i in range(300)]
    # and fires() holds no state: asking twice answers the same
    assert [a.fires(i) for i in range(300)] == pat_a


def test_empty_schedule_rejected():
    with pytest.raises(ValueError):
        fault.FaultSchedule()
    with pytest.raises(TypeError):
        fault.register("executor/feed", lambda step: None, schedule=None)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_register_fire_once_and_unregister():
    hits = []
    h = fault.register("executor/dispatch",
                       lambda step, **ctx: hits.append(step),
                       fault.FaultSchedule(steps=[1, 3]), once=True)
    assert fault.active()
    for i in range(5):
        fault.fire("executor/dispatch", i)
    assert hits == [1]                    # once=True disarmed after step 1
    assert fault.injections() == [("executor/dispatch", 1, h.name)]
    fault.unregister(h)
    assert not fault.active()


def _build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(bs, 8).astype("float32"),
             "label": rng.randint(0, 4, (bs, 1)).astype("int64")}
            for _ in range(n)]


def _drilled_run(steps=6):
    """One executor run with a poisoned batch + a NaN'd loss fetch on
    fixed schedules; returns (losses, injection log)."""
    fault.clear()
    fault.clear_injections()
    main, startup, loss = _build_mlp()
    fault.poison_batch("x", fault.FaultSchedule(steps=[2]))
    fault.inject_nan(loss.name, fault.FaultSchedule(steps=[4]))
    scope = fluid.Scope()
    out = []
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        for feed in _batches(steps):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(np.asarray(lv, "float32").tobytes())
    return out, fault.injections()


def test_injection_points_identical_across_runs():
    """The acceptance property: same schedules => identical injection
    points (and, faults being the only perturbation, identical loss
    bit-patterns) across two runs."""
    out1, log1 = _drilled_run()
    out2, log2 = _drilled_run()
    assert log1 == log2
    assert [p for p, _, _ in log1] == ["executor/feed",
                                       "executor/step_done"]
    assert [s for _, s, _ in log1] == [2, 4]
    assert out1 == out2
    # the poisoned batch made step 2's loss non-finite in-graph; the
    # injected fetch made step 4's
    assert not np.isfinite(np.frombuffer(out1[2], "float32")).all()
    assert not np.isfinite(np.frombuffer(out1[4], "float32")).all()


def test_inject_nan_into_scope_var():
    main, startup, loss = _build_mlp()
    fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[1]))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        feeds = _batches(3)
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        assert np.isfinite(np.asarray(scope.var("fc_0.w_0"))).all()
        exe.run(main, feed=feeds[1], fetch_list=[loss])
        assert not np.isfinite(np.asarray(scope.var("fc_0.w_0"))).any()
        # the poisoned weights make the NEXT loss non-finite
        (lv,) = exe.run(main, feed=feeds[2], fetch_list=[loss])
        assert not np.isfinite(np.asarray(lv)).all()


def test_inject_nan_unknown_var_raises():
    main, startup, loss = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        # registered after startup: the startup executor's own step 0
        # must not trip the drill
        fault.inject_nan("no_such_var", fault.FaultSchedule(steps=[0]))
        exe = fluid.Executor(fluid.CPUPlace())
        with pytest.raises(KeyError, match="no_such_var"):
            exe.run(main, feed=_batches(1)[0], fetch_list=[loss])


def test_poison_batch_misaimed_raises():
    """A misaimed poison drill fails LOUDLY: the firing is recorded in
    the injection log before the hook runs (kill/fail hooks never
    return), so a silent no-op would let a recovery test pass against a
    run that was never faulted."""
    fault.poison_batch("lbl", fault.FaultSchedule(steps=[0]))
    with pytest.raises(KeyError, match="not a feed"):
        fault.fire("executor/feed", 0,
                   feed_names=["label", "x"],
                   feed_vals=[np.zeros((2, 1), "int64"),
                              np.zeros((2, 4), "float32")])
    fault.clear()
    fault.poison_batch("label", fault.FaultSchedule(steps=[0]))
    with pytest.raises(TypeError, match="non-float"):
        fault.fire("executor/feed", 0, feed_names=["label"],
                   feed_vals=[np.zeros((2, 1), "int64")])


def test_fail_and_delay_dispatch():
    main, startup, loss = _build_mlp()
    fault.fail_dispatch(fault.FaultSchedule(steps=[1]))
    fault.delay_dispatch(0.05, fault.FaultSchedule(steps=[0]))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        exe = fluid.Executor(fluid.CPUPlace())
        feeds = _batches(3)
        t0 = time.perf_counter()
        exe.run(main, feed=feeds[0], fetch_list=[loss])
        assert time.perf_counter() - t0 > 0.04      # the delay landed
        with pytest.raises(fault.FaultInjectedError):
            exe.run(main, feed=feeds[1], fetch_list=[loss])
        # fail_dispatch is once by default: the run continues after
        exe.run(main, feed=feeds[2], fetch_list=[loss])


def test_checkpoint_write_points_fire():
    """The three checkpoint protocol points fire with the artifact's
    step — the registry form of the mid-save kill family (the real
    SIGKILL drill is tests/test_elastic_drill.py's kill_mode=save)."""
    from paddle_tpu.parallel import checkpoint as ck

    seen = []
    for point in ("before_write", "after_write", "before_commit"):
        fault.register(
            "checkpoint/" + point,
            lambda step, _p=point, **ctx: seen.append((_p, step)),
            fault.FaultSchedule(every=1))
    ts = ck.TrainState(5, {"w": np.zeros((2, 2), "float32")},
                       {"format": 1, "step": 5, "executors": {},
                        "readers": {}, "extra": {}})
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        ck.save_train_state(d + "/step_0000000005", ts)
    assert seen == [("before_write", 5), ("after_write", 5),
                    ("before_commit", 5)]


def test_private_fault_hooks_are_gone():
    from paddle_tpu.parallel import checkpoint as ck

    assert not hasattr(ck, "_FAULT_HOOKS")


# ---------------------------------------------------------------------------
# FLAGS_fault_spec
# ---------------------------------------------------------------------------

def test_install_from_spec_grammar():
    hooks = fault.install_from_spec(
        "nan_var:fc_0.w_0@5;poison_batch:x@3,9:once;"
        "delay:0.01@every=4+2;fail_dispatch:@prob=0.5;"
        "kill_save:before_commit@11")
    assert len(hooks) == 5
    names = {h.name for h in hooks}
    assert names == {"nan_var:fc_0.w_0", "poison_batch:x",
                     "delay_dispatch:0.01s", "fail_dispatch",
                     "kill_mid_save:before_commit"}
    by_name = {h.name: h for h in hooks}
    assert by_name["nan_var:fc_0.w_0"].once          # family default
    assert by_name["poison_batch:x"].once            # :once override
    assert not by_name["delay_dispatch:0.01s"].once
    assert by_name["delay_dispatch:0.01s"].schedule.fires(6)
    assert not by_name["delay_dispatch:0.01s"].schedule.fires(7)


def test_install_from_spec_rejects_malformed():
    for bad in ("nonsense", "unknown_family:x@3", "nan_var:w@",
                "delay:notafloat@3"):
        with pytest.raises(ValueError):
            fault.install_from_spec(bad)


def test_fault_spec_flag_installs(monkeypatch):
    fluid.set_flags({"FLAGS_fault_spec": "poison_batch:x@7"})
    try:
        assert fault.active()
        assert any(h.name == "poison_batch:x" for h in fault.hooks())
    finally:
        fluid.set_flags({"FLAGS_fault_spec": ""})
        fault.clear()


def test_install_from_spec_kill_save_honors_persist():
    # the :once/:persist suffix overrides EVERY family's default,
    # kill_save included (a respawning-supervisor drill needs persist)
    hooks = fault.install_from_spec(
        "kill_save:before_commit@every=10:persist")
    assert len(hooks) == 1 and not hooks[0].once
    hooks = fault.install_from_spec("kill_save:before_commit@11")
    assert hooks[0].once                      # family default unchanged


def test_install_from_spec_replaces_not_accumulates():
    # re-applying a spec is idempotent and a new spec swaps the drills:
    # the installed fault state mirrors the flag value
    fault.install_from_spec("nan_var:w@5")
    fault.install_from_spec("nan_var:w@5")
    assert len(fault.hooks()) == 1
    fault.install_from_spec("delay:0.01@every=8")
    assert {h.name for h in fault.hooks()} == {"delay_dispatch:0.01s"}
    # directly registered hooks are never touched by a spec swap
    direct = fault.poison_batch("x", fault.FaultSchedule(steps=[3]))
    fault.install_from_spec("nan_var:w@5")
    assert {h.name for h in fault.hooks()} == {"poison_batch:x",
                                               "nan_var:w"}
    # transactional: a malformed entry leaves the previous spec armed
    with pytest.raises(ValueError):
        fault.install_from_spec("nan_var:w2@3;unknown_family:x@3")
    assert {h.name for h in fault.hooks()} == {"poison_batch:x",
                                               "nan_var:w"}
    # empty spec disarms the spec-installed drills only
    fault.install_from_spec("")
    assert [h.name for h in fault.hooks()] == ["poison_batch:x"]
    fault.unregister(direct)
    assert not fault.active()


def test_fault_spec_flag_reset_and_clear():
    fluid.set_flags({"FLAGS_fault_spec": "delay:0.01@every=8"})
    fluid.set_flags({"FLAGS_fault_spec": "delay:0.01@every=8"})
    try:
        assert len(fault.hooks()) == 1
        fluid.set_flags({"FLAGS_fault_spec": ""})
        assert not fault.active()
    finally:
        fault.clear()


def test_rejected_flag_value_not_committed():
    """A raising on_set validator rolls the flag back: flag() keeps
    returning the last GOOD value and the installed fault state keeps
    mirroring it."""
    fluid.set_flags({"FLAGS_fault_spec": "delay:0.01@every=8"})
    try:
        with pytest.raises(ValueError):
            fluid.set_flags({"FLAGS_fault_spec": "nan_var:w@x"})
        assert fluid.get_flags("FLAGS_fault_spec")[
            "FLAGS_fault_spec"] == "delay:0.01@every=8"
        assert {h.name for h in fault.hooks()} == {"delay_dispatch:0.01s"}
        with pytest.raises(ValueError):
            fluid.set_flags({"FLAGS_guardian_policy": "skip,rolback"})
        assert "rollback" in fluid.get_flags("FLAGS_guardian_policy")[
            "FLAGS_guardian_policy"]
    finally:
        fluid.set_flags({"FLAGS_fault_spec": ""})
        fault.clear()
