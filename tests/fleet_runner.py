"""Pod-scale serving-fleet drill harness (ISSUE 18).

``replica`` mode is one serving host: a tiny paged decoder-LM
:class:`GenerationEngine` wrapped in a ``FleetReplica`` — data-plane
``MasterServer`` + a ``ClusterMember`` session whose heartbeats carry
the engine's live load report.  It warms its compile cache BEFORE
joining (the drill times routing, not XLA), serves until SIGTERM, then
drains and prints its page-leak evidence.

``supervise`` mode (also importable: ``supervise()``) runs the failover
drill — an in-process ``FleetMaster`` behind TCP, N replica
subprocesses, multi-turn affinity sessions, then open-loop load with
one replica SIGKILLed mid-flight — and asserts the acceptance criteria:

* ZERO lost requests (every submitted request returns an accepted
  completion; re-routed ones complete on a survivor);
* fleet-routed results bit-identical to the victim's own direct
  engine dispatch (printed as ``EXPECTED`` before it joins);
* every multi-turn session stays on one replica (affinity);
* survivors drain to zero pages in use with an empty leak ledger;
* with tracing on, the fleet-assembled span trees (client + master +
  replica JSONL in one shared log dir) are complete.

``scaling`` mode measures the aggregate-throughput curve: for each
fleet size R it runs a closed-loop load and reports req/s — the
near-linear-scaling evidence the bench rung embeds.

Run:  python fleet_runner.py supervise <workdir> [replicas] [requests]
      python fleet_runner.py scaling <workdir> [points-csv]
      python fleet_runner.py replica <id> <master> <logdir|-> <trace>
             <expected>
"""

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# generous vs the 4/3s heartbeat cadence: a replica's heartbeat thread
# can starve behind a cold per-bucket prefill compile on a loaded box,
# and a spurious expiry quarantines a healthy replica mid-drill.
# Failover latency does not ride this: the CLIENT detects a dead
# data-plane socket in ~data_retries * retry_interval and re-routes
# immediately; the lease only bounds membership cleanup.
LEASE_SECONDS = 4.0
VOCAB, MAX_LEN, SLOTS = 23, 48, 4
DIMS = dict(n_layer=1, n_head=2, d_model=16, d_inner=32)
MAX_NEW = 6
# one fixed length: prompts share a prefill bucket, so the warmup
# generate at replica startup covers every compile the load will hit
PROMPT_LEN = 6
PROMPTS = [[(7 * i + 3 * j) % VOCAB for j in range(PROMPT_LEN)]
           for i in range(8)]


def _build_engine():
    import paddle_tpu as fluid
    from paddle_tpu.serving import GenerationEngine, build_decoder_lm

    spec = build_decoder_lm(VOCAB, MAX_LEN, SLOTS, paged=True,
                            page_size=8, prefix="fleetlm", **DIMS)
    return GenerationEngine(spec, place=fluid.CPUPlace(),
                            max_new_tokens=MAX_NEW, timeout_s=120.0)


def _stub_tokens(prompt):
    return [(3 * t + 1) % VOCAB for t in prompt[:MAX_NEW]]


class _StubEngine:
    """GenerationEngine-shaped mock backend for the FABRIC scaling
    curve: ``slots`` concurrent requests, each holding a slot for a
    fixed ``dwell`` of wall-clock (the accelerator-bound service time a
    real TPU replica would spend with its host CPU idle).  On the
    1-core CI box a real engine's decode is host-CPU-bound, so N
    replicas share one core and aggregate req/s CANNOT scale — the
    stub keeps each replica a genuine finite-capacity resource
    (capacity = slots/dwell) so the curve measures the routing fabric,
    which is what this harness scales."""

    class _Req:
        def __init__(self, eng, prompt):
            self._eng, self._prompt = eng, prompt

        def result(self, timeout=None):
            eng = self._eng
            with eng._mu:
                eng._waiting += 1
            eng._sem.acquire()
            with eng._mu:
                eng._waiting -= 1
                eng._busy += 1
            try:
                time.sleep(eng.dwell)
                return {"tokens": _stub_tokens(self._prompt),
                        "prompt_len": len(self._prompt)}
            finally:
                with eng._mu:
                    eng._busy -= 1
                eng._sem.release()

    def __init__(self, dwell_s, slots=SLOTS):
        self.dwell = float(dwell_s)
        self.slots = slots
        self._sem = threading.BoundedSemaphore(slots)
        self._mu = threading.Lock()
        self._waiting = 0
        self._busy = 0

    def submit(self, prompt_ids, max_new_tokens=None, timeout_s=None):
        return self._Req(self, [int(t) for t in prompt_ids])

    def load_report(self):
        with self._mu:
            return {"queue_depth": self._waiting,
                    "busy_slots": self._busy,
                    "occupancy": self._busy / self.slots,
                    "p50_ms": None, "p99_ms": None}

    def close(self):
        pass


# ---------------------------------------------------------------------------
# replica host
# ---------------------------------------------------------------------------

def replica_main(argv):
    rid, master_addr, log_dir, trace, expected, stub_ms = argv
    from paddle_tpu import monitor
    from paddle_tpu.monitor import tracing
    from paddle_tpu.serving import FleetReplica

    if log_dir != "-":
        monitor.enable(log_dir=log_dir)
    if int(trace):
        tracing.enable()

    if float(stub_ms) > 0:
        eng = _StubEngine(float(stub_ms) / 1e3)
    else:
        eng = _build_engine()
        # warm the prefill bucket + decode before joining: the fleet
        # must never route onto a cold compile mid-drill
        warm = eng.submit(PROMPTS[0]).result(timeout=120)
        if int(expected):
            # the direct-dispatch reference for the bit-identical
            # check: what THIS engine produces with no fleet in between
            ref = [warm["tokens"]] + [
                eng.submit(p).result(timeout=120)["tokens"]
                for p in PROMPTS[1:]]
            print("EXPECTED", json.dumps(ref), flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    rep = FleetReplica(master_addr, eng, "rep-%s" % rid)
    print("REPLICA_READY", rid, rep.address, flush=True)
    while not stop.wait(0.2):
        pass
    # drain: the supervisor only SIGTERMs after its load completed, so
    # this bounds straggler bookkeeping, not in-flight requests
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        load = eng.load_report()
        if not load["queue_depth"] and not load["busy_slots"]:
            break
        time.sleep(0.1)
    rep.close(leave=True)
    if isinstance(eng, _StubEngine):
        print("PAGES_IN_USE 0", flush=True)
        print("LEAKS []", flush=True)
    else:
        print("PAGES_IN_USE", eng._alloc.pages_in_use(), flush=True)
        print("LEAKS", json.dumps(eng._alloc.check_leaks()),
              flush=True)
    eng.close()
    print("DONE", flush=True)


# ---------------------------------------------------------------------------
# supervisor plumbing
# ---------------------------------------------------------------------------

def _replica_cmd(rid, master, log_dir, trace, expected, stub_ms=0.0):
    return [sys.executable, os.path.abspath(__file__), "replica",
            str(rid), master, log_dir, str(int(trace)),
            str(int(expected)), repr(float(stub_ms))]


def _replica_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    return env


class _Replica:
    """One replica subprocess + a stdout-capture thread (the process
    stays interactive — markers are read live, not at communicate)."""

    def __init__(self, rid, master, log_dir, trace, expected,
                 stub_ms=0.0):
        self.rid = rid
        self.proc = subprocess.Popen(
            _replica_cmd(rid, master, log_dir, trace, expected,
                         stub_ms),
            env=_replica_env(), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        self.lines = []
        self.err_tail = collections.deque(maxlen=80)
        self.ready = threading.Event()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()
        # drain stderr too: a replica blocked on a full stderr pipe
        # (jax warnings) would hang the whole drill
        self._te = threading.Thread(target=self._pump_err, daemon=True)
        self._te.start()

    def _pump(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))
            if line.startswith("REPLICA_READY"):
                self.ready.set()

    def _pump_err(self):
        for line in self.proc.stderr:
            self.err_tail.append(line.rstrip("\n"))

    def marker(self, name):
        for line in self.lines:
            if line.startswith(name + " "):
                return line[len(name) + 1:]
        return None

    def stop(self, timeout=60.0):
        """SIGTERM -> drain -> rc; returns (rc, stderr tail)."""
        if self.proc.poll() is None:
            self.proc.terminate()
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._t.join(timeout=5)
        self._te.join(timeout=5)
        return self.proc.returncode, "\n".join(self.err_tail)

    def kill(self):
        self.proc.kill()        # SIGKILL: no drain, no leave, no flush
        return self.proc.wait()


def _start_fleet(n, log_dir, trace, timeout=240.0, stub_ms=0.0):
    """FleetMaster behind TCP + n warm replica subprocesses."""
    from paddle_tpu.cloud import MasterServer
    from paddle_tpu.serving import FleetMaster

    master = FleetMaster(lease_timeout=LEASE_SECONDS)
    srv = MasterServer(master).start()
    reps = [_Replica(i, srv.address, log_dir, trace,
                     expected=(i == 0 and not stub_ms),
                     stub_ms=stub_ms)
            for i in range(n)]
    deadline = time.monotonic() + timeout
    for r in reps:
        if not r.ready.wait(max(0.0, deadline - time.monotonic())):
            raise AssertionError(
                "replica %d not ready: rc=%s stderr=%s"
                % (r.rid, r.proc.poll(), "\n".join(r.err_tail)))
    return master, srv, reps


def _run_load(cli, n_requests, concurrency, on_complete=None,
              timeout=180.0, max_new=None):
    """Closed-loop worker pool over the prompt pool; returns
    (results, failures, wall_seconds)."""
    results, failures = [], []
    mu = threading.Lock()
    it = iter(range(n_requests))

    def worker():
        while True:
            with mu:
                idx = next(it, None)
            if idx is None:
                return
            prompt = PROMPTS[idx % len(PROMPTS)]
            t0 = time.monotonic()
            try:
                res = cli.generate(prompt, max_new_tokens=max_new,
                                   timeout=timeout)
            except Exception as e:  # noqa: BLE001 — a loss, recorded
                with mu:
                    failures.append({"idx": idx, "error": repr(e)})
                continue
            rec = {"idx": idx, "tokens": res["tokens"],
                   "replica": res["replica"],
                   "reroutes": res["reroutes"],
                   "latency_s": time.monotonic() - t0}
            with mu:
                results.append(rec)
            if on_complete is not None:
                on_complete(len(results))

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 60.0)
    return results, failures, time.monotonic() - t0


def _pctl(vals, q):
    vals = sorted(vals)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]


# ---------------------------------------------------------------------------
# the failover drill
# ---------------------------------------------------------------------------

def supervise(workdir, replicas=2, requests=32, concurrency=4,
              trace=True, timeout=420.0):
    """SIGKILL one replica of an N-replica fleet under open-loop load;
    returns the evidence dict (asserting the acceptance criteria along
    the way)."""
    sys.path.insert(0, REPO)
    from paddle_tpu import monitor
    from paddle_tpu.monitor import tracing
    from paddle_tpu.serving import FleetClient

    workdir = os.path.abspath(str(workdir))
    mon_dir = os.path.join(workdir, "monitor")
    os.makedirs(mon_dir, exist_ok=True)
    if trace:
        # client + master spans land in the SAME log dir as every
        # replica's: one request assembles into one cross-process tree
        monitor.enable(log_dir=mon_dir)
        tracing.enable()

    master, srv, reps = _start_fleet(
        replicas, mon_dir if trace else "-", trace, timeout=timeout)
    victim, evidence = reps[0], {}
    try:
        cli = FleetClient(srv.address)

        # -- phase 1: multi-turn sessions pin to one replica ----------
        sessions = {}
        for s in range(3):
            sid = "conv-%d" % s
            prompt = list(PROMPTS[s])
            for _turn in range(3):
                res = cli.generate(prompt, session=sid, timeout=180.0)
                sessions.setdefault(sid, []).append(res["replica"])
                # the real multi-turn shape: context grows by the
                # generated ids, and the pinned replica's paged prefix
                # sharing reuses the turn-1 KV pages
                prompt = prompt + res["tokens"]
        affinity_ok = all(len(set(v)) == 1 for v in sessions.values())
        assert affinity_ok, sessions

        # -- phase 2: open-loop load, SIGKILL the victim mid-flight ---
        kill_after = max(2, requests // 3)
        killed = threading.Event()

        def maybe_kill(done):
            if done >= kill_after and not killed.is_set():
                killed.set()
                victim.kill()

        results, failures, wall = _run_load(
            cli, requests, concurrency, on_complete=maybe_kill)
        assert killed.is_set(), "load finished before the kill fired"
        assert victim.proc.returncode == -signal.SIGKILL, \
            victim.proc.returncode

        # ZERO lost requests: every submitted request completed
        assert not failures, failures
        assert len(results) == requests, (len(results), requests)
        rerouted = [r for r in results if r["reroutes"] > 0]
        # the victim stays in the member set until the lease expires,
        # so post-kill routes MUST have hit it and re-routed
        assert rerouted, "no request was re-routed off the victim"
        survivors = {r["replica"] for r in results
                     if r["replica"] != "rep-0"}
        assert all(r["replica"] != "rep-0" for r in rerouted), rerouted

        # bit-identical to direct dispatch: the victim printed its own
        # engine's results for the prompt pool before joining
        expected = json.loads(victim.marker("EXPECTED"))
        parity_ok = all(
            r["tokens"] == expected[r["idx"] % len(PROMPTS)]
            for r in results)
        assert parity_ok, "fleet-routed tokens diverged from direct"

        # master-side evidence: quarantine verdict + reroute latency
        stats = None
        deadline = time.monotonic() + 3 * LEASE_SECONDS
        while time.monotonic() < deadline:
            stats = cli.stats()
            if "rep-0" in stats["quarantined"]:
                break
            time.sleep(0.25)
        assert stats and "rep-0" in stats["quarantined"], stats

        # -- phase 3: survivors drain clean (page-leak check) ---------
        for r in reps[1:]:
            rc, err = r.stop()
            assert rc == 0, (r.rid, rc, err)
            assert r.marker("PAGES_IN_USE") == "0", r.lines[-6:]
            assert json.loads(r.marker("LEAKS")) == [], r.lines[-6:]

        trace_summary = None
        if trace:
            # assemble the shared JSONL dir exactly like
            # tools/request_trace.py --assert-complete does
            sys.path.insert(0, os.path.join(REPO, "tools"))
            from request_trace import load_records

            records, _files = load_records([mon_dir])
            trees = tracing.assemble(records)
            fleet_trees = {tid: t for tid, t in trees.items()
                           if t["root"] is not None
                           and t["root"].get("name") == "fleet_request"}
            summary = tracing.breakdown_summary(fleet_trees)
            assert summary["terminal"] >= requests, summary
            assert summary["complete_fraction"] >= 0.99, summary
            trace_summary = {
                "requests": summary["requests"],
                "complete_fraction": summary["complete_fraction"],
                "route_p50_ms": summary["stages"]["route"]["p50_ms"],
            }

        lat = [r["latency_s"] for r in results]
        fleet = stats["fleet"]
        evidence = {
            "replicas": replicas, "requests": requests,
            "completed": len(results), "lost": requests - len(results),
            "rerouted_requests": len(rerouted),
            "client_reroutes": sum(r["reroutes"] for r in results),
            "reroute_latency_ms": fleet["reroute_latency_ms"],
            "affinity_ok": affinity_ok,
            "affinity_hit_rate": fleet["affinity_hit_rate"],
            "parity_ok": parity_ok,
            "survivors": sorted(survivors),
            "victim_rc": victim.proc.returncode,
            "quarantined": sorted(stats["quarantined"]),
            "aggregate_rps": round(len(results) / wall, 3),
            "p50_latency_ms": round(_pctl(lat, 0.50) * 1e3, 3),
            "p99_latency_ms": round(_pctl(lat, 0.99) * 1e3, 3),
            "stale_completions": fleet["counts"]["stale_completions"],
            "trace": trace_summary,
        }
        return evidence
    finally:
        for r in reps:
            if r.proc.poll() is None:
                r.proc.kill()
        srv.shutdown()
        if trace:
            monitor.disable()
            tracing.disable()


# ---------------------------------------------------------------------------
# the scaling curve
# ---------------------------------------------------------------------------

def scaling(workdir, points=(1, 2, 4), requests_per_replica=60,
            dwell_ms=40.0, timeout=420.0):
    """Aggregate routed req/s at fleet sizes ``points`` — the
    near-linear-scaling curve for the serving FABRIC.

    Replicas are mock backends (:class:`_StubEngine`) holding each
    request for a fixed ``dwell_ms`` of wall-clock across ``SLOTS``
    concurrent slots, so one replica's capacity is exactly
    ``SLOTS/dwell`` and the only way aggregate req/s grows is the
    router actually spreading load over more replicas.  A real engine
    on the CI box cannot serve this purpose: its decode is host-CPU-
    bound and N replica processes share the same cores (1 on the CI
    container), which measures the machine, not the fabric."""
    sys.path.insert(0, REPO)
    from paddle_tpu.serving import FleetClient

    capacity = SLOTS / (dwell_ms / 1e3)
    curve = []
    for n in points:
        master, srv, reps = _start_fleet(n, "-", trace=False,
                                         timeout=timeout,
                                         stub_ms=dwell_ms)
        try:
            cli = FleetClient(srv.address)
            # ramp: fill every replica's slots once before timing
            _run_load(cli, 2 * SLOTS * n, concurrency=2 * SLOTS * n)
            # offered concurrency 2x the fleet's slot count: admission
            # always finds a full fleet, per-request latency stays
            # queue-bounded (~2 dwells)
            results, failures, wall = _run_load(
                cli, requests_per_replica * n,
                concurrency=2 * SLOTS * n)
            assert not failures, failures[:3]
            lat = [r["latency_s"] for r in results]
            by_rep = {}
            for r in results:
                by_rep[r["replica"]] = by_rep.get(r["replica"], 0) + 1
            curve.append({
                "replicas": n, "requests": len(results),
                "aggregate_rps": round(len(results) / wall, 3),
                "capacity_rps": round(capacity * n, 1),
                "p99_latency_ms": round(_pctl(lat, 0.99) * 1e3, 3),
                "per_replica": by_rep})
            cli.close()
        finally:
            for r in reps:
                r.stop(timeout=60.0)
            srv.shutdown()
    return curve


def main():
    mode = sys.argv[1]
    if mode == "replica":
        replica_main(sys.argv[2:])
    elif mode == "supervise":
        evidence = supervise(sys.argv[2],
                             *[int(a) for a in sys.argv[3:]])
        print("FLEET_DRILL", json.dumps(evidence))
        print("FLEET_DRILL OK: %d/%d requests completed (0 lost), %d "
              "re-routed off the SIGKILLed replica, reroute p99 %s ms, "
              "affinity hit rate %s, parity with direct dispatch: %s"
              % (evidence["completed"], evidence["requests"],
                 evidence["rerouted_requests"],
                 (evidence["reroute_latency_ms"] or {}).get("p99_ms"),
                 evidence["affinity_hit_rate"],
                 evidence["parity_ok"]))
    elif mode == "scaling":
        pts = tuple(int(p) for p in sys.argv[3].split(",")) \
            if len(sys.argv) > 3 else (1, 2, 4)
        curve = scaling(sys.argv[2], points=pts)
        print("FLEET_SCALING", json.dumps(curve))
    else:
        raise SystemExit("unknown mode %r" % mode)


if __name__ == "__main__":
    main()
