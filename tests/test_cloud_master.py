"""Elastic coordinator tests — the Go master's test matrix rebuilt
(go/master/service_internal_test.go + the fault-tolerance behavior the
design docs specify: timeout requeue, failure_max, snapshot recover,
save-model arbitration, dead-consumer recovery)."""

import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.cloud import (AllTasksFailed, FileStore, InMemStore,
                              MasterClient, MasterServer, MasterService,
                              NoMoreAvailable, PassAfter, PassBefore,
                              master_reader, partition)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_service(**kw):
    kw.setdefault("store", InMemStore())
    kw.setdefault("timeout", 60.0)
    svc = MasterService(**kw)
    return svc


def test_partition_groups_chunks():
    tasks = partition(list(range(7)), chunks_per_task=3)
    assert [t.chunks for t in tasks] == [[0, 1, 2], [3, 4, 5], [6]]
    assert [t.task_id for t in tasks] == [0, 1, 2]


def test_lease_lifecycle_and_pass_rollover():
    svc = make_service(chunks_per_task=1)
    svc.set_dataset(["a", "b"])
    t0 = svc.get_task(0)
    t1 = svc.get_task(0)
    with pytest.raises(NoMoreAvailable):
        svc.get_task(0)
    svc.task_finished(t0.task_id)
    svc.task_finished(t1.task_id)
    # all done => pass rolled, done requeued as todo
    assert svc.stats() == {"todo": 2, "pending": 0, "done": 0,
                           "failed": 0, "cur_pass": 1}
    # pass handshake
    with pytest.raises(PassBefore):
        svc.get_task(0)
    with pytest.raises(PassAfter):
        svc.get_task(2)
    t = svc.get_task(1)
    assert t.chunks in (["a"], ["b"])


def test_failure_requeue_until_failure_max():
    svc = make_service(failure_max=2)
    svc.set_dataset(["only"])
    for expected_failures in (1, 2):
        t = svc.get_task(0)
        svc.task_failed(t.task_id, t.epoch)
        assert svc.stats()["todo"] == 1
        assert svc.todo[0].num_failure == expected_failures
    # third failure exceeds failure_max=2 -> discarded
    t = svc.get_task(0)
    svc.task_failed(t.task_id, t.epoch)
    assert svc.stats()["failed"] == 1
    with pytest.raises(AllTasksFailed):
        svc.get_task(0)


def test_pass_rolls_when_last_pending_lease_is_discarded():
    """A lease that dies for good (num_failure > failure_max) while all
    other tasks are done must still roll the pass — otherwise every
    trainer spins in NoMoreAvailable forever."""
    svc = make_service(failure_max=0)
    svc.set_dataset(["good", "bad"])
    ta = svc.get_task(0)
    tb = svc.get_task(0)
    svc.task_finished(ta.task_id)
    svc.task_failed(tb.task_id, tb.epoch)   # failure_max=0: discard
    st = svc.stats()
    assert st["cur_pass"] == 1 and st["todo"] == 2


def test_timeout_requeues_lease_with_epoch_guard():
    clk = FakeClock()
    svc = make_service(timeout=10.0, clock=clk)
    svc.set_dataset(["x"])
    t = svc.get_task(0)
    clk.advance(11.0)   # lease expires
    t2 = svc.get_task(0)  # sweep requeues, then re-leases
    assert t2.task_id == t.task_id and t2.epoch == t.epoch + 1
    # a stale failure report from the dead consumer must be ignored
    svc.task_failed(t.task_id, t.epoch)
    assert svc.stats()["pending"] == 1
    svc.task_finished(t2.task_id)
    assert svc.stats()["cur_pass"] == 1


def test_late_finish_after_timeout_is_ignored():
    clk = FakeClock()
    svc = make_service(timeout=10.0, clock=clk)
    svc.set_dataset(["x", "y"])
    t = svc.get_task(0)
    clk.advance(11.0)
    svc.task_finished(t.task_id)  # sweep expires it first; finish is late
    st = svc.stats()
    assert st["done"] == 0 and st["todo"] == 2 and st["pending"] == 0


def test_stale_epoch_finish_does_not_steal_release(
        ):
    """The dense-id staleness hole (the Go FIXME's actual worry): a
    holder whose lease timed out reports finished AFTER the task was
    re-dispatched under the same dense id.  The epoch guard must ignore
    the stale report — the NEW holder's lease stays pending — and the
    current-epoch finish still lands."""
    clk = FakeClock()
    svc = make_service(timeout=10.0, clock=clk)
    svc.set_dataset(["x"])
    t_old = svc.get_task(0)
    clk.advance(11.0)               # holder 1's lease times out
    # sweep requeues; the SAME dense id is re-leased at epoch+1
    relet = svc.get_task(0)
    assert relet.task_id == t_old.task_id
    assert relet.epoch == t_old.epoch + 1
    svc.task_finished(t_old.task_id, t_old.epoch)   # stale holder
    st = svc.stats()
    assert st["done"] == 0                   # not marked done
    assert st["pending"] == 1                # new lease NOT cleared
    svc.task_finished(relet.task_id, relet.epoch)   # real holder
    st = svc.stats()                         # all done -> pass rolled
    assert st["cur_pass"] == 1 and st["todo"] == 1
    # epoch=None (pre-guard caller) keeps the legacy by-id behavior
    t2 = svc.get_task(1)
    svc.task_finished(t2.task_id)
    assert svc.stats()["cur_pass"] == 2      # rolled again


def test_snapshot_recover_preserves_leases_and_deadlines(tmp_path):
    clk = FakeClock()
    store = FileStore(tmp_path / "snap.json")
    svc = MasterService(store=store, timeout=30.0, clock=clk)
    svc.set_dataset(["a", "b", "c"])
    ta = svc.get_task(0)
    svc.task_finished(ta.task_id)
    tb = svc.get_task(0)

    # master dies; new master over the same store (go recover :166)
    svc2 = MasterService(store=store, timeout=30.0, clock=clk)
    assert svc2.ready  # set_dataset not needed after recovery
    st = svc2.stats()
    assert st == {"todo": 1, "pending": 1, "done": 1, "failed": 0,
                  "cur_pass": 0}
    # the recovered lease keeps its ORIGINAL deadline: advancing past it
    # requeues tb even though the granting master is gone
    clk.advance(31.0)
    ids = {svc2.get_task(0).task_id, svc2.get_task(0).task_id}
    assert tb.task_id in ids


def test_recovered_lease_keeps_original_deadline_not_rearmed(tmp_path):
    """Store recovery preserves the LIVE deadline exactly: a lease with
    20s left must expire 20s later — not lease-timeout seconds after
    the new master came up (the go original re-arms nothing; we must
    not silently re-arm either)."""
    clk = FakeClock()
    store = FileStore(tmp_path / "snap.json")
    svc = MasterService(store=store, timeout=30.0, clock=clk)
    svc.set_dataset(["a", "b"])
    t = svc.get_task(0)            # deadline = t0 + 30
    clk.advance(10.0)

    svc2 = MasterService(store=store, timeout=30.0, clock=clk)
    clk.advance(15.0)              # t0+25: inside the ORIGINAL window
    with pytest.raises(NoMoreAvailable):
        # 'b' leased here; 'a' must still be pending, NOT requeued
        svc2.get_task(0)
        svc2.get_task(0)
    assert svc2.stats()["pending"] == 2
    clk.advance(6.0)               # t0+31: past the original deadline
    t2 = svc2.get_task(0)
    assert t2.task_id == t.task_id and t2.epoch == t.epoch + 1


def test_concurrent_lease_churn_stale_epochs_never_revoke():
    """Thread drill (the lease-expiry vs fresh-dispatch race): workers
    lease/finish/fail under a REAL clock with a tiny timeout while a
    saboteur replays stale ``task_failed`` reports for every lease ever
    observed.  Invariants: no crash, the task population is conserved
    across all queues, and the service still drains to a pass rollover
    afterwards — a stale epoch revoking a re-leased task would surface
    as a lost/duplicated task or a spurious failure count."""
    import random
    import threading
    import time as _time

    svc = make_service(timeout=0.03, clock=time.time, failure_max=10**6)
    ntasks = 6
    svc.set_dataset(list(range(ntasks)))
    stop = threading.Event()
    seen = []                     # every (task_id, epoch) ever leased
    errors = []

    def worker(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            try:
                t = svc.get_task(None)
            except (NoMoreAvailable, AllTasksFailed):
                _time.sleep(0.002)
                continue
            except Exception as e:  # noqa: BLE001 — drill invariant
                errors.append(e)
                return
            seen.append((t.task_id, t.epoch))
            # some leases intentionally outlive the timeout so they
            # expire and re-dispatch under live contention
            _time.sleep(rng.uniform(0.0, 0.05))
            try:
                if rng.random() < 0.5:
                    svc.task_finished(t.task_id)
                else:
                    svc.task_failed(t.task_id, t.epoch)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    def saboteur():
        rng = random.Random(99)
        while not stop.is_set():
            if seen:
                tid, ep = rng.choice(seen)
                try:
                    # strictly stale AND possibly-current replays: the
                    # epoch guard must drop every stale one silently
                    svc.task_failed(tid, ep - 1)
                    svc.task_failed(tid, ep)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
            _time.sleep(0.001)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)] + [threading.Thread(target=saboteur)]
    for th in threads:
        th.start()
    _time.sleep(1.2)
    stop.set()
    for th in threads:
        th.join(timeout=10)
    assert not errors, errors[:3]
    st = svc.stats()
    assert st["todo"] + st["pending"] + st["done"] + st["failed"] \
        == ntasks, st

    # quiesce: expire any straggler leases, then the service must still
    # drain cleanly to a pass rollover (no task lost or duplicated)
    _time.sleep(0.05)
    start_pass = svc.stats()["cur_pass"]
    deadline = _time.monotonic() + 30
    while svc.stats()["cur_pass"] == start_pass:
        assert _time.monotonic() < deadline, svc.stats()
        try:
            t = svc.get_task(None)
        except NoMoreAvailable:
            _time.sleep(0.002)
            continue
        svc.task_finished(t.task_id)
    assert svc.stats()["todo"] == ntasks


def test_set_dataset_idempotent_after_recovery(tmp_path):
    store = FileStore(tmp_path / "snap.json")
    svc = MasterService(store=store)
    svc.set_dataset(["a"])
    t = svc.get_task(0)
    svc2 = MasterService(store=store)
    svc2.set_dataset(["a"])  # must NOT reset the in-flight lease
    assert svc2.stats()["pending"] == 1
    svc2.task_finished(t.task_id)
    assert svc2.stats()["cur_pass"] == 1


def test_request_save_model_single_saver():
    clk = FakeClock()
    svc = make_service(clock=clk)
    svc.set_dataset(["x"])
    assert svc.request_save_model("trainer-3", 10.0) is True
    assert svc.request_save_model("trainer-0", 10.0) is False
    assert svc.request_save_model("trainer-3", 10.0) is True  # re-ask ok
    clk.advance(11.0)  # window expired: next asker wins
    assert svc.request_save_model("trainer-0", 10.0) is True
    with pytest.raises(ValueError):
        svc.request_save_model("", 1.0)


def test_tcp_server_client_roundtrip_and_dead_consumer():
    svc = MasterService(store=InMemStore(), timeout=0.5)
    svc.set_dataset([[i] for i in range(4)])
    server = MasterServer(svc).start()
    try:
        c = MasterClient(server.address)
        assert c.ping() == "pong"
        # consumer 1 leases a task and "dies" (never reports)
        dead = c.get_task(0)
        # consumer 2 drains everything else
        c2 = MasterClient(server.address)
        got = []
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                t = c2.get_task(0)
            except NoMoreAvailable:
                time.sleep(0.1)   # waiting for the dead lease to expire
                continue
            got.append(t.task_id)
            c2.task_finished(t.task_id)
            if svc.stats()["cur_pass"] == 1:
                break
        assert svc.stats()["cur_pass"] == 1
        assert dead.task_id in got  # requeued lease completed by c2
        c.close()
        c2.close()
    finally:
        server.shutdown()


def test_get_task_blocks_until_dataset_registered():
    svc = MasterService(store=InMemStore(), ready_timeout=5.0)
    import threading
    result = {}

    def late_consumer():
        result["task"] = svc.get_task(0)

    th = threading.Thread(target=late_consumer)
    th.start()
    time.sleep(0.2)
    svc.set_dataset(["x"])          # arrives after the consumer asked
    th.join(timeout=5)
    assert result["task"].chunks == ["x"]

    fast = MasterService(store=InMemStore(), ready_timeout=0.05)
    with pytest.raises(RuntimeError):
        fast.get_task(0)            # bounded wait, then a clear error


def test_master_reader_default_pass_reads_exactly_one_pass():
    svc = MasterService(store=InMemStore(), timeout=5.0)
    svc.set_dataset([[0], [1]])

    def chunk_reader(chunk):
        return iter(chunk)

    # pass_id=None pins the current pass: one full epoch, then stop
    assert sorted(master_reader(svc, chunk_reader)()) == [0, 1]
    assert svc.stats()["cur_pass"] == 1
    assert sorted(master_reader(svc, chunk_reader)()) == [0, 1]
    assert svc.stats()["cur_pass"] == 2


def test_master_reader_yields_all_samples():
    svc = MasterService(store=InMemStore(), timeout=5.0)
    chunks = [{"lo": 0, "hi": 3}, {"lo": 3, "hi": 7}]
    svc.set_dataset(chunks)

    def chunk_reader(chunk):
        return iter(range(chunk["lo"], chunk["hi"]))

    reader = master_reader(svc, chunk_reader, pass_id=0)
    assert sorted(reader()) == list(range(7))
    assert svc.stats()["cur_pass"] == 1


WORKER_SRC = r"""
import sys, time
from paddle_tpu.cloud import MasterClient, NoMoreAvailable, PassBefore, \
    AllTasksFailed
addr, mode = sys.argv[1], sys.argv[2]
c = MasterClient(addr)
if mode == "hang":          # lease one task, then hang until killed
    t = c.get_task(0)
    print("LEASED", t.task_id, flush=True)
    time.sleep(600)
else:                        # drain
    done = []
    while True:
        try:
            t = c.get_task(0)
        except (PassBefore, AllTasksFailed):
            break
        except NoMoreAvailable:
            time.sleep(0.1)
            continue
        done.append(t.task_id)
        c.task_finished(t.task_id)
        if c.stats()["cur_pass"] >= 1:
            break
    print("DONE", *done, flush=True)
"""


def test_subprocess_worker_killed_midtask_job_completes(tmp_path):
    """Fault injection with a real OS process (test_dist_base.py pattern:
    kill via signal, assert the surviving worker finishes the pass)."""
    svc = MasterService(store=InMemStore(), timeout=1.0)
    svc.set_dataset([[i] for i in range(3)])
    server = MasterServer(svc).start()
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER_SRC)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root)
    try:
        hanger = subprocess.Popen(
            [sys.executable, str(worker_py), server.address, "hang"],
            stdout=subprocess.PIPE, text=True, env=env)
        line = hanger.stdout.readline()
        assert line.startswith("LEASED")
        leased_id = int(line.split()[1])
        hanger.send_signal(signal.SIGKILL)
        hanger.wait(timeout=10)

        drainer = subprocess.run(
            [sys.executable, str(worker_py), server.address, "drain"],
            stdout=subprocess.PIPE, text=True, env=env, timeout=60)
        finished = [int(x) for x in
                    drainer.stdout.strip().split()[1:]]
        assert svc.stats()["cur_pass"] == 1
        assert leased_id in finished
    finally:
        server.shutdown()


def test_client_backoff_budget_exhausts_with_clear_error():
    """ISSUE 8 satellite: the reconnect loop backs off exponentially
    (bounded by max_retry_interval), counts reconnect attempts into the
    master/reconnects monitor counter, and a spent budget raises a
    ConnectionError naming the endpoint and attempt count instead of
    retrying forever."""
    from paddle_tpu import monitor

    monitor.enable()
    try:
        c = MasterClient("127.0.0.1:1", retry_interval=0.01,
                         max_retries=4, max_retry_interval=0.05,
                         jitter=0.0)
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError) as ei:
            c.ping()
        elapsed = time.perf_counter() - t0
        msg = str(ei.value)
        assert "after 4 attempts" in msg
        assert "127.0.0.1:1" in msg
        # exponential: 0.01 + 0.02 + 0.04 (capped), no trailing sleep
        assert elapsed < 2.0
        assert monitor.registry().get("master/reconnects").value == 3
    finally:
        monitor.disable()
        monitor.registry().reset()
