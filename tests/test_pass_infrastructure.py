"""Pass registry / PassBuilder / chain matcher (reference
framework/ir/pass.h REGISTER_PASS, pass_builder.cc, and
graph_pattern_detector.cc): named program-rewrite passes composed into
ordered pipelines."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.transpiler import (PassBuilder, apply_pass, find_chain,
                                   get_pass, list_passes, register_pass)


def _conv_bn_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(c, act="relu", is_test=True,
                                    use_global_stats=True)
        pred = fluid.layers.fc(b, size=2, act="softmax")
    return main, startup, pred


def test_registry_and_builtins():
    names = list_passes()
    for expected in ("fuse_conv_bn", "inference_optimize", "bfloat16",
                     "graph_viz", "memory_optimize"):
        assert expected in names, names
    assert callable(get_pass("fuse_conv_bn"))
    with pytest.raises(KeyError):
        get_pass("no_such_pass")
    with pytest.raises(KeyError):
        register_pass("fuse_conv_bn", lambda p: p)  # duplicate


def test_find_chain_matches_conv_bn():
    main, _, _ = _conv_bn_program()
    blk = main.global_block()
    chains = find_chain(blk, ["conv2d", "batch_norm"])
    assert len(chains) == 1
    i, j = chains[0]
    assert blk.ops[i].type == "conv2d" and blk.ops[j].type == "batch_norm"
    # a chain whose head output has >1 consumer must NOT match
    assert find_chain(blk, ["batch_norm", "conv2d"]) == []


def test_custom_pass_and_builder_pipeline(tmp_path):
    calls = []

    @register_pass("count_ops_test")
    def _count(program, tag=""):
        calls.append(tag)
        return len(program.global_block().ops)

    try:
        main, startup, pred = _conv_bn_program()
        n = apply_pass(main, "count_ops_test", tag="direct")
        assert n == len(main.global_block().ops)

        pb = (PassBuilder()
              .append_pass("count_ops_test", tag="in_pipeline")
              .append_pass("graph_viz", path=str(tmp_path / "g.dot")))
        assert pb.all_passes() == ["count_ops_test", "graph_viz"]
        results = pb.apply(main)
        assert results["count_ops_test"] == n
        assert (tmp_path / "g.dot").exists()
        assert calls == ["direct", "in_pipeline"]
    finally:
        from paddle_tpu.transpiler import passes as _p

        _p._PASSES.pop("count_ops_test", None)


def test_pipeline_program_chaining():
    """A pass returning a new Program (inference_optimize) feeds it to
    later passes: the graph_viz dot of the result has no train-only
    state."""
    rng = np.random.RandomState(0)
    main, startup, pred = _conv_bn_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        pb = (PassBuilder()
              .append_pass("inference_optimize", scope=scope)
              .append_pass("memory_optimize"))
        results = pb.apply(main)
        optimized = results["__program__"]
        assert optimized is not main
        # folded program still runs and matches the original forward
        x = rng.rand(2, 3, 8, 8).astype("float32")
        ref, = exe.run(main.clone(for_test=True), feed={"img": x},
                       fetch_list=[pred.name])
        out, = exe.run(optimized, feed={"img": x},
                       fetch_list=[pred.name])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
