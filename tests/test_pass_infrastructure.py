"""Pass registry / PassBuilder / chain matcher (reference
framework/ir/pass.h REGISTER_PASS, pass_builder.cc, and
graph_pattern_detector.cc): named program-rewrite passes composed into
ordered pipelines."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.transpiler import (PassBuilder, apply_pass, find_chain,
                                   get_pass, list_passes, register_pass)


def _conv_bn_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(c, act="relu", is_test=True,
                                    use_global_stats=True)
        pred = fluid.layers.fc(b, size=2, act="softmax")
    return main, startup, pred


def test_registry_and_builtins():
    names = list_passes()
    for expected in ("fuse_conv_bn", "inference_optimize", "bfloat16",
                     "graph_viz", "memory_optimize"):
        assert expected in names, names
    assert callable(get_pass("fuse_conv_bn"))
    with pytest.raises(KeyError):
        get_pass("no_such_pass")
    with pytest.raises(KeyError):
        register_pass("fuse_conv_bn", lambda p: p)  # duplicate


def test_find_chain_matches_conv_bn():
    main, _, _ = _conv_bn_program()
    blk = main.global_block()
    chains = find_chain(blk, ["conv2d", "batch_norm"])
    assert len(chains) == 1
    i, j = chains[0]
    assert blk.ops[i].type == "conv2d" and blk.ops[j].type == "batch_norm"
    # a chain whose head output has >1 consumer must NOT match
    assert find_chain(blk, ["batch_norm", "conv2d"]) == []


def test_custom_pass_and_builder_pipeline(tmp_path):
    calls = []

    @register_pass("count_ops_test")
    def _count(program, tag=""):
        calls.append(tag)
        return len(program.global_block().ops)

    try:
        main, startup, pred = _conv_bn_program()
        n = apply_pass(main, "count_ops_test", tag="direct")
        assert n == len(main.global_block().ops)

        pb = (PassBuilder()
              .append_pass("count_ops_test", tag="in_pipeline")
              .append_pass("graph_viz", path=str(tmp_path / "g.dot")))
        assert pb.all_passes() == ["count_ops_test", "graph_viz"]
        results = pb.apply(main)
        assert results["count_ops_test"] == n
        assert (tmp_path / "g.dot").exists()
        assert calls == ["direct", "in_pipeline"]
    finally:
        from paddle_tpu.transpiler import passes as _p

        _p._PASSES.pop("count_ops_test", None)


def test_registry_has_new_builtin_passes():
    names = list_passes()
    for expected in ("dead_var_eliminate", "const_fold",
                     "quantize_inference"):
        assert expected in names, names


# ---------------------------------------------------------------------------
# semantics-preserving passes (ROADMAP item 5 acceptance): >= 3
# registered passes asserted same-fetches with bit tolerance
# ---------------------------------------------------------------------------

def _run(program, exe, scope, feed, fetch_name):
    (out,) = exe.run(program, feed=feed, fetch_list=[fetch_name],
                     scope=scope)
    return np.asarray(out)


def test_dead_var_eliminate_preserves_semantics():
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[8])
        live = fluid.layers.fc(a, size=4, act="relu")
        fluid.layers.fc(a, size=32, act="relu")     # dead branch
        out = fluid.layers.fc(live, size=2)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"a": rng.rand(4, 8).astype("float32")}
        ref = _run(main, exe, scope, feed, out.name)
        n_ops = len(main.global_block().ops)
        res = apply_pass(main, "dead_var_eliminate",
                         fetch_names=[out.name])
        assert res["ops_removed"] >= 2 and res["vars_removed"] >= 1, res
        assert len(main.global_block().ops) < n_ops
        # same fetches, BIT-identical (the pass only removes dead work)
        np.testing.assert_array_equal(
            ref, _run(main, exe, scope, feed, out.name))


def test_dead_var_eliminate_default_keeps_terminal_outputs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[4])
        fluid.layers.fc(a, size=2)     # terminal: live by default
    n_ops = len(main.global_block().ops)
    res = apply_pass(main, "dead_var_eliminate")
    assert res["ops_removed"] == 0
    assert len(main.global_block().ops) == n_ops


def test_const_fold_preserves_semantics():
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        b = fluid.layers.data("b", shape=[4])
        c1 = fluid.layers.fill_constant(shape=[4], dtype="float32",
                                        value=2.0)
        c2 = fluid.layers.scale(c1, scale=0.5)
        c3 = fluid.layers.elementwise_add(
            c2, fluid.layers.fill_constant(shape=[4], dtype="float32",
                                           value=1.0))
        y = fluid.layers.elementwise_add(fluid.layers.fc(b, size=4), c3)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"b": rng.rand(3, 4).astype("float32")}
        ref = _run(main, exe, scope, feed, y.name)
        n = apply_pass(main, "const_fold")
        assert n >= 3, n
        types = [op.type for op in main.global_block().ops]
        assert "fill_constant" not in types
        assert types.count("assign_value") == 1    # one materialized
        # same fetches, BIT-identical (the folded value is the same
        # arithmetic, computed once at pass time)
        np.testing.assert_array_equal(
            ref, _run(main, exe, scope, feed, y.name))


def test_const_fold_never_folds_rebound_names():
    """Regression (review repro): a var name WRITTEN TWICE — constant
    first, runtime value second — must not fold consumers against the
    stale first write (the IR is not SSA; name-keyed constants are only
    sound for single-write names)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data("d", shape=[4])
        t = fluid.layers.fill_constant(shape=[1, 4], dtype="float32",
                                       value=2.0)
        blk = main.global_block()
        # rebind t to the runtime feed, then consume it
        blk.append_op(type="assign", inputs={"X": [data.name]},
                      outputs={"Out": [t.name]})
        u = fluid.layers.scale(t, scale=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"d": np.full((1, 4), 8.0, "float32")}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed=feed, fetch_list=[u.name])
        apply_pass(main, "const_fold")
        (out,) = exe.run(main, feed=feed, fetch_list=[u.name])
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full((1, 4), 4.0, "float32"))


def test_const_fold_skips_persistable_outputs():
    """Startup-program init ops write persistables through the
    executor's writeback — folding them away would skip parameter
    init."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        fluid.layers.fc(x, size=2)
    n_startup = len(startup.global_block().ops)
    assert apply_pass(startup, "const_fold") == 0
    assert len(startup.global_block().ops) == n_startup


def test_fuse_conv_bn_preserves_semantics():
    """fuse_conv_bn decomposes train-mode BNs around 1x1 convs into the
    fused producer/consumer op chain — same fetches within float
    tolerance (the test_conv_bn_fusion precedent band)."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[8, 8, 8])
            c1 = fluid.layers.conv2d(img, num_filters=16, filter_size=1,
                                     bias_attr=False)
            b1 = fluid.layers.batch_norm(c1, act="relu")
            c2 = fluid.layers.conv2d(b1, num_filters=4, filter_size=1,
                                     bias_attr=False)
            out = fluid.layers.mean(c2)
        return main, startup, out

    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(2, 8, 8, 8).astype("float32")}
    exe = fluid.Executor(fluid.CPUPlace())

    main, startup, out = build()
    # one parameter set, shared by name: the fused clone reads the same
    # scope values, so the A/B isolates the pass's arithmetic
    fused = main.clone()
    n = apply_pass(fused, "fuse_conv_bn")
    assert n >= 1
    types = [op.type for op in fused.global_block().ops]
    assert "batch_norm" not in types
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ref = _run(main, exe, scope, feed, out.name)
        np.testing.assert_allclose(
            ref, _run(fused, exe, scope, feed, out.name),
            rtol=2e-3, atol=2e-4)


def test_pipeline_program_chaining():
    """A pass returning a new Program (inference_optimize) feeds it to
    later passes: the graph_viz dot of the result has no train-only
    state."""
    rng = np.random.RandomState(0)
    main, startup, pred = _conv_bn_program()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        pb = (PassBuilder()
              .append_pass("inference_optimize", scope=scope)
              .append_pass("memory_optimize"))
        results = pb.apply(main)
        optimized = results["__program__"]
        assert optimized is not main
        # folded program still runs and matches the original forward
        x = rng.rand(2, 3, 8, 8).astype("float32")
        ref, = exe.run(main.clone(for_test=True), feed={"img": x},
                       fetch_list=[pred.name])
        out, = exe.run(optimized, feed={"img": x},
                       fetch_list=[pred.name])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
