"""sequence_pad/unpad/reshape/expand_as/scatter + im2sequence tests
(numpy oracles, OpTest pattern)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(build, feed):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fetches = build()
        exe = fluid.Executor(fluid.CPUPlace())
        return exe.run(feed=feed, fetch_list=list(fetches))


def test_sequence_pad_pads_and_reports_lengths():
    x = np.arange(24, dtype="float32").reshape(2, 4, 3)
    lens = np.array([2, 4], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[3], dtype="float32", lod_level=1)
        out, slen = fluid.layers.sequence_pad(xi, maxlen=6)
        return out, slen

    out, slen = _run(build, {"x": x, "x@LEN": lens})
    assert out.shape == (2, 6, 3)
    np.testing.assert_array_equal(out[0, :2], x[0, :2])
    assert (out[0, 2:] == 0).all()        # pad_value default 0
    np.testing.assert_array_equal(out[1, :4], x[1])
    np.testing.assert_array_equal(slen, [2, 4])


def test_sequence_unpad_roundtrip():
    x = np.random.rand(3, 5, 2).astype("float32")
    lens = np.array([5, 1, 3], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[5, 2], dtype="float32",
                               append_batch_size=False)
        xi.shape = (-1, 5, 2)
        ln = fluid.layers.data("ln", shape=[], dtype="int32",
                               append_batch_size=False)
        ln.shape = (-1,)
        seq = fluid.layers.sequence_unpad(xi, ln)
        pooled = fluid.layers.sequence_pool(seq, "sum")
        return seq, pooled

    seq, pooled = _run(build, {"x": x, "ln": lens})
    for i, l in enumerate(lens):
        np.testing.assert_allclose(seq[i, :l], x[i, :l], rtol=1e-6)
        assert (seq[i, l:] == 0).all()
        np.testing.assert_allclose(pooled[i], x[i, :l].sum(0), rtol=1e-5)


def test_sequence_reshape_rechunks():
    x = np.arange(2 * 4 * 6, dtype="float32").reshape(2, 4, 6)
    lens = np.array([2, 4], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[6], dtype="float32", lod_level=1)
        out = fluid.layers.sequence_reshape(xi, new_dim=3)
        ln = fluid.layers.sequence_length(out)
        return out, ln

    out, ln = _run(build, {"x": x, "x@LEN": lens})
    assert out.shape == (2, 8, 3)
    np.testing.assert_array_equal(ln, [4, 8])
    np.testing.assert_array_equal(out[0, :4].ravel(), x[0, :2].ravel())


def test_sequence_expand_as_repeats_rows():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], "float32")
    y = np.zeros((2, 5, 1), "float32")
    y_lens = np.array([3, 5], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[2])
        yi = fluid.layers.data("y", shape=[1], dtype="float32", lod_level=1)
        out = fluid.layers.sequence_expand_as(xi, yi)
        return (out,)

    (out,) = _run(build, {"x": x, "y": y, "y@LEN": y_lens})
    for t in range(3):
        np.testing.assert_array_equal(out[0, t], x[0])
    assert (out[0, 3:] == 0).all()
    for t in range(5):
        np.testing.assert_array_equal(out[1, t], x[1])


def test_sequence_scatter_adds_updates():
    x = np.zeros((2, 6), "float32")
    ids = np.array([[1, 3, 1], [0, 5, 0]], "int64")
    upd = np.array([[1.0, 2.0, 4.0], [7.0, 8.0, 9.0]], "float32")
    lens = np.array([3, 2], "int32")

    def build():
        xi = fluid.layers.data("x", shape=[6])
        ii = fluid.layers.data("ids", shape=[1], dtype="int64", lod_level=1)
        ui = fluid.layers.data("upd", shape=[1], dtype="float32",
                               lod_level=1)
        out = fluid.layers.sequence_scatter(xi, ii, ui)
        return (out,)

    (out,) = _run(build, {"x": x, "ids": ids[:, :, None], "ids@LEN": lens,
                          "upd": upd[:, :, None], "upd@LEN": lens})
    want0 = np.zeros(6)
    want0[1] = 1 + 4
    want0[3] = 2
    np.testing.assert_allclose(out[0], want0)
    want1 = np.zeros(6)
    want1[0] = 7
    want1[5] = 8                          # third update beyond len=2 ignored
    np.testing.assert_allclose(out[1], want1)


def test_im2sequence_matches_numpy_patches():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 6, 6).astype("float32")

    def build():
        xi = fluid.layers.data("img", shape=[3, 6, 6])
        out = fluid.layers.im2sequence(xi, filter_size=2, stride=2)
        ln = fluid.layers.sequence_length(out)
        return out, ln

    out, ln = _run(build, {"img": x})
    assert out.shape == (2, 9, 12)
    np.testing.assert_array_equal(ln, [9, 9])
    # oracle: patch at (i, j) -> features ordered (c, kh, kw)
    for b in range(2):
        for i in range(3):
            for j in range(3):
                patch = x[b, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2].ravel()
                np.testing.assert_allclose(out[b, i * 3 + j], patch,
                                           rtol=1e-6)


def test_sequence_pad_grad_flows():
    x = np.random.rand(2, 4, 3).astype("float32")
    lens = np.array([2, 3], "int32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xi = fluid.layers.data("x", shape=[3], dtype="float32", lod_level=1)
        xi.stop_gradient = False
        out, _ = fluid.layers.sequence_pad(xi, maxlen=5)
        loss = fluid.layers.reduce_sum(out * out)
        grads = fluid.calc_gradient(loss, [xi])
        exe = fluid.Executor(fluid.CPUPlace())
        (gv,) = exe.run(feed={"x": x, "x@LEN": lens}, fetch_list=grads)
    mask = np.arange(4)[None, :, None] < lens[:, None, None]
    np.testing.assert_allclose(gv, np.where(mask, 2 * x, 0), rtol=1e-5)
