"""Goodput ledger tests (ISSUE 10): the span->bucket classifier, the
ledger's gap/step arithmetic on synthetic timelines, the registry
publication, the disabled-is-free contract, and the acceptance drill —
a monitored run exercising checkpoint, rollback, and autotune-probe
paths whose bucket seconds sum to externally measured wall clock within
1% with no event double-counted."""

import glob
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import fault, monitor
from paddle_tpu.monitor.goodput import (BUCKETS, GoodputLedger,
                                        classify_span)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(autouse=True)
def monitor_off_after():
    yield
    fault.clear()
    fault.clear_injections()
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()
    monitor.goodput_reset()


# ---------------------------------------------------------------------------
# classifier: one table, two consumers (live ledger + trace_summary)
# ---------------------------------------------------------------------------

def test_classifier_table():
    assert classify_span("executor/fetch_sync") == "input_wait"
    assert classify_span("parallel_executor/h2d_transfer") == "input_wait"
    assert classify_span("executor/compile") == "trace_compile"
    assert classify_span("checkpoint/snapshot") == "checkpoint_stall"
    assert classify_span("guardian/rollback") == "recovery"
    # containers, nested spans, and overlapped background work are
    # excluded from direct attribution (compute is the step remainder)
    for name in ("executor/run", "executor/trace", "executor/dispatch",
                 "prefetch/h2d_transfer", "checkpoint/save",
                 "trainer/step", "trainer/checkpoint"):
        assert classify_span(name) is None, name
    # unknown spans attribute nowhere rather than guessing
    assert classify_span("somebody/new_span") is None


def test_classifier_bucket_hint_wins():
    # the executors tag their cold/warm step spans: a hint names the
    # bucket directly; the "compute" hint means "step remainder", which
    # the ledger derives rather than double-counting the span
    assert classify_span("executor/dispatch",
                         {"bucket": "trace_compile"}) == "trace_compile"
    assert classify_span("executor/compile",
                         {"bucket": "compute"}) is None
    # a bogus hint falls back to the name table
    assert classify_span("executor/compile",
                         {"bucket": "nonsense"}) == "trace_compile"
    assert classify_span("executor/compile",
                         {"run_id": "x"}) == "trace_compile"
    # RecordEvent args are an arbitrary user payload: non-dict args
    # must never raise into the step path (regression: review pass)
    assert classify_span("executor/compile", "a-label") == "trace_compile"
    assert classify_span("user/custom", ["x"]) is None


def test_non_dict_span_args_survive_the_monitored_step(fresh_programs):
    from paddle_tpu.profiler import RecordEvent

    monitor.enable()
    with RecordEvent("user/custom", args="label-string"):
        pass
    with RecordEvent("executor/compile", args=("tuple", "args")):
        pass
    assert monitor.goodput_ledger().totals()["trace_compile"] >= 0


# ---------------------------------------------------------------------------
# ledger arithmetic on synthetic timelines (no executors, no clocks)
# ---------------------------------------------------------------------------

def _step(ledger, ts, seconds, probe=False):
    rec = {"step_seconds": seconds, "ts": ts}
    if probe:
        rec["probe"] = True
    return ledger.note_step(rec, now=ts)


def test_ledger_step_and_gap_attribution():
    lg = GoodputLedger()
    lg.reset(now=1000.0)
    # a compile span inside the first step, which spans [1003, 1007]
    lg.note_span("executor/compile", 2.0, now=1005.0)
    _step(lg, 1007.0, 4.0)
    t = lg.totals()
    # gap [1000, 1003] had nothing classified -> other
    assert t["other"] == pytest.approx(3.0)
    assert t["trace_compile"] == pytest.approx(2.0)
    assert t["compute"] == pytest.approx(2.0)
    # a sync checkpoint leg in the next gap, then a 1s step at 1012
    lg.note_event({"event": "checkpoint_saved", "ts": 1009.0,
                   "seconds": 1.0, "async": False})
    lg.note_span("checkpoint/snapshot", 0.5, now=1008.0)
    _step(lg, 1012.0, 1.0)
    t = lg.totals()
    assert t["checkpoint_stall"] == pytest.approx(1.5)
    assert t["other"] == pytest.approx(3.0 + (4.0 - 1.5))
    assert t["compute"] == pytest.approx(3.0)
    # exhaustive by construction
    assert sum(t.values()) == pytest.approx(1012.0 - 1000.0)


def test_ledger_pipeline_bubble_carves_the_compute_remainder():
    """ISSUE 12: the pipeline_bubble span encodes the executed
    schedule's idle fraction (seconds = fraction * step_seconds); the
    ledger applies that fraction to the step's COMPUTE REMAINDER (the
    pipelined time), never to input-wait/compile seconds, and the
    bucket sum stays exclusive-exhaustive."""
    lg = GoodputLedger()
    lg.reset(now=0.0)
    # step [0, 4]: 1s of h2d carve-out, bubble span claiming 25% of the
    # step -> remainder 3s splits 0.75 bubble / 2.25 compute
    lg.note_span("executor/h2d_transfer", 1.0, now=3.0)
    lg.note_span("pipeline/bubble", 1.0,
                 args={"bucket": "pipeline_bubble", "fraction": 0.25},
                 now=3.9)
    _step(lg, 4.0, 4.0)
    t = lg.totals()
    assert t["input_wait"] == pytest.approx(1.0)
    assert t["pipeline_bubble"] == pytest.approx(0.75)
    assert t["compute"] == pytest.approx(2.25)
    assert sum(t.values()) == pytest.approx(4.0)
    # an io-dominated step: other carve-outs eat the whole step, the
    # bubble scales to the (empty) remainder instead of inventing time
    lg.note_span("executor/h2d_transfer", 4.0, now=7.9)
    lg.note_span("pipeline/bubble", 1.0,
                 args={"bucket": "pipeline_bubble"}, now=7.95)
    _step(lg, 8.0, 4.0)
    t = lg.totals()
    assert t["pipeline_bubble"] == pytest.approx(0.75)   # unchanged
    assert sum(t.values()) == pytest.approx(8.0)
    # name-table classification matches the hint path (trace_summary's
    # offline view agrees with the live ledger)
    assert classify_span("pipeline/bubble") == "pipeline_bubble"
    assert "pipeline_bubble" in BUCKETS


def test_ledger_async_save_is_overlap_not_stall():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    lg.note_event({"event": "checkpoint_saved", "ts": 5.0,
                   "seconds": 2.0, "async": True})
    _step(lg, 10.0, 1.0)
    t = lg.totals()
    assert t["checkpoint_stall"] == 0.0
    assert sum(t.values()) == pytest.approx(10.0)
    assert lg.summary(now=10.0)["overlap_seconds"][
        "checkpoint_save"] == pytest.approx(2.0)


def test_ledger_replay_debt_books_steps_as_recovery():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    _step(lg, 1.0, 1.0)
    lg.note_span("guardian/rollback", 0.5, now=2.0)
    lg.note_event({"event": "guardian_rollback", "ts": 2.0,
                   "replay_steps": 2})
    _step(lg, 3.0, 1.0)          # replayed
    _step(lg, 4.0, 1.0)          # replayed
    _step(lg, 5.0, 1.0)          # fresh work again
    t = lg.totals()
    # restore span (0.5, in the gap) + two replayed steps (2.0)
    assert t["recovery"] == pytest.approx(2.5)
    assert t["compute"] == pytest.approx(2.0)
    assert sum(t.values()) == pytest.approx(5.0)
    assert lg.summary(now=5.0)["recovery_replayed_steps"] == 2


def test_ledger_probe_step_and_probe_gap():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    # the gap leading into a probe step is probe work too (the tuner's
    # cost_analysis compiles run between its measured windows)
    _step(lg, 3.0, 1.0, probe=True)
    t = lg.totals()
    assert t["probe"] == pytest.approx(3.0)
    assert t["compute"] == 0.0
    s = lg.summary(now=3.0)
    assert s["probe_steps"] == 1


def test_ledger_stall_window_books_gap_idle():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    _step(lg, 1.0, 1.0)
    # watchdog fired at t=7 after 4s of no progress; the next step only
    # begins at t=9 — the stall overlap [3, 7] books as stall_idle
    lg.note_event({"event": "watchdog_stall", "ts": 7.0,
                   "stalled_for_s": 4.0})
    _step(lg, 10.0, 1.0)
    t = lg.totals()
    assert t["stall_idle"] == pytest.approx(4.0)
    assert t["other"] == pytest.approx(4.0)   # [1,3] + [7,9]
    assert sum(t.values()) == pytest.approx(10.0)


def test_ledger_in_step_clamp_keeps_sum_exhaustive():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    # classified in-step spans exceed the step wall (nesting noise):
    # the carve-out scales down, compute floors at 0, sum is preserved
    lg.note_span("executor/compile", 3.0, now=0.9)
    lg.note_span("executor/h2d_transfer", 1.0, now=0.95)
    _step(lg, 1.0, 1.0)
    t = lg.totals()
    assert t["compute"] == pytest.approx(0.0)
    assert t["trace_compile"] == pytest.approx(0.75)
    assert t["input_wait"] == pytest.approx(0.25)
    assert sum(t.values()) == pytest.approx(1.0)


def test_ledger_summary_tail_is_readonly():
    lg = GoodputLedger()
    lg.reset(now=0.0)
    _step(lg, 1.0, 1.0)
    lg.note_span("checkpoint/snapshot", 0.5, now=2.0)
    s1 = lg.summary(now=4.0)
    # the tail [1, 4] is attributed in the VIEW: snapshot + other
    assert s1["buckets"]["checkpoint_stall"] == pytest.approx(0.5)
    assert s1["buckets"]["other"] == pytest.approx(2.5)
    assert s1["wall_seconds"] == pytest.approx(4.0)
    # ...without consuming the pending span or moving the watermark
    s2 = lg.summary(now=4.0)
    assert s2 == s1
    _step(lg, 5.0, 1.0)
    t = lg.totals()
    assert t["checkpoint_stall"] == pytest.approx(0.5)
    assert sum(t.values()) == pytest.approx(5.0)


def test_ledger_registry_publication():
    from paddle_tpu.monitor.registry import MetricsRegistry

    reg = MetricsRegistry()
    lg = GoodputLedger(reg)
    lg.reset(now=0.0)
    lg.note_span("executor/compile", 1.0, now=1.5)
    _step(lg, 2.0, 1.0)
    assert reg.get("badput/trace_compile_seconds").value \
        == pytest.approx(1.0)
    assert reg.get("goodput/compute_seconds").value == pytest.approx(0.0)
    assert reg.get("badput/other_seconds").value == pytest.approx(1.0)
    assert reg.get("goodput/wall_seconds").value == pytest.approx(2.0)
    assert 0.0 <= reg.get("goodput/ratio").value <= 1.0
    # counters survive a registry reset via handle re-binding
    reg.reset()
    _step(lg, 3.0, 1.0)
    assert reg.get("goodput/compute_seconds").value == pytest.approx(1.0)
    exposed = reg.expose_text()
    assert "badput_trace_compile_seconds" in exposed or \
        "goodput_ratio" in exposed


# ---------------------------------------------------------------------------
# monitor wiring
# ---------------------------------------------------------------------------

def _build_mlp():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    return loss


def test_disabled_monitor_never_touches_the_ledger(fresh_programs):
    """The disabled-cost contract, A/B-enforced structurally: with the
    monitor off, a step must make ZERO ledger calls (the one
    module-global bool read gates everything) — any call would raise
    here."""
    monitor.disable()
    loss = _build_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    lg = monitor.goodput_ledger()
    orig = (lg.note_span, lg.note_step, lg.note_event)

    def boom(*a, **k):
        raise AssertionError("ledger touched while monitor disabled")

    lg.note_span = lg.note_step = lg.note_event = boom
    try:
        assert not monitor.enabled()
        for _ in range(3):
            exe.run(feed={"x": np.random.rand(4, 8).astype("float32"),
                          "label": np.zeros((4, 1), "int64")},
                    fetch_list=[loss])
    finally:
        lg.note_span, lg.note_step, lg.note_event = orig
    assert lg.steps == 0


def test_step_records_carry_goodput_deltas(tmp_path, fresh_programs):
    """Monitored steps stamp their per-step attribution delta into the
    JSONL record; a cumulative ``goodput`` record lands too; the ratio
    gauge is live in /metrics text; and the end-to-end exclusive-
    exhaustive invariant holds — bucket seconds sum to externally
    measured wall clock within 1% (the slow-marked drill below extends
    this to checkpoint/rollback/probe paths)."""
    log_dir = str(tmp_path / "logs")
    loss = _build_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    monitor.enable(log_dir=log_dir)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    t0 = time.time()
    monitor.goodput_ledger().reset(now=t0)
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32"),
                      "label": np.zeros((4, 1), "int64")},
                fetch_list=[loss])
    summ = monitor.goodput_ledger().summary(now=time.time())
    wall = time.time() - t0
    assert abs(sum(summ["buckets"].values()) - wall) \
        <= 0.01 * wall + 0.005, (summ["buckets"], wall)
    monitor.goodput_stamp()
    assert "goodput_ratio" in monitor.expose_text()
    monitor.disable()
    events = []
    for p in glob.glob(os.path.join(log_dir, "*.jsonl")):
        with open(p) as f:
            events += [json.loads(l) for l in f if l.strip()]
    steps = [e for e in events if e.get("event") == "step_stats"]
    assert steps and any(isinstance(e.get("goodput"), dict)
                         and e["goodput"] for e in steps)
    stamps = [e for e in events if e.get("event") == "goodput"]
    assert stamps
    final = max(stamps, key=lambda e: e.get("wall_seconds") or 0)
    assert set(final["buckets"]) == set(BUCKETS)
    assert 0 < final["goodput_ratio"] <= 1


def test_trainer_stamps_goodput_even_on_abort(tmp_path, fresh_programs):
    """The Trainer's exit stamp lives in the finally: a run that dies
    via GuardianAbortError (the run that NEEDS a post-mortem) still
    leaves the cumulative goodput record in the JSONL (regression:
    review pass)."""
    from paddle_tpu import guardian
    from paddle_tpu.contrib import Trainer
    from paddle_tpu.reader import checkpointable

    log_dir = str(tmp_path / "logs")
    monitor.enable(log_dir=log_dir)
    fault.clear()
    fault.clear_injections()
    # a persistent NaN with no checkpoint config: the guardian wants a
    # rollback, the Trainer has nothing to roll back to -> typed abort
    fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[2]),
                     once=True)

    def train_func():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        return _build_mlp()

    def samples():
        srng = np.random.RandomState(0)
        for _ in range(32):
            x = srng.rand(8).astype("float32")
            yield x, np.array([0], "int64")

    trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                      optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                      guardian_config={"policy": "rollback,abort"})
    with pytest.raises(guardian.GuardianAbortError):
        trainer.train(num_epochs=1, event_handler=lambda ev: None,
                      reader=checkpointable(
                          fluid.batch(samples, batch_size=4)),
                      feed_order=["x", "label"])
    monitor.disable()
    events = []
    for p in glob.glob(os.path.join(log_dir, "*.jsonl")):
        with open(p) as f:
            events += [json.loads(l) for l in f if l.strip()]
    assert any(e.get("event") == "goodput" for e in events)


def test_goodput_report_tool_replays_the_log(tmp_path, fresh_programs,
                                             capsys):
    """tools/goodput_report.py renders the same attribution from the
    JSONL replay (table + --json), like program_report does for the
    profile registry.  Invoked in-process (the tool is importable; the
    CLI wrapper is the same main()) to keep the suite off the
    interpreter-spawn cost."""
    log_dir = str(tmp_path / "logs")
    loss = _build_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    monitor.enable(log_dir=log_dir)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32"),
                      "label": np.zeros((4, 1), "int64")},
                fetch_list=[loss])
    live = monitor.goodput_stamp()
    monitor.disable()
    sys.path.insert(0, TOOLS)
    try:
        import goodput_report
    finally:
        sys.path.remove(TOOLS)
    assert goodput_report.main([log_dir, "--json"]) == 0
    replayed = json.loads(capsys.readouterr().out)
    assert replayed["buckets"] == live["buckets"]
    assert replayed["goodput_ratio"] == live["goodput_ratio"]
    assert goodput_report.main([log_dir]) == 0
    table = capsys.readouterr().out
    assert "goodput ratio" in table and "trace_compile" in table


def test_watchdog_stall_dump_includes_goodput_snapshot(fresh_programs):
    """The stall diagnostic names where the wall clock has been going —
    actionable ('97% input_wait') instead of 'no step completed'."""
    loss = _build_mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    monitor.enable(stall_seconds=3600)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(2):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32"),
                      "label": np.zeros((4, 1), "int64")},
                fetch_list=[loss])
    from paddle_tpu.monitor import _stall_probe

    diag = _stall_probe()
    gp = diag["goodput"]
    assert gp["recent_steps"] >= 2
    assert gp["recent_fractions"]
    assert abs(sum(gp["recent_fractions"].values()) - 1.0) < 0.02
    # and the formatter renders it
    from paddle_tpu.monitor import _format_diag

    line = _format_diag(dict(diag, stalled_for_s=1.0))
    assert "goodput last" in line


# ---------------------------------------------------------------------------
# acceptance: exclusive-exhaustive over a run with checkpoint, rollback
# and probe paths (ISSUE 10 acceptance criterion)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_exclusive_buckets_sum_to_wall_clock(tmp_path, fresh_programs):
    """A ~50-step monitored run with a forced (synchronous) checkpoint
    cadence, an injected-NaN guardian rollback, and an autotune probe:
    bucket seconds sum to externally measured wall clock within 1%, no
    event is double-counted (checkpoint_stall reconciles against the
    snapshot spans + sync saves that produced it; recovery covers
    exactly the rollback + replayed steps), and every badput source
    shows up in its own bucket.

    ``slow``-marked for the tier-1 wall-clock budget (the precedent of
    the sp_pp parity drills): the invariant itself stays tier-1-
    enforced by the synthetic-timeline unit tests above plus the
    end-to-end 1% check in
    ``test_step_records_carry_goodput_deltas``; this drill additionally
    exercises the checkpoint/rollback/probe classification on the real
    Trainer machinery (run with ``-m slow``)."""
    from paddle_tpu import autotune
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    from paddle_tpu.reader import checkpointable

    log_dir = str(tmp_path / "logs")
    monitor.enable(log_dir=log_dir)
    fault.clear()
    fault.clear_injections()
    fault.inject_nan("fc_0.w_0", fault.FaultSchedule(steps=[8]),
                     once=True)

    t0 = time.time()
    monitor.goodput_ledger().reset(now=t0)

    # --- an autotune probe (its steps and lead-in compiles are PROBE)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[16])
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64")
        p = fluid.layers.fc(img, size=4, act="softmax")
        ploss = fluid.layers.mean(fluid.layers.cross_entropy(p, lbl))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(ploss)
        rng = np.random.RandomState(0)

        def make_feed(b):
            return {"img": rng.rand(b, 16).astype("float32"),
                    "lbl": rng.randint(0, 4, (b, 1)).astype("int64")}

        autotune.tune_batch_size(
            fluid.default_main_program(),
            fluid.default_startup_program(), make_feed, ploss,
            fluid.CPUPlace(), ladder=[8, 16], probe_steps=2,
            warmup_steps=1)

    # --- the guarded training run: NaN at step 8 -> rollback + replay
    def train_func():
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        return _build_mlp()

    def samples():
        srng = np.random.RandomState(0)
        for _ in range(200):
            x = srng.rand(8).astype("float32")
            yield x, np.array([int(np.argmax(x[:4]))], "int64")

    losses = []

    def handler(ev):
        if hasattr(ev, "metrics"):
            losses.append(float(np.ravel(ev.metrics[0])[0]))

    trainer = Trainer(
        train_func=train_func, place=fluid.CPUPlace(),
        optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
        checkpoint_config=CheckpointConfig(
            checkpoint_dir=str(tmp_path / "ckpt"), step_interval=5,
            async_save=False),
        guardian_config={"policy": "rollback,abort"})
    trainer.train(num_epochs=1, event_handler=handler,
                  reader=checkpointable(
                      fluid.batch(samples, batch_size=4)),
                  feed_order=["x", "label"])
    assert len(losses) >= 50 and np.isfinite(losses[-1])

    summary = monitor.goodput_ledger().summary(now=time.time())
    wall = time.time() - t0
    monitor.disable()

    buckets = summary["buckets"]
    total = sum(buckets.values())
    # exhaustive: the buckets cover the externally measured wall clock
    assert abs(total - wall) <= 0.01 * wall, (total, wall, buckets)
    assert summary["wall_seconds"] == pytest.approx(total)
    # every exercised badput source lands in ITS bucket
    assert buckets["probe"] > 0
    assert buckets["checkpoint_stall"] > 0
    assert buckets["recovery"] > 0
    assert buckets["trace_compile"] > 0
    assert buckets["compute"] > 0
    assert summary["probe_steps"] > 0
    assert summary["recovery_replayed_steps"] > 0

    # exclusivity / no double count: checkpoint_stall never exceeds the
    # sync legs that produced it (snapshot spans + sync save events),
    # and recovery never exceeds rollback span + replayed step time
    reg = monitor.registry()
    snap = reg.get("span/checkpoint/snapshot")
    snap_total = snap.sum if snap is not None else 0.0
    events = []
    for path in glob.glob(os.path.join(log_dir, "*.jsonl")):
        with open(path) as f:
            events += [json.loads(l) for l in f if l.strip()]
    sync_saves = sum(e.get("seconds", 0.0)
                     for e in events if e.get("event") == "checkpoint_saved"
                     and not e.get("async"))
    assert buckets["checkpoint_stall"] <= snap_total + sync_saves + 1e-6
    rb_span = reg.get("span/guardian/rollback")
    rb_total = rb_span.sum if rb_span is not None else 0.0
    replay_wall = sum(
        e.get("step_seconds", 0.0) for e in events
        if e.get("event") == "step_stats"
        and "recovery" in (e.get("goodput") or {}))
    assert rb_total > 0
    assert buckets["recovery"] <= rb_total + replay_wall + 1e-6
    # the rollback event's replay debt is exactly what got booked
    rollbacks = [e for e in events if e.get("event") == "guardian_rollback"]
    assert len(rollbacks) == 1
    assert summary["recovery_replayed_steps"] \
        == rollbacks[0]["replay_steps"]
