"""Tests for metrics.py (streaming metric classes), clip.py (gradient
clipping numerics), regularizer.py (L1/L2 decay) — VERDICT weak item 5
named these untested."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import metrics


# ---------------------------------------------------------------- metrics

def test_precision_recall_streaming():
    p = metrics.Precision()
    r = metrics.Recall()
    preds1 = np.array([1, 1, 0, 1])
    labels1 = np.array([1, 0, 1, 1])
    preds2 = np.array([0, 1])
    labels2 = np.array([0, 1])
    for m in (p, r):
        m.update(preds1, labels1)
        m.update(preds2, labels2)
    # tp=3, fp=1, fn=1
    assert p.eval() == pytest.approx(3 / 4)
    assert r.eval() == pytest.approx(3 / 4)


def test_accuracy_weighted():
    a = metrics.Accuracy()
    a.update(0.5, 10)
    a.update(1.0, 30)
    assert a.eval() == pytest.approx((0.5 * 10 + 1.0 * 30) / 40)
    with pytest.raises(Exception):
        metrics.Accuracy().update(value=None, weight=None)


def test_edit_distance_metric():
    m = metrics.EditDistance()
    m.update(np.array([[2.0], [0.0]]), 2)
    m.update(np.array([[1.0]]), 1)
    avg, err = m.eval()
    assert avg == pytest.approx(3.0 / 3)
    assert err == pytest.approx(2.0 / 3)


def test_auc_against_sklearn_style_oracle():
    rng = np.random.RandomState(0)
    n = 500
    labels = rng.randint(0, 2, n)
    # informative scores
    scores = np.clip(labels * 0.3 + rng.rand(n) * 0.7, 0, 1)
    m = metrics.Auc(num_thresholds=4095)
    m.update(scores, labels)
    got = m.eval()

    # oracle: exact ROC AUC via rank statistic
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    cmp = (pos[:, None] > neg[None, :]).sum() + \
        0.5 * (pos[:, None] == neg[None, :]).sum()
    want = cmp / (len(pos) * len(neg))
    assert got == pytest.approx(want, abs=5e-3)


def test_composite_metric():
    c = metrics.CompositeMetric()
    c.add_metric(metrics.Precision())
    c.add_metric(metrics.Recall())
    c.update(np.array([1, 0]), np.array([1, 1]))
    prec, rec = c.eval()
    assert prec == pytest.approx(1.0)
    assert rec == pytest.approx(0.5)


# ---------------------------------------------------------------- clipping

def _train_once_with_clip(clip, lr=1.0):
    """One SGD step on a linear model; returns (w_before, w_after, grad)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 9
        x = fluid.layers.data("x", shape=[3])
        y = fluid.layers.fc(
            x, size=1, act=None,
            param_attr=fluid.ParamAttr(name="w_clip"),
            bias_attr=False)
        # big loss scale so unclipped grads exceed the thresholds
        loss = fluid.layers.reduce_sum(y) * 100.0
        if clip is not None:
            fluid.clip.set_gradient_clip(clip)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            w0 = np.asarray(scope.var("w_clip")).copy()
            xv = np.ones((2, 3), "float32")
            exe.run(feed={"x": xv}, fetch_list=[loss])
            w1 = np.asarray(scope.var("w_clip"))
    # effective applied grad = (w0 - w1) / lr
    return w0, w1, (w0 - w1) / lr


def test_gradient_clip_by_value():
    # unclipped grad of each w element = 100 * sum_b x_b = 200
    _, _, g = _train_once_with_clip(
        fluid.clip.GradientClipByValue(max=5.0))
    np.testing.assert_allclose(g, np.full((3, 1), 5.0), rtol=1e-5)


def test_gradient_clip_by_norm():
    _, _, g = _train_once_with_clip(
        fluid.clip.GradientClipByNorm(clip_norm=3.0))
    assert np.linalg.norm(g) == pytest.approx(3.0, rel=1e-5)
    # direction preserved: proportional to all-200 vector
    np.testing.assert_allclose(g / np.linalg.norm(g),
                               np.full((3, 1), 1 / np.sqrt(3)), rtol=1e-5)


def test_gradient_clip_by_global_norm():
    _, _, g = _train_once_with_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=1.0))
    assert np.linalg.norm(g) == pytest.approx(1.0, rel=1e-4)


def test_no_clip_baseline():
    _, _, g = _train_once_with_clip(None)
    np.testing.assert_allclose(g, np.full((3, 1), 200.0), rtol=1e-4)


def test_error_clip_by_value():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[2])
        x.stop_gradient = False
        y = x * 100.0
        loss = fluid.layers.reduce_sum(y)
        prog = fluid.default_main_program()
        y_var = prog.global_block().var(y.name)
        y_var.error_clip = fluid.clip.ErrorClipByValue(max=7.0)
        grads = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        (gx,) = exe.run(feed={"x": np.ones((2, 2), "float32")},
                        fetch_list=grads)
    # dloss/dy = 1 -> clip(1, 7) = 1 -> dx = 100; with max=0.005 it clips
    np.testing.assert_allclose(gx, np.full((2, 2), 100.0), rtol=1e-5)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[2])
        x.stop_gradient = False
        y = x * 100.0
        loss = fluid.layers.reduce_sum(y) * 5.0
        prog = fluid.default_main_program()
        prog.global_block().var(y.name).error_clip = \
            fluid.clip.ErrorClipByValue(max=2.0)
        grads = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        (gx,) = exe.run(feed={"x": np.ones((2, 2), "float32")},
                        fetch_list=grads)
    # dloss/dy = 5 -> clipped to 2 -> dx = 200
    np.testing.assert_allclose(gx, np.full((2, 2), 200.0), rtol=1e-5)


# ------------------------------------------------------------ regularizer

def _sgd_step_with_reg(reg, lr=0.1):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 10
        x = fluid.layers.data("x", shape=[3])
        y = fluid.layers.fc(x, size=1, act=None,
                            param_attr=fluid.ParamAttr(
                                name="w_reg", regularizer=reg),
                            bias_attr=False)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            w0 = np.asarray(scope.var("w_reg")).copy()
            xv = np.zeros((2, 3), "float32")   # data grad = 0
            exe.run(feed={"x": xv}, fetch_list=[loss])
            w1 = np.asarray(scope.var("w_reg"))
    return w0, w1, lr


def test_l2_decay_regularizer():
    coeff = 0.5
    w0, w1, lr = _sgd_step_with_reg(
        fluid.regularizer.L2DecayRegularizer(regularization_coeff=coeff))
    # zero data grad: w1 = w0 - lr * coeff * w0
    np.testing.assert_allclose(w1, w0 * (1 - lr * coeff), rtol=1e-5)


def test_l1_decay_regularizer():
    coeff = 0.5
    w0, w1, lr = _sgd_step_with_reg(
        fluid.regularizer.L1DecayRegularizer(regularization_coeff=coeff))
    np.testing.assert_allclose(w1, w0 - lr * coeff * np.sign(w0),
                               rtol=1e-5)
