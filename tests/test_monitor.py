"""Monitor subsystem tests (ISSUE 2): registry semantics, StepStats
from real executor runs, JSONL + Prometheus-exposition round-trips, the
HTTP endpoint, and the watchdog firing on a stalled pipeline within its
configured window — all with NO profiler session, which is the point:
the monitor is the always-on layer."""

import json
import os
import time
import urllib.request

import urllib.error

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.monitor import (Counter, Gauge, Histogram, MetricsRegistry,
                                Watchdog)


@pytest.fixture(autouse=True)
def monitor_off_after():
    """Every test leaves the process-global monitor disabled and its
    registry/aggregator empty — telemetry state must never leak into
    other test modules."""
    yield
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    r = MetricsRegistry()
    c = r.counter("steps")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = r.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2

    h = r.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(5.555)
    snap = h.snapshot()
    assert snap["counts"] == [1, 1, 1, 1]  # one per bucket + overflow


def test_registry_get_or_create_and_type_conflict():
    r = MetricsRegistry()
    assert r.counter("x") is r.counter("x")
    with pytest.raises(TypeError):
        r.gauge("x")
    with pytest.raises(ValueError):
        r.histogram("h", buckets=(1.0,))
        r.histogram("h", buckets=(2.0,))
    assert r.get("nope") is None


def test_expose_text_prometheus_round_trip():
    r = MetricsRegistry()
    r.counter("monitor/steps_total").inc(7)
    r.gauge("queue depth").set(2.5)
    h = r.histogram("step", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    text = r.expose_text()
    lines = text.splitlines()
    # names sanitized to prometheus-legal, values parseable
    assert "# TYPE monitor_steps_total counter" in lines
    assert "monitor_steps_total 7" in lines
    assert "queue_depth 2.5" in lines
    assert 'step_bucket{le="0.1"} 1' in lines
    assert 'step_bucket{le="1"} 2' in lines
    assert 'step_bucket{le="+Inf"} 3' in lines
    assert "step_count 3" in lines
    # round-trip: every sample line parses as "name[{labels}] value"
    for ln in lines:
        if ln.startswith("#"):
            continue
        name, val = ln.rsplit(" ", 1)
        float(val)
        assert name


def test_jsonl_writer_rotates(tmp_path):
    w = monitor.JsonlWriter(str(tmp_path), max_bytes=400, backups=2)
    for i in range(40):
        w.write({"event": "step_stats", "step": i, "pad": "x" * 40})
    w.close()
    files = sorted(os.listdir(str(tmp_path)))
    assert os.path.basename(w.path) in files
    assert any(f.endswith(".1") for f in files)       # rotated generation
    assert not any(f.endswith(".3") for f in files)   # backups honored
    # every line in every generation is valid JSON
    for f in files:
        for ln in open(os.path.join(str(tmp_path), f)):
            json.loads(ln)


def test_http_endpoint_serves_exposition():
    r = MetricsRegistry()
    r.counter("hits").inc(3)
    server = monitor.start_http_server(0, r.expose_text)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=5).read().decode()
        assert "hits 3" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://127.0.0.1:%d/other" % port, timeout=5)
    finally:
        server.shutdown()
        server.server_close()


def test_http_metrics_content_type_and_run_id():
    """/metrics must declare the Prometheus text exposition format
    version (scrapers key on it), and the monitor-level exposition
    leads with the run correlation id."""
    server = monitor.start_http_server(0, monitor.expose_text)
    try:
        port = server.server_address[1]
        resp = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=5)
        ctype = resp.headers.get("Content-Type", "")
        assert ctype.startswith("text/plain; version=0.0.4"), ctype
        body = resp.read().decode()
        assert body.startswith("# run_id %s\n" % monitor.run_id())
    finally:
        server.shutdown()
        server.server_close()


def test_jsonl_rotation_under_concurrent_writers(tmp_path):
    """Rotation racing concurrent step logging: no write may crash, no
    line may tear, every surviving generation stays valid JSONL."""
    import threading

    w = monitor.JsonlWriter(str(tmp_path), max_bytes=500, backups=2)
    errors = []

    def writer(tid):
        try:
            for i in range(200):
                w.write({"event": "step_stats", "thread": tid, "step": i,
                         "pad": "x" * 30})
        except Exception as e:  # noqa: BLE001 — the assertion below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    w.close()
    assert not errors
    files = sorted(os.listdir(str(tmp_path)))
    assert any(f.endswith(".1") for f in files)        # rotation happened
    assert not any(f.endswith(".3") for f in files)    # backups honored
    n = 0
    for f in files:
        for ln in open(os.path.join(str(tmp_path), f)):
            rec = json.loads(ln)                        # no torn lines
            assert rec["event"] == "step_stats"
            n += 1
    # rotation drops whole old generations, never corrupts lines; with
    # 800 writes and ~8 lines per 500-byte generation, the live file +
    # 2 backups must hold a sane tail of them
    assert n >= 8


# ---------------------------------------------------------------------------
# StepStats from real runs
# ---------------------------------------------------------------------------

def _build_mlp():
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=3, act="relu")
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_step_stats_from_three_step_run(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # drop the startup-program step so the counts below are exactly the
    # 3 training steps
    monitor.registry().reset()
    monitor.step_stats().reset()
    x = np.random.rand(8, 4).astype("float32")
    for _ in range(3):
        exe.run(feed={"x": x}, fetch_list=[loss])
    agg = monitor.step_stats()
    assert agg.steps == 3
    rec = agg.last()
    assert rec["executor"] == "executor"
    assert rec["examples"] == 8
    assert rec["step_seconds"] > 0
    assert rec["examples_per_sec"] > 0
    assert rec["dispatch_queue_depth"] == 0       # return_numpy=True syncs
    assert 0.0 <= rec["compile_cache"]["hit_ratio"] <= 1.0
    assert "fetch_sync_wait_s" in rec
    assert rec["device"].get("live_arrays", 0) >= 1
    # registry mirrors: histogram count == steps, examples counter
    assert monitor.registry().get("monitor/step_seconds").count == 3
    assert monitor.registry().get("monitor/examples_total").value == 24
    s = agg.summary()
    assert s["steps"] == 3 and s["mean_step_seconds"] > 0


def test_fifty_step_mlp_run_produces_jsonl_stepstats(tmp_path):
    """Acceptance: monitoring enabled (no profiler session), 50-step MLP
    run -> JSONL log whose StepStats carry step time, examples/sec,
    compile-cache hit ratio, and dispatch-queue depth."""
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.rand(16, 4).astype("float32")
    for _ in range(50):
        exe.run(feed={"x": x}, fetch_list=[loss], return_numpy=False)
    exe.sync()
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".jsonl")]
    assert len(files) == 1
    records = [json.loads(ln) for ln in
               open(os.path.join(str(tmp_path), files[0]))]
    steps = [r for r in records if r.get("event") == "step_stats"
             and r.get("examples")]
    assert len(steps) == 50
    for r in steps:
        assert r["step_seconds"] >= 0
        assert r["examples_per_sec"] > 0
        assert "hit_ratio" in r["compile_cache"]
        assert "dispatch_queue_depth" in r
    # async fast path actually ran ahead: some step saw a non-empty
    # dispatch window
    assert max(r["dispatch_queue_depth"] for r in steps) >= 1
    # step 1 paid the compile; the other 49 dispatched warm
    assert steps[0]["warm"] is False
    assert all(r["warm"] for r in steps[1:])
    assert monitor.step_stats().summary()["steps_compiled"] >= 1
    assert steps[-1]["step"] > steps[0]["step"]


def test_prefetcher_occupancy_visible_in_stepstats(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(6):
            yield {"x": rng.rand(4, 4).astype("float32")}

    pf = fluid.reader.DevicePrefetcher(reader, place=fluid.CPUPlace(),
                                       capacity=4)
    with pf:
        for feed in pf:
            exe.run(feed=feed, fetch_list=[loss])
    rec = monitor.step_stats().last()
    assert rec["prefetch"]["capacity"] >= 4
    states = [s for s in monitor.queue_states()
              if s.get("kind") == "prefetcher"]
    assert states and states[0]["stopped"]


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_unit_fire_and_rearm():
    fired = []
    w = Watchdog(0.2, sink=fired.append,
                 probe=lambda: {"queues": [{"kind": "dispatch_queue",
                                            "depth": 3}]})
    w.heartbeat("prefetch/producer")
    assert w.check(now=time.monotonic() + 0.1) is None   # not stalled yet
    diag = w.check(now=time.monotonic() + 0.5)
    assert diag is not None and fired
    assert diag["event"] == "watchdog_stall"
    assert diag["stalled_for_s"] >= 0.2
    assert diag["queues"][0]["depth"] == 3
    assert "prefetch/producer" in diag["heartbeat_age_s"]
    # one fire per window, then re-fires after another full window
    assert w.check(now=time.monotonic() + 0.55) is None
    assert w.check(now=time.monotonic() + 0.8) is not None
    # progress re-arms and clears the stall
    w.step_completed()
    assert w.check() is None


def test_watchdog_fires_on_stalled_pipeline_within_window(tmp_path):
    """Acceptance: a deliberately stalled dispatch queue (no step
    completes) triggers the watchdog diagnostic — with queue state and
    the last completed span — within the configured window."""
    monitor.enable(log_dir=str(tmp_path), stall_seconds=0.3)
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.rand(8, 4).astype("float32")
    exe.run(feed={"x": x}, fetch_list=[loss])
    # stall: nothing completes for > stall_seconds; the background
    # watchdog thread (interval = stall/4) must fire within ~2 windows
    deadline = time.monotonic() + 2.0
    stalls = monitor.registry().counter("monitor/watchdog_stalls")
    while stalls.value == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert stalls.value >= 1, "watchdog did not fire within the window"
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".jsonl")]
    records = [json.loads(ln) for ln in
               open(os.path.join(str(tmp_path), files[0]))]
    dumps = [r for r in records if r.get("event") == "watchdog_stall"]
    assert dumps, "stall diagnostic missing from the JSONL log"
    d = dumps[0]
    assert d["stalled_for_s"] >= 0.3
    kinds = {q.get("kind") for q in d.get("queues", [])}
    assert "dispatch_queue" in kinds
    assert d.get("last_span") is not None   # spans ran sans profiler


# ---------------------------------------------------------------------------
# enable/disable + overhead gating
# ---------------------------------------------------------------------------

def test_disabled_monitor_records_nothing():
    assert not monitor.enabled()
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.random.rand(4, 4).astype("float32")},
            fetch_list=[loss])
    assert monitor.step_stats().steps == 0
    assert monitor.registry().get("monitor/steps_total") is None
    monitor.mark("nope")
    monitor.observe_span("nope", 1.0)
    assert monitor.registry().get("mark/nope") is None


def test_flags_drive_enablement_and_teardown(tmp_path):
    fluid.set_flags({"FLAGS_monitor_log_dir": str(tmp_path)})
    assert monitor.enabled()     # log_dir alone implies the switch
    fluid.set_flags({"FLAGS_monitor_log_dir": ""})
    assert not monitor.enabled()
    monitor.enable()
    assert monitor.enabled()
    monitor.disable()
    assert not monitor.enabled()


def test_spans_double_publish_into_monitor_histograms():
    monitor.enable()
    from paddle_tpu.profiler import RecordEvent
    with RecordEvent("unit/span"):
        pass
    h = monitor.registry().get("span/unit/span")
    assert h is not None and h.count == 1
    assert monitor.last_span()[0] == "unit/span"
    # marks become counters
    from paddle_tpu.profiler import mark_event
    mark_event("unit/mark")
    mark_event("unit/mark")
    assert monitor.registry().get("mark/unit/mark").value == 2
    # ... and none of it entered the profiler's event buffer (no session)
    from paddle_tpu import profiler
    with profiler._events_lock:
        assert not any(e["name"].startswith("unit/")
                       for e in profiler._events)


def test_batch_examples_prefers_batch_dim_var():
    """examples/sec must come from the batch-dim feed, not whatever
    array feed sorts first alphabetically."""
    from paddle_tpu.executor import _batch_examples

    fluid.layers.data("x", shape=[4])          # program shape (-1, 4)
    blk = fluid.default_main_program().global_block()
    blk.create_var(name="aaa_scale", shape=[3], dtype="float32")
    vals = [np.zeros((3,), "float32"), np.zeros((16, 4), "float32")]
    assert _batch_examples(blk, ["aaa_scale", "x"], vals) == 16
    # no declared batch var: fall back to the max leading dim
    assert _batch_examples(blk, ["aaa_scale"], vals[:1]) == 3
    assert _batch_examples(blk, [], []) == 0


def test_registry_reset_while_enabled_rebinds_handles():
    """registry().reset() mid-session must not orphan the cached span/
    StepStats metric handles: later observations land in fresh metrics
    visible to exposition."""
    monitor.enable()
    from paddle_tpu.profiler import RecordEvent
    with RecordEvent("gen/span"):
        pass
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    monitor.registry().reset()
    with RecordEvent("gen/span"):
        pass
    exe.run(feed={"x": np.random.rand(4, 4).astype("float32")},
            fetch_list=[loss])
    assert monitor.registry().get("span/gen/span").count == 1
    assert monitor.registry().get("monitor/steps_total").value == 1
    assert "gen_span" in monitor.expose_text()


def test_console_reporter_formats_summary(capsys):
    monitor.enable()
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.random.rand(4, 4).astype("float32")},
            fetch_list=[loss])
    rep = monitor.ConsoleReporter(monitor.step_stats(), monitor.registry(),
                                  interval_s=3600)
    line = rep.format_line()
    assert line.startswith("[monitor] steps=")
    assert "step_ms=" in line and "ex/s=" in line
