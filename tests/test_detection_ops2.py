"""Second detection batch: mine_hard_examples, ssd_loss end-to-end,
spp, unpool, DetectionMAP metric."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layer_helper import LayerHelper


def _run(build, feed):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fetches = build()
        exe = fluid.Executor(fluid.CPUPlace())
        return exe.run(feed=feed, fetch_list=list(fetches))


def test_mine_hard_examples_max_negative():
    # 1 positive (prior 0), ratio 2 -> pick the 2 highest-loss eligible
    # negatives; prior 3 ineligible (dist >= threshold)
    cls_loss = np.array([[0.1, 0.9, 0.5, 2.0, 0.7]], "float32")
    match = np.array([[0, -1, -1, -1, -1]], "int32")
    mdist = np.array([[0.8, 0.1, 0.2, 0.6, 0.3]], "float32")

    def build():
        cl = fluid.layers.data("cl", shape=[5], append_batch_size=False)
        cl.shape = (-1, 5)
        m = fluid.layers.data("m", shape=[5], dtype="int32",
                              append_batch_size=False)
        m.shape = (-1, 5)
        d = fluid.layers.data("d", shape=[5], append_batch_size=False)
        d.shape = (-1, 5)
        neg, updated = fluid.layers.mine_hard_examples(
            cl, m, d, neg_pos_ratio=2.0, neg_dist_threshold=0.5)
        return neg, updated

    neg, updated = _run(build, {"cl": cls_loss, "m": match, "d": mdist})
    # eligible: priors 1 (0.9), 2 (0.5), 4 (0.7); top-2 by loss: 1, 4
    assert set(neg[0, :2].tolist()) == {1, 4}
    assert (neg[0, 2:] == -1).all()
    np.testing.assert_array_equal(updated, match)


def test_ssd_loss_trains():
    """End-to-end: ssd_loss decreases when location/confidence heads
    learn the synthetic targets."""
    B, P, G, C = 4, 8, 2, 3
    rng = np.random.RandomState(0)
    priors = np.stack([np.linspace(0, 0.7, P)] * 2 +
                      [np.linspace(0.3, 1.0, P)] * 2, -1).astype(
        "float32")
    gtb = np.tile(np.array([[[0.0, 0.0, 0.35, 0.35],
                             [0.5, 0.5, 0.95, 0.95]]], "float32"),
                  (B, 1, 1))
    gtl = np.tile(np.array([[[1], [2]]], "int64"), (B, 1, 1))
    feats = rng.rand(B, 16).astype("float32")

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 5
        x = fluid.layers.data("x", shape=[16])
        gb = fluid.layers.data("gb", shape=[G, 4],
                               append_batch_size=False)
        gb.shape = (-1, G, 4)
        gl = fluid.layers.data("gl", shape=[G, 1], dtype="int64",
                               append_batch_size=False)
        gl.shape = (-1, G, 1)
        pb = fluid.layers.data("pb", shape=[P, 4],
                               append_batch_size=False)
        pb.shape = (P, 4)
        loc = fluid.layers.reshape(
            fluid.layers.fc(x, size=P * 4, act=None), shape=[-1, P, 4])
        conf = fluid.layers.reshape(
            fluid.layers.fc(x, size=P * C, act=None), shape=[-1, P, C])
        loss = fluid.layers.mean(fluid.layers.ssd_loss(
            loc, conf, gb, gl, pb, None))
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = []
            for _ in range(25):
                (lv,) = exe.run(
                    feed={"x": feats, "gb": gtb, "gl": gtl, "pb": priors},
                    fetch_list=[loss])
                losses.append(float(lv.ravel()[0]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_spp_levels_and_values():
    x = np.arange(2 * 3 * 4 * 4, dtype="float32").reshape(2, 3, 4, 4)

    def build():
        xi = fluid.layers.data("x", shape=[3, 4, 4])
        helper = LayerHelper("spp")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="spp", inputs={"X": [xi]},
                         outputs={"Out": [out]},
                         attrs={"pyramid_height": 2,
                                "pooling_type": "max"})
        return (out,)

    (out,) = _run(build, {"x": x})
    # level0: 1 bin, level1: 4 bins -> (1+4)*C = 15 features
    assert out.shape == (2, 15)
    np.testing.assert_allclose(out[0, :3], x[0].max((1, 2)))
    # level-1 first bin of channel 0 = max of top-left 2x2
    np.testing.assert_allclose(out[0, 3], x[0, 0, :2, :2].max())


def test_unpool_scatters_to_argmax_positions():
    x = np.array([[[[1.0, 3.0], [7.0, 5.0]]]], "float32")

    def build():
        xi = fluid.layers.data("img", shape=[1, 4, 4])
        helper = LayerHelper("max_pool2d_with_index")
        pooled = helper.create_variable_for_type_inference("float32")
        mask = helper.create_variable_for_type_inference("int32")
        helper.append_op(type="max_pool2d_with_index",
                         inputs={"X": [xi]},
                         outputs={"Out": [pooled], "Mask": [mask]},
                         attrs={"ksize": [2, 2], "strides": [2, 2]})
        helper2 = LayerHelper("unpool")
        out = helper2.create_variable_for_type_inference("float32")
        helper2.append_op(type="unpool",
                          inputs={"X": [pooled], "Indices": [mask]},
                          outputs={"Out": [out]},
                          attrs={"ksize": [2, 2], "strides": [2, 2]})
        return pooled, out

    rng = np.random.RandomState(1)
    img = rng.rand(1, 1, 4, 4).astype("float32")
    pooled, out = _run(build, {"img": img})
    assert out.shape == (1, 1, 4, 4)
    # unpooled contains each pooled max at its original position
    np.testing.assert_allclose(sorted(out[out != 0]),
                               sorted(pooled.ravel()))
    for v in pooled.ravel():
        pos = np.argwhere(img[0, 0] == v)
        assert len(pos) >= 1
        i, j = pos[0]
        assert out[0, 0, i, j] == pytest.approx(v)


def test_detection_map_metric():
    from paddle_tpu.metrics import DetectionMAP

    # coords are normalized [0, 1] (the op contract: dets are clipped,
    # detection_map_op.h ClipBBox)
    m = DetectionMAP(overlap_threshold=0.5)
    gt = np.array([[0.0, 0.0, 0.3, 0.3], [0.5, 0.5, 0.8, 0.8]])
    gt_labels = np.array([1, 2])
    dets = np.array([
        [1, 0.9, 0.0, 0.0, 0.3, 0.3],    # TP class 1
        [1, 0.8, 0.6, 0.0, 0.9, 0.3],    # FP class 1
        [2, 0.7, 0.5, 0.5, 0.8, 0.8],    # TP class 2
    ])
    m.update(dets, gt, gt_labels)
    # class1 AP (integral): recall hits 1.0 at precision 1.0 -> 1.0;
    # class2 AP = 1.0 -> mAP 1.0
    assert m.eval() == pytest.approx(1.0)

    m2 = DetectionMAP()
    m2.update(dets[[1]], gt, gt_labels)   # only the FP
    assert m2.eval() == pytest.approx(0.0)


def test_generate_proposals_decodes_clips_and_nms():
    # 4 anchors on a 20x20 image; deltas zero -> proposals = anchors
    anchors = np.array([[0, 0, 7, 7], [1, 1, 8, 8],
                        [12, 12, 19, 19], [30, 30, 37, 37]], "float32")
    variances = np.ones((4, 4), "float32")
    scores = np.array([[0.9, 0.8, 0.7, 0.6]], "float32")
    deltas = np.zeros((1, 4, 4), "float32")
    im_info = np.array([[20.0, 20.0, 1.0]], "float32")

    def build():
        s = fluid.layers.data("s", shape=[4], append_batch_size=False)
        s.shape = (-1, 4)
        d = fluid.layers.data("d", shape=[4, 4], append_batch_size=False)
        d.shape = (-1, 4, 4)
        ii = fluid.layers.data("ii", shape=[3], append_batch_size=False)
        ii.shape = (-1, 3)
        a = fluid.layers.data("a", shape=[4, 4], append_batch_size=False)
        a.shape = (4, 4)
        va = fluid.layers.data("va", shape=[4, 4],
                               append_batch_size=False)
        va.shape = (4, 4)
        rois, probs = fluid.layers.generate_proposals(
            s, d, ii, a, va, post_nms_top_n=4, nms_thresh=0.5,
            min_size=1.0)
        ln = fluid.layers.sequence_length(rois)
        return rois, probs, ln

    rois, probs, ln = _run(build, {"s": scores, "d": deltas,
                                   "ii": im_info, "a": anchors,
                                   "va": variances})
    # anchor1 suppressed by anchor0 (IoU ~0.53 > 0.5); anchor3 clipped
    # to the image boundary then kept (degenerate corner box)
    n = int(ln[0])
    kept = rois[0, :n]
    assert probs[0, 0, 0] == pytest.approx(0.9)
    np.testing.assert_allclose(kept[0], anchors[0])
    assert not any(np.allclose(kept[i], anchors[1]) for i in range(n))
    assert (kept[:, 2] <= 19.0).all() and (kept[:, 3] <= 19.0).all()


def test_rpn_target_assign_labels_and_targets():
    anchors = np.array([[0, 0, 9, 9], [10, 10, 19, 19],
                        [0, 0, 4, 4], [50, 50, 59, 59]], "float32")
    gt = np.array([[[0, 0, 9, 9], [0, 0, 0, 0]]], "float32")
    gt_len = np.array([1], "int32")

    def build():
        a = fluid.layers.data("a", shape=[4, 4], append_batch_size=False)
        a.shape = (4, 4)
        g = fluid.layers.data("g", shape=[2, 4], append_batch_size=False)
        g.shape = (-1, 2, 4)
        gl = fluid.layers.data("gl", shape=[], dtype="int32",
                               append_batch_size=False)
        gl.shape = (-1,)
        labels, tgt, w = fluid.layers.rpn_target_assign(
            a, g, rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
            gt_length=gl)
        return labels, tgt, w

    labels, tgt, w = _run(build, {"a": anchors, "g": gt, "gl": gt_len})
    assert labels[0, 0] == 1          # perfect-overlap anchor -> fg
    assert labels[0, 1] == 0          # zero overlap -> bg
    assert labels[0, 3] == 0          # far anchor -> bg
    # fg anchor's regression target is zero (anchor == gt)
    np.testing.assert_allclose(tgt[0, 0], np.zeros(4), atol=1e-6)
    np.testing.assert_allclose(w[0, :, 0], (labels[0] == 1).astype(
        np.float32))


def test_rpn_target_assign_unbatched_gt():
    anchors = np.array([[0, 0, 9, 9], [30, 30, 39, 39]], "float32")
    gt2d = np.array([[0, 0, 9, 9]], "float32")   # [G, 4], no batch dim

    def build():
        a = fluid.layers.data("a", shape=[2, 4], append_batch_size=False)
        a.shape = (2, 4)
        g = fluid.layers.data("g", shape=[1, 4], append_batch_size=False)
        g.shape = (1, 4)
        return fluid.layers.rpn_target_assign(a, g)

    labels, tgt, w = _run(build, {"a": anchors, "g": gt2d})
    assert labels.shape == (1, 2)
    assert labels[0, 0] == 1 and labels[0, 1] == 0


def test_mine_hard_examples_hard_example_mode():
    """hard_example mode (mine_hard_examples_op.cc kHardExample): every
    prior competes on cls+loc loss, top sample_size survive; unmined
    matched priors lose their match, mined unmatched become negatives."""
    cls_loss = np.array([[0.1, 0.9, 0.5, 2.0, 0.7]], "float32")
    loc_loss = np.array([[0.0, 0.0, 1.6, 0.0, 0.0]], "float32")
    match = np.array([[0, -1, -1, 1, -1]], "int32")
    mdist = np.zeros((1, 5), "float32")

    def build():
        cl = fluid.layers.data("cl", shape=[5], append_batch_size=False)
        cl.shape = (-1, 5)
        ll = fluid.layers.data("ll", shape=[5], append_batch_size=False)
        ll.shape = (-1, 5)
        m = fluid.layers.data("m", shape=[5], dtype="int32",
                              append_batch_size=False)
        m.shape = (-1, 5)
        d = fluid.layers.data("d", shape=[5], append_batch_size=False)
        d.shape = (-1, 5)
        neg, updated = fluid.layers.mine_hard_examples(
            cl, m, d, loc_loss=ll, mining_type="hard_example",
            sample_size=2)
        return neg, updated

    neg, updated = _run(build, {"cl": cls_loss, "ll": loc_loss,
                                "m": match, "d": mdist})
    # combined loss: [0.1, 0.9, 2.1, 2.0, 0.7] -> top-2 = priors 2, 3
    # prior 3 is matched (kept); prior 2 unmatched -> negative;
    # prior 0 matched but unmined -> match dropped to -1
    assert neg[0, 0] == 2 and (neg[0, 1:] == -1).all()
    np.testing.assert_array_equal(updated[0], [-1, -1, -1, 1, -1])


def test_adaptive_nms_eta():
    """eta < 1 decays the NMS threshold after each kept box
    (multiclass_nms_op.cc NMSFast adaptive_threshold)."""
    # three boxes in score order with IoU(0,1) ~ 0.55, IoU(1,2) ~ 0.55:
    # plain nms_thresh=0.6 keeps all three; eta=0.7 decays the threshold
    # to 0.42 after the first keep, suppressing the later overlaps
    from paddle_tpu.ops.detection import _nms_class
    import jax.numpy as jnp
    boxes = jnp.asarray([[0.0, 0.0, 10.0, 10.0],
                         [3.0, 0.0, 13.0, 10.0],
                         [6.0, 0.0, 16.0, 10.0]])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    keep_plain = np.asarray(_nms_class(boxes, scores, 0.0, 0.6, -1, True))
    keep_adapt = np.asarray(_nms_class(boxes, scores, 0.0, 0.6, -1, True,
                                       eta=0.7))
    assert keep_plain.tolist() == [True, True, True]
    assert keep_adapt.tolist() == [True, False, True]


def test_detection_map_op_matches_host_metric():
    """The in-graph detection_map op agrees with the host-side streaming
    DetectionMAP metric on a single batch (the op is the reference's
    empty-state path, detection_map_op.h)."""
    from paddle_tpu.metrics import DetectionMAP

    # image 0: det0 hits gt0 (label 1), det1 misses; image 1: det for
    # label 2 hits, plus a duplicate (second match -> FP)
    dets = np.array([
        [[1, 0.9, 0.1, 0.1, 0.4, 0.4],
         [1, 0.7, 0.6, 0.6, 0.9, 0.9],
         [-1, 0.0, 0, 0, 0, 0]],
        [[2, 0.8, 0.2, 0.2, 0.5, 0.5],
         [2, 0.6, 0.21, 0.2, 0.5, 0.5],
         [-1, 0.0, 0, 0, 0, 0]],
    ], "float32")
    dlen = np.array([2, 2], "int32")
    gts = np.array([
        [[1, 0.1, 0.1, 0.4, 0.4, 0], [1, 0.0, 0.6, 0.2, 0.9, 0]],
        [[2, 0.2, 0.2, 0.5, 0.5, 0], [0, 0, 0, 0, 0, 0]],
    ], "float32")
    glen = np.array([2, 1], "int32")

    for ap in ("integral", "11point"):
        host = DetectionMAP(overlap_threshold=0.5, ap_version=ap)
        for i in range(2):
            d = dets[i][:dlen[i]]
            host.update(d, gts[i][:glen[i], 1:5], gts[i][:glen[i], 0])
        expect = host.eval()

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            block = prog.global_block()
            for name, arr in [("d", dets), ("dl", dlen), ("g", gts),
                              ("gl", glen)]:
                block.create_var(name=name, shape=arr.shape,
                                 dtype=arr.dtype, is_data=True)
            block.append_op(
                type="detection_map",
                inputs={"DetectRes": ["d"], "DetectResLength": ["dl"],
                        "Label": ["g"], "GtLength": ["gl"]},
                outputs={"MAP": ["map"], "AccumPosCount": ["pc"]},
                attrs={"class_num": 3, "overlap_threshold": 0.5,
                       "ap_type": ap})
        exe = fluid.Executor(fluid.CPUPlace())
        m, pc = exe.run(prog, feed={"d": dets, "dl": dlen, "g": gts,
                                    "gl": glen},
                        fetch_list=["map", "pc"])
        np.testing.assert_allclose(float(np.asarray(m)[0]), expect,
                                   rtol=1e-5, atol=1e-6,
                                   err_msg="ap_type=%s" % ap)
        np.testing.assert_array_equal(np.asarray(pc).ravel(), [0, 2, 1])


def test_detection_map_layer_with_nms_output():
    """layers.detection_map consumes multiclass_nms's padded output +
    count companion end-to-end."""
    scores = np.zeros((1, 3, 3), "float32")   # [B, C, M]
    scores[0, 1, 0] = 0.9
    scores[0, 2, 1] = 0.8
    boxes = np.array([[[0.1, 0.1, 0.4, 0.4],
                       [0.5, 0.5, 0.8, 0.8],
                       [0.0, 0.0, 0.1, 0.1]]], "float32")
    gts = np.array([[[1, 0.1, 0.1, 0.4, 0.4, 0],
                     [2, 0.5, 0.5, 0.8, 0.8, 0]]], "float32")

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        b = fluid.layers.data("b", shape=[1, 3, 4],
                              append_batch_size=False)
        s = fluid.layers.data("s", shape=[1, 3, 3],
                              append_batch_size=False)
        g = fluid.layers.data("g", shape=[1, 2, 6],
                              append_batch_size=False)
        out = fluid.layers.multiclass_nms(b, s, score_threshold=0.1,
                                          nms_threshold=0.5,
                                          keep_top_k=5)
        m = fluid.layers.detection_map(out, g, class_num=3,
                                       overlap_threshold=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"b": boxes, "s": scores, "g": gts},
                     fetch_list=[m.name])
    # both detections hit their gt exactly: mAP = 1
    np.testing.assert_allclose(float(np.asarray(got)[0]), 1.0, atol=1e-6)


def test_detection_map_skips_undetected_classes():
    """A class with ground truth but zero detections is SKIPPED, not
    averaged as AP=0 (detection_map_op.h CalcMAP: true_pos.find ==
    end -> continue) — in both the op and the host metric."""
    from paddle_tpu.metrics import DetectionMAP

    dets = np.array([[[1, 0.9, 0.1, 0.1, 0.4, 0.4]]], "float32")
    gts = np.array([[[1, 0.1, 0.1, 0.4, 0.4, 0],
                     [2, 0.6, 0.6, 0.9, 0.9, 0]]], "float32")

    host = DetectionMAP(overlap_threshold=0.5)
    host.update(dets[0], gts[0, :, 1:5], gts[0, :, 0])
    assert host.eval() == 1.0          # class 2 skipped, not halved

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        for name, arr in [("d", dets), ("g", gts)]:
            block.create_var(name=name, shape=arr.shape, dtype=arr.dtype,
                             is_data=True)
        block.append_op(
            type="detection_map",
            inputs={"DetectRes": ["d"], "Label": ["g"]},
            outputs={"MAP": ["map"], "AccumPosCount": ["pc"]},
            attrs={"class_num": 3, "overlap_threshold": 0.5,
                   "ap_type": "integral"})
    exe = fluid.Executor(fluid.CPUPlace())
    (m,) = exe.run(prog, feed={"d": dets, "g": gts}, fetch_list=["map"])
    np.testing.assert_allclose(float(np.asarray(m)[0]), 1.0, atol=1e-6)
