"""RecordIO-equivalent tests: native C++ codec round-trips, native<->
python cross-compat (same on-disk format), chunk sharding, corruption
detection, reader integration, elastic-master chunk leases."""

import os
import pickle

import numpy as np
import pytest

from paddle_tpu import recordio
from paddle_tpu.recordio import _pyimpl


RECORDS = [b"hello", b"", b"x" * 5000, "unicode ☃".encode("utf-8"),
           np.arange(100, dtype="int64").tobytes()]


def test_native_library_builds():
    assert recordio.native_available(), \
        "g++ is in the image; the native codec must build"


@pytest.mark.parametrize("compressor", ["none", "zlib"])
def test_roundtrip_native(tmp_path, compressor):
    p = str(tmp_path / "a.rio")
    with recordio.Writer(p, compressor=compressor) as w:
        for r in RECORDS:
            w.write(r)
    with recordio.Scanner(p) as s:
        got = list(s)
    assert got == RECORDS


def test_python_reads_native_and_vice_versa(tmp_path):
    pn = str(tmp_path / "native.rio")
    with recordio.Writer(pn) as w:
        for r in RECORDS:
            w.write(r)
    assert list(_pyimpl.PyScanner(pn)) == RECORDS

    pp = str(tmp_path / "py.rio")
    pw = _pyimpl.PyWriter(pp)
    for r in RECORDS:
        pw.write(r)
    pw.close()
    with recordio.Scanner(pp) as s:
        assert list(s) == RECORDS
    assert recordio.num_chunks(pp) == _pyimpl.py_num_chunks(pp)


def test_chunk_boundaries_and_skip(tmp_path):
    p = str(tmp_path / "c.rio")
    with recordio.Writer(p, max_chunk_bytes=1 << 30) as w:
        for i in range(10):
            w.write(b"rec%d" % i)
            if i % 3 == 2:
                w.flush_chunk()       # chunks: [0-2],[3-5],[6-8],[9]
    assert recordio.num_chunks(p) == 4
    with recordio.Scanner(p, skip_chunks=2) as s:
        assert list(s) == [b"rec6", b"rec7", b"rec8", b"rec9"]
    with recordio.Scanner(p, skip_chunks=99) as s:
        assert list(s) == []


def test_small_max_chunk_bytes_auto_flush(tmp_path):
    p = str(tmp_path / "s.rio")
    with recordio.Writer(p, max_chunk_bytes=64) as w:
        for i in range(100):
            w.write(os.urandom(32))
    assert recordio.num_chunks(p) > 10
    with recordio.Scanner(p) as s:
        assert sum(1 for _ in s) == 100


def test_corruption_detected(tmp_path):
    p = str(tmp_path / "d.rio")
    with recordio.Writer(p, compressor="none") as w:
        for r in RECORDS:
            w.write(r)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF         # flip a payload byte
    open(p, "wb").write(bytes(blob))
    with pytest.raises(IOError):
        with recordio.Scanner(p) as s:
            list(s)


def test_reader_creator_and_converter(tmp_path):
    p = str(tmp_path / "r.rio")

    def samples():
        rng = np.random.RandomState(0)
        for _ in range(7):
            yield rng.rand(4).astype("float32"), int(rng.randint(10))

    n = recordio.convert_reader_to_recordio_file(p, samples)
    assert n == 7
    got = [pickle.loads(r) for r in recordio.reader_creator(p)()]
    want = list(samples())
    assert len(got) == 7
    for (xa, ya), (xb, yb) in zip(got, want):
        np.testing.assert_array_equal(xa, xb)
        assert ya == yb


def test_chunks_lease_through_elastic_master(tmp_path):
    """End-to-end with the coordinator: partition a record file by
    chunk spans, lease them, read each span via skip_chunks (the Go
    master's recordio-chunk task model, go/master/service.go:106)."""
    from paddle_tpu.cloud import MasterService, InMemStore, master_reader

    p = str(tmp_path / "m.rio")
    with recordio.Writer(p) as w:
        for i in range(12):
            w.write(b"%03d" % i)
            if i % 2 == 1:
                w.flush_chunk()
    nchunks = recordio.num_chunks(p)
    assert nchunks == 6

    svc = MasterService(store=InMemStore(), chunks_per_task=2)
    svc.set_dataset([{"path": p, "chunk": k} for k in range(nchunks)])

    def chunk_reader(desc):
        with recordio.Scanner(desc["path"],
                              skip_chunks=desc["chunk"]) as s:
            for i, rec in enumerate(s):
                if i >= 2:     # each chunk holds exactly 2 records
                    break
                yield rec

    got = sorted(master_reader(svc, chunk_reader, pass_id=0)())
    assert got == [b"%03d" % i for i in range(12)]


def test_scanner_chunk_range(tmp_path):
    """Scanner(skip_chunks, max_chunks) reads exactly [skip, skip+max)."""
    from paddle_tpu import recordio as rio

    path = str(tmp_path / "ranged.rio")
    with rio.Writer(path, max_chunk_bytes=1) as w:   # one record per chunk
        for i in range(10):
            w.write(b"rec%02d" % i)
    assert rio.num_chunks(path) == 10
    got = list(rio.Scanner(path, skip_chunks=3, max_chunks=4))
    assert got == [b"rec%02d" % i for i in range(3, 7)]
    # ranges tile the file exactly
    allrecs = []
    for start in range(0, 10, 2):
        allrecs += list(rio.Scanner(path, skip_chunks=start, max_chunks=2))
    assert allrecs == [b"rec%02d" % i for i in range(10)]


def test_open_recordio_files_parallel(tmp_path):
    """open_files parity: chunk-sharded multi-process multi-file scan
    returns every sample exactly once; in-worker mapper applies."""
    import pickle

    from paddle_tpu import recordio as rio
    from paddle_tpu.reader.creator import open_recordio_files

    paths = []
    want = set()
    for f in range(3):
        p = str(tmp_path / ("f%d.rio" % f))
        with rio.Writer(p, max_chunk_bytes=64) as w:
            for i in range(20):
                val = f * 100 + i
                want.add(val)
                w.write(pickle.dumps(val))
        paths.append(p)

    r = open_recordio_files(paths, num_workers=3, chunks_per_task=1)
    got = list(r())
    assert sorted(got) == sorted(want)

    r2 = open_recordio_files(paths, num_workers=2, chunks_per_task=2,
                             mapper=lambda v: v * 2)
    got2 = list(r2())
    assert sorted(got2) == sorted(v * 2 for v in want)

    # single worker: deterministic file-then-chunk order
    r1 = open_recordio_files(paths, num_workers=1)
    assert list(r1()) == sorted(want)
