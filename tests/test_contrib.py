"""Smoke tests for paddle_tpu.contrib (Trainer + Inferencer).

Parity: reference ``python/paddle/fluid/contrib/{trainer,inferencer}.py``
exercised via the book-style recognize_digits flow (train a tiny MLP a few
steps, save params, reload through Inferencer and predict).
"""

import numpy as np
import pytest


def test_contrib_imports():
    import paddle_tpu.contrib as contrib
    assert hasattr(contrib, "Trainer")
    assert hasattr(contrib, "Inferencer")
    assert hasattr(contrib, "CheckpointConfig")


def test_trainer_inferencer_roundtrip(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu.contrib import Trainer, Inferencer

    def net():
        img = fluid.layers.data("img", shape=[8])
        h = fluid.layers.fc(img, size=16, act="relu")
        return fluid.layers.fc(h, size=4, act="softmax")

    def train_func():
        pred = net()
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        return loss

    rng = np.random.RandomState(0)
    # learnable task: label = argmax of the first 4 features
    feats = rng.rand(64, 8).astype("float32")
    data = [(x, int(np.argmax(x[:4]))) for x in feats]

    def reader():
        for i in range(0, len(data), 8):
            yield data[i:i + 8]

    losses = []
    trainer = Trainer(train_func=train_func,
                      optimizer_func=lambda: fluid.optimizer.SGD(0.5),
                      place=fluid.CPUPlace())
    trainer.train(num_epochs=6,
                  event_handler=lambda e: losses.append(
                      float(np.asarray(e.metrics[0]).reshape(())))
                  if hasattr(e, "metrics") else None,
                  reader=reader, feed_order=["img", "label"])
    assert losses and np.isfinite(losses).all()
    assert np.mean(losses[-8:]) < np.mean(losses[:8])

    param_dir = str(tmp_path / "params")
    trainer.save_params(param_dir)

    inferencer = Inferencer(infer_func=net, param_path=param_dir,
                            place=fluid.CPUPlace())
    x = rng.rand(5, 8).astype("float32")
    (probs,) = inferencer.infer({"img": x})
    assert probs.shape == (5, 4)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(5), atol=1e-5)

    # the loaded params must equal the saved tensors — catches silent load
    # failures (e.g. parameter-name drift between Trainer and Inferencer)
    import os
    from paddle_tpu.framework import Parameter
    inf_params = [
        name for name, v in
        inferencer.inference_program.global_block().vars.items()
        if isinstance(v, Parameter)]
    assert inf_params
    for name in inf_params:
        saved = np.load(os.path.join(param_dir, name + ".npy"))
        loaded = np.asarray(inferencer.scope.var(name))
        np.testing.assert_array_equal(saved, loaded)

    with pytest.raises(ValueError):
        inferencer.infer([x])
    with pytest.raises(ValueError):
        Inferencer(infer_func=net, param_path=str(tmp_path / "nope"),
                   place=fluid.CPUPlace())


def test_trainer_checkpoint_on_sigterm(tmp_path):
    """SIGTERM mid-train flushes a checkpoint at the step boundary, then
    the signal proceeds (SURVEY §5 checkpoint-on-signal); a fresh
    Trainer resumes from it."""
    import os
    import signal
    import subprocess
    import sys

    ckpt = str(tmp_path / "sig_ckpt")
    script = tmp_path / "trainer_sig.py"
    script.write_text('''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.contrib import Trainer, CheckpointConfig

def train_func():
    img = fluid.layers.data("img", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

rng = np.random.RandomState(0)
data = [(x, int(np.argmax(x[:4]))) for x in rng.rand(64, 8).astype("float32")]

def reader():
    for i in range(0, len(data), 8):
        yield data[i:i + 8]

cfg = CheckpointConfig(checkpoint_dir=%r, step_interval=10**9)
trainer = Trainer(train_func=train_func,
                  optimizer_func=lambda: fluid.optimizer.SGD(0.5),
                  place=fluid.CPUPlace(), checkpoint_config=cfg)
if cfg.load_serial is not None:
    print("RESUMED", cfg.load_serial, flush=True)
    sys.exit(0)

def handler(event):
    if hasattr(event, "metrics"):
        print("STEP", flush=True)

trainer.train(num_epochs=10**6, event_handler=handler, reader=reader,
              feed_order=["img", "label"])
''' % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ckpt))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen([sys.executable, str(script)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, bufsize=1, env=env)
    for line in p.stdout:
        if line.startswith("STEP"):
            p.send_signal(signal.SIGTERM)
            break
    p.stdout.read()
    err = p.stderr.read()
    p.wait(timeout=300)
    # the flush ran, then the original SIGTERM behavior proceeded
    assert p.returncode == -signal.SIGTERM, (p.returncode, err[-3000:])
    assert os.path.isdir(ckpt) and os.listdir(ckpt), err[-3000:]

    # a fresh run resumes from the flushed checkpoint
    out2 = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert out2.returncode == 0, out2.stderr[-3000:]
    assert "RESUMED" in out2.stdout, out2.stdout[-2000:]


def test_memory_usage_calc_and_op_frequence():
    import paddle_tpu as fluid
    from paddle_tpu.contrib import memory_usage_calc, op_frequence

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        h = fluid.layers.fc(x, size=8, act="relu")
        fluid.layers.mean(h)
    lo, hi, unit = memory_usage_calc.memory_usage(main, batch_size=32)
    assert 0 < lo < hi and unit in ("B", "KB", "MB")
    uni, adj = op_frequence.op_freq_statistic(main)
    assert uni.get("mul", 0) >= 1 and uni.get("relu", 0) >= 1
    assert any(k.endswith(" relu") for k in adj)
    import pytest
    with pytest.raises(ValueError):
        memory_usage_calc.memory_usage(main, batch_size=0)
