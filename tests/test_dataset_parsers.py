"""Dataset parser tests on synthetic fixtures (no network): mnist idx
files, cifar pickled tars, uci_housing table, imikolov ptb tar, imdb
aclImdb tar, synthetic — VERDICT weak item 5 (dataset/ untested)."""

import gzip
import io
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu.dataset as dataset


def test_mnist_idx_parser(tmp_path):
    from paddle_tpu.dataset import mnist

    n, rows, cols = 7, 4, 4
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (n, rows * cols), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,), dtype=np.uint8)
    img_path = tmp_path / "img.gz"
    lbl_path = tmp_path / "lbl.gz"
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(imgs.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())

    samples = list(mnist.reader_creator(str(img_path), str(lbl_path),
                                        buffer_size=3)())
    assert len(samples) == n
    for (im, lb), want_img, want_lbl in zip(samples, imgs, labels):
        assert lb == want_lbl
        np.testing.assert_allclose(
            im, want_img.astype("float32") / 255.0 * 2.0 - 1.0,
            rtol=1e-6)
        assert im.min() >= -1.0 and im.max() <= 1.0


def _make_cifar_tar(path, sub_names, n=5, label_key=b"labels"):
    rng = np.random.RandomState(1)
    with tarfile.open(path, "w:gz") as tf:
        for name in sub_names:
            batch = {b"data": rng.randint(0, 256, (n, 3072),
                                          dtype=np.uint8),
                     label_key: rng.randint(0, 10, (n,)).tolist()}
            blob = pickle.dumps(batch)
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))


def test_cifar_tar_parser(tmp_path):
    from paddle_tpu.dataset import cifar

    p = tmp_path / "cifar.tar.gz"
    _make_cifar_tar(p, ["cifar/data_batch_1", "cifar/data_batch_2",
                        "cifar/test_batch"], n=4)
    train = list(cifar.reader_creator(str(p), "data_batch")())
    test = list(cifar.reader_creator(str(p), "test_batch")())
    assert len(train) == 8 and len(test) == 4
    for im, lb in train:
        assert im.shape == (3072,) and im.dtype == np.float32
        assert 0.0 <= im.min() and im.max() <= 1.0
        assert 0 <= lb < 10


def test_uci_housing_split_and_normalization(tmp_path, monkeypatch):
    from paddle_tpu.dataset import uci_housing

    rng = np.random.RandomState(2)
    table = rng.rand(10, uci_housing.FEATURE_NUM) * 100
    data_path = tmp_path / "housing.data"
    np.savetxt(data_path, table)
    monkeypatch.setattr(uci_housing.common, "download",
                        lambda url, mod, md5: str(data_path))
    uci_housing._cache.clear()
    try:
        train = list(uci_housing.train()())
        test = list(uci_housing.test()())
    finally:
        uci_housing._cache.clear()
    assert len(train) == 8 and len(test) == 2
    x0, y0 = train[0]
    assert x0.shape == (uci_housing.FEATURE_NUM - 1,)
    assert y0.shape == (1,)
    # feature normalization: (v - avg) / (max - min) of the whole table
    maxs, mins, avgs = table.max(0), table.min(0), table.mean(0)
    np.testing.assert_allclose(
        x0, ((table[0, :-1] - avgs[:-1]) / (maxs[:-1] - mins[:-1]))
        .astype("float32"), rtol=1e-5)


def test_imikolov_ngram_and_seq(tmp_path, monkeypatch):
    from paddle_tpu.dataset import imikolov

    text = b"the cat sat\nthe dog sat\n"
    tar_path = tmp_path / "ptb.tgz"
    with tarfile.open(tar_path, "w:gz") as tf:
        for split in ("train", "valid"):
            info = tarfile.TarInfo(
                "./simple-examples/data/ptb.%s.txt" % split)
            info.size = len(text)
            tf.addfile(info, io.BytesIO(text))
    monkeypatch.setattr(imikolov.common, "download",
                        lambda url, mod, md5: str(tar_path))

    word_idx = imikolov.build_dict(min_word_freq=0)
    assert "<s>" in word_idx and "<e>" in word_idx and "<unk>" in word_idx
    n = 3
    grams = list(imikolov.train(word_idx, n)())
    # each line has 3 words + <s>/<e> = 5 tokens -> 3 trigram windows
    assert len(grams) == 6
    assert all(len(g) == n for g in grams)
    seqs = list(imikolov.train(word_idx, 20,
                               imikolov.DataType.SEQ)())
    assert len(seqs) == 2
    src, trg = seqs[0]
    assert src[0] == word_idx["<s>"]
    assert trg[-1] == word_idx["<e>"]
    assert src[1:] == trg[:-1]


def test_imdb_tar_parser(tmp_path, monkeypatch):
    from paddle_tpu.dataset import imdb
    import re

    tar_path = tmp_path / "aclImdb.tgz"
    docs = {
        "aclImdb/train/pos/0_9.txt": b"great movie loved it",
        "aclImdb/train/pos/1_8.txt": b"great fun",
        "aclImdb/train/neg/0_2.txt": b"terrible movie hated it",
    }
    with tarfile.open(tar_path, "w:gz") as tf:
        for name, blob in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    monkeypatch.setattr(imdb.common, "download",
                        lambda url, mod, md5: str(tar_path))

    word_idx = imdb.build_dict(re.compile(r"aclImdb/train/.*\.txt$"), 0)
    assert "great" in word_idx and "<unk>" in word_idx
    samples = list(imdb.train(word_idx)())
    assert len(samples) == 3
    labels = [lb for _, lb in samples]
    assert labels.count(0) == 2 and labels.count(1) == 1  # pos=0, neg=1
    ids, _ = samples[0]
    assert all(isinstance(i, int) for i in ids)


def test_synthetic_dataset_shapes():
    from paddle_tpu.dataset import synthetic

    r = synthetic.images(n=5, shape=(3, 8, 8), classes=4, seed=0)
    samples = list(r())
    assert len(samples) == 5
    im, lb = samples[0]
    assert im.shape == (3, 8, 8) and 0 <= lb < 4
    # deterministic per seed
    again = list(synthetic.images(n=5, shape=(3, 8, 8), classes=4,
                                  seed=0)())
    np.testing.assert_array_equal(im, again[0][0])
    xs, ys = next(iter(synthetic.regression(n=2, dim=6, seed=1)()))
    assert xs.shape == (6,) and np.asarray(ys).size == 1
