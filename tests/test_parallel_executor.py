"""ParallelExecutor tests on the 8-device virtual CPU mesh (reference
``test_parallel_executor_mnist.py`` pattern: run the same model via
Executor and ParallelExecutor and compare losses; plus kReduce sharded-
optimizer parity and mesh utilities)."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh


def _build_mlp(seed=7):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    img = fluid.layers.data("img", shape=[32])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=64, act="relu")
    pred = fluid.layers.fc(h, size=8, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _data(steps=6, batch=16):
    rng = np.random.RandomState(0)
    proj = rng.rand(32, 8).astype("float32")
    out = []
    for _ in range(steps):
        x = rng.rand(batch, 32).astype("float32")
        y = (x @ proj).argmax(1).astype("int64").reshape(-1, 1)
        out.append({"img": x, "label": y})
    return out


def _run_single(batches, loss):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return [
        float(np.asarray(exe.run(feed=b, fetch_list=[loss])[0]).ravel()[0])
        for b in batches
    ]


def _run_parallel(batches, loss, build_strategy=None, mesh=None):
    pe = fluid.ParallelExecutor(
        loss_name=loss.name, build_strategy=build_strategy, mesh=mesh)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return [
        float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0]).ravel()[0])
        for b in batches
    ]


def test_parallel_matches_single_device():
    batches = _data()
    loss = _build_mlp()
    single = _run_single(batches, loss)

    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss)

    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)
    assert par[-1] < par[0]  # actually trained


def test_parallel_kreduce_sharded_optimizer():
    batches = _data()
    loss = _build_mlp()
    single = _run_single(batches, loss)

    bs = fluid.BuildStrategy()
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, build_strategy=bs)

    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_parallel_2d_mesh_dp_tp():
    batches = _data(batch=8)
    loss = _build_mlp()
    single = _run_single(batches, loss)

    mesh = make_mesh((4, 2), ("dp", "tp"))
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, mesh=mesh)
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_parallel_feed_list_form():
    """Reference per-device feed list (feed_parallel)."""
    loss = _build_mlp()
    b = _data(steps=1, batch=16)[0]
    split = [
        {k: v[i * 2:(i + 1) * 2] for k, v in b.items()} for i in range(8)
    ]
    pe = fluid.ParallelExecutor(loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (merged,) = pe.run(feed=split, fetch_list=[loss])
    assert np.isfinite(np.asarray(merged)).all()


def test_parallel_uneven_batch_matches_single_device():
    """Epoch with a ragged final batch (reference
    details/data_balance_op_handle.cc capability): the replication pad
    keeps the loss trajectory EXACTLY on the single-device run's."""
    batches = _data(steps=5) + _data(steps=1, batch=9)  # 9 % 8 != 0
    loss = _build_mlp()
    single = _run_single(batches, loss)

    with fluid.scope_guard(fluid.Scope()):
        pe = fluid.ParallelExecutor(loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        par = []
        for b in batches:
            out = pe.run(feed=b, fetch_list=[loss])
            par.append(float(np.asarray(out[0]).ravel()[0]))
        assert pe.uneven_batches_padded == 1
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_parallel_uneven_batch_trims_per_sample_fetches():
    loss = _build_mlp()
    # the softmax pred var: per-sample fetch [B, 8]
    pred = None
    for op in fluid.default_main_program().global_block().ops:
        if op.type == "softmax_with_cross_entropy":
            pred = op.inputs["Logits"][0]
    assert pred is not None
    pe = fluid.ParallelExecutor(loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    b = _data(steps=1, batch=9)[0]
    logits, l = pe.run(feed=b, fetch_list=[pred, loss])
    assert np.asarray(logits).shape[0] == 9   # trimmed back from 72
    assert np.isfinite(np.asarray(l)).all()
    assert pe.uneven_batches_padded == 1


def test_parallel_rejects_indivisible_batch_when_disabled():
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    bs = fluid.BuildStrategy()
    bs.pad_uneven_batches = False
    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs)
    bad = _data(steps=1, batch=9)[0]
    with pytest.raises(ValueError, match="divisible"):
        pe.run(feed=bad, fetch_list=[loss])


def test_parallel_tensor_parallel_policy():
    """param_sharding_fn: shard fc weight out-columns over tp; loss must
    match the single-device run exactly (GSPMD only changes layout)."""
    from jax.sharding import PartitionSpec as P

    batches = _data(batch=8)
    loss = _build_mlp()
    single = _run_single(batches, loss)

    def param_spec(name, shape):
        if len(shape) == 2 and shape[1] % 2 == 0:
            return P(None, "tp")
        return None

    bs = fluid.BuildStrategy()
    bs.param_sharding_fn = param_spec
    mesh = make_mesh((4, 2), ("dp", "tp"))
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, build_strategy=bs, mesh=mesh)
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_parallel_bad_policy_spec_raises():
    from jax.sharding import PartitionSpec as P

    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    bs = fluid.BuildStrategy()
    # 64 columns not divisible by mesh size 8 on dim 0 of shape (32, 64)?
    # use a spec that cannot divide: shard the 8-wide output over dp=8
    # after slicing to odd size via the bias (1-D shape 9 impossible) —
    # simplest: shard dim0 of the [32,64] weight over a 5-way product
    bs.param_sharding_fn = lambda name, shape: (
        P(("dp", "tp")) if len(shape) == 1 and shape[0] % 16 != 0 else None)
    mesh = make_mesh((4, 2), ("dp", "tp"))
    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                mesh=mesh)
    b = _data(steps=1, batch=8)[0]
    with pytest.raises(ValueError, match="does not divide"):
        pe.run(feed=b, fetch_list=[loss])


@pytest.mark.slow   # ~110s: the 8-device dryrun also runs standalone as run_ci step 3
def test_graft_entry_dryrun_inprocess():
    """The driver's multichip dryrun runs in-process on the virtual mesh."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as graft
    graft.dryrun_multichip(8)


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.devices.size == len(jax.devices())
    m2 = make_mesh((2, 2, 2), ("dp", "tp", "sp"))
    assert m2.axis_names == ("dp", "tp", "sp")
    with pytest.raises(ValueError):
        make_mesh((16, 16))
