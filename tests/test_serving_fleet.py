"""Pod-scale serving fabric (ISSUE 18): FleetMaster routing policy,
epoch-guarded re-dispatch, session affinity, and the multi-replica
serving path.

Tier-1 coverage: fake-clock routing-policy units over a direct
FleetMaster (least-loaded admission from heartbeat load reports +
the in-flight ledger, affinity pin/unpin, lease-expiry quarantine with
attempt fencing, stale/unknown completion verdicts, report_failure,
ticket expiry, FleetMetrics), plus a real two-replica fleet in ONE
process over TCP: fleet-routed results bit-identical to direct engine
dispatch, multi-turn sessions pinned, cross-process trace trees
complete, replica pages drained.  The multi-process SIGKILL failover
drill (``fleet_runner.supervise``) is slow-marked; ``tools/run_ci.sh``
step 18 drives the same supervisor from the CLI."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import paddle_tpu as fluid                                  # noqa: E402
from paddle_tpu import monitor                              # noqa: E402
from paddle_tpu.cloud import MasterServer                   # noqa: E402
from paddle_tpu.monitor import tracing                      # noqa: E402
from paddle_tpu.serving import (FleetClient, FleetMaster,   # noqa: E402
                                FleetMetrics, FleetReplica,
                                GenerationEngine, NoReplicasError,
                                build_decoder_lm)
from paddle_tpu.serving.fleet import decode_feed, encode_feed  # noqa: E402


@pytest.fixture(autouse=True)
def telemetry_off_after():
    tracing.reset()
    yield
    tracing.disable()
    tracing.reset()
    monitor.disable()
    monitor.registry().reset()


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _fleet_master(n=2, clock=None, lease=10.0, **kw):
    m = FleetMaster(lease_timeout=lease, clock=clock or _Clock(), **kw)
    for i in range(n):
        m.join("rep-%d" % i, {"address": "127.0.0.1:%d" % (9000 + i),
                              "kind": "generate"})
    return m


# ---------------------------------------------------------------------------
# routing policy (fake clock, direct service)
# ---------------------------------------------------------------------------

def test_least_loaded_routing_in_flight_ledger_and_tiebreak():
    m = _fleet_master(2)
    # equal scores: deterministic tiebreak on sorted host id
    a = m.route(None, "generate", 8)
    assert a["replica"] == "rep-0" and a["attempt"] == 1
    assert a["ticket"].startswith("tkt-")
    # rep-0 now has one in-flight ticket -> rep-1 is less loaded
    b = m.route(None, "generate", 8)
    assert b["replica"] == "rep-1"
    # completion drains the ledger; the next route balances again
    assert m.complete(a["ticket"], a["attempt"]) == {"accepted": True}
    assert m.route(None, "generate", 8)["replica"] == "rep-0"


def test_heartbeat_load_report_steers_admission():
    m = _fleet_master(2)
    # rep-0 reports a deep queue via its heartbeat meta; join-time
    # identity (address) must survive the merge
    m.heartbeat("rep-0", None, {"load": {"queue_depth": 7}})
    for _ in range(3):
        asn = m.route(None, "generate", 8)
        assert asn["replica"] == "rep-1"
        m.complete(asn["ticket"], asn["attempt"])
    stats = m.fleet_stats()
    assert stats["replicas"]["rep-0"]["address"] == "127.0.0.1:9000"
    assert stats["replicas"]["rep-0"]["load"]["queue_depth"] == 7


def test_session_affinity_pins_across_turns():
    m = _fleet_master(2)
    first = m.route("conv-1", "generate", 8)
    # load the pinned replica heavily: affinity still wins over
    # least-loaded for the session's later turns
    m.heartbeat(first["replica"], None, {"load": {"queue_depth": 9}})
    again = m.route("conv-1", "generate", 8)
    assert again["replica"] == first["replica"]
    s = m.fleet_metrics.summary()
    assert s["counts"]["affinity_hits"] == 1
    assert s["affinity_hit_rate"] == 1.0
    # an unrelated sessionless request routes by load
    assert m.route(None, "generate", 8)["replica"] != first["replica"]


def test_lease_expiry_quarantines_fences_and_reroutes():
    clock = _Clock()
    m = _fleet_master(2, clock=clock, lease=10.0)
    asn = m.route("conv-9", "generate", 8)
    assert asn["replica"] == "rep-0" and asn["attempt"] == 1
    # rep-0 dies: only rep-1 keeps heartbeating past rep-0's lease
    clock.t += 6.0
    m.heartbeat("rep-1")
    clock.t += 5.0
    m.heartbeat("rep-1")
    stats = m.fleet_stats()
    assert "rep-0" in stats["quarantined"]
    assert stats["quarantined"]["rep-0"]["orphaned"] == 1
    assert stats["pending_reroute"] == 1
    # the zombie's completion is STALE (attempt was fenced to 2)...
    late = m.complete(asn["ticket"], asn["attempt"])
    assert late == {"accepted": False, "reason": "stale_attempt",
                    "attempt": 2}
    # ...and the client's re-route lands on the survivor, re-pins the
    # session, and its completion is the one accepted
    clock.t += 3.0
    re = m.route("conv-9", "generate", 8, asn["ticket"])
    assert re["ticket"] == asn["ticket"]
    assert re["replica"] == "rep-1" and re["attempt"] == 3
    assert m.complete(re["ticket"], re["attempt"]) == {"accepted": True}
    s = m.fleet_metrics.summary()
    assert s["counts"]["stale_completions"] == 1
    assert s["counts"]["quarantined_replicas"] == 1
    assert s["reroutes_measured"] == 1
    # first route -> accepted completion = the 14s the clock advanced
    assert s["reroute_latency_ms"]["p99_ms"] == pytest.approx(14000.0)


def test_report_failure_fences_and_next_route_avoids():
    m = _fleet_master(2)
    asn = m.route("s", "generate", 4)
    ack = m.report_failure(asn["ticket"], asn["attempt"], "ECONNRESET")
    assert ack["accepted"] and ack["attempt"] == 2
    # the stale attempt can no longer complete
    assert not m.complete(asn["ticket"], 1)["accepted"]
    re = m.route("s", "generate", 4, asn["ticket"])
    assert re["replica"] != asn["replica"]
    assert re["attempt"] == 3
    # a repeated/late failure report for a fenced attempt is a no-op
    assert not m.report_failure(asn["ticket"], 1, "late")["accepted"]


def test_sole_survivor_is_rerouted_to_despite_avoid():
    m = _fleet_master(1)
    asn = m.route(None, "generate", 4)
    m.report_failure(asn["ticket"], asn["attempt"], "reset")
    re = m.route(None, "generate", 4, asn["ticket"])
    assert re["replica"] == "rep-0"     # nowhere else to go


def test_unroutable_fleet_reports_unavailable():
    m = FleetMaster(lease_timeout=10.0, clock=_Clock())
    assert m.route(None, "generate", 4)["unavailable"]
    # a member with NO data-plane address (a trainer host, say) is not
    # a routing candidate
    m.join("host-x", {"kind": "trainer"})
    assert m.route(None, "generate", 4)["unavailable"]
    assert m.fleet_metrics.summary()["counts"]["unavailable"] == 2


def test_unknown_ticket_completion_is_not_a_drop():
    # a master restart loses the ledger; the client KEEPS its computed
    # result (never-drop is client-anchored) — the verdict says so
    m = _fleet_master(1)
    res = m.complete("tkt-999999", 1)
    assert res == {"accepted": False, "reason": "unknown_ticket"}


def test_ticket_expiry_is_ledger_hygiene():
    clock = _Clock()
    m = _fleet_master(1, clock=clock, lease=1e6, ticket_timeout=600.0)
    m.route(None, "generate", 4)
    clock.t += 601.0
    m.heartbeat("rep-0")
    assert m.fleet_stats()["tickets_inflight"] == 0
    assert m.fleet_metrics.summary()["counts"]["expired_tickets"] == 1


def test_graceful_leave_orphans_without_quarantine():
    m = _fleet_master(2)
    asn = m.route("conv", "generate", 4)
    assert asn["replica"] == "rep-0"
    m.leave("rep-0")
    stats = m.fleet_stats()
    assert "rep-0" not in stats["quarantined"]    # no verdict: it left
    assert stats["pending_reroute"] == 1
    re = m.route("conv", "generate", 4, asn["ticket"])
    assert re["replica"] == "rep-1"


def test_fleet_metrics_reroute_window_and_counts():
    fm = FleetMetrics()
    fm.note_route(None)
    fm.note_route(True)
    fm.note_route(False)
    for ms in (10.0, 20.0, 30.0):
        fm.note_reroute_complete(ms / 1e3)
    s = fm.summary()
    assert s["counts"]["routes"] == 3
    assert s["affinity_hit_rate"] == 0.5
    assert s["reroutes_measured"] == 3
    assert s["reroute_latency_ms"]["p50_ms"] == 20.0


def test_feed_codec_roundtrip_is_exact():
    import numpy as np

    feed = {"x": np.arange(6, dtype="float32").reshape(2, 3) / 7,
            "ids": np.array([[1, 2]], dtype="int64")}
    out = decode_feed(encode_feed(feed))
    for k in feed:
        assert out[k].dtype == feed[k].dtype
        assert (out[k] == feed[k]).all()


# ---------------------------------------------------------------------------
# two real replicas in one process, routed over TCP
# ---------------------------------------------------------------------------

def _tiny_engine(prefix):
    spec = build_decoder_lm(23, 32, 2, paged=True, page_size=8,
                            prefix=prefix, n_layer=1, n_head=2,
                            d_model=16, d_inner=32)
    return GenerationEngine(spec, place=fluid.CPUPlace(),
                            max_new_tokens=5, timeout_s=60.0)


@pytest.mark.slow   # two decoder-LM engines + TCP fleet, ~20s
def test_fleet_routed_generation_end_to_end():
    tracing.enable()
    master = FleetMaster(lease_timeout=10.0)
    srv = MasterServer(master).start()
    engines = [_tiny_engine("fleet_e2e_%d" % i) for i in range(2)]
    reps, cli = [], None
    prompts = [[(5 * i + j) % 23 for j in range(4)] for i in range(5)]
    try:
        # direct dispatch BEFORE the fleet exists: the parity reference
        direct = [engines[0].generate(p)["tokens"] for p in prompts]
        reps = [FleetReplica(srv.address, eng, "rep-%d" % i)
                for i, eng in enumerate(engines)]
        cli = FleetClient(srv.address)

        # bit-identical: fleet-routed == direct engine dispatch
        routed = [cli.generate(p) for p in prompts]
        assert [r["tokens"] for r in routed] == direct
        assert all(r["reroutes"] == 0 for r in routed)
        assert {r["replica"] for r in routed} <= {"rep-0", "rep-1"}

        # multi-turn affinity: one replica per session
        ctx = list(prompts[0])
        homes = set()
        for _ in range(3):
            res = cli.generate(ctx, session="conv-1")
            homes.add(res["replica"])
            ctx = ctx + res["tokens"]
        assert len(homes) == 1
        assert cli.stats()["fleet"]["affinity_hit_rate"] == 1.0

        # one request = ONE cross-process span tree: client root,
        # master route decision, replica request subtree
        trees = tracing.assemble(tracing.spans())
        fleet_trees = {tid: t for tid, t in trees.items()
                       if t["root"] is not None
                       and t["root"]["name"] == "fleet_request"}
        assert len(fleet_trees) == len(prompts) + 3
        assert all(t["complete"] for t in fleet_trees.values())
        names = {s["name"] for t in fleet_trees.values()
                 for s in t["spans"]}
        assert {"fleet_request", "rpc/route", "rpc_server/route",
                "route", "rpc/generate", "rpc_server/generate",
                "request", "queue_wait", "prefill",
                "decode"} <= names
        summary = tracing.breakdown_summary(fleet_trees)
        assert summary["complete_fraction"] == 1.0
        assert summary["stages"]["route"]["p50_ms"] > 0.0
    finally:
        if cli is not None:
            cli.close()
        for r in reps:
            r.close()
        srv.shutdown()
        for eng in engines:
            try:
                assert eng._alloc.check_leaks() == []
                assert eng._alloc.pages_in_use() == 0
            finally:
                eng.close()


@pytest.mark.slow
def test_fleet_client_timeout_with_no_replicas():
    master = FleetMaster(lease_timeout=10.0)
    srv = MasterServer(master).start()
    cli = FleetClient(srv.address, reroute_backoff=0.01)
    try:
        with pytest.raises(NoReplicasError):
            cli.generate([1, 2, 3], timeout=0.2)
    finally:
        cli.close()
        srv.shutdown()


# ---------------------------------------------------------------------------
# the multi-process SIGKILL failover drill
# ---------------------------------------------------------------------------

@pytest.mark.slow   # 2 engine subprocesses + kill, ~60s
def test_sigkill_under_load_zero_lost_requests(tmp_path):
    from fleet_runner import supervise

    evidence = supervise(str(tmp_path), replicas=2, requests=24)
    # supervise() asserts the headline criteria; pin the evidence shape
    # so the drill cannot silently weaken
    assert evidence["lost"] == 0
    assert evidence["completed"] == evidence["requests"]
    assert evidence["rerouted_requests"] >= 1
    assert evidence["victim_rc"] == -9
    assert evidence["parity_ok"] and evidence["affinity_ok"]
    assert evidence["quarantined"] == ["rep-0"]
    assert evidence["reroute_latency_ms"]["p99_ms"] is not None
    assert evidence["trace"]["complete_fraction"] >= 0.99
