"""CTC family tests: warpctc loss vs brute-force path enumeration,
training smoke, ctc_align and edit_distance vs python oracles."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid


def _brute_force_ctc_nll(logits, t_len, label, blank):
    """-log P(label | logits) by enumerating all alignment paths."""
    p = np.exp(logits[:t_len] - logits[:t_len].max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    c = logits.shape[1]
    total = 0.0
    for path in itertools.product(range(c), repeat=t_len):
        # collapse: merge repeats then drop blanks
        collapsed = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                collapsed.append(s)
            prev = s
        if collapsed == list(label):
            prob = 1.0
            for t, s in enumerate(path):
                prob *= p[t, s]
            total += prob
    return -np.log(total)


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(0)
    b, t, c = 3, 4, 3          # classes incl. blank=0
    logits = rng.randn(b, t, c).astype("float32")
    t_lens = np.array([4, 3, 4], "int32")
    labels = np.array([[1, 2], [1, 0], [2, 2]], "int64")
    u_lens = np.array([2, 1, 2], "int32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[c], dtype="float32", lod_level=1)
        lb = fluid.layers.data("lb", shape=[1], dtype="int64", lod_level=1)
        loss = fluid.layers.warpctc(x, lb, blank=0)
        exe = fluid.Executor(fluid.CPUPlace())
        (lv,) = exe.run(feed={"x": logits, "x@LEN": t_lens,
                              "lb": labels[:, :, None], "lb@LEN": u_lens},
                        fetch_list=[loss])
    for i in range(b):
        want = _brute_force_ctc_nll(logits[i], int(t_lens[i]),
                                    labels[i, :u_lens[i]], 0)
        assert lv[i, 0] == pytest.approx(want, rel=1e-4), i


def test_warpctc_trains():
    rng = np.random.RandomState(1)
    b, t, c = 8, 10, 5
    xs = rng.randn(b, t, 6).astype("float32")
    t_lens = np.full((b,), t, "int32")
    labels = rng.randint(1, c, (b, 4)).astype("int64")
    u_lens = np.full((b,), 4, "int32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 3
        x = fluid.layers.data("x", shape=[6], dtype="float32", lod_level=1)
        lb = fluid.layers.data("lb", shape=[1], dtype="int64", lod_level=1)
        logits = fluid.layers.fc(x, size=c, num_flatten_dims=2, act=None)
        logits._seq_len_name = x._seq_len_name
        cost = fluid.layers.mean(fluid.layers.warpctc(logits, lb))
        fluid.optimizer.Adam(learning_rate=5e-2).minimize(cost)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = []
            for _ in range(30):
                (lv,) = exe.run(
                    feed={"x": xs, "x@LEN": t_lens,
                          "lb": labels[:, :, None], "lb@LEN": u_lens},
                    fetch_list=[cost])
                losses.append(float(lv.ravel()[0]))
    assert losses[-1] < losses[0] * 0.7


def test_ctc_align_merge_and_blank_removal():
    x = np.array([[0, 1, 1, 0, 2, 2, 0, 3],
                  [1, 1, 2, 0, 0, 3, 3, 1]], "int64")
    lens = np.array([8, 6], "int32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xin = fluid.layers.data("x", shape=[1], dtype="int64", lod_level=1)
        probs = fluid.layers.one_hot(xin, depth=4)
        dec = fluid.layers.ctc_greedy_decoder(probs, blank=0)
        ln = fluid.layers.sequence_length(dec)
        exe = fluid.Executor(fluid.CPUPlace())
        out, out_len = exe.run(
            feed={"x": x[:, :, None], "x@LEN": lens},
            fetch_list=[dec, ln])
    # seq 0 (len 8): 0 1 1 0 2 2 0 3 -> 1 2 3
    np.testing.assert_array_equal(out[0, :3].ravel(), [1, 2, 3])
    assert out_len[0] == 3
    # seq 1 (len 6): 1 1 2 0 0 3 -> 1 2 3
    np.testing.assert_array_equal(out[1, :3].ravel(), [1, 2, 3])
    assert out_len[1] == 3
    assert (out[0, 3:] == 0).all() and (out[1, 3:] == 0).all()


def _py_edit_distance(a, b):
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1))
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + cost)
    return d[m, n]


def test_edit_distance_vs_python_oracle():
    rng = np.random.RandomState(2)
    b = 6
    hyps = rng.randint(0, 5, (b, 7)).astype("int64")
    refs = rng.randint(0, 5, (b, 9)).astype("int64")
    h_lens = rng.randint(1, 8, (b,)).astype("int32")
    r_lens = rng.randint(1, 10, (b,)).astype("int32")
    for normalized in (False, True):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            h = fluid.layers.data("h", shape=[1], dtype="int64",
                                  lod_level=1)
            r = fluid.layers.data("r", shape=[1], dtype="int64",
                                  lod_level=1)
            dist, seq_num = fluid.layers.edit_distance(
                h, r, normalized=normalized)
            exe = fluid.Executor(fluid.CPUPlace())
            dv, nv = exe.run(
                feed={"h": hyps[:, :, None], "h@LEN": h_lens,
                      "r": refs[:, :, None], "r@LEN": r_lens},
                fetch_list=[dist, seq_num])
        assert int(nv[0]) == b
        for i in range(b):
            want = _py_edit_distance(hyps[i, :h_lens[i]],
                                     refs[i, :r_lens[i]])
            if normalized:
                want /= max(r_lens[i], 1)
            assert dv[i, 0] == pytest.approx(want, rel=1e-5), \
                (normalized, i)
