"""Program-level autodiff tests: duplicate-grad summation, stop_gradient
pruning, regularizers/clipping (reference backward.py behaviors)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name


def test_duplicate_consumer_grads_are_summed():
    # x feeds two branches; d(loss)/dx must be the sum of both paths
    x = fluid.layers.data("x", shape=[4])
    x.stop_gradient = False
    a = fluid.layers.scale(x, scale=2.0)
    b = fluid.layers.scale(x, scale=3.0)
    s = fluid.layers.elementwise_add(a, b)
    loss = fluid.layers.mean(s)
    fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), dtype="float32")
    (gx,) = exe.run(feed={"x": xv}, fetch_list=[grad_var_name("x")])
    np.testing.assert_allclose(gx, np.full((2, 4), 5.0 / 8.0), rtol=1e-5)


def test_dropout_output_fanout_grads_summed():
    # regression: custom grad makers must use GRAD:: slots so accumulated
    # contributions are summed before the grad op consumes them
    x = fluid.layers.data("x", shape=[4])
    x.stop_gradient = False
    d = fluid.layers.dropout(x, dropout_prob=0.0)  # p=0: mask == 1
    a = fluid.layers.scale(d, scale=2.0)
    b = fluid.layers.scale(d, scale=3.0)
    loss = fluid.layers.mean(fluid.layers.elementwise_add(a, b))
    fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), dtype="float32")
    (gx,) = exe.run(feed={"x": xv}, fetch_list=[grad_var_name("x")])
    np.testing.assert_allclose(gx, np.full((2, 4), 5.0 / 8.0), rtol=1e-5)


def test_minimize_outside_program_guard():
    # regression: optimizer vars must land in the loss's program, not the
    # ambient default program
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(
        loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (lv,) = exe.run(main, feed={"x": np.ones((3, 4), "float32")},
                    fetch_list=[loss])
    assert np.isfinite(lv).all()


def test_calc_gradient_target_gradients():
    x = fluid.layers.data("x", shape=[3])
    x.stop_gradient = False
    y = fluid.layers.scale(x, scale=2.0)
    ct = fluid.layers.data("ct", shape=[3])  # custom cotangent
    (gx,) = fluid.calc_gradient(y, x, target_gradients=ct)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 3), dtype="float32")
    ctv = np.full((2, 3), 5.0, dtype="float32")
    (g,) = exe.run(feed={"x": xv, "ct": ctv}, fetch_list=[gx.name])
    np.testing.assert_allclose(g, np.full((2, 3), 10.0), rtol=1e-5)


def test_stop_gradient_prunes_branch():
    x = fluid.layers.data("x", shape=[4])
    x.stop_gradient = False
    frozen = fluid.layers.data("frozen", shape=[4])  # stop_gradient=True
    s = fluid.layers.elementwise_add(x, frozen)
    loss = fluid.layers.mean(s)
    fluid.append_backward(loss)
    main = fluid.default_main_program()
    assert not main.global_block().has_var(grad_var_name("frozen"))
    assert main.global_block().has_var(grad_var_name("x"))


def test_params_and_grads_returned():
    x = fluid.layers.data("x", shape=[6])
    y = fluid.layers.fc(x, size=3)
    loss = fluid.layers.mean(y)
    p_g = fluid.append_backward(loss)
    names = {p.name for p, g in p_g}
    params = {p.name for p in
              fluid.default_main_program().global_block().all_parameters()}
    assert names == params
    for p, g in p_g:
        assert g.name == grad_var_name(p.name)


def test_grad_matches_jax_reference():
    # fc + softmax_with_cross_entropy grads vs a hand-written numpy check
    rng = np.random.RandomState(0)
    x = fluid.layers.data("x", shape=[5])
    x.stop_gradient = False
    w_init = rng.uniform(-1, 1, (5, 3)).astype("float32")
    y = fluid.layers.fc(
        x, size=3,
        param_attr=fluid.ParamAttr(
            name="w_fixed",
            initializer=fluid.initializer.NumpyArrayInitializer(w_init)),
        bias_attr=False,
    )
    loss = fluid.layers.mean(y)
    fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = rng.uniform(-1, 1, (4, 5)).astype("float32")
    gx, gw = exe.run(
        feed={"x": xv},
        fetch_list=[grad_var_name("x"), grad_var_name("w_fixed")],
    )
    # loss = mean(x @ w) -> dx = w.sum(1)/12, dw = x.sum(0)/12
    np.testing.assert_allclose(
        gx, np.tile(w_init.sum(axis=1) / 12.0, (4, 1)), rtol=1e-4
    )
    np.testing.assert_allclose(
        gw, np.tile(xv.sum(axis=0, keepdims=True).T / 12.0, (1, 3)),
        rtol=1e-4,
    )


def test_regularizer_applied():
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(x, size=2, bias_attr=False,
                        param_attr=fluid.ParamAttr(name="w_reg"))
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.SGD(
        learning_rate=0.1,
        regularization=fluid.regularizer.L2Decay(0.5),
    )
    opt.minimize(loss)
    types = [op.type for op in
             fluid.default_main_program().global_block().ops]
    # L2 decay: a scale(param) + sum into grad before sgd
    assert "sgd" in types
    i_sgd = types.index("sgd")
    assert "sum" in types[:i_sgd]


def test_gradient_clip_by_global_norm():
    x = fluid.layers.data("x", shape=[4])
    x.stop_gradient = False
    y = fluid.layers.fc(x, size=2, bias_attr=False)
    loss = fluid.layers.mean(y)
    fluid.clip.set_gradient_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=0.01))
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    fluid.clip.set_gradient_clip(None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(1).rand(8, 4).astype("float32") * 100
    # just verify it runs and params stay finite (clipped update)
    for _ in range(3):
        (lv,) = exe.run(feed={"x": xv}, fetch_list=[loss])
    assert np.isfinite(lv).all()


def test_calc_gradient_multiple_targets():
    """Multi-target calc_gradient: d(sum_i <t_i, tg_i>)/dx."""
    x = fluid.layers.data("x", shape=[3])
    x.stop_gradient = False
    y1 = fluid.layers.scale(x, scale=2.0)
    y2 = fluid.layers.scale(x, scale=-3.0)
    (gx,) = fluid.calc_gradient([y1, y2], x)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 3), dtype="float32")
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx.name])
    # d/dx (sum(2x) + sum(-3x)) = -1 everywhere
    np.testing.assert_allclose(g, np.full((2, 3), -1.0), rtol=1e-5)


def test_calc_gradient_multiple_targets_with_cotangents():
    x = fluid.layers.data("x", shape=[2])
    x.stop_gradient = False
    y1 = fluid.layers.scale(x, scale=2.0)
    y2 = fluid.layers.elementwise_mul(x, x)   # x^2
    ct1 = fluid.layers.data("ct1", shape=[2])
    ct2 = fluid.layers.data("ct2", shape=[2])
    (gx,) = fluid.calc_gradient([y1, y2], x, target_gradients=[ct1, ct2])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[1.0, 2.0]], dtype="float32")
    c1 = np.array([[1.0, 1.0]], dtype="float32")
    c2 = np.array([[3.0, 0.5]], dtype="float32")
    (g,) = exe.run(feed={"x": xv, "ct1": c1, "ct2": c2},
                   fetch_list=[gx.name])
    # d/dx (<2x, c1> + <x^2, c2>) = 2*c1 + 2*x*c2
    np.testing.assert_allclose(g, 2 * c1 + 2 * xv * c2, rtol=1e-5)


def test_gradless_inplace_op_passes_cotangent_through():
    """Regression: the producer-side pending clear must not fire for ops
    that appended no grad ops — a grad-less in-place op (increment)
    shares its output name with its input, and the cotangent must keep
    flowing through that name to the real producer."""
    x = fluid.layers.data("x", shape=[6])
    x.stop_gradient = False
    y = fluid.layers.scale(x, scale=2.0)
    fluid.layers.increment(y, value=1.0, in_place=True)
    loss = fluid.layers.reduce_mean(y)
    (gx,) = fluid.calc_gradient(loss, x)
    assert gx is not None
    exe = fluid.Executor(fluid.CPUPlace())
    (g,) = exe.run(feed={"x": np.ones((1, 6), "float32")},
                   fetch_list=[gx.name])
    np.testing.assert_allclose(g, np.full((1, 6), 2 / 6), rtol=1e-5)
