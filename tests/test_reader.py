"""Reader decorator + PyReader tests (reference
python/paddle/reader/tests/decorator_test.py pattern)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as rd
from paddle_tpu.dataset import synthetic


def _count_reader(n=10):
    def reader():
        yield from range(n)
    return reader


def test_batch():
    b = rd.batch(_count_reader(10), 3)
    batches = list(b())
    assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    b = rd.batch(_count_reader(10), 3, drop_last=True)
    assert len(list(b())) == 3


def test_shuffle_preserves_multiset():
    out = list(rd.shuffle(_count_reader(20), 5)())
    assert sorted(out) == list(range(20))


def test_chain_compose_firstn_map():
    c = rd.chain(_count_reader(3), _count_reader(2))
    assert list(c()) == [0, 1, 2, 0, 1]
    comp = rd.compose(_count_reader(3), _count_reader(3))
    assert list(comp()) == [(0, 0), (1, 1), (2, 2)]
    with pytest.raises(rd.decorator.ComposeNotAligned):
        list(rd.compose(_count_reader(3), _count_reader(2))())
    f = rd.firstn(_count_reader(100), 4)
    assert list(f()) == [0, 1, 2, 3]
    m = rd.map_readers(lambda a, b: a + b, _count_reader(3),
                       _count_reader(3))
    assert list(m()) == [0, 2, 4]


def test_buffered_and_xmap():
    out = list(rd.buffered(_count_reader(10), 2)())
    assert out == list(range(10))
    x = rd.xmap_readers(lambda v: v * 2, _count_reader(10), 3, 4)
    assert sorted(x()) == [2 * i for i in range(10)]


def test_cache():
    calls = []

    def creator():
        def reader():
            calls.append(1)
            yield from range(5)
        return reader

    cached = rd.cache(creator())
    assert list(cached()) == list(range(5))
    assert list(cached()) == list(range(5))
    assert len(calls) == 1


def test_synthetic_datasets():
    imgs = list(synthetic.images(n=5)())
    assert imgs[0][0].shape == (3, 32, 32)
    seqs = list(synthetic.sequences(n=5)())
    assert seqs[0][0].ndim == 1
    regs = list(synthetic.regression(n=5)())
    assert regs[0][0].shape == (13,)


def test_pyreader_trains_model():
    x = fluid.layers.data("x", shape=[13])
    y = fluid.layers.data("y", shape=[1])
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.smooth_l1(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    feeder = fluid.DataFeeder(feed_list=[x, y])
    train_reader = rd.batch(synthetic.regression(n=64), 16)
    py_reader = rd.PyReader(capacity=2).decorate_batch_reader(
        train_reader, feeder, fluid.CPUPlace())
    losses = []
    for epoch in range(4):
        for feed in py_reader:
            (lv,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0]


def test_bucket_by_length_batches_and_flush():
    from paddle_tpu.reader import decorator as dec

    lengths = [3, 15, 9, 2, 30, 14, 4, 16, 31, 1, 20, 8]

    def reader():
        for n in lengths:
            yield (np.arange(n),)

    r = dec.bucket_by_length(reader, lambda s: len(s[0]),
                             bucket_bounds=[8, 16, 32], batch_size=2)
    batches = list(r())
    for bound, samples in batches:
        assert len(samples) <= 2
        assert all(len(s[0]) <= bound for s in samples)
        # every sample belongs in THIS bucket, not a smaller one
        prev = {8: 0, 16: 8, 32: 16}[bound]
        assert all(len(s[0]) > prev for s in samples)
    # all samples come back exactly once
    got = sorted(len(s[0]) for _, b in batches for s in b)
    assert got == sorted(lengths)
    # full batches first per bucket, trailing partials flushed at end
    r2 = dec.bucket_by_length(reader, lambda s: len(s[0]),
                              bucket_bounds=[8, 16, 32], batch_size=2,
                              drop_last=True)
    got2 = [len(s[0]) for _, b in r2() for s in b]
    assert len(got2) < len(lengths)  # partials dropped

    with pytest.raises(ValueError, match="exceeds"):
        list(dec.bucket_by_length(reader, lambda s: len(s[0]),
                                  bucket_bounds=[8], batch_size=2)())


def test_data_feeder_per_call_pad_to():
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        w = fluid.layers.data("w", shape=[1], dtype="int64", lod_level=1)
        feeder = fluid.DataFeeder(feed_list=[w], place=fluid.CPUPlace())
        batch = [(np.array([1, 2, 3]),), (np.array([4],),)]
        out = feeder.feed(batch, pad_to=8)
        assert out["w"].shape == (2, 8, 1)
        np.testing.assert_array_equal(out["w@LEN"], [3, 1])
        # constructor default unaffected
        out2 = feeder.feed(batch)
        assert out2["w"].shape == (2, 3, 1)


def test_bucket_by_length_sizes_sort_with_bounds():
    """Regression: per-bucket batch sizes pair positionally with the
    CALLER's bound order, surviving the internal sort."""
    from paddle_tpu.reader import decorator as dec

    def reader():
        for n in [2, 3, 2, 20, 2, 2]:
            yield (np.arange(n),)

    r = dec.bucket_by_length(reader, lambda s: len(s[0]),
                             bucket_bounds=[64, 8], batch_size=[1, 4])
    batches = list(r())
    for bound, samples in batches:
        if bound == 8:
            assert len(samples) <= 4
        else:
            assert len(samples) == 1  # long bucket batches 1
    sizes = {(b, len(s)) for b, s in batches}
    assert (8, 4) in sizes and (64, 1) in sizes


def test_multiprocess_reader_worker_crash_raises():
    """Regression: a dead worker must raise, never read as a clean
    (silently truncated) end-of-stream."""
    from paddle_tpu.reader import decorator as dec

    def good():
        yield from range(3)

    def bad():
        yield 100
        raise IOError("shard corrupt")

    r = dec.multiprocess_reader([good, bad])
    with pytest.raises(RuntimeError, match="worker failed"):
        list(r())


def test_open_recordio_files_repeat_streams_epochs():
    import pickle
    import itertools
    import tempfile

    from paddle_tpu import recordio as rio
    from paddle_tpu.reader.creator import open_recordio_files

    tmp = tempfile.mkdtemp()
    p = tmp + "/r.rio"
    with rio.Writer(p, max_chunk_bytes=64) as w:
        for i in range(5):
            w.write(pickle.dumps(i))
    r = open_recordio_files([p], num_workers=1, repeat=True)
    got = list(itertools.islice(r(), 12))   # > 2 epochs, no exhaustion
    assert sorted(set(got)) == [0, 1, 2, 3, 4]
    assert len(got) == 12


def test_fake_reader_replays_first_epoch():
    from paddle_tpu.reader.decorator import Fake

    calls = []

    def source():
        calls.append(1)
        for i in range(3):
            yield i

    fake = Fake()
    r = fake(source, 7)
    assert list(r()) == [0, 1, 2, 0, 1, 2, 0]
    assert list(r()) == [0, 1, 2, 0, 1, 2, 0]
    assert len(calls) == 1  # the source ran exactly once


def test_pipe_reader_lines():
    from paddle_tpu.reader.decorator import PipeReader

    pr = PipeReader("printf 'a\\nbb\\nccc'", bufsize=4)
    assert list(pr.get_line()) == ["a", "bb", "ccc"]
    import pytest
    with pytest.raises(TypeError):
        PipeReader(["not", "a", "string"])
    with pytest.raises(TypeError):
        PipeReader("cat x", file_type="zip")


def test_pipe_reader_multibyte_across_buffer_and_quoting(tmp_path):
    from paddle_tpu.reader.decorator import Fake, PipeReader

    # a multi-byte char straddling the tiny read buffer must survive
    p = tmp_path / "my data.txt"   # space in path: needs shlex quoting
    p.write_text("abécd\n中文\n", encoding="utf-8")
    pr = PipeReader('cat "%s"' % p, bufsize=3)
    assert list(pr.get_line()) == ["abécd", "中文"]

    import pytest
    with pytest.raises(ValueError, match="no samples"):
        list(Fake()(lambda: iter(()), 5)())
