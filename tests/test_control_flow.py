"""Control-flow construct tests (reference test_recurrent_op.py,
test_while_op.py, test_dyn_rnn.py, test_ifelse.py, test_switch.py,
test_beam_search_op.py patterns: numpy oracles + trainability)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------

def test_static_rnn_accumulator_oracle():
    t_len, b, d = 5, 3, 4
    x = fluid.layers.data("x", shape=[t_len, b, d], dtype="float32",
                          append_batch_size=False)
    h0 = fluid.layers.data("h0", shape=[b, d], dtype="float32",
                           append_batch_size=False)

    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_pre = rnn.memory(init=h0)
        h = fluid.layers.elementwise_add(
            fluid.layers.scale(h_pre, scale=0.5), x_t)
        rnn.update_memory(h_pre, h)
        rnn.step_output(h)
    out = rnn()

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    xv = rng.rand(t_len, b, d).astype("float32")
    h0v = rng.rand(b, d).astype("float32")
    (ov,) = exe.run(feed={"x": xv, "h0": h0v}, fetch_list=[out])

    ref = np.zeros_like(xv)
    h = h0v.copy()
    for t in range(t_len):
        h = 0.5 * h + xv[t]
        ref[t] = h
    np.testing.assert_allclose(ov, ref, rtol=1e-5)


def test_static_rnn_grad_numeric():
    """Analytic grad through lax.scan matches central differences."""
    t_len, b, d = 4, 2, 3
    x = fluid.layers.data("x", shape=[t_len, b, d], dtype="float32",
                          append_batch_size=False, stop_gradient=False)
    h0 = fluid.layers.data("h0", shape=[b, d], dtype="float32",
                           append_batch_size=False)
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_pre = rnn.memory(init=h0)
        h = fluid.layers.tanh(
            fluid.layers.elementwise_add(
                fluid.layers.scale(h_pre, scale=0.7), x_t))
        rnn.update_memory(h_pre, h)
        rnn.step_output(h)
    out = rnn()
    loss = fluid.layers.reduce_sum(out)
    fluid.append_backward(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(1)
    xv = rng.rand(t_len, b, d).astype("float32") * 0.5
    h0v = rng.rand(b, d).astype("float32") * 0.5

    lv, gx = exe.run(feed={"x": xv, "h0": h0v},
                     fetch_list=[loss, grad_var_name("x")])

    eps = 1e-3
    num = np.zeros_like(xv)
    for idx in np.ndindex(*xv.shape):
        for sgn in (1, -1):
            xp = xv.copy()
            xp[idx] += sgn * eps
            (l2,) = exe.run(feed={"x": xp, "h0": h0v}, fetch_list=[loss])
            num[idx] += sgn * float(np.asarray(l2).ravel()[0])
    num /= 2 * eps
    np.testing.assert_allclose(gx, num, rtol=5e-2, atol=5e-3)


def test_static_rnn_with_params_trains():
    """fc inside the step block: weight grads flow through the scan."""
    fluid.default_main_program().random_seed = 11
    fluid.default_startup_program().random_seed = 11
    t_len, b, d, h_dim = 6, 4, 5, 5
    x = fluid.layers.data("x", shape=[t_len, b, d], dtype="float32",
                          append_batch_size=False)
    label = fluid.layers.data("label", shape=[b, 1], dtype="int64",
                              append_batch_size=False)

    h0 = fluid.layers.fill_constant(shape=[b, h_dim], dtype="float32",
                                    value=0.0)
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_pre = rnn.memory(init=h0)
        h = fluid.layers.fc(
            fluid.layers.concat([x_t, h_pre], axis=1), size=h_dim,
            act="tanh")
        rnn.update_memory(h_pre, h)
        rnn.step_output(h)
    out = rnn()
    last = fluid.layers.slice(out, axes=[0], starts=[t_len - 1],
                              ends=[t_len])
    last = fluid.layers.reshape(last, shape=[b, h_dim])
    pred = fluid.layers.fc(last, size=3, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=5e-2).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    xv = rng.rand(t_len, b, d).astype("float32")
    yv = rng.randint(0, 3, (b, 1)).astype("int64")
    losses = []
    for _ in range(30):
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.5, losses


def test_static_rnn_mixed_dtype_inputs_keep_grads():
    """An int64 step input (token ids) must not disqualify the float step
    input from differentiation."""
    t_len, b, d, v = 3, 2, 4, 6
    x = fluid.layers.data("x", shape=[t_len, b, d], dtype="float32",
                          append_batch_size=False, stop_gradient=False)
    ids = fluid.layers.data("ids", shape=[t_len, b, 1], dtype="int64",
                            append_batch_size=False)
    rnn = fluid.layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        id_t = rnn.step_input(ids)
        emb = fluid.layers.embedding(id_t, size=[v, d])
        h_pre = rnn.memory(shape=[d], batch_ref=x_t, init_value=0.0)
        h = fluid.layers.tanh(fluid.layers.elementwise_add(
            fluid.layers.elementwise_add(h_pre, x_t), emb))
        rnn.update_memory(h_pre, h)
        rnn.step_output(h)
    out = rnn()
    loss = fluid.layers.reduce_sum(out)
    fluid.append_backward(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(12)
    xv = rng.rand(t_len, b, d).astype("float32") * 0.1
    iv = rng.randint(0, v, (t_len, b, 1)).astype("int64")
    lv, gx = exe.run(feed={"x": xv, "ids": iv},
                     fetch_list=[loss, grad_var_name("x")])
    assert np.isfinite(gx).all()
    assert np.abs(gx).sum() > 0   # gradient actually flows


# ---------------------------------------------------------------------------
# DynamicRNN
# ---------------------------------------------------------------------------

def test_dynamic_rnn_masks_padding():
    b, t_len, d = 3, 5, 2
    x = fluid.layers.data("x", shape=[d], dtype="float32", lod_level=1)

    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(x)
        h_pre = drnn.memory(shape=[d], value=0.0)
        h = fluid.layers.elementwise_add(h_pre, x_t)
        drnn.update_memory(h_pre, h)
        drnn.output(h)
    out = drnn()

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(3)
    xv = rng.rand(b, t_len, d).astype("float32")
    lens = np.array([5, 2, 3], "int32")
    (ov,) = exe.run(feed={"x": xv, "x@LEN": lens}, fetch_list=[out])

    ref = np.zeros((b, t_len, d), "float32")
    for bi in range(b):
        acc = np.zeros(d, "float32")
        for t in range(lens[bi]):
            acc = acc + xv[bi, t]
            ref[bi, t] = acc
    np.testing.assert_allclose(ov, ref, rtol=1e-5)
    # final memory holds at length; outputs past length are zero
    assert np.all(ov[1, 2:] == 0) and np.all(ov[2, 3:] == 0)


def test_dynamic_rnn_trains_sequence_sum():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    d, h_dim = 3, 8
    x = fluid.layers.data("x", shape=[d], dtype="float32", lod_level=1)
    y = fluid.layers.data("y", shape=[1], dtype="float32")

    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(x)
        h_pre = drnn.memory(shape=[h_dim], value=0.0)
        h = fluid.layers.fc(fluid.layers.concat([x_t, h_pre], axis=1),
                            size=h_dim, act="tanh")
        drnn.update_memory(h_pre, h)
        drnn.output(h)
    out = drnn()
    last = fluid.layers.sequence_pool(out, "last")
    pred = fluid.layers.fc(last, size=1, act=None)
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    fluid.optimizer.Adam(learning_rate=2e-2).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(5)
    losses = []
    for _ in range(40):
        xv = rng.rand(8, 6, d).astype("float32")
        lens = rng.randint(2, 7, (8,)).astype("int32")
        yv = np.array([
            xv[i, :lens[i]].sum(axis=(0, 1), keepdims=False).sum()
            for i in range(8)], "float32").reshape(-1, 1) / 6.0
        (lv,) = exe.run(feed={"x": xv, "x@LEN": lens, "y": yv},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.5, losses


# ---------------------------------------------------------------------------
# While + arrays
# ---------------------------------------------------------------------------

def test_while_loop_sums_array():
    t_len, d = 4, 3
    x = fluid.layers.data("x", shape=[t_len, d], dtype="float32",
                          append_batch_size=False)
    # array of per-step rows, while-accumulated sum
    arr = fluid.layers.create_array("float32", capacity=t_len,
                                    element_shape=[d])
    i0 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=t_len)
    acc = fluid.layers.fill_constant(shape=[d], dtype="float32", value=0.0)
    # write rows into the array first (outside the loop)
    for t in range(t_len):
        it = fluid.layers.fill_constant(shape=[1], dtype="int64", value=t)
        row = fluid.layers.reshape(
            fluid.layers.slice(x, axes=[0], starts=[t], ends=[t + 1]),
            shape=[d])
        arr = fluid.layers.array_write(row, it, array=arr)

    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond)
    with w.block():
        row = fluid.layers.array_read(arr, i)
        fluid.layers.assign(fluid.layers.elementwise_add(acc, row),
                            output=acc)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(4)
    xv = rng.rand(t_len, d).astype("float32")
    (accv,) = exe.run(feed={"x": xv}, fetch_list=[acc])
    np.testing.assert_allclose(accv, xv.sum(0), rtol=1e-5)


def test_while_requires_cond_update():
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=3)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond)
    with pytest.raises(ValueError, match="condition"):
        with w.block():
            fluid.layers.increment(i, value=1)


# ---------------------------------------------------------------------------
# IfElse / Switch / ConditionalBlock
# ---------------------------------------------------------------------------

def test_ifelse_row_select():
    b, d = 6, 3
    x = fluid.layers.data("x", shape=[d])
    limit = fluid.layers.fill_constant(shape=[1], dtype="float32", value=1.5)
    row_sum = fluid.layers.reduce_sum(x, dim=1, keep_dim=True)
    cond = fluid.layers.less_than(row_sum, limit)

    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        d_in = ie.input(x)
        ie.output(fluid.layers.scale(d_in, scale=2.0))
    with ie.false_block():
        d_in = ie.input(x)
        ie.output(fluid.layers.scale(d_in, scale=-1.0))
    out = ie()

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(6)
    xv = rng.rand(b, d).astype("float32")
    (ov,) = exe.run(feed={"x": xv}, fetch_list=[out])
    mask = xv.sum(1, keepdims=True) < 1.5
    ref = np.where(mask, 2.0 * xv, -1.0 * xv)
    np.testing.assert_allclose(ov, ref, rtol=1e-5)


def test_conditional_block_scalar():
    x = fluid.layers.data("x", shape=[1], dtype="float32",
                          append_batch_size=False)
    thresh = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                        value=0.5)
    out = fluid.layers.fill_constant(shape=[1], dtype="float32", value=-1.0)
    cond = fluid.layers.less_than(x, thresh)
    cb = fluid.layers.ConditionalBlock([cond])
    with cb.block():
        fluid.layers.assign(fluid.layers.scale(x, scale=10.0), output=out)

    exe = fluid.Executor(fluid.CPUPlace())
    (o1,) = exe.run(feed={"x": np.array([0.2], "float32")},
                    fetch_list=[out])
    (o2,) = exe.run(feed={"x": np.array([0.9], "float32")},
                    fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o1).ravel(), [2.0], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(o2).ravel(), [-1.0], rtol=1e-5)


def test_switch_piecewise():
    """The piecewise-LR pattern: value by step range."""
    step = fluid.layers.data("step", shape=[1], dtype="float32",
                             append_batch_size=False)
    lr = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    b1 = fluid.layers.fill_constant(shape=[1], dtype="float32", value=10.0)
    b2 = fluid.layers.fill_constant(shape=[1], dtype="float32", value=20.0)

    with fluid.layers.Switch() as switch:
        with switch.case(fluid.layers.less_than(step, b1)):
            fluid.layers.assign(
                fluid.layers.fill_constant([1], "float32", 1.0), output=lr)
        with switch.case(fluid.layers.less_than(step, b2)):
            fluid.layers.assign(
                fluid.layers.fill_constant([1], "float32", 0.5), output=lr)
        with switch.default():
            fluid.layers.assign(
                fluid.layers.fill_constant([1], "float32", 0.1), output=lr)

    exe = fluid.Executor(fluid.CPUPlace())
    for sv, expect in [(5.0, 1.0), (15.0, 0.5), (25.0, 0.1)]:
        (lv,) = exe.run(feed={"step": np.array([sv], "float32")},
                        fetch_list=[lr])
        np.testing.assert_allclose(np.asarray(lv).ravel(), [expect],
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------

def _np_beam_step(pre_ids, pre_scores, scores, end_id):
    b, k, v = scores.shape
    out_ids = np.zeros((b, k), "int64")
    out_scores = np.zeros((b, k), scores.dtype)
    out_parent = np.zeros((b, k), "int64")
    for bi in range(b):
        cands = []
        for ki in range(k):
            if pre_ids[bi, ki] == end_id:
                cands.append((pre_scores[bi, ki], ki, end_id))
                continue
            for vi in range(v):
                cands.append(
                    (pre_scores[bi, ki] + scores[bi, ki, vi], ki, vi))
        cands.sort(key=lambda c: -c[0])
        for j in range(k):
            s, ki, vi = cands[j]
            out_scores[bi, j] = s
            out_parent[bi, j] = ki
            out_ids[bi, j] = vi
    return out_ids, out_scores, out_parent


def test_beam_search_step_oracle():
    b, k, v, end_id = 2, 3, 7, 0
    rng = np.random.RandomState(8)
    pre_ids = np.array([[3, 0, 2], [1, 4, 0]], "int64")   # some finished
    pre_scores = rng.rand(b, k).astype("float32") * -1.0
    scores = np.log(rng.dirichlet(np.ones(v), size=(b, k))
                    .astype("float32") + 1e-9)

    p_ids = fluid.layers.data("pre_ids", shape=[b, k], dtype="int64",
                              append_batch_size=False)
    p_sc = fluid.layers.data("pre_scores", shape=[b, k], dtype="float32",
                             append_batch_size=False)
    sc = fluid.layers.data("scores", shape=[b, k, v], dtype="float32",
                           append_batch_size=False)
    ids, out_sc, parent = fluid.layers.beam_search(
        p_ids, p_sc, sc, beam_size=k, end_id=end_id)

    exe = fluid.Executor(fluid.CPUPlace())
    iv, sv, pv = exe.run(
        feed={"pre_ids": pre_ids, "pre_scores": pre_scores,
              "scores": scores},
        fetch_list=[ids, out_sc, parent])

    ref_ids, ref_scores, ref_parent = _np_beam_step(
        pre_ids, pre_scores, scores, end_id)
    np.testing.assert_allclose(sv, ref_scores, rtol=1e-4)
    np.testing.assert_array_equal(iv, ref_ids)
    np.testing.assert_array_equal(pv, ref_parent)


def test_beam_search_decode_backtrack():
    # T=3, B=1, K=2; beam 0 path: a->c->e; beam 1 final came via parents
    ids = np.array([[[5, 6]], [[7, 8]], [[9, 10]]], "int64")   # [T,1,2]
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], "int64")
    scores = np.array([[0.9, 0.4]], "float32")

    idv = fluid.layers.data("ids", shape=[3, 1, 2], dtype="int64",
                            append_batch_size=False)
    pav = fluid.layers.data("parents", shape=[3, 1, 2], dtype="int64",
                            append_batch_size=False)
    scv = fluid.layers.data("scores", shape=[1, 2], dtype="float32",
                            append_batch_size=False)
    sent, out_sc = fluid.layers.beam_search_decode(
        idv, pav, scv, beam_size=2, end_id=0)

    exe = fluid.Executor(fluid.CPUPlace())
    sv, scv_out = exe.run(
        feed={"ids": ids, "parents": parents, "scores": scores},
        fetch_list=[sent, out_sc])
    # beam 0 at T-1 token 9, parent 1 -> step1 beam1 token 8, parent 0
    # -> step0 beam0 token 5
    np.testing.assert_array_equal(sv[0, 0], [5, 8, 9])
    # beam 1 at T-1 token 10, parent 0 -> step1 beam0 token 7 -> token 5
    np.testing.assert_array_equal(sv[0, 1], [5, 7, 10])
    np.testing.assert_allclose(scv_out, scores)


# ---------------------------------------------------------------------------
# While backward (bounded max_trip_count -> predicated scan, WhileGrad)
# ---------------------------------------------------------------------------

def test_while_backward_trains_through_loop():
    """A training step whose loss path crosses a While: iteratively apply
    y <- tanh(y @ W) for a data-dependent number of trips (bounded), and
    train W by gradient descent.  Gradients are checked against the
    jax reference of the unrolled computation."""
    import jax
    import jax.numpy as jnp

    d, trips = 3, 3
    x = fluid.layers.data("x", shape=[d])
    y = fluid.layers.assign(x)
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=trips)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond, max_trip_count=8)
    with w.block():
        fluid.layers.assign(
            fluid.layers.fc(y, size=d, bias_attr=False, act="tanh",
                            param_attr=fluid.ParamAttr(name="while_w")),
            output=y)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)
    loss = fluid.layers.reduce_mean(fluid.layers.square(y))
    sgd = fluid.optimizer.SGD(learning_rate=0.1)
    sgd.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.rand(4, d).astype("float32")
    scope = fluid.global_scope()
    w0 = np.array(scope.var("while_w"))

    (lv,) = exe.run(feed={"x": xv}, fetch_list=[loss])
    w1 = np.array(scope.var("while_w"))

    # jax reference: same trips unrolled
    def ref_loss(wv):
        yv = jnp.asarray(xv)
        for _ in range(trips):
            yv = jnp.tanh(yv @ wv)
        return jnp.mean(jnp.square(yv))

    g = jax.grad(ref_loss)(jnp.asarray(w0))
    np.testing.assert_allclose(np.asarray(lv)[0], ref_loss(jnp.asarray(w0)),
                               rtol=1e-5)
    np.testing.assert_allclose(w1, w0 - 0.1 * np.asarray(g), rtol=1e-4,
                               atol=1e-6)


def test_while_backward_without_bound_raises():
    d = 2
    x = fluid.layers.data("x", shape=[d])
    y = fluid.layers.assign(x)
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=2)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond)
    with w.block():
        fluid.layers.assign(fluid.layers.scale(y, scale=0.5), output=y)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)
    loss = fluid.layers.reduce_mean(y)
    with pytest.raises(RuntimeError, match="max_trip_count"):
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)


def test_while_upstream_producer_gradient_not_double_counted():
    """Regression: the loop carry's upstream producer must receive ONLY
    the through-loop gradient — the name-based grad accumulator used to
    also leak the post-loop cotangent into it (in-place Out aliasing)."""
    x = fluid.layers.data("x", shape=[6])
    x.stop_gradient = False
    y = fluid.layers.scale(x, scale=1.0)
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=3)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond, max_trip_count=8)
    with w.block():
        fluid.layers.assign(fluid.layers.scale(y, scale=0.5), output=y)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)
    loss = fluid.layers.reduce_mean(y)
    (gx,) = fluid.calc_gradient(loss, x)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((1, 6), dtype="float32")
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx.name])
    np.testing.assert_allclose(g, np.full((1, 6), 0.5 ** 3 / 6), rtol=1e-6)


def test_ifelse_backward():
    """Gradients flow through IfElse's split/merge predication
    (reference while_op.cc-era conditional backward; here
    split_lod_tensor/merge_lod_tensor/conditional_block grads):
    d(out)/dx is the branch's slope on each row."""
    b, d = 6, 3
    x = fluid.layers.data("x", shape=[d])
    x.stop_gradient = False
    limit = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                       value=1.5)
    row_sum = fluid.layers.reduce_sum(x, dim=1, keep_dim=True)
    cond = fluid.layers.less_than(row_sum, limit)

    ie = fluid.layers.IfElse(cond)
    with ie.true_block():
        d_in = ie.input(x)
        ie.output(fluid.layers.scale(d_in, scale=2.0))
    with ie.false_block():
        d_in = ie.input(x)
        ie.output(fluid.layers.scale(d_in, scale=-1.0))
    out = ie()

    loss = fluid.layers.reduce_sum(out)
    (gx,) = fluid.calc_gradient(loss, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(7)
    xv = rng.rand(b, d).astype("float32")
    (gv,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    mask = xv.sum(1, keepdims=True) < 1.5
    want = np.where(mask, 2.0, -1.0) * np.ones_like(xv)
    np.testing.assert_allclose(gv, want, rtol=1e-5)


def test_tensor_array_write_read_backward():
    """array_write -> array_read roundtrip gradient (reference
    tensor_array_read_write_op.cc grads): cotangents route through the
    fixed-capacity array's dynamic slice."""
    x = fluid.layers.data("x", shape=[4])
    x.stop_gradient = False
    i0 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    i1 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=1)
    arr = fluid.layers.array_write(
        fluid.layers.scale(x, scale=3.0), i0, capacity=2)
    arr = fluid.layers.array_write(
        fluid.layers.scale(x, scale=5.0), i1, array=arr)
    y0 = fluid.layers.array_read(arr, i0)
    y1 = fluid.layers.array_read(arr, i1)
    loss = fluid.layers.reduce_sum(
        fluid.layers.elementwise_add(y0, y1))
    (gx,) = fluid.calc_gradient(loss, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), "float32")
    (gv,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(gv, 8.0 * np.ones_like(xv), rtol=1e-5)


def test_while_backward_coupled_carry_unread_var():
    """Coupled While carries where the loss reads only ONE of them:
    b += a each trip, loss = sum(b).  a's cotangent exists only through
    the in-place carry (no direct downstream read), so its input-side
    gradient lands under the bare @GRAD name — the consumed-tracking in
    backward.py must keep it (dloss/dx = trips + 1 through b0 = x... 0
    + per-trip a contributions)."""
    d, trips = 3, 3
    x = fluid.layers.data("x", shape=[d])
    x.stop_gradient = False
    a = fluid.layers.assign(x)
    b = fluid.layers.assign(x)
    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=trips)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond, max_trip_count=4)
    with w.block():
        fluid.layers.assign(fluid.layers.elementwise_add(b, a), output=b)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)
    loss = fluid.layers.reduce_sum(b)
    (gx,) = fluid.calc_gradient(loss, [x])
    assert gx is not None, "gradient through the unread coupled carry lost"
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, d), "float32")
    (gv,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    # b_final = x + trips * a = (1 + trips) * x
    np.testing.assert_allclose(gv, (1.0 + trips) * np.ones_like(xv),
                               rtol=1e-5)
