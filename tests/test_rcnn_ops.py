"""Faster-RCNN training ops: generate_proposal_labels +
roi_perspective_transform (reference detection/generate_proposal_labels_op.cc
and detection/roi_perspective_transform_op.cc).

Oracles are direct numpy ports of the reference CPU kernels.
"""

import numpy as np

import paddle_tpu as fluid


# -- generate_proposal_labels ----------------------------------------------

def _iou(a, b):
    """Inclusive-pixel IoU (bbox_util.h BboxOverlaps)."""
    aa = (a[2] - a[0] + 1) * (a[3] - a[1] + 1)
    ab = (b[2] - b[0] + 1) * (b[3] - b[1] + 1)
    iw = max(min(a[2], b[2]) - max(a[0], b[0]) + 1, 0)
    ih = max(min(a[3], b[3]) - max(a[1], b[1]) + 1, 0)
    inter = iw * ih
    return inter / (aa + ab - inter) if aa + ab - inter > 0 else 0.0


def _run_gpl(rois, gt_cls, crowd, gt, im_info, attrs, roi_len=None,
             gt_len=None):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        feeds = {"RpnRois": rois, "GtClasses": gt_cls, "IsCrowd": crowd,
                 "GtBoxes": gt, "ImInfo": im_info}
        if roi_len is not None:
            feeds["RpnRoisLength"] = roi_len
        if gt_len is not None:
            feeds["GtLength"] = gt_len
        for name, arr in feeds.items():
            block.create_var(name=name, shape=arr.shape, dtype=arr.dtype,
                             is_data=True)
        ins = {k: [k] for k in feeds}
        outs = ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
                "BboxOutsideWeights", "RoisNum"]
        block.append_op(type="generate_proposal_labels", inputs=ins,
                        outputs={k: [k] for k in outs}, attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    return [np.asarray(v) for v in
            exe.run(prog, feed=feeds, fetch_list=outs)], outs


def test_generate_proposal_labels_basic():
    """gt boxes (prepended as proposals, IoU=1 with themselves) become fg
    with zero deltas; a far-away roi becomes bg; crowd gt is excluded."""
    gt = np.array([[[0, 0, 9, 9], [20, 20, 29, 29]]], "float32")
    gt_cls = np.array([[3, 5]], "int32")
    crowd = np.array([[0, 1]], "int32")           # second gt is crowd
    rois = np.array([[[0, 0, 9, 9],               # dup of gt0 -> fg
                      [40, 40, 49, 49],           # no overlap -> bg
                      [41, 40, 50, 49]]], "float32")  # no overlap -> bg
    im_info = np.array([[60, 60, 1.0]], "float32")
    attrs = {"batch_size_per_im": 6, "fg_fraction": 0.5, "fg_thresh": 0.5,
             "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "bbox_reg_weights": [1.0, 1.0, 1.0, 1.0], "class_nums": 8,
             "use_random": False}
    (rois_o, labels, tgts, inw, outw, num), _ = _run_gpl(
        rois, gt_cls, crowd, gt, im_info, attrs)
    n = int(num[0])
    # fg: gt0-as-proposal and the duplicate roi (both IoU 1 with gt0);
    # crowd gt1 is excluded from everything; 2 far rois are bg
    labels0 = labels[0, :, 0]
    fg_labels = labels0[:2]
    assert sorted(fg_labels.tolist()) == [3, 3]
    assert n == 4
    assert (labels0[2:n] == 0).all()              # bg slots
    assert (labels0[n:] == 0).all()               # padding
    # fg rows matched to an identical gt: deltas are exactly zero but the
    # inside weights are 1 at the label's 4 columns
    for i in range(2):
        lbl = labels0[i]
        cols = slice(4 * lbl, 4 * lbl + 4)
        np.testing.assert_allclose(tgts[0, i, cols], 0.0, atol=1e-5)
        np.testing.assert_allclose(inw[0, i, cols], 1.0)
        assert inw[0, i].sum() == 4.0             # only those columns
    assert (inw[0, 2:] == 0).all()
    np.testing.assert_allclose(outw, inw)


def test_generate_proposal_labels_deltas_and_scale():
    """A shifted fg proposal gets the BoxToDelta regression target divided
    by bbox_reg_weights; rois are emitted back at im_scale."""
    gt = np.array([[[10, 10, 29, 29]]], "float32")
    gt_cls = np.array([[2]], "int32")
    crowd = np.array([[0]], "int32")
    # proposal at scale 2: after /scale it's [11,11,30,30] -> IoU ~0.8 fg
    rois = np.array([[[22, 22, 60, 60]]], "float32")
    im_info = np.array([[100, 100, 2.0]], "float32")
    w = [10.0, 10.0, 5.0, 5.0]
    attrs = {"batch_size_per_im": 4, "fg_fraction": 0.5, "fg_thresh": 0.5,
             "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "bbox_reg_weights": w, "class_nums": 4, "use_random": False}
    (rois_o, labels, tgts, inw, _, num), _ = _run_gpl(
        rois, gt_cls, crowd, gt, im_info, attrs)
    labels0 = labels[0, :, 0]
    # slot 0 = gt-as-proposal (fg, label 2); slot 1 = the shifted roi
    assert labels0[0] == 2 and labels0[1] == 2
    ex = np.array([11.0, 11.0, 30.0, 30.0])
    g = np.array([10.0, 10.0, 29.0, 29.0])
    ew, eh = ex[2] - ex[0] + 1, ex[3] - ex[1] + 1
    gw, gh = g[2] - g[0] + 1, g[3] - g[1] + 1
    expect = np.array([
        ((g[0] + gw / 2) - (ex[0] + ew / 2)) / ew / w[0],
        ((g[1] + gh / 2) - (ex[1] + eh / 2)) / eh / w[1],
        np.log(gw / ew) / w[2], np.log(gh / eh) / w[3]])
    np.testing.assert_allclose(tgts[0, 1, 8:12], expect, atol=1e-5)
    # rois scaled back up by im_scale
    np.testing.assert_allclose(rois_o[0, 1], ex * 2.0, atol=1e-4)


def test_generate_proposal_labels_fg_cap_random():
    """With use_random=True the fg sample is capped at
    floor(S*fg_fraction) and slots stay fg-first."""
    g = np.array([[[0, 0, 9, 9]]], "float32")
    gt_cls = np.array([[1]], "int32")
    crowd = np.array([[0]], "int32")
    # 6 near-duplicates of the gt: all fg candidates
    rois = np.tile(np.array([[0, 0, 9, 9]], "float32"), (6, 1))[None]
    im_info = np.array([[50, 50, 1.0]], "float32")
    attrs = {"batch_size_per_im": 4, "fg_fraction": 0.5, "fg_thresh": 0.5,
             "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "bbox_reg_weights": [1.0] * 4, "class_nums": 2,
             "use_random": True}
    (rois_o, labels, *_rest, num), _ = _run_gpl(
        rois, gt_cls, crowd, g, im_info, attrs)
    labels0 = labels[0, :, 0]
    assert (labels0[:2] == 1).all()               # fg cap = floor(4*0.5)
    assert (labels0[2:] == 0).all()               # nothing else qualifies
    assert int(num[0]) == 2


# -- roi_perspective_transform ---------------------------------------------

def _ref_roi_persp(x, rois, roi2im, scale, th, tw):
    """Direct port of the reference CPU kernel
    (roi_perspective_transform_op.cc)."""
    eps = 1e-4

    def gt(a, b):
        return (a - b) > eps

    def gte(a, b):
        return (a > b) or abs(a - b) < eps

    def lte(a, b):
        return (a < b) or abs(a - b) < eps

    def in_quad(px, py, rx, ry):
        for i in range(4):
            xs, ys = rx[i], ry[i]
            xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
            if abs(ys - ye) < eps:
                if abs(py - ys) < eps and abs(py - ye) < eps and \
                        gte(px, min(xs, xe)) and lte(px, max(xs, xe)):
                    return True
            else:
                ix = (py - ys) * (xe - xs) / (ye - ys) + xs
                if abs(ix - px) < eps and gte(py, min(ys, ye)) and \
                        lte(py, max(ys, ye)):
                    return True
        n_cross = 0
        for i in range(4):
            xs, ys = rx[i], ry[i]
            xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
            if abs(ys - ye) < eps:
                continue
            if lte(py, min(ys, ye)) or gt(py, max(ys, ye)):
                continue
            ix = (py - ys) * (xe - xs) / (ye - ys) + xs
            if abs(ix - px) < eps:
                return True
            if gt(ix, px):
                n_cross += 1
        return n_cross % 2 == 1

    def matrix(rx, ry):
        x0, x1, x2, x3 = rx
        y0, y1, y2, y3 = ry
        l1 = np.hypot(x0 - x1, y0 - y1)
        l2 = np.hypot(x1 - x2, y1 - y2)
        l3 = np.hypot(x2 - x3, y2 - y3)
        l4 = np.hypot(x3 - x0, y3 - y0)
        est_h = (l2 + l4) / 2.0
        est_w = (l1 + l3) / 2.0
        nh = th
        nw = min(int(round(est_w * (nh - 1) / est_h)) + 1, tw)
        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        m = np.zeros(9)
        m[6] = (dx3 * dy2 - dx2 * dy3) / (dx1 * dy2 - dx2 * dy1) / (nw - 1)
        m[7] = (dx1 * dy3 - dx3 * dy1) / (dx1 * dy2 - dx2 * dy1) / (nh - 1)
        m[8] = 1
        m[3] = (y1 - y0 + m[6] * (nw - 1) * y1) / (nw - 1)
        m[4] = (y3 - y0 + m[7] * (nh - 1) * y3) / (nh - 1)
        m[5] = y0
        m[0] = (x1 - x0 + m[6] * (nw - 1) * x1) / (nw - 1)
        m[1] = (x3 - x0 + m[7] * (nh - 1) * x3) / (nh - 1)
        m[2] = x0
        return m

    def bilinear(img, in_w, in_h):
        hgt, wid = img.shape
        if gt(-0.5, in_w) or gt(in_w, wid - 0.5) or gt(-0.5, in_h) or \
                gt(in_h, hgt - 0.5):
            return 0.0
        in_w = max(in_w, 0.0)
        in_h = max(in_h, 0.0)
        wf, hf = int(np.floor(in_w)), int(np.floor(in_h))
        if wf >= wid - 1:
            wc = wf = wid - 1
            in_w = float(wf)
        else:
            wc = wf + 1
        if hf >= hgt - 1:
            hc = hf = hgt - 1
            in_h = float(hf)
        else:
            hc = hf + 1
        fw, fh = in_w - wf, in_h - hf
        return ((1 - fw) * (1 - fh) * img[hf, wf]
                + (1 - fw) * fh * img[hc, wf]
                + fw * fh * img[hc, wc] + (1 - fh) * fw * img[hf, wc])

    r, c = rois.shape[0], x.shape[1]
    out = np.zeros((r, c, th, tw), "float32")
    for n in range(r):
        rx = rois[n, 0::2] * scale
        ry = rois[n, 1::2] * scale
        m = matrix(rx, ry)
        for ch in range(c):
            img = x[roi2im[n], ch]
            for oh in range(th):
                for ow in range(tw):
                    u = m[0] * ow + m[1] * oh + m[2]
                    v = m[3] * ow + m[4] * oh + m[5]
                    wq = m[6] * ow + m[7] * oh + m[8]
                    iw, ih = u / wq, v / wq
                    if in_quad(iw, ih, rx, ry):
                        out[n, ch, oh, ow] = bilinear(img, iw, ih)
    return out


def test_roi_perspective_transform_matches_reference():
    rs = np.random.RandomState(11)
    x = rs.rand(2, 3, 8, 8).astype("float32")
    # one axis-aligned box + one genuine quadrilateral, on different images
    rois = np.array([
        [1, 1, 6, 1, 6, 6, 1, 6],
        [2, 1, 7, 2, 6, 7, 1, 5],
    ], "float32")
    roi2im = np.array([0, 1], "int32")
    th, tw, scale = 4, 4, 1.0
    expect = _ref_roi_persp(x, rois, roi2im, scale, th, tw)

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        for name, arr in [("X", x), ("ROIs", rois),
                          ("RoisImageId", roi2im)]:
            block.create_var(name=name, shape=arr.shape, dtype=arr.dtype,
                             is_data=True)
        block.append_op(
            type="roi_perspective_transform",
            inputs={"X": ["X"], "ROIs": ["ROIs"],
                    "RoisImageId": ["RoisImageId"]},
            outputs={"Out": ["Out"]},
            attrs={"spatial_scale": scale, "transformed_height": th,
                   "transformed_width": tw})
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"X": x, "ROIs": rois,
                                 "RoisImageId": roi2im},
                     fetch_list=["Out"])
    np.testing.assert_allclose(np.asarray(got), expect, atol=1e-4)


def test_roi_perspective_transform_spatial_scale():
    x = np.arange(36, dtype="float32").reshape(1, 1, 6, 6)
    rois = np.array([[2, 2, 10, 2, 10, 10, 2, 10]], "float32")
    roi2im = np.array([0], "int32")
    expect = _ref_roi_persp(x, rois, roi2im, 0.5, 3, 3)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        for name, arr in [("X", x), ("ROIs", rois),
                          ("RoisImageId", roi2im)]:
            block.create_var(name=name, shape=arr.shape, dtype=arr.dtype,
                             is_data=True)
        block.append_op(
            type="roi_perspective_transform",
            inputs={"X": ["X"], "ROIs": ["ROIs"],
                    "RoisImageId": ["RoisImageId"]},
            outputs={"Out": ["Out"]},
            attrs={"spatial_scale": 0.5, "transformed_height": 3,
                   "transformed_width": 3})
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"X": x, "ROIs": rois,
                                 "RoisImageId": roi2im},
                     fetch_list=["Out"])
    np.testing.assert_allclose(np.asarray(got), expect, atol=1e-4)
