"""SelectedRows sparse-gradient tests (reference selected_rows.h /
lookup_table_op.cc sparse path / optimizer SelectedRows kernels:
sparse-vs-dense parity, lazy-update semantics, duplicate-row merging,
multi-use accumulation, and the mesh-sharded embedding path)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name
from paddle_tpu.param_attr import ParamAttr

V, D = 20, 6


def _build(is_sparse, opt_factory, seed=13):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[V, D], is_sparse=is_sparse,
        param_attr=ParamAttr(name="emb_w"))
    pooled = fluid.layers.reduce_mean(emb, dim=1)          # [B, D]
    pred = fluid.layers.fc(pooled, size=1, act=None,
                           param_attr=ParamAttr(name="fc_w"),
                           bias_attr=ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    opt_factory().minimize(loss)
    return loss


def _batches(steps=8, b=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, V, (b, 4, 1)).astype("int64")  # dup rows likely
        yv = rng.rand(b, 1).astype("float32")
        out.append({"ids": ids, "y": yv})
    return out


def _train(is_sparse, opt_factory, steps=8):
    from paddle_tpu.framework import program_guard

    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), program_guard(main, startup):
        loss = _build(is_sparse, opt_factory)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [
            float(np.asarray(exe.run(main, feed=b,
                                     fetch_list=[loss])[0]).ravel()[0])
            for b in _batches(steps)
        ]
        emb_w = np.asarray(scope.var("emb_w"))
    return losses, emb_w


@pytest.mark.parametrize("opt", [
    lambda: fluid.optimizer.SGD(learning_rate=0.1),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
])
def test_sparse_matches_dense(opt):
    """For SGD/Adagrad a zero dense grad row is a no-op, so lazy sparse
    updates must match the dense path exactly.  (Momentum/Adam are NOT
    expected to match: their dense kernels keep moving untouched rows via
    velocity/moment decay while the reference sparse kernels are lazy —
    covered by the laziness tests below.)"""
    dense_losses, dense_w = _train(False, opt)
    sparse_losses, sparse_w = _train(True, opt)
    np.testing.assert_allclose(dense_losses, sparse_losses, rtol=1e-4)
    np.testing.assert_allclose(dense_w, sparse_w, rtol=1e-4, atol=1e-6)


def test_sparse_momentum_is_lazy():
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, lambda: fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w1 = np.asarray(scope.var("emb_w")).copy()
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w2 = np.asarray(scope.var("emb_w"))
        np.testing.assert_array_equal(w1[:4], w2[:4])   # frozen
        assert np.abs(w2[10:14] - w1[10:14]).sum() > 0


def test_sparse_adam_is_lazy():
    """Reference lazy-adam semantics: a row not touched this step keeps
    bit-identical param + moments (dense adam keeps moving it via
    momentum decay)."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, lambda: fluid.optimizer.Adam(learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())

        # step 1: touch rows {0..3}
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w_after1 = np.asarray(scope.var("emb_w")).copy()
        moment_names = [n for n in scope.local_var_names()
                        if "emb_w" in n and "moment" in n]
        assert moment_names, list(scope.local_var_names())
        m1_after1 = np.asarray(scope.var(moment_names[0])).copy()

        # step 2: touch rows {10..13} only
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w_after2 = np.asarray(scope.var("emb_w"))

        # rows 0..3 untouched in step 2: bit-identical
        np.testing.assert_array_equal(w_after1[:4], w_after2[:4])
        # rows 10..13 did move
        assert np.abs(w_after2[10:14] - w_after1[10:14]).sum() > 0
        assert np.isfinite(m1_after1).all()


def test_sparse_grad_densifies_to_dense_grad():
    """get_tensor_from_selected_rows(lookup grad) == the dense grad."""
    ids = fluid.layers.data("ids", shape=[3, 1], dtype="int64")
    emb_sparse = fluid.layers.embedding(
        ids, size=[V, D], is_sparse=True,
        param_attr=ParamAttr(name="w_sp"))
    loss = fluid.layers.reduce_sum(
        fluid.layers.elementwise_mul(emb_sparse, emb_sparse))
    fluid.append_backward(loss)
    g = fluid.default_main_program().global_block().create_var(
        name="dense_of_sparse", shape=[V, D], dtype="float32")
    fluid.default_main_program().global_block().append_op(
        type="get_tensor_from_selected_rows",
        inputs={"X": [grad_var_name("w_sp")]},
        outputs={"Out": [g]})

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    idv = rng.randint(0, V, (4, 3, 1)).astype("int64")
    idv[0, 0, 0] = idv[0, 1, 0] = 5        # duplicate rows
    (gd,) = exe.run(feed={"ids": idv}, fetch_list=[g])

    scope = fluid.global_scope()
    w = np.asarray(scope.var("w_sp"))
    ref = np.zeros((V, D), "float32")
    for i in idv.reshape(-1):
        ref[i] += 2.0 * w[i]
    np.testing.assert_allclose(gd, ref, rtol=1e-5)


def test_embedding_used_twice_accumulates():
    """Two lookups on one table: sparse contributions concatenate."""
    a = fluid.layers.data("a", shape=[2, 1], dtype="int64")
    b = fluid.layers.data("b", shape=[2, 1], dtype="int64")
    ea = fluid.layers.embedding(a, size=[V, D], is_sparse=True,
                                param_attr=ParamAttr(name="w2"))
    eb = fluid.layers.embedding(b, size=[V, D], is_sparse=True,
                                param_attr=ParamAttr(name="w2"))
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_add(ea, eb))
    fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)

    scope = fluid.global_scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w0 = np.asarray(scope.var("w2")).copy()
    av = np.array([[[1], [2]]], "int64")
    bv = np.array([[[2], [3]]], "int64")
    exe.run(feed={"a": av, "b": bv}, fetch_list=[loss])
    w1 = np.asarray(scope.var("w2"))
    delta = w0 - w1
    # d(loss)/d(w[r]) = count of r among all looked-up ids
    np.testing.assert_allclose(delta[1], np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[2], 2 * np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[3], np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[0], np.zeros(D), atol=1e-6)


def test_distributed_embedding_sharding_fn():
    """is_distributed tables are auto-row-sharded by the helper."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import (
        make_mesh, distributed_embedding_sharding_fn)

    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[V, D], is_distributed=True,
        param_attr=ParamAttr(name="dist_w"))
    other = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=2)

    mesh = make_mesh((4, 2), ("dp", "ep"))
    fn = distributed_embedding_sharding_fn(
        fluid.default_main_program(), mesh)
    assert fn("dist_w", (V, D)) == P("ep")
    assert fn("fc_0.w_0", (D, 2)) is None
    # indivisible height falls back to replicated
    assert fn("dist_w", (V + 1, D)) is None


def test_sharded_embedding_parallel_parity():
    """Embedding table sharded over the mesh (the pserver sharded-table
    replacement): loss parity with the single-device run, sparse grads
    under pjit."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import make_mesh

    def opt():
        return fluid.optimizer.SGD(learning_rate=0.1)

    dense_losses, dense_w = _train(False, opt)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, opt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())

        bs = fluid.BuildStrategy()
        bs.param_sharding_fn = lambda name, shape: (
            P("dp") if name == "emb_w" and shape and shape[0] % 4 == 0
            else None)
        mesh = make_mesh((4,), ("dp",))
        pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                    mesh=mesh, scope=scope)
        losses = [
            float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0]).ravel()[0])
            for b in _batches()
        ]
        w = np.asarray(scope.var("emb_w"))
    np.testing.assert_allclose(dense_losses, losses, rtol=1e-4)
    np.testing.assert_allclose(dense_w, w, rtol=1e-4, atol=1e-6)
