"""SelectedRows sparse-gradient tests (reference selected_rows.h /
lookup_table_op.cc sparse path / optimizer SelectedRows kernels:
sparse-vs-dense parity, lazy-update semantics, duplicate-row merging,
multi-use accumulation, and the mesh-sharded embedding path)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name
from paddle_tpu.param_attr import ParamAttr

V, D = 20, 6


def _build(is_sparse, opt_factory, seed=13):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[V, D], is_sparse=is_sparse,
        param_attr=ParamAttr(name="emb_w"))
    pooled = fluid.layers.reduce_mean(emb, dim=1)          # [B, D]
    pred = fluid.layers.fc(pooled, size=1, act=None,
                           param_attr=ParamAttr(name="fc_w"),
                           bias_attr=ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    opt_factory().minimize(loss)
    return loss


def _batches(steps=8, b=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, V, (b, 4, 1)).astype("int64")  # dup rows likely
        yv = rng.rand(b, 1).astype("float32")
        out.append({"ids": ids, "y": yv})
    return out


def _train(is_sparse, opt_factory, steps=8):
    from paddle_tpu.framework import program_guard

    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), program_guard(main, startup):
        loss = _build(is_sparse, opt_factory)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [
            float(np.asarray(exe.run(main, feed=b,
                                     fetch_list=[loss])[0]).ravel()[0])
            for b in _batches(steps)
        ]
        emb_w = np.asarray(scope.var("emb_w"))
    return losses, emb_w


@pytest.mark.parametrize("opt", [
    lambda: fluid.optimizer.SGD(learning_rate=0.1),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
])
def test_sparse_matches_dense(opt):
    """For SGD/Adagrad a zero dense grad row is a no-op, so lazy sparse
    updates must match the dense path exactly.  (Momentum/Adam are NOT
    expected to match: their dense kernels keep moving untouched rows via
    velocity/moment decay while the reference sparse kernels are lazy —
    covered by the laziness tests below.)"""
    dense_losses, dense_w = _train(False, opt)
    sparse_losses, sparse_w = _train(True, opt)
    np.testing.assert_allclose(dense_losses, sparse_losses, rtol=1e-4)
    np.testing.assert_allclose(dense_w, sparse_w, rtol=1e-4, atol=1e-6)


def test_sparse_momentum_is_lazy():
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, lambda: fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w1 = np.asarray(scope.var("emb_w")).copy()
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w2 = np.asarray(scope.var("emb_w"))
        np.testing.assert_array_equal(w1[:4], w2[:4])   # frozen
        assert np.abs(w2[10:14] - w1[10:14]).sum() > 0


def test_sparse_adam_is_lazy():
    """Reference lazy-adam semantics: a row not touched this step keeps
    bit-identical param + moments (dense adam keeps moving it via
    momentum decay)."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, lambda: fluid.optimizer.Adam(learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())

        # step 1: touch rows {0..3}
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w_after1 = np.asarray(scope.var("emb_w")).copy()
        moment_names = [n for n in scope.local_var_names()
                        if "emb_w" in n and "moment" in n]
        assert moment_names, list(scope.local_var_names())
        m1_after1 = np.asarray(scope.var(moment_names[0])).copy()

        # step 2: touch rows {10..13} only
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w_after2 = np.asarray(scope.var("emb_w"))

        # rows 0..3 untouched in step 2: bit-identical
        np.testing.assert_array_equal(w_after1[:4], w_after2[:4])
        # rows 10..13 did move
        assert np.abs(w_after2[10:14] - w_after1[10:14]).sum() > 0
        assert np.isfinite(m1_after1).all()


def test_sparse_grad_densifies_to_dense_grad():
    """get_tensor_from_selected_rows(lookup grad) == the dense grad."""
    ids = fluid.layers.data("ids", shape=[3, 1], dtype="int64")
    emb_sparse = fluid.layers.embedding(
        ids, size=[V, D], is_sparse=True,
        param_attr=ParamAttr(name="w_sp"))
    loss = fluid.layers.reduce_sum(
        fluid.layers.elementwise_mul(emb_sparse, emb_sparse))
    fluid.append_backward(loss)
    g = fluid.default_main_program().global_block().create_var(
        name="dense_of_sparse", shape=[V, D], dtype="float32")
    fluid.default_main_program().global_block().append_op(
        type="get_tensor_from_selected_rows",
        inputs={"X": [grad_var_name("w_sp")]},
        outputs={"Out": [g]})

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    idv = rng.randint(0, V, (4, 3, 1)).astype("int64")
    idv[0, 0, 0] = idv[0, 1, 0] = 5        # duplicate rows
    (gd,) = exe.run(feed={"ids": idv}, fetch_list=[g])

    scope = fluid.global_scope()
    w = np.asarray(scope.var("w_sp"))
    ref = np.zeros((V, D), "float32")
    for i in idv.reshape(-1):
        ref[i] += 2.0 * w[i]
    np.testing.assert_allclose(gd, ref, rtol=1e-5)


def test_embedding_used_twice_accumulates():
    """Two lookups on one table: sparse contributions concatenate."""
    a = fluid.layers.data("a", shape=[2, 1], dtype="int64")
    b = fluid.layers.data("b", shape=[2, 1], dtype="int64")
    ea = fluid.layers.embedding(a, size=[V, D], is_sparse=True,
                                param_attr=ParamAttr(name="w2"))
    eb = fluid.layers.embedding(b, size=[V, D], is_sparse=True,
                                param_attr=ParamAttr(name="w2"))
    loss = fluid.layers.reduce_sum(fluid.layers.elementwise_add(ea, eb))
    fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)

    scope = fluid.global_scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w0 = np.asarray(scope.var("w2")).copy()
    av = np.array([[[1], [2]]], "int64")
    bv = np.array([[[2], [3]]], "int64")
    exe.run(feed={"a": av, "b": bv}, fetch_list=[loss])
    w1 = np.asarray(scope.var("w2"))
    delta = w0 - w1
    # d(loss)/d(w[r]) = count of r among all looked-up ids
    np.testing.assert_allclose(delta[1], np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[2], 2 * np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[3], np.ones(D), atol=1e-6)
    np.testing.assert_allclose(delta[0], np.zeros(D), atol=1e-6)


def test_distributed_embedding_sharding_fn():
    """is_distributed tables are auto-row-sharded by the helper."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import (
        make_mesh, distributed_embedding_sharding_fn)

    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    emb = fluid.layers.embedding(
        ids, size=[V, D], is_distributed=True,
        param_attr=ParamAttr(name="dist_w"))
    other = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=2)

    mesh = make_mesh((4, 2), ("dp", "ep"))
    fn = distributed_embedding_sharding_fn(
        fluid.default_main_program(), mesh)
    assert fn("dist_w", (V, D)) == P("ep")
    assert fn("fc_0.w_0", (D, 2)) is None
    # indivisible height falls back to replicated
    assert fn("dist_w", (V + 1, D)) is None


def test_sharded_embedding_parallel_parity():
    """Embedding table sharded over the mesh (the pserver sharded-table
    replacement): loss parity with the single-device run, sparse grads
    under pjit."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import make_mesh

    def opt():
        return fluid.optimizer.SGD(learning_rate=0.1)

    dense_losses, dense_w = _train(False, opt)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build(True, opt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())

        bs = fluid.BuildStrategy()
        bs.param_sharding_fn = lambda name, shape: (
            P("dp") if name == "emb_w" and shape and shape[0] % 4 == 0
            else None)
        mesh = make_mesh((4,), ("dp",))
        pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                    mesh=mesh, scope=scope)
        losses = [
            float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0]).ravel()[0])
            for b in _batches()
        ]
        w = np.asarray(scope.var("emb_w"))
    np.testing.assert_allclose(dense_losses, losses, rtol=1e-4)
    np.testing.assert_allclose(dense_w, w, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# ISSUE 15: end-to-end SelectedRows path — bit-level parity, survivability
# through clip/regularizer aggregation, warm-path lowering count, and the
# row-sharded mesh update
# ---------------------------------------------------------------------------

def _build_tower(is_sparse, opt_factory, vocab=V, clip=None, reg=None,
                 seed=5):
    """Embedding -> mean-pool -> fc tower with optional global clip and
    per-param regularizer on the table."""
    main = fluid.default_main_program()
    main.random_seed = seed
    fluid.default_startup_program().random_seed = seed
    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[vocab, D], is_sparse=is_sparse,
        param_attr=ParamAttr(name="emb_w", regularizer=reg))
    pred = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=1,
                           param_attr=ParamAttr(name="fc_w"),
                           bias_attr=ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    if clip is not None:
        fluid.clip.set_gradient_clip(clip)
    opt_factory().minimize(loss)
    return loss


def _dup_batches(vocab, steps=2, b=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, vocab, (b, 4, 1)).astype("int64")
        ids[0, 0, 0] = ids[0, 1, 0] = 3      # guaranteed duplicate row
        out.append({"ids": ids, "y": rng.rand(b, 1).astype("float32")})
    return out


def _one_run(is_sparse, opt_factory, vocab=V, steps=1, clip=None,
             reg=None, scope=None, table="emb_w"):
    from paddle_tpu.framework import program_guard

    scope = scope or fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), program_guard(main, startup):
        loss = _build_tower(is_sparse, opt_factory, vocab=vocab,
                            clip=clip, reg=reg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [
            float(np.asarray(exe.run(main, feed=f,
                                     fetch_list=[loss])[0]).ravel()[0])
            for f in _dup_batches(vocab, steps)
        ]
        w = np.array(np.asarray(scope.var(table)), copy=True)
        slots = {n: np.array(np.asarray(scope.var(n)), copy=True)
                 for n in scope.local_var_names()
                 if n.startswith(table + "_")
                 and ("moment" in n or "velocity" in n)}
    return losses, w, slots


@pytest.mark.parametrize("opt", [
    lambda: fluid.optimizer.Adam(learning_rate=0.1),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
])
def test_sparse_update_bitwise_matches_dense_first_step(opt):
    """Touched rows match the dense update BIT-FOR-BIT (duplicate rows
    included: merge_rows sums duplicates exactly like the dense
    backward's scatter-add), and untouched rows are bit-identical
    trivially — so after one step from identical init the whole table
    and every slot var are bitwise equal across the two paths.  (Adam /
    Adagrad merge duplicates before the kernel; plain SGD scatter-adds
    duplicates sequentially, which is duplicate-safe but associates the
    sum differently — covered by test_sparse_matches_dense at rtol.)"""
    def norm(slots):
        # the unique-name counter differs between the two builds
        # (emb_w_moment1_0 vs _1): key by the stripped slot kind
        return {n.rsplit("_", 1)[0]: a for n, a in slots.items()}

    _, w_sp, s_sp = _one_run(True, opt, steps=1)
    _, w_dn, s_dn = _one_run(False, opt, steps=1)
    np.testing.assert_array_equal(w_sp, w_dn)
    s_sp, s_dn = norm(s_sp), norm(s_dn)
    assert set(s_sp) == set(s_dn) and s_sp
    for n in s_sp:
        np.testing.assert_array_equal(s_sp[n], s_dn[n])


def test_sparse_adam_untouched_moments_bit_stable():
    """The lazy kernel's defining invariant: a row not touched this step
    keeps bit-identical param AND Adam moments across the step."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build_tower(True, lambda: fluid.optimizer.Adam(
            learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        moment_names = [n for n in scope.local_var_names()
                        if n.startswith("emb_w_") and "moment" in n]
        assert len(moment_names) >= 2, scope.local_var_names()
        w1 = np.array(np.asarray(scope.var("emb_w")), copy=True)
        m1 = {n: np.array(np.asarray(scope.var(n)), copy=True)
              for n in moment_names}
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        untouched = list(range(4)) + list(range(14, V))
        w2 = np.asarray(scope.var("emb_w"))
        np.testing.assert_array_equal(w1[untouched], w2[untouched])
        for n in moment_names:
            m2 = np.asarray(scope.var(n))
            np.testing.assert_array_equal(m1[n][untouched],
                                          m2[untouched])
            # and the touched rows' moments DID move
            assert np.abs(m2[10:14] - m1[n][10:14]).sum() > 0


def test_sparse_grad_survives_global_clip_and_decay():
    """The survivability tentpole: global-norm clip + L2 decay on an
    is_sparse table no longer densify (or crash) — the summed gradient
    var keeps SELECTED_ROWS type through clip/regularizer appenders,
    the optimizer still sees a SelectedRows gradient (lazy semantics
    hold), and the numerics match the dense path."""
    from paddle_tpu.core import VarType

    def opt():
        return fluid.optimizer.Adam(learning_rate=0.1)

    clip = fluid.clip.GradientClipByGlobalNorm(clip_norm=0.5)
    reg = fluid.regularizer.L2Decay(1e-3)

    # (a) laziness survives the whole aggregation chain
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build_tower(True, opt, clip=clip, reg=reg)
        main = fluid.default_main_program()
        adam_grads = [
            op.inputs["Grad"][0] for op in main.global_block().ops
            if op.type == "adam"
            and op.inputs["Param"][0] == "emb_w"]
        assert adam_grads, "no adam op on emb_w"
        gvar = main.global_block()._find_var_recursive(adam_grads[0])
        assert gvar.type == VarType.SELECTED_ROWS, (
            "clip/decay densified the sparse gradient")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        ids1 = np.array([[[0], [1], [2], [3]]] * 2, "int64")
        exe.run(feed={"ids": ids1, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w1 = np.array(np.asarray(scope.var("emb_w")), copy=True)
        ids2 = np.array([[[10], [11], [12], [13]]] * 2, "int64")
        exe.run(feed={"ids": ids2, "y": np.ones((2, 1), "float32")},
                fetch_list=[loss])
        w2 = np.asarray(scope.var("emb_w"))
        # rows 4..9 never touched: decay must NOT have moved them
        # (the lazy decay applies to touched rows only)
        np.testing.assert_array_equal(w1[4:10], w2[4:10])

    # (b) numeric parity with the dense path under the same global clip
    # (clip is merge-exact: the sparse squared_l2_norm equals the dense
    # grad's, the scale is uniform).  Adagrad, not Adam: a dense zero
    # grad row is a no-op for Adagrad, so lazy == dense over many steps
    # (the lazy-Adam trajectory legitimately diverges once a previously
    # touched row goes untouched — test_sparse_matches_dense's note)
    def adagrad():
        return fluid.optimizer.Adagrad(learning_rate=0.1)

    sp_losses, w_sp, _ = _one_run(True, adagrad, steps=3, clip=clip)
    dn_losses, w_dn, _ = _one_run(False, adagrad, steps=3, clip=clip)
    np.testing.assert_allclose(sp_losses, dn_losses, rtol=1e-4)
    np.testing.assert_allclose(w_sp, w_dn, rtol=1e-4, atol=1e-6)

    # (c) decay semantics: on the FIRST step from identical init the
    # touched rows' decayed update matches the dense regularized update
    # (same merged grad + coeff*w term, zero prior moments), while the
    # dense path moves every untouched row too (full-table decay) and
    # the lazy path leaves them bit-identical — the documented
    # difference that keeps the update O(touched)
    batch = _dup_batches(V, steps=1)[0]
    touched = sorted(set(batch["ids"].ravel().tolist()))
    untouched = [r for r in range(V) if r not in touched]
    _, w_sp1, _ = _one_run(True, opt, steps=1, reg=reg)
    _, w_dn1, _ = _one_run(False, opt, steps=1, reg=reg)
    np.testing.assert_allclose(w_sp1[touched], w_dn1[touched],
                               rtol=1e-6, atol=1e-7)
    assert untouched
    assert np.abs(w_dn1[untouched] - w_sp1[untouched]).max() > 0


def test_warm_sparse_step_pays_zero_lowerings():
    """Acceptance: the sparse path costs no extra trace/compile on the
    warm step path — after the cold step, further steps (same feed
    signature) lower nothing."""
    from jax._src import test_util as jtu

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        loss = _build_tower(True, lambda: fluid.optimizer.Adam(
            learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        batches = _dup_batches(V, steps=3)
        exe.run(feed=batches[0], fetch_list=[loss])      # cold
        with jtu.count_jit_and_pmap_lowerings() as n:
            for f in batches[1:]:
                exe.run(feed=f, fetch_list=[loss])
        assert n[0] == 0, "warm sparse step paid %d lowerings" % n[0]


def _build_dist_tower(vocab, opt_factory, seed=5):
    main = fluid.default_main_program()
    main.random_seed = seed
    fluid.default_startup_program().random_seed = seed
    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[vocab, D], is_sparse=True, is_distributed=True,
        param_attr=ParamAttr(name="emb_w"))
    pred = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=1,
                           param_attr=ParamAttr(name="fc_w"),
                           bias_attr=ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(fluid.layers.square(
        fluid.layers.elementwise_sub(pred, y)))
    opt_factory().minimize(loss)
    return loss


def test_rowsharded_pe_sparse_update_engages_and_matches(monkeypatch):
    """The mesh tentpole on a 4-virtual-device dp x ep mesh: the
    row-sharded table's lookup AND lazy update run through the explicit
    shard_map lowerings (spied), optimizer slot vars inherit the row
    sharding, losses/table match the single-device sparse run, and
    untouched rows stay bit-stable across steps ON the mesh."""
    from paddle_tpu.framework import program_guard
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel import embedding as emb_mod

    def opt():
        return fluid.optimizer.Adam(learning_rate=0.1)

    batches = _dup_batches(V, steps=3)

    # single-device sparse reference
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), program_guard(main, startup):
        loss = _build_tower(True, opt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ref = [float(np.asarray(exe.run(main, feed=f,
                                        fetch_list=[loss])[0]).ravel()[0])
               for f in batches]
        ref_w = np.array(np.asarray(scope.var("emb_w")), copy=True)

    calls = {"lookup": 0, "update": 0}
    orig_lookup = emb_mod.sharded_sparse_lookup
    orig_update = emb_mod.sharded_sparse_update

    def spy_lookup(*a, **kw):
        out = orig_lookup(*a, **kw)
        if out is not None:
            calls["lookup"] += 1
        return out

    def spy_update(*a, **kw):
        out = orig_update(*a, **kw)
        if out is not None:
            calls["update"] += 1
        return out

    monkeypatch.setattr(emb_mod, "sharded_sparse_lookup", spy_lookup)
    monkeypatch.setattr(emb_mod, "sharded_sparse_update", spy_update)

    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), program_guard(main, startup):
        loss = _build_dist_tower(V, opt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        mesh = make_mesh((2, 2), ("dp", "ep"))
        bs = fluid.BuildStrategy()
        bs.param_sharding_fn = emb_mod.distributed_embedding_sharding_fn(
            main, mesh)
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs, scope=scope)
        sharded = []
        w_prev = None
        for f in batches:
            sharded.append(float(np.asarray(
                pe.run(feed=f, fetch_list=[loss])[0]).ravel()[0]))
            w_now = np.array(np.asarray(scope.var("emb_w")), copy=True)
            if w_prev is not None:
                touched = set(f["ids"].ravel().tolist())
                stable = [r for r in range(V) if r not in touched]
                np.testing.assert_array_equal(w_prev[stable],
                                              w_now[stable])
            w_prev = w_now
        w = np.asarray(scope.var("emb_w"))
        # slot vars ride the table's row sharding (never a replicated
        # [vocab, D] moment buffer)
        moments = [n for n in scope.local_var_names()
                   if n.startswith("emb_w_") and "moment" in n]
        assert moments
        for n in moments:
            arr = scope.var(n)
            spec = tuple(getattr(arr.sharding, "spec", ()))
            assert spec and spec[0] == "ep", (n, spec)

    assert calls["lookup"] >= 1, "sharded lookup never engaged"
    assert calls["update"] >= 1, "sharded sparse update never engaged"
    np.testing.assert_allclose(sharded, ref, rtol=1e-4)
    np.testing.assert_allclose(w, ref_w, rtol=1e-4, atol=1e-6)


@pytest.mark.slow   # two PE compiles on an 8-device virtual mesh; the
# engagement + parity invariants stay tier-1 via the test above
def test_mesh_sharded_sparse_never_materializes_dense_table_grad():
    """The no-dense-materialization acceptance: per-device argument
    bytes of the row-sharded sparse run carry only the 1/N table+slot
    share, and per-device peak stays far under the replicated run's
    (which holds the full table per device) — i.e. the update never
    all-gathers the table or builds a dense [vocab, D] gradient."""
    from paddle_tpu import compile_cache, monitor
    from paddle_tpu.framework import program_guard
    from paddle_tpu.monitor import program_profile
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel import embedding as emb_mod

    monitor.enable()
    vocab, ep = 4096, 4

    def opt():
        return fluid.optimizer.Adam(learning_rate=0.1)

    peaks, args_bytes = {}, {}
    for label, shard in (("replicated", False), ("sharded", True)):
        scope = fluid.Scope()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.scope_guard(scope), program_guard(main, startup):
            loss = _build_dist_tower(vocab, opt)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            mesh = make_mesh((2, ep), ("dp", "ep"))
            bs = fluid.BuildStrategy()
            if shard:
                bs.param_sharding_fn = \
                    emb_mod.distributed_embedding_sharding_fn(main, mesh)
            pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                        build_strategy=bs, scope=scope)
            f = _dup_batches(vocab, steps=1)[0]
            pe.run(feed=f, fetch_list=[loss])
            prof = program_profile.get(
                compile_cache.program_fingerprint(main))
            assert prof is not None, label
            b = prof.breakdown()
            peaks[label] = b["peak_hbm_bytes"]
            args_bytes[label] = b["argument_bytes"]

    table_bytes = vocab * D * 4 * 3      # param + 2 Adam moments
    saved = args_bytes["replicated"] - args_bytes["sharded"]
    # the sharded run sheds ~(1 - 1/ep) of the table+slots per device
    assert saved > table_bytes * (1 - 1.0 / ep) * 0.8, (
        saved, table_bytes)
    # and its peak must stay well under the replicated peak: a dense
    # [vocab, D] grad or an all-gathered table would erase the gap
    assert peaks["sharded"] < peaks["replicated"] - \
        table_bytes * (1 - 1.0 / ep) * 0.5, peaks


@pytest.mark.slow   # ~1e6-row tables: the vocab-scaling acceptance
# drill (the bench rung's predicate, asserted with generous margins;
# run solo — CPU wall clock under concurrent load is noise)
def test_vocab_scaling_sparse_flat_dense_linear():
    """Acceptance: sparse step time ~flat in vocab while dense grows
    linearly — >=3x advantage at vocab=1e6 on CPU (the bench rung
    measures 14x; the test asserts a floor robust to load)."""
    import time as _time

    from paddle_tpu.framework import program_guard

    def opt():
        return fluid.optimizer.Adam(learning_rate=1e-3)

    def step_time(vocab, is_sparse):
        scope = fluid.Scope()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.scope_guard(scope), program_guard(main, startup):
            loss = _build_tower(is_sparse, opt, vocab=vocab)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feeds = _dup_batches(vocab, steps=5)
            times = []
            for i, f in enumerate(feeds):
                t0 = _time.monotonic()
                out = exe.run(main, feed=f, fetch_list=[loss])
                float(np.asarray(out[0]).ravel()[0])
                if i >= 2:
                    times.append(_time.monotonic() - t0)
        return min(times)

    sp_small = step_time(10_000, True)
    sp_big = step_time(1_000_000, True)
    dn_big = step_time(1_000_000, False)
    assert dn_big / sp_big >= 3.0, (sp_big, dn_big)
    assert sp_big / sp_small < 3.0, (sp_small, sp_big)
