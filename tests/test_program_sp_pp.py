"""Program-surface sequence/pipeline parallelism (VERDICT r2 #3): the REAL
``models/transformer.py`` trains through ParallelExecutor on meshes with
``sp`` (ring attention) and ``pp`` (pipeline) axes, loss-parity-checked
against the single-device Executor.  Runs on the 8-device virtual CPU
mesh (conftest)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh

# The r8-era "sp/pp numeric-parity drift" was the legacy
# non-partitionable threefry lowering: jax.random bits generated inside
# a GSPMD-partitioned computation depended on the MESH SHAPE (dropout
# masks on a (2, 4) mesh differed from one device / a 1-D dp mesh), so
# every dropout-bearing mesh run drifted off the single-device
# trajectory by one mask's worth of loss.  paddle_tpu now enables
# jax_threefry_partitionable at import (sharding-invariant streams) and
# these parity checks hold again — the xfail(strict=False) markers are
# gone.  They stay `slow` purely for tier-1 budget (~230s of transformer
# compiles); run explicitly with -m slow.
def _mesh_parity_drift(fn):
    return pytest.mark.slow(fn)


def _build_transformer(seed=11, batch=8, t=16, vocab=64, dropout=0.1):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    from paddle_tpu.models import transformer as tfm
    src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                            lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                            lod_level=1)
    lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                            lod_level=1)
    cost, _ = tfm.transformer(src, tgt, lbl, t, t, vocab, vocab, n_layer=2,
                              n_head=2, d_model=16, d_inner=32,
                              dropout_rate=dropout)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)
    return cost


def _batches(steps=4, batch=8, t=16, vocab=64):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(steps):
        ids = rng.randint(2, vocab, (batch, t, 1)).astype("int64")
        # ragged lengths exercise the k_len mask through the ring
        lens = rng.randint(t // 2, t + 1, (batch,)).astype("int32")
        out.append({"src_word": ids, "src_word@LEN": lens,
                    "tgt_word": ids, "tgt_word@LEN": lens,
                    "lbl_word": ids, "lbl_word@LEN": lens})
    return out


def _run_single(batches, loss):
    # startup runs on its own executor so the training executor's
    # per-step PRNG counter starts at 0, aligned with ParallelExecutor's
    # (dropout-mask parity requires identical per-step keys)
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    exe = fluid.Executor(fluid.CPUPlace())
    return [float(np.asarray(exe.run(feed=b, fetch_list=[loss])[0])
                  .ravel()[0]) for b in batches]


def _run_parallel(batches, loss, mesh, build_strategy=None):
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                build_strategy=build_strategy)
    return [float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0])
                  .ravel()[0]) for b in batches]


@pytest.mark.parametrize("mesh_shape,axes", [
    ((2, 4), ("dp", "sp")),
    ((1, 8), ("dp", "sp")),
])
@_mesh_parity_drift
def test_transformer_trains_under_sp_mesh(mesh_shape, axes, monkeypatch):
    """The real transformer, ring attention over sp, loss-parity with the
    single-device run — including dropout (the counter-hash mask is
    position-keyed, so sharding does not change it) and ragged k_len."""
    import paddle_tpu.ops.attention as att

    calls = {"ring": 0}
    orig = att._ring_attention

    def spy(*a, **kw):
        calls["ring"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(att, "_ring_attention", spy)

    batches = _batches()
    loss = _build_transformer()
    single = _run_single(batches, loss)
    assert calls["ring"] == 0   # single device never rings

    mesh = make_mesh(mesh_shape, axes)
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, mesh)
    # 6 fused_attention sites traced once each (fwd; bwd re-traces via vjp)
    assert calls["ring"] >= 6
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=2e-4)
    assert par[-1] < par[0]


@_mesh_parity_drift
def test_sp_mesh_without_sp_divisibility_falls_back(monkeypatch):
    """T not divisible by sp -> clean fallback to the single-chip kernel
    (still correct, just not ring-parallel)."""
    import paddle_tpu.ops.attention as att

    calls = {"ring": 0}
    orig = att._ring_attention

    def spy(*a, **kw):
        calls["ring"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(att, "_ring_attention", spy)

    batches = _batches(steps=2, t=10)
    loss = _build_transformer(t=10)
    single = _run_single(batches, loss)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, mesh)
    assert calls["ring"] == 0
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# pipeline (pp axis) program surface
# ---------------------------------------------------------------------------

def _build_pipelined_transformer(seed=13, t=16, vocab=64, dropout=0.1,
                                 microbatches=2):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    from paddle_tpu.models import transformer as tfm
    src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                            lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                            lod_level=1)
    lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                            lod_level=1)
    cost, _ = tfm.transformer(src, tgt, lbl, t, t, vocab, vocab, n_layer=2,
                              n_head=2, d_model=16, d_inner=32,
                              dropout_rate=dropout,
                              pipeline_microbatches=microbatches)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)
    return cost


def test_pipelined_transformer_emits_regions():
    loss = _build_pipelined_transformer()
    ops = [op.type for op in
           fluid.default_main_program().global_block().ops]
    assert ops.count("pipeline_region") == 2          # enc + dec stacks
    assert ops.count("pipeline_region_grad") == 2     # differentiable


@_mesh_parity_drift
def test_pipelined_transformer_trains_under_pp_mesh():
    """The REAL transformer staged into GPipe regions, dropout on:
    single-device sequential lowering vs a (dp=1, pp=2) mesh GPipe
    schedule must be loss-parity-exact (same stage template, same PRNG
    folds; dp=1 keeps in-stage draws identical), and train."""
    batches = _batches()
    loss = _build_pipelined_transformer()
    single = _run_single(batches, loss)

    mesh = make_mesh((1, 2), ("dp", "pp"))
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, mesh)
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=2e-4)
    assert par[-1] < par[0]


@_mesh_parity_drift
def test_pipelined_transformer_dp_sharded_pp_mesh():
    """(dp=2, pp=2): microbatch slices shard over dp (no redundant
    compute).  With dropout OFF parity with the sequential lowering is
    exact; with dropout ON the per-shard draws decorrelate, so just
    assert training progresses."""
    batches = _batches()
    loss = _build_pipelined_transformer(dropout=0.0)
    single = _run_single(batches, loss)
    mesh = make_mesh((2, 2), ("dp", "pp"))
    with fluid.scope_guard(fluid.Scope()):
        par = _run_parallel(batches, loss, mesh)
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=2e-4)

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss2 = _build_pipelined_transformer(dropout=0.1)
        with fluid.scope_guard(fluid.Scope()):
            par2 = _run_parallel(batches, loss2, mesh)
    assert par2[-1] < par2[0]


def test_pipelined_matches_plain_transformer_no_dropout():
    """Sequential lowering of the staged program computes the same math
    as the unstaged model (dropout off so PRNG structure is irrelevant)."""
    batches = _batches(steps=3)
    loss = _build_transformer(seed=13, dropout=0.0)
    plain = _run_single(batches, loss)

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss2 = _build_pipelined_transformer(seed=13, dropout=0.0)
        with fluid.scope_guard(fluid.Scope()):
            staged = _run_single(batches, loss2)
    np.testing.assert_allclose(plain, staged, rtol=2e-4, atol=2e-4)


@_mesh_parity_drift
@pytest.mark.parametrize("schedule", ["1f1b", "interleaved"])
def test_pipelined_transformer_schedule_parity(schedule):
    """Schedule equivalence through the program path: the REAL
    transformer staged into pipeline regions trains under 1F1B and
    interleaved schedules with the same loss trajectory as the
    single-device sequential lowering (same stage template, same PRNG
    folds — dropout ON).  Interleaved runs 4 program stages as v=2
    chunks per device on pp=2."""
    n_layer = 4 if schedule == "interleaved" else 2
    batches = _batches()
    # built at the right depth (interleaved needs stages % pp == 0
    # with v > 1)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 13
        fluid.default_startup_program().random_seed = 13
        from paddle_tpu.models import transformer as tfm
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                lod_level=1)
        loss, _ = tfm.transformer(src, tgt, lbl, 16, 16, 64, 64,
                                  n_layer=n_layer, n_head=2, d_model=16,
                                  d_inner=32, dropout_rate=0.1,
                                  pipeline_microbatches=2)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        single = _run_single(batches, loss)

        mesh = make_mesh((1, 2), ("dp", "pp"))
        bs = fluid.BuildStrategy()
        bs.pipeline_schedule = schedule
        with fluid.scope_guard(fluid.Scope()):
            par = _run_parallel(batches, loss, mesh, build_strategy=bs)
        np.testing.assert_allclose(single, par, rtol=2e-4, atol=2e-4)
        assert par[-1] < par[0]


@_mesh_parity_drift
def test_pipeline_bubble_attributed_and_smaller_interleaved():
    """The goodput ledger's pipeline_bubble bucket: warm pipelined
    steps carve out the executed schedule's exact per-tick stage-idle
    fraction, and the measured interleaved fraction is SMALLER than
    gpipe's at equal (S, M) — the ISSUE 12 acceptance, from the
    artifact, not the formula."""
    from paddle_tpu import monitor

    batches = _batches()
    fractions, losses = {}, {}
    mesh = make_mesh((1, 2), ("dp", "pp"))
    monitor.enable()
    try:
        # EQUAL (S, M): the same 4-layer model, M=2 microbatches, on
        # the same pp=2 mesh — gpipe runs it as 2 fat stages (2 layers
        # each), interleaved as 4 thin stages = v=2 chunks per device
        for schedule, lps in (("gpipe", 2), ("interleaved", 1)):
            with fluid.program_guard(fluid.Program(), fluid.Program()):
                fluid.default_main_program().random_seed = 13
                fluid.default_startup_program().random_seed = 13
                from paddle_tpu.models import transformer as tfm
                src = fluid.layers.data("src_word", shape=[1],
                                        dtype="int64", lod_level=1)
                tgt = fluid.layers.data("tgt_word", shape=[1],
                                        dtype="int64", lod_level=1)
                lbl = fluid.layers.data("lbl_word", shape=[1],
                                        dtype="int64", lod_level=1)
                loss, _ = tfm.transformer(
                    src, tgt, lbl, 16, 16, 64, 64, n_layer=4, n_head=2,
                    d_model=16, d_inner=32, dropout_rate=0.0,
                    pipeline_microbatches=2,
                    pipeline_layers_per_stage=lps)
                fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
                bs = fluid.BuildStrategy()
                bs.pipeline_schedule = schedule
                with fluid.scope_guard(fluid.Scope()):
                    fluid.Executor(fluid.CPUPlace()).run(
                        fluid.default_startup_program())
                    pe = fluid.ParallelExecutor(loss_name=loss.name,
                                                mesh=mesh,
                                                build_strategy=bs)
                    # warm the trace first, then open a fresh
                    # attribution window: the cold step's compile
                    # residual is not pipelined time
                    pe.run(feed=batches[0], fetch_list=[loss])
                    monitor.goodput_reset()
                    losses[schedule] = [
                        float(np.asarray(pe.run(feed=b,
                                                fetch_list=[loss])[0])
                              .ravel()[0]) for b in batches]
                summ = monitor.goodput_summary()
                assert summ["buckets"]["pipeline_bubble"] > 0, summ
                # normalize against the warm step path only: the cold
                # step's compile wall would swamp the fraction
                warm = summ["buckets"]["pipeline_bubble"] + \
                    summ["buckets"]["compute"]
                fractions[schedule] = \
                    summ["buckets"]["pipeline_bubble"] / warm
    finally:
        monitor.disable()
    # same trajectory (schedules/stagings are layout, not math:
    # dropout off makes the two stagings' PRNG structure irrelevant)...
    np.testing.assert_allclose(losses["gpipe"], losses["interleaved"],
                               rtol=2e-4, atol=2e-4)
    # ...but interleaved measurably wastes less of the step
    assert fractions["interleaved"] < fractions["gpipe"], fractions


def test_pipeline_rejects_structurally_different_stages():
    """Stages differing in op attrs (not just types) must be rejected —
    the template lowering would silently run stage 0's math otherwise."""
    x0 = fluid.layers.data("x", shape=[4])
    pipe = fluid.layers.Pipeline(microbatches=2)
    for i, rate in enumerate([0.1, 0.5]):      # differing dropout attrs
        with pipe.stage():
            h = pipe.carry(x0 if i == 0 else None)
            h = fluid.layers.fc(h, size=4)
            h = fluid.layers.dropout(h, dropout_prob=rate)
            pipe.emit(h)
    out = pipe()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with pytest.raises(Exception, match="structurally identical"):
        exe.run(feed={"x": np.zeros((4, 4), "float32")},
                fetch_list=[out])


def test_pipeline_rejects_undeclared_float_side():
    """A float activation consumed inside a stage without pipe.side()
    must fail loudly at region close (silent zero grads otherwise)."""
    x0 = fluid.layers.data("x", shape=[4])
    bias = fluid.layers.fc(x0, size=4)          # float, not persistable
    pipe = fluid.layers.Pipeline(microbatches=2)
    with pipe.stage():
        h = pipe.carry(x0)
        h = fluid.layers.elementwise_add(h, bias)   # undeclared side
        pipe.emit(h)
    with pytest.raises(ValueError, match="side"):
        pipe()
