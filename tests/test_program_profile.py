"""Program-level cost & memory attribution tests (ISSUE 5): profile
capture at the cold dispatch with ZERO extra lowerings, the HBM
preflight, registry-served cost_analysis, per-program step accounting
and /metrics family, run-id correlation across JSONL / chrome traces /
exposition, the program_report CLI, and the watchdog's suspect-program
line."""

import json
import os
import subprocess
import sys
import time
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import compile_cache, monitor, profiler
from paddle_tpu.monitor import program_profile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_profile_state():
    """Every test starts and ends with default preflight flags, a
    disabled monitor, and an empty profile registry."""
    fluid.set_flags({"FLAGS_preflight_oom": "auto",
                     "FLAGS_preflight_hbm_bytes": 0})
    program_profile.reset()
    yield
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()
    program_profile.reset()
    fluid.set_flags({"FLAGS_preflight_oom": "auto",
                     "FLAGS_preflight_hbm_bytes": 0})


def _build_mlp(seed=0):
    fluid.default_main_program().random_seed = seed
    x = fluid.layers.data("x", shape=[4])
    h = fluid.layers.fc(x, size=8, act="relu")
    loss = fluid.layers.mean(fluid.layers.fc(h, size=3))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _run_steps(loss, steps=3, batch=8):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.RandomState(0).rand(batch, 4).astype("float32")
    for _ in range(steps):
        exe.run(feed={"x": x}, fetch_list=[loss])
    return exe


# ---------------------------------------------------------------------------
# capture + accounting + report
# ---------------------------------------------------------------------------

def test_cold_dispatch_captures_cost_and_memory_profile():
    monitor.enable()
    loss = _build_mlp()
    _run_steps(loss, steps=3)
    fp = compile_cache.program_fingerprint(fluid.default_main_program())
    prof = program_profile.get(fp)
    assert prof is not None and prof.kind == "executor"
    # the compiler's own accounting, not a heuristic
    assert prof.flops > 0
    assert prof.bytes_accessed > 0
    assert prof.argument_bytes > 0          # params + feed cross the step
    assert prof.peak_hbm_bytes > 0
    assert set(prof.breakdown()) == {
        "argument_bytes", "output_bytes", "temp_bytes",
        "generated_code_bytes", "alias_bytes", "peak_hbm_bytes"}
    # step accounting joined the profile
    acct = program_profile.accounting()[fp]
    assert acct["steps"] == 3
    assert acct["examples"] == 24
    assert acct["wall_s"] > 0
    # per-program /metrics family
    fp12 = fp[:12]
    reg = monitor.registry()
    assert reg.get("program/%s/steps_total" % fp12).value == 3
    assert reg.get("program/%s/step_seconds" % fp12).count == 3
    assert reg.get("program/%s/examples_total" % fp12).value == 24


def test_two_program_run_report_acceptance():
    """Acceptance: MLP + transformer in one monitored run -> report
    rows with distinct fingerprints, compiler-accounted flops/bytes/
    peak-HBM per program, correct step counts, wall-clock shares."""
    from paddle_tpu.models import transformer as tfm

    monitor.enable()
    mlp_loss = _build_mlp()
    exe = _run_steps(mlp_loss, steps=4)
    mlp_fp = compile_cache.program_fingerprint(fluid.default_main_program())

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                  lod_level=1)
        cost, _ = tfm.transformer(
            src, tgt, label, 8, 8, 12, 12, n_layer=1, n_head=2,
            d_model=16, d_inner=32, dropout_rate=0.0)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        tfm_prog = fluid.default_main_program()
        tfm_fp = compile_cache.program_fingerprint(tfm_prog)

        feeder = fluid.DataFeeder(feed_list=[src, tgt, label], pad_to=8)
        rng = np.random.RandomState(0)
        rows = [[rng.randint(1, 12, (8,)), rng.randint(1, 12, (8,)),
                 rng.randint(1, 12, (8,))] for _ in range(2)]
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(fluid.default_startup_program())
        for _ in range(2):
            exe2.run(feed=feeder.feed(rows), fetch_list=[cost])

    assert mlp_fp != tfm_fp
    report = program_profile.report_rows(peak_tflops=100.0)
    by_fp = {r["fingerprint"]: r for r in report}
    assert mlp_fp in by_fp and tfm_fp in by_fp
    assert by_fp[mlp_fp]["steps"] == 4
    assert by_fp[tfm_fp]["steps"] == 2
    for fp in (mlp_fp, tfm_fp):
        assert by_fp[fp]["flops_per_step"] > 0
        assert by_fp[fp]["bytes_per_step"] > 0
        assert by_fp[fp]["peak_hbm_bytes"] > 0
        assert by_fp[fp]["mfu"] is not None and by_fp[fp]["mfu"] >= 0
    # the transformer step does vastly more arithmetic than the MLP
    assert by_fp[tfm_fp]["flops_per_step"] > by_fp[mlp_fp]["flops_per_step"]
    shares = sum(r["wall_share"] for r in report)
    assert shares == pytest.approx(1.0, abs=0.01)
    # the rendered table carries one line per program
    table = program_profile.render_table(report)
    assert mlp_fp[:12] in table and tfm_fp[:12] in table


def test_profile_capture_costs_zero_extra_lowerings():
    """The acceptance gate: lowering AND backend-compile counts (jax's
    own counters plus the trace cache's) are IDENTICAL between a
    profile-off and a profile-on run of the same fresh program — the
    capture is the one compile, not an extra one."""
    from jax._src import test_util as jtu

    def arm():
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            loss = _build_mlp()
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                _run_steps(loss, steps=3)

    arm()                                   # warmup: jnp helper modules

    # default flags, monitor off: capture is dormant (auto mode)
    assert not program_profile.capture_enabled()
    compile_cache.clear()
    compile_cache.reset_stats()
    with jtu.count_jit_and_pmap_lowerings() as off_n, \
            jtu.count_jit_compilation_cache_miss() as off_c:
        arm()
    off_cc = compile_cache.stats()["lowerings"]

    monitor.enable()
    assert program_profile.capture_enabled()
    compile_cache.clear()
    compile_cache.reset_stats()
    with jtu.count_jit_and_pmap_lowerings() as on_n, \
            jtu.count_jit_compilation_cache_miss() as on_c:
        arm()
    on_cc = compile_cache.stats()["lowerings"]

    assert on_n[0] == off_n[0], "profile capture added jax lowerings"
    assert on_c[0] == off_c[0], "profile capture added backend compiles"
    assert on_cc == off_cc, "profile capture added trace-cache lowerings"
    assert program_profile.profiles(), "profile-on arm captured nothing"


def test_monitor_off_captures_nothing_by_default():
    """Default flags (preflight auto) + monitor off: the executors run
    their unmodified jit path — no profiles, no accounting, no AOT
    executables."""
    assert not monitor.enabled()
    assert not program_profile.capture_enabled()
    loss = _build_mlp()
    exe = _run_steps(loss, steps=2)
    assert program_profile.profiles() == []
    assert program_profile.accounting() == {}
    assert all(not c.aot for c in exe._cache.values())
    # explicit "off" dominates even with the monitor on
    fluid.set_flags({"FLAGS_preflight_oom": "off"})
    monitor.enable()
    assert program_profile.capture_enabled()   # profiles still wanted
    fluid.set_flags({"FLAGS_monitor": False})


# ---------------------------------------------------------------------------
# HBM preflight
# ---------------------------------------------------------------------------

def test_preflight_warns_with_buffer_class_breakdown():
    # "warn" forces capture+preflight even on this unmonitored run
    fluid.set_flags({"FLAGS_preflight_oom": "warn",
                     "FLAGS_preflight_hbm_bytes": 16})   # mocked capacity
    loss = _build_mlp()
    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        _run_steps(loss, steps=2)
    msgs = [str(w.message) for w in ws
            if "HBM preflight" in str(w.message)]
    assert msgs, "no preflight warning at 16-byte capacity"
    m = msgs[0]
    for cls in ("arguments", "outputs", "temps", "generated code",
                "aliased"):
        assert cls in m, "breakdown missing %r: %s" % (cls, m)
    assert "exceeds capacity" in m


def test_preflight_strict_raises_before_first_dispatch():
    fluid.set_flags({"FLAGS_preflight_oom": "strict",
                     "FLAGS_preflight_hbm_bytes": 16})
    _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(program_profile.PreflightOOMError,
                       match="exceeds capacity"):
        exe.run(fluid.default_startup_program())
    # the raise happened BEFORE the dispatch: the startup program never
    # wrote its parameters back, and a retry still preflights (the
    # signature was never marked seen)
    with pytest.raises(program_profile.PreflightOOMError):
        exe.run(fluid.default_startup_program())
    # widening the mocked capacity unblocks the same executor
    fluid.set_flags({"FLAGS_preflight_hbm_bytes": 1 << 30})
    exe.run(fluid.default_startup_program())


def test_preflight_normal_run_unaffected():
    """A normal monitored run: capture happens (auto mode), but CPU
    devices report no capacity and no override is set — no warning,
    steps run normally."""
    monitor.enable()
    loss = _build_mlp()
    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        _run_steps(loss, steps=2)
    assert program_profile.profiles()          # capture did run
    assert not [w for w in ws if "HBM preflight" in str(w.message)]


# ---------------------------------------------------------------------------
# cost_analysis served from the registry
# ---------------------------------------------------------------------------

def test_cost_analysis_free_on_warm_program():
    from jax._src import test_util as jtu

    monitor.enable()
    loss = _build_mlp()
    exe = _run_steps(loss, steps=2)
    feed = {"x": np.zeros((8, 4), "float32")}
    with jtu.count_jit_and_pmap_lowerings() as n:
        ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    assert n[0] == 0, "warm cost_analysis paid a lowering"
    assert ca["flops"] > 0 and ca["bytes accessed"] > 0
    # compile_if_missing=False on a never-analyzed signature -> None
    cold = {"x": np.zeros((16, 4), "float32")}     # unseen batch size
    assert exe.cost_analysis(feed=cold, fetch_list=[loss],
                             compile_if_missing=False) is None


def test_cost_analysis_distinguishes_fetch_sets():
    """The profile registry keys on the fetch set too: asking for a
    smaller fetch set must not serve the full train-step module's
    numbers (different fetch lists lower to different XLA modules)."""
    monitor.enable()
    x = fluid.layers.data("x", shape=[4])
    h = fluid.layers.fc(x, size=8, act="relu")
    loss = fluid.layers.mean(fluid.layers.fc(h, size=3))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.zeros((8, 4), "float32")}
    exe.run(feed=feed, fetch_list=[loss])      # captures the train module
    train_ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    # fwd-only fetch of the hidden layer: not served from the train
    # profile (registry miss on the fetch set), and cheaper than the
    # fwd+bwd+update module
    fwd_ca = exe.cost_analysis(feed=feed, fetch_list=[h])
    assert fwd_ca["flops"] < train_ca["flops"]
    # and the fwd-only analysis is now itself registry-served
    assert exe.cost_analysis(feed=feed, fetch_list=[h],
                             compile_if_missing=False) is not None


def test_cost_analysis_fallback_seeds_registry():
    """A never-run program pays one explicit compile, after which the
    registry serves repeats for free."""
    from jax._src import test_util as jtu

    fluid.set_flags({"FLAGS_preflight_oom": "off"})    # no auto-capture
    loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.zeros((4, 4), "float32")}
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    assert ca["flops"] > 0
    with jtu.count_jit_and_pmap_lowerings() as n:
        ca2 = exe.cost_analysis(feed=feed, fetch_list=[loss])
    assert n[0] == 0 and ca2["flops"] == ca["flops"]


# ---------------------------------------------------------------------------
# correlation ids: JSONL <-> chrome trace <-> /metrics
# ---------------------------------------------------------------------------

def test_run_id_and_fingerprint_correlate_all_sinks(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    profiler.reset_profiler()
    profiler.start_profiler("CPU")
    _run_steps(loss, steps=2)
    profiler.stop_profiler(profile_path=None)
    trace_path = str(tmp_path / "trace.json")
    profiler.export_chrome_tracing(trace_path)

    fp = compile_cache.program_fingerprint(fluid.default_main_program())
    rid = monitor.run_id()

    # JSONL: step records carry run_id + fingerprint; profile event too
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".jsonl")]
    records = [json.loads(ln)
               for ln in open(os.path.join(str(tmp_path), files[0]))]
    steps = [r for r in records if r.get("event") == "step_stats"
             and r.get("fingerprint") == fp]
    assert len(steps) == 2
    assert all(r["run_id"] == rid for r in steps)
    profs = [r for r in records if r.get("event") == "program_profile"
             and r.get("fingerprint") == fp]
    assert profs and profs[0]["run_id"] == rid
    assert profs[0]["flops"] > 0

    # chrome trace: top-level metadata + process metadata + span args
    trace = json.load(open(trace_path))
    assert trace["metadata"]["run_id"] == rid
    procs = [e for e in trace["traceEvents"]
             if e.get("name") == "process_name"]
    assert procs and procs[0]["args"]["run_id"] == rid
    tagged = [e for e in trace["traceEvents"]
              if e.get("args", {}).get("fingerprint") == fp[:12]]
    assert tagged, "no span tagged with the program fingerprint"
    assert all(e["args"]["run_id"] == rid for e in tagged)
    assert {e["name"] for e in tagged} <= {"executor/compile",
                                           "executor/dispatch"}

    # /metrics: run_id comment + the per-program family
    text = monitor.expose_text()
    assert text.startswith("# run_id %s\n" % rid)
    assert ("program_%s_steps_total" % fp[:12]) in text


# ---------------------------------------------------------------------------
# program_report CLI
# ---------------------------------------------------------------------------

def test_program_report_cli_from_jsonl(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    _run_steps(loss, steps=3)
    fp = compile_cache.program_fingerprint(fluid.default_main_program())
    # live-registry view, read before disable() resets the accounting
    live = {r["fingerprint"]: r for r in program_profile.report_rows()}
    monitor.disable()

    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "program_report.py"),
         str(tmp_path), "--json", "--run_id", monitor.run_id()],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120, check=True).stdout
    # stable --json schema: {programs: [...], devices: {...}} (devices
    # empty on backends with no memory stats)
    payload = json.loads(out)
    rows = {r["fingerprint"]: r for r in payload["programs"]}
    assert isinstance(payload["devices"], dict)
    assert rows[fp]["steps"] == 3
    assert rows[fp]["flops_per_step"] > 0
    assert rows[fp]["peak_hbm_bytes"] > 0
    assert 0 < rows[fp]["wall_share"] <= 1.0
    # the offline JSONL replay agrees with the live registry's table
    from tools.program_report import load_records, rows_from_records
    replay = rows_from_records(load_records(str(tmp_path)),
                               run_id=monitor.run_id())
    row = [ln for ln in program_profile.render_table(replay).splitlines()
           if ln.startswith(fp[:12])]
    assert row and row[0].split()[2] == "3"     # steps column
    assert live[fp]["steps"] == rows[fp]["steps"]


# ---------------------------------------------------------------------------
# watchdog names the suspect program
# ---------------------------------------------------------------------------

def test_watchdog_stall_diag_names_last_program(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    loss = _build_mlp()
    _run_steps(loss, steps=2)
    fp = compile_cache.program_fingerprint(fluid.default_main_program())
    # arm the short stall window only after the (slow, cold-compiling)
    # steps, so the first firing reports the completed run's state
    fluid.set_flags({"FLAGS_monitor_stall_seconds": 0.2})
    deadline = time.monotonic() + 2.0
    stalls = monitor.registry().counter("monitor/watchdog_stalls")
    while stalls.value == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert stalls.value >= 1
    files = [f for f in os.listdir(str(tmp_path)) if f.endswith(".jsonl")]
    records = [json.loads(ln)
               for ln in open(os.path.join(str(tmp_path), files[0]))]
    dumps = [r for r in records if r.get("event") == "watchdog_stall"]
    assert dumps
    suspect = dumps[0].get("last_program")
    assert suspect is not None
    assert suspect["fingerprint"] == fp[:12]
    assert suspect["steps"] == 2
    assert suspect["flops"] > 0
    assert suspect["peak_hbm_bytes"] > 0


# ---------------------------------------------------------------------------
# ParallelExecutor: capture + per-device gauges
# ---------------------------------------------------------------------------

def test_parallel_executor_capture_and_device_gauges():
    import jax

    monitor.enable()
    fluid.default_main_program().random_seed = 3
    img = fluid.layers.data("img", shape=[16])
    h = fluid.layers.fc(img, size=8, act="relu")
    loss = fluid.layers.mean(h)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name)
    x = np.random.RandomState(0).rand(16, 16).astype("float32")
    for _ in range(2):
        pe.run(feed={"img": x}, fetch_list=[loss.name])

    fp = compile_cache.program_fingerprint(fluid.default_main_program())
    prof = program_profile.get(fp, kind="parallel_executor")
    assert prof is not None
    assert prof.flops > 0
    acct = program_profile.accounting()[fp]
    assert acct["steps"] == 2 and acct["kind"] == "parallel_executor"
    # one steps_total counter per local mesh device
    reg = monitor.registry()
    dev_counters = [n for n in reg.names()
                    if n.startswith("device/") and n.endswith("steps_total")]
    assert len(dev_counters) == len(jax.local_devices())
    assert all(reg.get(n).value == 2 for n in dev_counters)
