"""Multiprocess elastic-resume drill (ISSUE 13 acceptance): SIGKILL one
member of a 2-host cluster mid-run; the survivor detects the lease
expiry at the step barrier, reshapes to a single-host mesh, restores
the last committed per-host sharded checkpoint, and finishes with a
loss trajectory in the float-noise parity band of an uninterrupted
smaller-mesh run.  The harness (and all assertions) live in
``cluster_runner.supervise``; ``tools/run_ci.sh`` step 13 drives the
same supervisor from the CLI."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow   # 3 subprocess worlds, ~30-60s
def test_kill_one_member_survivor_reshapes_and_resumes(tmp_path):
    from cluster_runner import supervise

    evidence = supervise(str(tmp_path))
    # supervise() asserts the headline criteria; pin the evidence shape
    # so the drill cannot silently weaken
    assert 0 < evidence["resumed_from"] < evidence["kill_step"]
    assert evidence["max_rel_loss_dev"] <= evidence["parity_rtol"]
    assert len(evidence["per_writer_bytes"]) == 2
    assert evidence["max_writer_fraction"] < 0.7
