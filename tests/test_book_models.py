"""Book-style end-to-end configs (reference tests/book/test_*.py: build
the real model, train a few iterations, assert the loss drops) for the
configs not covered elsewhere: fit_a_line, word2vec,
recommender_system, understand_sentiment (conv).  Data is synthetic
(the book tests' assertion pattern, offline)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _train(loss, feeds, steps, lr=0.01, opt="sgd", seed=1):
    fluid.default_startup_program().random_seed = seed
    optimizer = {"sgd": fluid.optimizer.SGD,
                 "adam": fluid.optimizer.Adam}[opt](learning_rate=lr)
    optimizer.minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = []
        for i in range(steps):
            (lv,) = exe.run(feed=feeds[i % len(feeds)],
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    return losses


def _sentiment_batch(seed, n, t, vocab):
    """Synthetic sentiment task shared by the understand_sentiment
    variants: label = whether token 7 appears within the valid prefix.
    Returns the feed dict for a words/label program."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, vocab, (n, t)).astype("int64")
    lens = rng.randint(4, t + 1, (n,)).astype("int32")
    lbl = np.array([1 if 7 in row[:l] else 0
                    for row, l in zip(ids, lens)], "int64")[:, None]
    return {"words": ids[:, :, None], "words@LEN": lens, "label": lbl}


def test_fit_a_line():
    """Linear regression (book/test_fit_a_line.py) on uci_housing-shaped
    synthetic data."""
    from paddle_tpu.dataset import synthetic

    samples = list(synthetic.regression(n=128, dim=13, seed=0)())
    xs = np.stack([s[0] for s in samples]).astype("float32")
    ys = np.stack([np.ravel(s[1]) for s in samples]).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[13])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(x, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, y)))
        losses = _train(loss, [{"x": xs, "y": ys}], steps=60, lr=0.05)
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_word2vec_ngram():
    """N-gram LM (book/test_word2vec.py): 4 context embeddings concat ->
    fc -> softmax over the vocab."""
    vocab, emb, n = 40, 16, 5
    rng = np.random.RandomState(2)
    # learnable pattern: next word = (sum of context) % vocab
    ctx = rng.randint(0, vocab, (256, n - 1)).astype("int64")
    nxt = (ctx.sum(1) % vocab).astype("int64")[:, None]
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        words = [fluid.layers.data("w%d" % i, shape=[1], dtype="int64")
                 for i in range(n - 1)]
        label = fluid.layers.data("nextw", shape=[1], dtype="int64")
        embs = [fluid.layers.embedding(
                    w, size=[vocab, emb],
                    param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words]
        concat = fluid.layers.concat(embs, axis=-1)
        concat = fluid.layers.reshape(concat, shape=[-1, emb * (n - 1)])
        hidden = fluid.layers.fc(concat, size=64, act="relu")
        pred = fluid.layers.fc(hidden, size=vocab, act=None)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label))
        feed = {"nextw": nxt}
        for i in range(n - 1):
            feed["w%d" % i] = ctx[:, i:i + 1]
        losses = _train(loss, [feed], steps=80, lr=5e-3, opt="adam")
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_recommender_system():
    """Two-tower rating model (book/test_recommender_system.py): user
    and item embeddings -> cos_sim -> scaled square loss."""
    n_users, n_items, emb = 30, 50, 8
    rng = np.random.RandomState(3)
    u = rng.randint(0, n_users, (256, 1)).astype("int64")
    it = rng.randint(0, n_items, (256, 1)).astype("int64")
    # synthetic preference: rating from hashed pair, in [0, 5]
    r = (((u * 13 + it * 7) % 11) / 2.0).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        usr = fluid.layers.data("usr", shape=[1], dtype="int64")
        mov = fluid.layers.data("mov", shape=[1], dtype="int64")
        rating = fluid.layers.data("rating", shape=[1])
        usr_emb = fluid.layers.reshape(
            fluid.layers.embedding(usr, size=[n_users, emb]),
            shape=[-1, emb])
        mov_emb = fluid.layers.reshape(
            fluid.layers.embedding(mov, size=[n_items, emb]),
            shape=[-1, emb])
        usr_feat = fluid.layers.fc(usr_emb, size=32, act="relu")
        mov_feat = fluid.layers.fc(mov_emb, size=32, act="relu")
        sim = fluid.layers.cos_sim(usr_feat, mov_feat)
        pred = fluid.layers.scale(sim, scale=5.0)
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, rating)))
        losses = _train(loss, [{"usr": u, "mov": it, "rating": r}],
                        steps=60, lr=1e-2, opt="adam")
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_understand_sentiment_conv():
    """Text classification via sequence_conv+pool
    (book/test_understand_sentiment.py convolution_net)."""
    vocab, emb, t = 60, 16, 12
    feed = _sentiment_batch(seed=4, n=128, t=t, vocab=vocab)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        data = fluid.layers.data("words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        embedded = fluid.layers.embedding(data, size=[vocab, emb])
        conv = fluid.nets.sequence_conv_pool(
            input=embedded, num_filters=32, filter_size=3,
            act="tanh", pool_type="max")
        pred = fluid.layers.fc(conv, size=2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        losses = _train(loss, [feed], steps=60, lr=5e-3, opt="adam")
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_memory_optimize_reports_footprint():
    """memory_optimize is deliberately a no-op rewrite on TPU (XLA owns
    buffer reuse) but must report the recyclable temp footprint."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[64])
        h = fluid.layers.fc(x, size=128, act="relu")
        fluid.layers.fc(h, size=8, act="softmax")
        n = fluid.memory_optimize(fluid.default_main_program())
        assert n > 0
        assert fluid.release_memory(fluid.default_main_program()) == 0


@pytest.mark.slow   # ~36s; resnet train coverage also in test_models (tier-1 budget)
def test_image_classification_cifar_resnet():
    """Cifar image classification with the book's resnet_cifar10
    (book/test_image_classification.py net_type='resnet')."""
    from paddle_tpu.models.resnet import resnet_cifar10
    rng = np.random.RandomState(7)
    # separable synthetic cifar: class = brightest channel
    imgs = rng.rand(32, 3, 32, 32).astype("float32") * 0.2
    lbls = rng.randint(0, 3, (32, 1)).astype("int64")
    for i, l in enumerate(lbls[:, 0]):
        imgs[i, l] += 0.8
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("pixel", shape=[3, 32, 32])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = resnet_cifar10(img, class_dim=3, depth=20)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        losses = _train(loss, [{"pixel": imgs, "label": lbls}],
                        steps=12, lr=3e-3, opt="adam")
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


@pytest.mark.slow   # ~46s; vgg build/run coverage also in test_models (tier-1 budget)
def test_image_classification_cifar_vgg():
    """Cifar image classification with the book's VGG
    (book/test_image_classification.py net_type='vgg')."""
    from paddle_tpu.models.vgg import vgg16_bn_drop
    rng = np.random.RandomState(8)
    imgs = rng.rand(16, 3, 32, 32).astype("float32") * 0.2
    lbls = rng.randint(0, 3, (16, 1)).astype("int64")
    for i, l in enumerate(lbls[:, 0]):
        imgs[i, l] += 0.8
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("pixel", shape=[3, 32, 32])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        feats = vgg16_bn_drop(img, class_dim=3)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(feats, label))
        losses = _train(loss, [{"pixel": imgs, "label": lbls}],
                        steps=6, lr=1e-3, opt="adam")
    assert np.isfinite(losses).all()


def test_understand_sentiment_stacked_lstm():
    """Stacked bidirectional-ish LSTM classifier
    (book/notest_understand_sentiment.py stacked_lstm_net)."""
    vocab, emb, hid, t = 60, 16, 24, 12
    feed = _sentiment_batch(seed=9, n=96, t=t, vocab=vocab)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        data = fluid.layers.data("words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        embedded = fluid.layers.embedding(data, size=[vocab, emb])
        # the book stacks fc+lstm pairs, pooling the last layer
        fc1 = fluid.layers.fc(embedded, size=hid * 4,
                              num_flatten_dims=2)
        lstm1, _c = fluid.layers.dynamic_lstm(fc1, size=hid * 4)
        fc2 = fluid.layers.fc(lstm1, size=hid * 4, num_flatten_dims=2)
        lstm2, _c2 = fluid.layers.dynamic_lstm(fc2, size=hid * 4,
                                               is_reverse=True)
        pooled = fluid.layers.concat(
            [fluid.layers.sequence_pool(lstm1, "max"),
             fluid.layers.sequence_pool(lstm2, "max")], axis=1)
        pred = fluid.layers.fc(pooled, size=2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        losses = _train(loss, [feed], steps=40, lr=5e-3, opt="adam")
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_understand_sentiment_dynamic_rnn():
    """DynamicRNN-cell classifier (the book's dyn_rnn_lstm variant:
    per-step lstm_unit inside a DynamicRNN block)."""
    vocab, emb, hid, t = 60, 16, 24, 10
    feed = _sentiment_batch(seed=10, n=96, t=t, vocab=vocab)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        data = fluid.layers.data("words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        sent_emb = fluid.layers.embedding(data, size=[vocab, emb])
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sent_emb)
            prev_h = drnn.memory(shape=[hid], value=0.0)
            prev_c = drnn.memory(shape=[hid], value=0.0)
            h, c = fluid.layers.lstm_unit(word, prev_h, prev_c)
            drnn.update_memory(prev_h, h)
            drnn.update_memory(prev_c, c)
            drnn.output(h)
        last = fluid.layers.sequence_pool(drnn(), "last")
        pred = fluid.layers.fc(last, size=2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        losses = _train(loss, [feed], steps=40, lr=5e-3, opt="adam")
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
