"""Small fluid-parity modules: lod_tensor helpers (reference
lod_tensor.py:23,92 over the padded+@LEN design), average.py
WeightedAverage, net_drawer.draw_graph."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_create_lod_tensor_from_lists_and_feed():
    seqs = [[1, 2], [3, 4, 5]]
    t = fluid.create_lod_tensor(seqs, [[2, 3]], fluid.CPUPlace())
    assert t.shape() == (2, 3, 1)
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.has_valid_recursive_sequence_lengths()
    np.testing.assert_array_equal(t.data[0, :2, 0], [1, 2])
    np.testing.assert_array_equal(t.data[1, :, 0], [3, 4, 5])
    assert t.data[0, 2, 0] == 0  # padding

    # feeds a lod_level=1 data var end-to-end
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        words = fluid.layers.data("words", shape=[1], dtype="int64",
                                  lod_level=1)
        emb = fluid.layers.embedding(words, size=[10, 4])
        pooled = fluid.layers.sequence_pool(emb, "average")
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            out, = exe.run(feed=t.as_feed("words"),
                           fetch_list=[pooled.name])
    assert np.asarray(out).shape == (2, 4)

    # mismatched lengths must raise, as the reference asserts
    with pytest.raises(AssertionError):
        fluid.create_lod_tensor(seqs, [[3, 2]], fluid.CPUPlace())


def test_create_lod_tensor_from_flat_array_and_roundtrip():
    flat = np.arange(10, dtype="float32").reshape(5, 2)
    t = fluid.create_lod_tensor(flat, [[2, 3]], fluid.CPUPlace())
    assert t.shape() == (2, 3, 2)
    # re-wrapping an existing PaddedSequence round-trips
    t2 = fluid.create_lod_tensor(t, [[2, 3]], fluid.CPUPlace())
    np.testing.assert_array_equal(t.data, t2.data)
    with pytest.raises(NotImplementedError):
        fluid.create_lod_tensor(flat, [[1], [2, 3]], fluid.CPUPlace())


def test_create_lod_tensor_empty_sequence():
    """Zero-length sequences pad to all-zero rows, both input forms."""
    t = fluid.create_lod_tensor([[1, 2], []], [[2, 0]], fluid.CPUPlace())
    assert t.shape() == (2, 2, 1)
    np.testing.assert_array_equal(t.seq_lens, [2, 0])
    np.testing.assert_array_equal(t.data[1], np.zeros((2, 1)))
    flat = np.arange(4, dtype="float32").reshape(2, 2)
    t2 = fluid.create_lod_tensor(flat, [[2, 0]], fluid.CPUPlace())
    np.testing.assert_array_equal(t2.seq_lens, [2, 0])


def test_create_random_int_lodtensor():
    t = fluid.create_random_int_lodtensor([[3, 1, 2]], [1],
                                          fluid.CPUPlace(), 0, 7)
    assert t.shape() == (3, 3, 1)
    assert t.data.dtype == np.int64
    assert t.data.min() >= 0 and t.data.max() <= 7
    np.testing.assert_array_equal(t.seq_lens, [3, 1, 2])


def test_weighted_average():
    with pytest.warns(Warning):
        avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=2)
    assert abs(avg.eval() - 10.0 / 3) < 1e-9
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()
    with pytest.raises(ValueError):
        avg.add(value="x", weight=1)


def test_net_drawer(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        fluid.layers.fc(x, size=2)
    out = fluid.net_drawer.draw_graph(
        startup, main, path=str(tmp_path / "g.dot"),
        startup_path=str(tmp_path / "s.dot"))
    dot = open(out).read()
    assert "digraph" in dot and "mul" in dot
    assert (tmp_path / "s.dot").exists()


def test_default_scope_funcs_stack():
    """default_scope_funcs: thread-local scope stack (reference
    default_scope_funcs.py:1)."""
    from paddle_tpu import default_scope_funcs as dsf

    root = dsf.get_cur_scope()
    dsf.var("x")
    assert dsf.find_var("x") is not None

    inner = dsf.enter_local_scope()
    assert dsf.get_cur_scope() is inner
    dsf.var("y")
    assert dsf.find_var("x") is not None  # parent lookup
    dsf.leave_local_scope()
    assert dsf.get_cur_scope() is root
    assert not root.has_var("y")

    seen = []
    dsf.scoped_function(lambda: seen.append(dsf.var("tmp")))
    assert seen and dsf.get_cur_scope() is root
    with pytest.raises(RuntimeError):
        dsf.leave_local_scope()


def test_annotations_deprecated():
    from paddle_tpu.annotations import deprecated

    @deprecated(since="0.1", instead="new_fn")
    def old_fn(a):
        return a + 1

    import warnings
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn(1) == 2
    assert any("deprecated since 0.1" in str(x.message) for x in w)
    assert "new_fn" in old_fn.__doc__


def test_op_factory_builds_runnable_spec():
    """op.Operator builds an op-spec dict the Block accepts (reference
    op.py OperatorFactory -> OpDesc)."""
    from paddle_tpu.op import Operator, get_all_op_protos

    protos = get_all_op_protos()
    assert len(protos) > 200 and all(p.type for p in protos)

    spec = Operator("scale", X="x", Out="y", scale=3.0, bias=1.0)
    assert spec["inputs"]["X"] == ["x"] and spec["attrs"]["scale"] == 3.0

    with pytest.raises(ValueError):
        Operator("scale", "positional")
    with pytest.raises(KeyError):
        Operator("not_an_op")

    # the spec drives Block.append_op end-to-end
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        y = main.current_block().create_var(name="y", dtype="float32")
        main.current_block().append_op(**Operator(
            "scale", X=x.name, Out="y", scale=3.0, bias=1.0))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            out, = exe.run(feed={"x": np.ones((2, 3), "float32")},
                           fetch_list=["y"])
    np.testing.assert_allclose(out, 4.0 * np.ones((2, 3)), rtol=1e-6)
