"""Book-style RNN encoder-decoder e2e (reference
``tests/book/test_rnn_encoder_decoder.py`` / ``test_machine_translation.py``
capability): train a seq2seq model on a copy task with DynamicRNN, then
generate with a While-loop decoder through the beam_search ops and check
the decoded output reproduces the source."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import program_guard
from paddle_tpu.param_attr import ParamAttr

V, D, H, TMAX = 8, 16, 64, 4
BOS, EOS = 1, 0


def _encoder(src):
    emb = fluid.layers.embedding(src, size=[V, D],
                                 param_attr=ParamAttr(name="src_emb_w"))
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(emb)
        h_pre = drnn.memory(shape=[H], value=0.0)
        h = fluid.layers.fc(fluid.layers.concat([x_t, h_pre], axis=1),
                            size=H, act="tanh",
                            param_attr=ParamAttr(name="enc_fc_w"),
                            bias_attr=ParamAttr(name="enc_fc_b"))
        drnn.update_memory(h_pre, h)
        drnn.output(h)
    enc = drnn()
    return fluid.layers.sequence_pool(enc, "last")    # [B, H]


def _dec_cell(emb_t, h_pre):
    return fluid.layers.fc(fluid.layers.concat([emb_t, h_pre], axis=1),
                           size=H, act="tanh",
                           param_attr=ParamAttr(name="dec_fc_w"),
                           bias_attr=ParamAttr(name="dec_fc_b"))


def _dec_logits(h):
    return fluid.layers.fc(h, size=V, act=None,
                           param_attr=ParamAttr(name="out_fc_w"),
                           bias_attr=ParamAttr(name="out_fc_b"))


def _build_train():
    src = fluid.layers.data("src", shape=[1], dtype="int64", lod_level=1)
    tgt = fluid.layers.data("tgt", shape=[1], dtype="int64", lod_level=1)
    lbl = fluid.layers.data("lbl", shape=[1], dtype="int64", lod_level=1)
    enc_last = _encoder(src)

    temb = fluid.layers.embedding(tgt, size=[V, D],
                                  param_attr=ParamAttr(name="tgt_emb_w"))
    drnn = fluid.layers.DynamicRNN()
    with drnn.block():
        e_t = drnn.step_input(temb)
        h_pre = drnn.memory(init=enc_last)
        h = _dec_cell(e_t, h_pre)
        drnn.update_memory(h_pre, h)
        drnn.output(_dec_logits(h))
    logits = drnn()                                     # [B, T, V]

    lbl3 = lbl
    cost = fluid.layers.softmax_with_cross_entropy(logits, lbl3)
    tgt_len = tgt.block._find_var_recursive(tgt._seq_len_name)
    mask = fluid.layers.padding_mask(tgt_len, logits)   # [B, T]
    masked = fluid.layers.elementwise_mul(
        cost, fluid.layers.unsqueeze(mask, axes=[2]))
    loss = fluid.layers.elementwise_div(
        fluid.layers.reduce_sum(masked), fluid.layers.reduce_sum(mask))
    return loss


def _build_decode(beam_size):
    """While-loop generation: at each step feed the previous ids, run the
    shared decoder cell, expand with beam_search, and record the chosen
    tokens + backpointers for beam_search_decode."""
    k = beam_size
    src = fluid.layers.data("src", shape=[1], dtype="int64", lod_level=1)
    enc_last = _encoder(src)                            # [B, H]

    def bsl(shape, value, dtype, out_dim=0):
        return fluid.layers.fill_constant_batch_size_like(
            input=enc_last, shape=shape, dtype=dtype, value=value,
            input_dim_idx=0, output_dim_idx=out_dim)

    # beam state: ids/scores [B, K]; hidden [B, K*H] flattened so the
    # while carry keeps rank-2 vars
    cur_ids = bsl([-1, k], BOS, "int64")
    init_scores = np.zeros((1, k), "float32")
    init_scores[0, 1:] = -1e9                       # expand from beam 0 only
    score_row = fluid.layers.assign(init_scores)
    cur_scores = fluid.layers.elementwise_add(
        bsl([-1, k], 0.0, "float32"), score_row)    # [B, K] broadcast row
    h = fluid.layers.expand(
        fluid.layers.unsqueeze(enc_last, axes=[1]), expand_times=[1, k, 1])
    h = fluid.layers.reshape(h, shape=[0, k * H])   # [B, K*H]

    ids_arr = bsl([TMAX, -1, k], 0, "int64", out_dim=1)
    par_arr = bsl([TMAX, -1, k], 0, "int64", out_dim=1)

    i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=TMAX)
    cond = fluid.layers.less_than(i, n)
    w = fluid.layers.While(cond)
    with w.block():
        flat_ids = fluid.layers.reshape(cur_ids, shape=[-1, 1])
        emb = fluid.layers.embedding(
            flat_ids, size=[V, D], param_attr=ParamAttr(name="tgt_emb_w"))
        h_flat = fluid.layers.reshape(h, shape=[-1, H])     # [B*K, H]
        h_new = _dec_cell(emb, h_flat)                      # [B*K, H]
        logits = _dec_logits(h_new)                         # [B*K, V]
        logp = fluid.layers.log(fluid.layers.softmax(logits))
        scores3 = fluid.layers.reshape(logp, shape=[-1, k, V])
        sel_ids, sel_scores, parent = fluid.layers.beam_search(
            cur_ids, cur_scores, scores3, beam_size=k, end_id=EOS)
        # reorder hidden by backpointer: one_hot(parent) @ h
        onehot = fluid.layers.one_hot(
            fluid.layers.unsqueeze(parent, axes=[2]), depth=k)  # [B,K,K]
        h3 = fluid.layers.reshape(h_new, shape=[-1, k, H])
        h_sel = fluid.layers.matmul(onehot, h3)                 # [B,K,H]
        fluid.layers.assign(
            fluid.layers.reshape(h_sel, shape=[0, k * H]), output=h)
        fluid.layers.assign(sel_ids, output=cur_ids)
        fluid.layers.assign(sel_scores, output=cur_scores)
        fluid.layers.assign(
            fluid.layers.array_write(sel_ids, i, array=ids_arr),
            output=ids_arr)
        fluid.layers.assign(
            fluid.layers.array_write(parent, i, array=par_arr),
            output=par_arr)
        fluid.layers.increment(i, value=1)
        fluid.layers.less_than(i, n, cond=cond)

    sentences, final_scores = fluid.layers.beam_search_decode(
        ids_arr, par_arr, cur_scores, beam_size=k, end_id=EOS)
    return sentences, final_scores


def _copy_batch(rng, b):
    rows = []
    for _ in range(b):
        ln = rng.randint(2, TMAX + 1)
        seq = rng.randint(2, V, (ln,)).astype("int64")
        tgt = np.concatenate([[BOS], seq[:-1]]).astype("int64")
        rows.append((seq, tgt, seq))
    return rows


def test_rnn_encoder_decoder_train_and_beam_decode():
    fluid.default_main_program().random_seed = 42
    fluid.default_startup_program().random_seed = 42

    loss = _build_train()
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    feeder = fluid.DataFeeder(
        feed_list=[
            fluid.default_main_program().global_block().var("src"),
            fluid.default_main_program().global_block().var("tgt"),
            fluid.default_main_program().global_block().var("lbl"),
        ], pad_to=TMAX)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(600):
        feed = feeder.feed(_copy_batch(rng, 16))
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0] * 0.4, (losses[0], losses[-1])

    # ---- generation with the SAME params (shared scope, fixed names) ----
    decode_prog, decode_startup = fluid.Program(), fluid.Program()
    with program_guard(decode_prog, decode_startup):
        sentences, scores = _build_decode(beam_size=3)

    batch = _copy_batch(rng, 8)
    src_pad = np.zeros((8, TMAX, 1), "int64")
    src_len = np.zeros((8,), "int32")
    for bi, (s, _, _) in enumerate(batch):
        src_pad[bi, :len(s), 0] = s
        src_len[bi] = len(s)

    sv, scv = exe.run(decode_prog,
                      feed={"src": src_pad, "src@LEN": src_len},
                      fetch_list=[sentences, scores])
    sv = np.asarray(sv)          # [B, K, TMAX]
    assert sv.shape == (8, 3, TMAX)

    # top beam should reproduce the source on a well-trained copy model
    correct = total = 0
    for bi, (s, _, _) in enumerate(batch):
        got = sv[bi, 0, :len(s)]
        correct += int((got == s).sum())
        total += len(s)
    assert correct / total > 0.7, (correct, total, sv[:2, 0])
