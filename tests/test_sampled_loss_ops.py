"""nce, hierarchical_sigmoid, bilinear_tensor_product, fake_quantize,
precision_recall tests (numpy oracles + training smoke)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_nce_cost_formula_and_training():
    rng = np.random.RandomState(0)
    b, d, c = 16, 8, 20
    xs = rng.rand(b, d).astype("float32")
    ys = rng.randint(0, c, (b, 1)).astype("int64")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 1
        fluid.default_main_program().random_seed = 1
        x = fluid.layers.data("x", shape=[d])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        cost = fluid.layers.nce(x, label, num_total_classes=c,
                                num_neg_samples=5)
        avg = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(avg)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [float(exe.run(feed={"x": xs, "label": ys},
                                    fetch_list=[avg])[0].ravel()[0])
                      for _ in range(25)]
    assert losses[-1] < losses[0] * 0.8
    assert all(np.isfinite(losses))


def test_nce_backward_uses_same_samples_as_forward():
    """The weight gradient must be nonzero ONLY on rows the forward
    sampled (generic auto-vjp recompute must re-draw identical
    negatives via the forward op's PRNG index)."""
    rng = np.random.RandomState(7)
    b, d, c = 4, 5, 30
    xs = rng.rand(b, d).astype("float32")
    ys = rng.randint(0, c, (b, 1)).astype("int64")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 2
        fluid.default_main_program().random_seed = 2
        x = fluid.layers.data("x", shape=[d])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        cost = fluid.layers.nce(x, label, num_total_classes=c,
                                num_neg_samples=3, bias_attr=False,
                                param_attr=fluid.ParamAttr(name="nce_w"))
        avg = fluid.layers.mean(cost)
        prog = fluid.default_main_program()
        grads = fluid.calc_gradient(avg, [prog.global_block().var("nce_w")])
        sample_labels = [op.outputs["SampleLabels"][0]
                         for op in prog.global_block().ops
                         if op.type == "nce"][0]
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            samples, gw = exe.run(feed={"x": xs, "label": ys},
                                  fetch_list=[sample_labels, grads[0]])
    sampled_rows = set(np.asarray(samples).ravel().tolist())
    grad_rows = set(np.nonzero(np.abs(gw).sum(1) > 1e-12)[0].tolist())
    assert grad_rows <= sampled_rows, (grad_rows, sampled_rows)
    # bias_attr=False must not create a bias parameter
    pnames = [p.name for p in prog.global_block().all_parameters()]
    assert pnames == ["nce_w"], pnames


def _py_hsigmoid(x, w, bias, label, num_classes):
    """Oracle from matrix_bit_code.h SimpleCode: c = label + num_classes,
    len = floor(log2(c)); node(bit) = (c >> (bit+1)) - 1, target = bit-th
    LSB of c."""
    out = np.zeros((x.shape[0], 1), "float64")
    for i in range(x.shape[0]):
        c = int(label[i]) + num_classes
        length = int(np.floor(np.log2(c)))
        for bit in range(length):
            node = (c >> (bit + 1)) - 1
            target = (c >> bit) & 1
            pre = x[i] @ w[node] + (bias[node, 0] if bias is not None
                                    else 0.0)
            out[i, 0] += np.log1p(np.exp(pre)) - target * pre
    return out


def test_hsigmoid_matches_bitcode_oracle():
    rng = np.random.RandomState(2)
    b, d, c = 6, 5, 7
    xs = rng.randn(b, d).astype("float32")
    ys = rng.randint(0, c, (b, 1)).astype("int64")
    wv = rng.randn(c - 1, d).astype("float32")
    bv = rng.randn(c - 1, 1).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[d])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        out = fluid.layers.hsigmoid(
            x, label, num_classes=c,
            param_attr=fluid.ParamAttr(name="hs_w"),
            bias_attr=fluid.ParamAttr(name="hs_b"))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            scope.set_var("hs_w", wv)
            scope.set_var("hs_b", bv)
            exe = fluid.Executor(fluid.CPUPlace())
            (ov,) = exe.run(feed={"x": xs, "label": ys}, fetch_list=[out])
    want = _py_hsigmoid(xs, wv, bv, ys[:, 0], c)
    np.testing.assert_allclose(ov, want, rtol=2e-4)


def test_hsigmoid_trains():
    rng = np.random.RandomState(3)
    b, d, c = 32, 6, 8
    xs = rng.randn(b, d).astype("float32")
    ys = (xs[:, :3].argmax(1)).astype("int64")[:, None]
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 4
        x = fluid.layers.data("x", shape=[d])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        cost = fluid.layers.mean(fluid.layers.hsigmoid(x, label, c))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(cost)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            losses = [float(exe.run(feed={"x": xs, "label": ys},
                                    fetch_list=[cost])[0].ravel()[0])
                      for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5


def test_bilinear_tensor_product_oracle():
    rng = np.random.RandomState(4)
    b, dx, dy, k = 3, 4, 5, 2
    xs = rng.randn(b, dx).astype("float32")
    ys = rng.randn(b, dy).astype("float32")
    wv = rng.randn(k, dx, dy).astype("float32")
    bv = rng.randn(1, k).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[dx])
        y = fluid.layers.data("y", shape=[dy])
        out = fluid.layers.bilinear_tensor_product(
            x, y, size=k, param_attr=fluid.ParamAttr(name="btp_w"),
            bias_attr=fluid.ParamAttr(name="btp_b"))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            scope.set_var("btp_w", wv)
            scope.set_var("btp_b", bv)
            exe = fluid.Executor(fluid.CPUPlace())
            (ov,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[out])
    want = np.einsum("bi,kij,bj->bk", xs, wv, ys) + bv
    np.testing.assert_allclose(ov, want, rtol=1e-4)


def test_fake_quantize_dequantize_roundtrip_and_ste_grad():
    rng = np.random.RandomState(5)
    xs = (rng.randn(4, 6) * 3).astype("float32")
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[6])
        x.stop_gradient = False
        helper_out = []
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("fake_quantize_abs_max")
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="fake_quantize_abs_max",
                         inputs={"X": [x]},
                         outputs={"Out": [out], "OutScale": [scale]},
                         attrs={"bit_length": 8})
        loss = fluid.layers.reduce_sum(out)
        grads = fluid.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        ov, sv, gv = exe.run(feed={"x": xs},
                             fetch_list=[out, scale, grads[0]])
    s = np.abs(xs).max()
    want = np.round(xs / s * 127) * s / 127
    np.testing.assert_allclose(ov, want, rtol=1e-5)
    assert sv[0] == pytest.approx(s, rel=1e-6)
    # straight-through estimator: grad of sum(out) w.r.t. x is all-ones
    np.testing.assert_allclose(gv, np.ones_like(xs))
    # max quantization error is scale/range/2
    assert np.abs(ov - xs).max() <= s / 127 / 2 + 1e-6


def test_precision_recall_streaming_vs_sklearn_style_oracle():
    rng = np.random.RandomState(6)
    c = 4
    ids1 = rng.randint(0, c, (10, 1)).astype("int32")
    lab1 = rng.randint(0, c, (10, 1)).astype("int32")
    ids2 = rng.randint(0, c, (8, 1)).astype("int32")
    lab2 = rng.randint(0, c, (8, 1)).astype("int32")

    def np_states(ids, labels):
        st = np.zeros((c, 4))
        for i, l in zip(ids[:, 0], labels[:, 0]):
            if i == l:
                st[i, 0] += 1
                st[:, 2] += 1
                st[i, 2] -= 1
            else:
                st[i, 1] += 1
                st[l, 3] += 1
                st[:, 2] += 1
                st[i, 2] -= 1
                st[l, 2] -= 1
        return st

    def np_metrics(st):
        def calc(a, b):
            return a / (a + b) if (a > 0 or b > 0) else 1.0
        precs = [calc(st[i, 0], st[i, 1]) for i in range(c)]
        recs = [calc(st[i, 0], st[i, 3]) for i in range(c)]
        mp, mr = np.mean(precs), np.mean(recs)

        def f1(p, r):
            return 2 * p * r / (p + r) if (p > 0 or r > 0) else 0.0
        tp, fp, fn = st[:, 0].sum(), st[:, 1].sum(), st[:, 3].sum()
        up, ur = calc(tp, fp), calc(tp, fn)
        return [mp, mr, f1(mp, mr), up, ur, f1(up, ur)]

    from paddle_tpu.layer_helper import LayerHelper
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        idx = fluid.layers.data("idx", shape=[1], dtype="int32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int32")
        states = fluid.layers.data("states", shape=[c, 4],
                                   append_batch_size=False)
        helper = LayerHelper("precision_recall")
        bm = helper.create_variable_for_type_inference("float32")
        am = helper.create_variable_for_type_inference("float32")
        ast = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="precision_recall",
            inputs={"MaxProbs": [idx], "Indices": [idx], "Labels": [lab],
                    "StatesInfo": [states]},
            outputs={"BatchMetrics": [bm], "AccumMetrics": [am],
                     "AccumStatesInfo": [ast]},
            attrs={"class_number": c})
        exe = fluid.Executor(fluid.CPUPlace())
        st1 = np_states(ids1, lab1)
        bmv, amv, astv = exe.run(
            feed={"idx": ids2, "lab": lab2,
                  "states": st1.astype("float32")},
            fetch_list=[bm, am, ast])
    st2 = np_states(ids2, lab2)
    np.testing.assert_allclose(astv, st1 + st2, atol=1e-5)
    np.testing.assert_allclose(bmv, np_metrics(st2), rtol=1e-5)
    np.testing.assert_allclose(amv, np_metrics(st1 + st2), rtol=1e-5)
