"""Model-zoo integration tests (reference tests/book pattern: build the
real model, train a few steps, assert loss decreases / stays finite)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import mnist, resnet, se_resnext, vgg


def _train_steps(loss, feed_fn, steps=4, lr=0.01):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(feed=feed_fn(), fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_mnist_cnn_trains():
    # seeded init: see test_smallnet_trains
    fluid.default_main_program().random_seed = 5
    fluid.default_startup_program().random_seed = 5
    img = fluid.layers.data("img", shape=[1, 28, 28])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = mnist.cnn_model(img, class_dim=10)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    rng = np.random.RandomState(0)
    base = rng.rand(10, 64, 1, 28, 28).astype("float32")

    def feed():
        i = feed.step % 10
        feed.step += 1
        x = base[i]
        y = (x.mean(axis=(1, 2, 3), keepdims=False) * 10).astype(
            "int64").reshape(-1, 1) % 10
        return {"img": x, "label": y}
    feed.step = 0

    losses = _train_steps(loss, feed, steps=12)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_resnet_cifar10_trains():
    img = fluid.layers.data("img", shape=[3, 16, 16])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = resnet.resnet_cifar10(img, class_dim=10, depth=8)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)

    rng = np.random.RandomState(1)

    def feed():
        x = rng.rand(8, 3, 16, 16).astype("float32")
        y = rng.randint(0, 10, (8, 1)).astype("int64")
        return {"img": x, "label": y}

    losses = _train_steps(loss, feed, steps=3)
    assert all(np.isfinite(losses)), losses


def test_resnet_imagenet_builds_and_runs():
    img = fluid.layers.data("img", shape=[3, 64, 64])
    pred = resnet.resnet_imagenet(img, class_dim=100, depth=18, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().prune_feed_fetch(
        ["img"], [pred.name])
    x = np.random.RandomState(2).rand(2, 3, 64, 64).astype("float32")
    (out,) = exe.run(test_prog, feed={"img": x}, fetch_list=[pred.name])
    assert out.shape == (2, 100)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_vgg16_builds_and_runs():
    img = fluid.layers.data("img", shape=[3, 32, 32])
    pred = vgg.vgg16_bn_drop(img, class_dim=10, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().prune_feed_fetch(
        ["img"], [pred.name])
    x = np.random.RandomState(3).rand(2, 3, 32, 32).astype("float32")
    (out,) = exe.run(test_prog, feed={"img": x}, fetch_list=[pred.name])
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.slow   # ~30s build of the largest model zoo entry (tier-1 budget)
def test_se_resnext_builds_and_runs():
    img = fluid.layers.data("img", shape=[3, 64, 64])
    pred = se_resnext.SE_ResNeXt(img, class_dim=10, depth=50, cardinality=8,
                                 reduction_ratio=4, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().prune_feed_fetch(
        ["img"], [pred.name])
    x = np.random.RandomState(4).rand(2, 3, 64, 64).astype("float32")
    (out,) = exe.run(test_prog, feed={"img": x}, fetch_list=[pred.name])
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_smallnet_trains():
    """Era benchmark trio 1/3 (benchmark/paddle/image/smallnet_mnist_cifar.py)."""
    from paddle_tpu.models import smallnet as m

    # seed the init: an unseeded program draws from the global numpy
    # stream, making convergence depend on test collection order
    fluid.default_main_program().random_seed = 5
    fluid.default_startup_program().random_seed = 5
    img = fluid.layers.data("img", shape=[3, 32, 32])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = m.smallnet(img, class_dim=10)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)

    rng = np.random.RandomState(5)
    base = rng.rand(4, 8, 3, 32, 32).astype("float32")

    def feed():
        i = feed.step % 4
        feed.step += 1
        x = base[i]
        y = (x.mean(axis=(1, 2, 3)) * 30).astype("int64").reshape(-1, 1) % 10
        return {"img": x, "label": y}
    feed.step = 0

    losses = _train_steps(loss, feed, steps=12)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_alexnet_trains():
    """Era benchmark trio 2/3 (benchmark/paddle/image/alexnet.py): full
    227x227 topology incl. the LRN layers, tiny batch, 2 steps finite."""
    from paddle_tpu.models import alexnet as m

    img = fluid.layers.data("img", shape=[3, 227, 227])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = m.alexnet(img, class_dim=1000)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Momentum(learning_rate=1e-3, momentum=0.9).minimize(loss)

    rng = np.random.RandomState(6)

    def feed():
        return {"img": rng.rand(2, 3, 227, 227).astype("float32"),
                "label": rng.randint(0, 1000, (2, 1)).astype("int64")}

    losses = _train_steps(loss, feed, steps=2)
    assert all(np.isfinite(losses)), losses


def test_googlenet_builds_and_runs():
    """Era benchmark trio 3/3 (benchmark/paddle/image/googlenet.py): all
    9 inception blocks; forward inference on a small input."""
    from paddle_tpu.models import googlenet as m

    img = fluid.layers.data("img", shape=[3, 224, 224])
    pred = m.googlenet_v1(img, class_dim=1000, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().prune_feed_fetch(
        ["img"], [pred.name])
    x = np.random.RandomState(7).rand(2, 3, 224, 224).astype("float32")
    (out,) = exe.run(test_prog, feed={"img": x}, fetch_list=[pred.name])
    assert out.shape == (2, 1000)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)
